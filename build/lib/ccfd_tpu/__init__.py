"""ccfd_tpu — a TPU-native credit-card fraud-detection framework.

A ground-up JAX/XLA re-design of the capability surface of the
``ccfd-demo-summit`` reference (see /root/repo/SURVEY.md): a streaming
fraud-scoring pipeline (producer -> bus -> router -> TPU scorer -> process
engine -> notification loop) with Prometheus-compatible observability,
online retraining, and multi-chip sharding via ``jax.sharding``.

Layer map (reference layer -> ccfd_tpu module):

  L1 producer        -> ccfd_tpu.producer   (CSV/S3 stream -> bus topic)
  L2 Kafka           -> ccfd_tpu.bus        (in-process broker, Kafka-shaped API)
  L3 Camel router    -> ccfd_tpu.router     (micro-batching decision router)
  L4 Seldon model    -> ccfd_tpu.models + ccfd_tpu.serving (jit/pjit scorer, REST)
  L5 KIE/jBPM        -> ccfd_tpu.process    (BPMN-style engine, DMN, user tasks)
  L6 notification    -> ccfd_tpu.notify     (simulated customer round-trip)
  L7 Prometheus      -> ccfd_tpu.metrics    (text-format registry, dashboard parity)
  scale-out/retrain  -> ccfd_tpu.parallel   (mesh, shardings, sharded train step)
"""

__version__ = "0.1.0"

from ccfd_tpu.config import Config  # noqa: F401
