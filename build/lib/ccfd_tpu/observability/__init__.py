from ccfd_tpu.observability.dashboards import build_all_dashboards, write_dashboards  # noqa: F401
