"""HTTP client for a remote prediction server (Seldon-contract).

The reference router and KIE server call Seldon over REST with a pooled
HTTP client configured by ``SELDON_URL``/``SELDON_ENDPOINT``/``SELDON_TOKEN``
/``SELDON_TIMEOUT``/``SELDON_POOL_SIZE`` (reference deploy/router.yaml:65-68,
README.md:370-402). This client reproduces that contract over stdlib
``http.client`` with a bounded connection pool, so the router/process-engine
can run on a different host than the TPU scorer. Returned as a plain
``score_fn(np (B,30)) -> np (B,)`` so it is interchangeable with the
in-process ``Scorer.score`` everywhere.
"""

from __future__ import annotations

import http.client
import json
import queue
import time
import urllib.parse
from typing import Any

import numpy as np

from ccfd_tpu.config import Config
from ccfd_tpu.data.ccfd import FEATURE_NAMES


class SeldonClient:
    def __init__(self, cfg: Config):
        self.cfg = cfg
        u = urllib.parse.urlparse(cfg.seldon_url)
        if u.scheme not in ("http", ""):
            raise ValueError(f"unsupported scheme in SELDON_URL: {cfg.seldon_url!r}")
        self._host = u.hostname or "localhost"
        self._port = u.port or 80
        self._path = "/" + cfg.seldon_endpoint.lstrip("/")
        self._timeout = cfg.seldon_timeout_ms / 1000.0
        self._pool: "queue.Queue[http.client.HTTPConnection]" = queue.Queue()
        for _ in range(max(1, cfg.seldon_pool_size)):
            self._pool.put(self._connect())

    def _connect(self) -> http.client.HTTPConnection:
        # Nagle off: headers+body ride separate segments, and a delayed ACK
        # would stall the predict hop ~40 ms (see utils/httpclient.py)
        from ccfd_tpu.utils.httpclient import _NodelayHTTPConnection

        return _NodelayHTTPConnection(self._host, self._port, timeout=self._timeout)

    def _request(self, body: dict[str, Any]) -> dict[str, Any]:
        """POST with per-attempt SELDON_TIMEOUT and bounded retries.

        Retries (CCFD_CLIENT_RETRIES, with short linear backoff) cover the
        window where the supervisor is restarting a crashed scorer — the
        reference has no app-level retry, only the timeout knob
        (README.md:386-393), so a scorer restart drops messages there.
        """
        conn = self._pool.get()
        try:
            payload = json.dumps(body)
            headers = {"Content-Type": "application/json"}
            if self.cfg.seldon_token:
                headers["Authorization"] = f"Bearer {self.cfg.seldon_token}"
            attempts = max(1, self.cfg.client_retries + 1)
            last_exc: Exception | None = None
            for attempt in range(attempts):
                try:
                    conn.request("POST", self._path, payload, headers)
                    resp = conn.getresponse()
                    data = resp.read()
                    if resp.status != 200:
                        raise RuntimeError(
                            f"prediction server returned {resp.status}: {data[:200]!r}"
                        )
                    return json.loads(data)
                except (http.client.HTTPException, OSError) as e:
                    # stale pooled connection or server mid-restart: reconnect
                    last_exc = e
                    conn.close()
                    if attempt < attempts - 1:
                        time.sleep(0.05 * (attempt + 1))
                    conn = self._connect()
            raise ConnectionError(
                f"prediction server unreachable after {attempts} attempts"
            ) from last_exc
        finally:
            self._pool.put(conn)

    def score(self, x: np.ndarray) -> np.ndarray:
        """(B, 30) -> (B,) proba_1 via POST <SELDON_URL>/<SELDON_ENDPOINT>."""
        x = np.asarray(x, np.float32)
        out = self._request(
            {"data": {"names": list(FEATURE_NAMES), "ndarray": x.tolist()}}
        )
        nd = out["data"]["ndarray"]
        return np.asarray([row[1] for row in nd], np.float32)

    def close(self) -> None:
        while not self._pool.empty():
            try:
                self._pool.get_nowait().close()
            except queue.Empty:  # pragma: no cover
                break
