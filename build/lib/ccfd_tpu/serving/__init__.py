from ccfd_tpu.serving.scorer import Scorer  # noqa: F401
