from ccfd_tpu.notify.service import NotificationService  # noqa: F401
