// Native hot-path: CSV transaction decode + batch assembly.
//
// The reference's per-message hop runs feature extraction inside a JVM Camel
// route (reference deploy/router.yaml, README.md:549); our router instead
// assembles one (B, 30) float32 matrix per micro-batch and the Python
// dict-walk is the slowest host-side stage at high throughput. This decoder
// parses newline-separated CSV transaction rows straight into the caller's
// float32 buffer — one pass, no allocations, no Python per-field overhead.
//
// Exposed via ctypes (see ccfd_tpu/native/__init__.py); the fallback numpy
// path implements identical semantics, asserted by tests.

#include <cstddef>
#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// Parse up to max_rows CSV rows of exactly n_features floats each from
// buf[0..len) into out (row-major, max_rows * n_features floats).
// Rows with parse errors or the wrong field count are zero-filled and
// counted in *bad_rows. Returns the number of rows consumed.
int ccfd_decode_csv(const char* buf, size_t len, float* out, int max_rows,
                    int n_features, int* bad_rows) {
  int rows = 0;
  int bad = 0;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end && rows < max_rows) {
    const char* line_end = static_cast<const char*>(memchr(p, '\n', end - p));
    if (line_end == nullptr) line_end = end;
    float* row_out = out + static_cast<size_t>(rows) * n_features;
    int field = 0;
    bool ok = true;
    const char* q = p;
    while (q < line_end && field < n_features) {
      char* next = nullptr;
      float v = strtof(q, &next);
      if (next == q) {  // no parse progress
        ok = false;
        break;
      }
      row_out[field++] = v;
      q = next;
      if (q < line_end) {
        if (*q == ',') {
          ++q;
        } else if (*q != '\n' && *q != '\r') {
          ok = false;
          break;
        }
      }
    }
    // trailing \r (CRLF) is fine; any other leftover content means the row
    // had extra fields — reject it like the numpy fallback does
    while (q < line_end && *q == '\r') ++q;
    if (!ok || field != n_features || q != line_end) {
      memset(row_out, 0, sizeof(float) * n_features);
      ++bad;
    }
    ++rows;
    p = (line_end < end) ? line_end + 1 : end;
  }
  if (bad_rows != nullptr) *bad_rows = bad;
  return rows;
}

// Batch assembly: scatter variable-count rows into a zero-padded bucket.
// src is n_rows * n_features floats; dst is bucket_rows * n_features and is
// fully zeroed first (padding rows score as zeros).
void ccfd_pad_batch(const float* src, int n_rows, int n_features, float* dst,
                    int bucket_rows) {
  const size_t row_bytes = sizeof(float) * static_cast<size_t>(n_features);
  memset(dst, 0, row_bytes * static_cast<size_t>(bucket_rows));
  const int copy = n_rows < bucket_rows ? n_rows : bucket_rows;
  memcpy(dst, src, row_bytes * static_cast<size_t>(copy));
}

// Seldon predict payload decode: parse the numeric matrix out of
//   {"data": {"ndarray": [[f, f, ...], [f, ...]], ...}, ...}
// straight into the caller's float32 buffer — the REST hot path's JSON
// cost without a JSON library (reference request shape README.md:454-459).
//
// Deliberately narrow: ONLY the common canonical-order payload qualifies.
// Returns the row count (>= 0) on success, writing row widths' max to
// *width_out; bails with -1 (caller falls back to the Python JSON path) on
// anything unusual: a "names" key anywhere (caller must remap columns),
// nested objects/strings inside ndarray, no ndarray key, rows wider than
// n_features, or more than max_rows rows. Short rows zero-pad (same
// semantics as the Python path).
int ccfd_decode_ndarray(const char* buf, size_t len, float* out, int max_rows,
                        int n_features, int* width_out) {
  const char* end = buf + len;
  // a "names" key means column remapping — Python path owns that
  for (const char* s = buf; (s = static_cast<const char*>(
                                 memchr(s, '"', end - s))) != nullptr;) {
    if (end - s >= 7 && memcmp(s, "\"names\"", 7) == 0) return -1;
    ++s;
  }
  // require the Seldon "data" wrapper, then "ndarray" after it — a bare
  // {"ndarray": ...} body is NOT the contract and must 400 via the Python
  // path, exactly as the JSON route always did
  const char* data_key = nullptr;
  for (const char* s = buf; (s = static_cast<const char*>(
                                 memchr(s, '"', end - s))) != nullptr;) {
    if (end - s >= 6 && memcmp(s, "\"data\"", 6) == 0) { data_key = s + 6; break; }
    ++s;
  }
  if (data_key == nullptr) return -1;
  const char* nd = nullptr;
  for (const char* s = data_key; (s = static_cast<const char*>(
                                     memchr(s, '"', end - s))) != nullptr;) {
    if (end - s >= 9 && memcmp(s, "\"ndarray\"", 9) == 0) { nd = s + 9; break; }
    ++s;
  }
  if (nd == nullptr) return -1;
  const char* p = nd;
  while (p < end && (*p == ' ' || *p == ':' || *p == '\t' || *p == '\n' ||
                     *p == '\r'))
    ++p;
  if (p >= end || *p != '[') return -1;
  ++p;  // inside the outer array
  int rows = 0;
  int max_width = 0;
  while (p < end) {
    while (p < end && (*p == ' ' || *p == ',' || *p == '\t' || *p == '\n' ||
                       *p == '\r'))
      ++p;
    if (p < end && *p == ']') {  // matrix closed: the tail must close the
      ++p;                       // enclosing objects — a truncated body is
      int depth = 2;             // invalid JSON and must 400, not score
      while (p < end) {
        char c = *p++;
        if (c == '}') {
          --depth;
        } else if (c != ' ' && c != '\t' && c != '\n' && c != '\r' &&
                   c != ',') {
          return -1;  // trailing keys/values: Python path owns them
        }
      }
      if (depth != 0) return -1;  // truncated or over-closed wrappers
      *width_out = max_width;
      return rows;
    }
    if (p >= end || *p != '[') return -1;
    ++p;  // inside a row
    if (rows >= max_rows) return -1;
    float* row_out = out + static_cast<size_t>(rows) * n_features;
    memset(row_out, 0, sizeof(float) * static_cast<size_t>(n_features));
    int col = 0;
    while (p < end) {
      while (p < end && (*p == ' ' || *p == ',' || *p == '\t' || *p == '\n' ||
                         *p == '\r'))
        ++p;
      if (p < end && *p == ']') { ++p; break; }  // row done
      char* next = nullptr;
      float v = strtof(p, &next);
      if (next == p) return -1;  // non-numeric cell: Python path owns it
      if (col >= n_features) return -1;  // wider than the schema
      row_out[col++] = v;
      p = next;
    }
    if (col > max_width) max_width = col;
    ++rows;
  }
  return -1;  // ran off the end without closing the outer array
}

}  // extern "C"
