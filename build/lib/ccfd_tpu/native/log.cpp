// Segment-log framing + recovery scan — the bus's native hot path.
//
// The durable bus (ccfd_tpu/bus/log.py) persists every record as
//   [u32 payload_len][u32 crc32(payload)][payload]   (little-endian)
// mirroring the role of Kafka's on-disk log segments (the reference's
// de-facto recovery mechanism is Kafka log + committed offsets,
// reference deploy/frauddetection_cr.yaml:73-77; SURVEY.md §5).
//
// C++ carries the two byte-crunching loops:
//   ccfd_log_frame — frame a batch of payloads (CRC + headers) in one pass
//   ccfd_log_scan  — replay scan: validate frames, stop at the first torn
//                    or corrupt frame, report the valid prefix length so
//                    the writer can truncate a crashed tail
// File I/O stays in Python: the ctypes boundary passes plain buffers, so
// there is no FILE*/fd ownership crossing languages.

#include <cstddef>
#include <cstdint>
#include <cstring>

namespace {

// CRC-32 (IEEE 802.3, poly 0xEDB88320) — bit-identical to binascii.crc32,
// which the pure-Python fallback uses.
uint32_t crc_table[256];
bool crc_init_done = false;

void crc_init() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_init_done = true;
}

inline uint32_t crc32(const uint8_t* data, size_t len) {
  if (!crc_init_done) crc_init();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; ++i) c = crc_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

inline void put_u32(uint8_t* p, uint32_t v) {
  p[0] = v & 0xFF; p[1] = (v >> 8) & 0xFF; p[2] = (v >> 16) & 0xFF; p[3] = (v >> 24) & 0xFF;
}

inline uint32_t get_u32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

}  // namespace

extern "C" {

uint32_t ccfd_crc32(const uint8_t* data, size_t len) { return crc32(data, len); }

// Frame `n` payloads (concatenated in `payloads`, lengths in `lens`) into
// `out`. `out` must hold sum(lens) + 8*n bytes. Returns bytes written.
size_t ccfd_log_frame(const uint8_t* payloads, const uint32_t* lens, int n,
                      uint8_t* out) {
  size_t in_off = 0, out_off = 0;
  for (int i = 0; i < n; ++i) {
    uint32_t len = lens[i];
    put_u32(out + out_off, len);
    put_u32(out + out_off + 4, crc32(payloads + in_off, len));
    std::memcpy(out + out_off + 8, payloads + in_off, len);
    in_off += len;
    out_off += len + 8;
  }
  return out_off;
}

// Scan up to `max_records` frames from `buf`. Writes each payload's offset
// (into buf) and length. Sets *consumed to the end of the last valid frame
// seen in THIS call. Returns the number of valid records on a clean stop
// (EOF, partial tail, or max_records reached); on corruption (bad CRC or
// insane length) returns -(valid_records + 1) so the caller still learns
// how many leading frames of this call were good.
int ccfd_log_scan(const uint8_t* buf, size_t len, uint64_t* out_off,
                  uint32_t* out_len, int max_records, size_t* consumed) {
  size_t pos = 0;
  int n = 0;
  *consumed = 0;
  while (n < max_records) {
    if (pos + 8 > len) break;  // clean truncation (partial header)
    uint32_t plen = get_u32(buf + pos);
    uint32_t want = get_u32(buf + pos + 4);
    if (plen > (1u << 30)) { *consumed = pos; return -(n + 1); }
    if (pos + 8 + plen > len) break;  // torn tail: frame extends past EOF
    if (crc32(buf + pos + 8, plen) != want) { *consumed = pos; return -(n + 1); }
    out_off[n] = pos + 8;
    out_len[n] = plen;
    pos += 8 + (size_t)plen;
    ++n;
  }
  *consumed = pos;
  return n;
}

}  // extern "C"
