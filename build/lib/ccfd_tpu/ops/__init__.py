from ccfd_tpu.ops.fused_mlp import fold_for_kernel, fused_mlp_score  # noqa: F401
