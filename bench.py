"""Benchmark: transaction-scoring throughput + latency, end to end.

Seven timed surfaces, matching the hops the reference instruments on its
SeldonCore/Router dashboards (SURVEY.md §3 stack A, §6):

1. **Scorer hop** — host feature matrix -> bucketed jit dispatch
   (ccfd_tpu/serving/scorer.py) -> probabilities on host. Full H2D +
   XLA executable + D2H round trip, the number ``metric``/``value`` report.
2. **Fused vs XLA A/B** — the same hop through the Pallas fused kernel and
   through plain XLA, so the kernel's win (or loss) is a recorded number
   (VERDICT r1 next-steps #2).
3. **REST hop** — concurrent HTTP clients -> PredictionServer ->
   DynamicBatcher -> scorer; p50/p99 per request plus aggregate req/s and
   rows/s. This is the hop the reference's Seldon engine histograms
   measure (reference deploy/grafana/SeldonCore.json:499-531).
4. **Pipeline loop** — producer -> bus -> router (micro-batch + rules) ->
   engine (batched process starts) sustained tx/s with the real fraud
   process at a realistic fired mix.
5. **Mesh scoring** — batch sharded over the data axis of a device mesh
   (runs when >1 device is visible; SURVEY.md §7 stage 6).
6. **Online retrain** — SGD steps/s and labels/s for the loop the engine's
   label topic feeds (BASELINE.json configs[4]); sharded when >1 device.
7. **Sequence scoring** — the per-customer history transformer
   (long-context family; ring attention over the mesh when >1 device).

Prints ONE JSON line; primary fields:
  {"metric": ..., "value": tx/s, "unit": "tx/s", "vs_baseline": ratio,
   "p99_ms": ..., "platform": ...}
plus sections ``rest`` / ``pipeline`` / ``fused_ab`` / ``mesh`` /
``retrain`` / ``seq`` / ``zoo`` (logreg + GBT scorer hop) /
``quant_int8`` (int8 vs the bf16 headline on the same hop; TPU-gated,
force with CCFD_BENCH_QUANT=1) / ``replay`` (bulk re-score rate of a
recorded window through the live path at bulk priority, with the live
lane's fast-window SLO breach count — held zero — alongside).

``vs_baseline`` is the ratio against the 50,000 tx/s north-star target
(BASELINE.json; the reference publishes no numbers of its own). ``p99_ms``
covers the p99 < 10 ms target on the REST surface when measured, else the
scorer hop.

Robustness (VERDICT r1 next-steps #1): the accelerator backend is probed
in a SUBPROCESS with a timeout — a wedged TPU tunnel would otherwise hang
``jax.devices()`` forever and take the whole bench with it — and the probe
RETRIES with backoff (CCFD_BENCH_PROBE_ATTEMPTS x CCFD_BENCH_PROBE_S,
CCFD_BENCH_PROBE_BACKOFF_S apart) because the tunnel wedges
intermittently. On fallback the bench runs on CPU, says so in
``platform``, and attaches the newest cached TPU result
(BENCH_TPU_LAST_GOOD.json, written on every successful TPU run) under
``last_good_tpu`` with its capture time.

Env knobs: CCFD_BENCH_BATCH (default 131072), CCFD_BENCH_SECONDS (default 3),
CCFD_BENCH_PIPELINE (in-flight dispatch depth, default 2),
CCFD_BENCH_LATENCY_BATCH (default 4096), CCFD_BENCH_PLATFORM=cpu to force
CPU, CCFD_BENCH_PROBE_S (per-attempt probe timeout, default 90),
CCFD_BENCH_PROBE_ATTEMPTS (default 5), CCFD_BENCH_PROBE_BACKOFF_S (default
45), CCFD_BENCH_REST_CLIENTS (default 4), CCFD_BENCH_REST_ROWS (rows per
request, default 128 - the sweep-measured best configuration,
REST_SWEEP_r04_cpu.json; the sweep artifact carries the full grid),
CCFD_BENCH_SKIP=rest,pipeline,ab,mesh,retrain,seq,zoo,quant,replay to
skip sections, CCFD_BENCH_MAX_S (whole-bench watchdog, default 1500 —
a tunnel that wedges MID-run would otherwise hang the bench forever;
on expiry every section that COMPLETED before the wedge is printed,
clearly labeled partial, with the newest cached TPU result attached,
and the process exits 3).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

NORTH_STAR_TX_S = 50_000.0  # BASELINE.json north_star: >=50k tx/s on v5e-1
NORTH_STAR_P99_MS = 10.0  # BASELINE.json north_star: p99 e2e predict <10ms
LAST_GOOD_PATH = os.path.join(os.path.dirname(__file__), "BENCH_TPU_LAST_GOOD.json")

# Sections append here as they complete so a mid-run wedge (watchdog fire)
# still reports every number that was actually measured, clearly labeled,
# instead of discarding the whole run.
_PARTIAL: dict = {}


def _triage_verdict(root: str | None = None,
                    max_age_h: float | None = None) -> str | None:
    """The newest FRESH tools/tpu_triage.py artifact's verdict (ISSUE 10
    satellite): on accelerator-probe fallback the platform string names
    WHERE the attachment is wedged (``wedged_relay_dead`` vs
    ``wedged_backend``) instead of the generic probe-failed label.

    Freshness gates on the artifact's own ``ts`` stamp
    (CCFD_BENCH_TRIAGE_MAX_AGE_H, default 24): a weeks-old checked-in
    triage must not be asserted as the root cause of TODAY's probe
    failure — stale or absent artifacts fall back to the generic label
    (None)."""
    import glob

    if max_age_h is None:
        max_age_h = float(os.environ.get("CCFD_BENCH_TRIAGE_MAX_AGE_H",
                                         "24"))
    root = root or os.path.dirname(os.path.abspath(__file__))
    paths = glob.glob(os.path.join(root, "TPU_TRIAGE_*.json"))
    best: tuple[float, str, str] | None = None  # (age_ok sort key…)
    for path in paths:
        try:
            with open(path) as f:
                report = json.load(f)
        except (OSError, ValueError):
            continue
        verdict = report.get("verdict")
        ts = report.get("ts", "")
        if not isinstance(verdict, str) or not verdict:
            continue
        try:
            import calendar

            # timegm, not mktime: the ts is UTC, and mktime's local-time
            # (DST-dependent) interpretation would skew the gate an hour
            stamped = calendar.timegm(time.strptime(ts,
                                                    "%Y-%m-%dT%H:%M:%SZ"))
        except (TypeError, ValueError, OverflowError):
            continue  # unparseable stamp: cannot prove freshness
        if time.time() - stamped > max_age_h * 3600.0:
            continue
        if best is None or stamped > best[0]:
            best = (stamped, verdict, ts)
    if best is None:
        return None
    return f"triage: {best[1]} @ {best[2]}"


def _fresh_triage(timeout_s: float | None = None) -> str | None:
    """Run ``tools/tpu_triage.py`` NOW for a live verdict (ISSUE 11
    satellite): when the accelerator probe just failed, the platform
    string must name where the attachment is wedged *today*, not fold a
    checked-in artifact from an earlier wedge — a stale verdict asserted
    as the root cause of a fresh failure is exactly the misdiagnosis the
    freshness gate in :func:`_triage_verdict` exists to refuse. Invoked
    as a subprocess (the triage's own jax probe must not wedge the
    bench); ``--json`` so checked-in artifacts are never clobbered,
    ``--no-trace`` to skip the LD_PRELOAD audit's compile cost. Returns
    the ``triage: <verdict> @ <ts> (live)`` label, or None when the run
    fails/times out (callers then fall back to the cached-artifact path).
    ``CCFD_BENCH_TRIAGE_LIVE=0`` skips the live run entirely (CI boxes
    with no attachment to triage)."""
    if os.environ.get("CCFD_BENCH_TRIAGE_LIVE", "1") in ("0", "false"):
        return None
    if timeout_s is None:
        timeout_s = float(os.environ.get("CCFD_BENCH_TRIAGE_TIMEOUT_S",
                                         "120"))
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "tpu_triage.py")
    try:
        r = subprocess.run(
            [sys.executable, script, "--json", "--no-trace",
             "--probe-s", "20"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        report = json.loads(r.stdout.strip())
    except (subprocess.SubprocessError, OSError, ValueError):
        return None
    verdict = report.get("verdict")
    ts = report.get("ts", "")
    if not isinstance(verdict, str) or not verdict:
        return None
    return f"triage: {verdict} @ {ts} (live)"


class _DeviceMeter:
    """Per-section device telemetry for bench rows (ISSUE 10 satellite):
    installs a DeviceTelemetry plane as the process default — every
    scorer the sections build stages through it — and hands out per-
    section H2D byte deltas + the running peak device memory."""

    def __init__(self, attach_rows: bool):
        from ccfd_tpu.observability.device import (
            DeviceTelemetry,
            set_default,
        )

        self.attach_rows = attach_rows
        self.tele = DeviceTelemetry()
        set_default(self.tele)
        self._last_bytes = 0

    def section(self, row) -> None:
        """Attach {h2d_bytes, peak_device_memory_bytes} to a completed
        section row (on-device runs; the CPU fallback exercises the same
        counters but its rows stay unchanged)."""
        if self.tele is None:
            return
        total = self.tele.h2d_bytes()
        delta, self._last_bytes = total - self._last_bytes, total
        if not (self.attach_rows and isinstance(row, dict)):
            return
        row["device"] = {
            "h2d_bytes": int(delta),
            "peak_device_memory_bytes": self.tele.peak_memory_bytes(),
        }


def _probe_backend(timeout_s: float, attempts: int, backoff_s: float) -> bool:
    """Can this environment initialize its default jax backend? Run the
    check in a child so a wedged TPU tunnel can't hang the bench itself,
    and retry: the tunnel wedges intermittently, and one failed probe must
    not cost the whole round its TPU number."""
    for i in range(max(1, attempts)):
        if i:
            time.sleep(backoff_s)
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout_s,
                capture_output=True,
            )
            if r.returncode == 0:
                return True
        except (subprocess.SubprocessError, OSError):
            pass
    return False


def _bench_scorer(scorer, X, batch, lat_batch, seconds, depth):
    import numpy as np

    x = X[:batch]
    n_rows = 0
    t0 = time.perf_counter()
    while True:
        proba = scorer.score_pipelined(x, depth=depth)
        n_rows += x.shape[0]
        elapsed = time.perf_counter() - t0
        if elapsed >= seconds:
            break
    assert proba.shape == (batch,)
    tx_per_s = n_rows / elapsed

    xl = X[:lat_batch]
    lat = []
    t_end = time.perf_counter() + max(1.0, seconds / 2)
    while time.perf_counter() < t_end:
        t1 = time.perf_counter()
        scorer.score(xl)
        lat.append((time.perf_counter() - t1) * 1e3)
    lat_a = np.asarray(lat)
    return tx_per_s, float(np.percentile(lat_a, 50)), float(np.percentile(lat_a, 99))


# The REST client lives in ccfd_tpu/utils/loadgen.py (_CLIENT): ONE copy
# shared with `ccfd_tpu loadgen`, so operator-side numbers against a
# deployed scorer compare directly with the bench's rest section.


def _loadgen_client() -> str:
    from ccfd_tpu.utils.loadgen import _CLIENT

    return _CLIENT


def _hop_buckets(top: int) -> tuple[int, ...]:
    """ONE bucket ladder for every per-model bench section. The sections
    had drifted apart — rest compiled (16, 128, 1024, top), zoo/quant a
    single (top,) bucket — so the 'same' hop ran different executable
    sets and padding regimes on CPU vs TPU and the captures were not
    comparable (ROADMAP item 1 / SNIPPETS PR-5 header). Every section now
    compiles the canonical serving ladder clipped at its top size."""
    return tuple(b for b in (16, 128, 1024, 4096) if b < top) + (int(top),)


def _section_scorer(model, params, top, use_fused=None, host_tier_rows=0,
                    partitioner=None):
    """The shared Scorer construction for the rest/zoo/quant/mesh sections:
    same bucket ladder (:func:`_hop_buckets`), same bfloat16 compute
    dtype, differing ONLY in what the section is isolating (fused path
    on/off; host tier 0 for raw device-hop rates, None = auto for the
    REST section, whose serving policy includes the host tier;
    ``partitioner`` shards the same construction over a device mesh — the
    devices=N scaling row and tools/multichip_scaling.py both build
    through here so their numbers stay comparable)."""
    from ccfd_tpu.serving.scorer import Scorer

    kw = {} if use_fused is None else {"use_fused": use_fused}
    s = Scorer(
        model_name=model, params=params, batch_sizes=_hop_buckets(top),
        compute_dtype="bfloat16", host_tier_rows=host_tier_rows,
        partitioner=partitioner, **kw,
    )
    s.warmup()
    return s


def _bench_rest(scorer_params, lat_batch, seconds, n_clients, rows_per_req,
                native=True):
    """HTTP clients -> PredictionServer -> DynamicBatcher -> scorer: the full
    REST round trip. Clients run in SUBPROCESSES — in-process client threads
    would share the GIL with the server handlers and pollute the p99 with
    client-side scheduling, which is not the hop under test. ``native``
    selects the C++ front vs the Python transport (the A/B records the
    native front's win as a number)."""
    import numpy as np

    from ccfd_tpu.config import Config
    from ccfd_tpu.serving.server import PredictionServer

    scorer = _section_scorer("mlp", scorer_params, lat_batch,
                             host_tier_rows=None)
    srv = PredictionServer(scorer, Config(dynamic_batching=True,
                                          native_front=native))
    port = srv.start(host="127.0.0.1", port=0)
    transport = type(srv._httpd).__name__  # read before stop() nulls it
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _loadgen_client(),
             "127.0.0.1", str(port), "/api/v0.1/predictions",
             str(rows_per_req), str(seconds)],
            stdout=subprocess.PIPE,
        )
        for _ in range(n_clients)
    ]
    lat: list[float] = []
    rate = 0.0
    ok = 0
    errors = 0
    try:
        for p in procs:
            # throughput aggregates per-client measured windows: the
            # parent's wall clock would also count interpreter startup
            # (~2 s of site hooks here), which is not the hop under test
            try:
                out, _ = p.communicate(timeout=seconds + 120)
            except subprocess.TimeoutExpired:
                p.kill()
                continue
            if p.returncode == 0:
                try:
                    r = json.loads(out)
                except ValueError:
                    continue
                lat.extend(r["lat"])
                rate += len(r["lat"]) / max(r["loop_s"], 1e-9)
                errors += int(r.get("errors", 0))
                ok += 1
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        srv.stop()
    if not lat:
        return {"error": "all REST bench clients failed"}
    lat_a = np.asarray(lat)
    return {
        "clients": ok,
        "rows_per_request": rows_per_req,
        "requests_s": round(rate, 1),
        "tx_s": round(rate * rows_per_req, 1),
        "p50_ms": round(float(np.percentile(lat_a, 50)), 3),
        "p99_ms": round(float(np.percentile(lat_a, 99)), 3),
        # transparency: small request batches may score on the serving
        # host tier (numpy) instead of paying the device RTT — by design
        "host_tier_rows": scorer.host_tier_rows,
        "transport": transport,
        # non-200s during the run (the shared client counts, never dies)
        "errors": errors,
    }


def _bench_pipeline(scorer_params, seconds):
    """producer -> bus -> router -> engine sustained loop, realistic mix.

    Records ride the wire as raw CSV rows — the reference's producer
    streams creditcard.csv lines to the topic (reference
    deploy/kafka/ProducerDeployment.yaml:90-95), and the router decodes
    that format through the native C++ path (decode.cpp); dict-format
    records remain covered by tests/test_pipeline.py."""
    from ccfd_tpu.bus.broker import Broker
    from ccfd_tpu.config import Config
    from ccfd_tpu.data.ccfd import synthetic_dataset
    from ccfd_tpu.metrics.prom import Registry
    from ccfd_tpu.process.fraud import build_engine
    from ccfd_tpu.router.router import Router
    from ccfd_tpu.serving.scorer import Scorer

    cfg = Config()
    broker = Broker()
    reg = Registry()
    engine = build_engine(cfg, broker, reg, None)
    scorer = Scorer(model_name="mlp", params=scorer_params)
    scorer.warmup()
    router = Router(cfg, broker, scorer.score, engine, reg, max_batch=4096)

    ds = synthetic_dataset(n=8192, fraud_rate=0.01, seed=1)
    recs = [
        ",".join(f"{v:.6g}" for v in ds.X[i]).encode()
        for i in range(len(ds.X))
    ]
    keys = list(range(len(recs)))

    # one saturated-phase harness for BOTH router shapes: a feeder thread
    # keeps the topic ahead of the consumer under one backpressure policy,
    # so the workers=N row is ratioed against a baseline measured under
    # identical feed conditions
    import threading

    def saturated_run(broker_x, c_in, router_obj) -> float:
        stop_x = threading.Event()

        def feed() -> None:
            while not stop_x.is_set():
                backlog = sum(broker_x.end_offsets(cfg.kafka_topic))
                if backlog - c_in.value() > 50_000:
                    time.sleep(0.002)
                    continue
                broker_x.produce_batch(cfg.kafka_topic, recs, keys)

        feeder = threading.Thread(target=feed, daemon=True)
        feeder.start()
        t0 = time.perf_counter()
        th = router_obj.start(poll_timeout_s=0.05, pipeline=True)
        time.sleep(seconds)
        router_obj.stop()
        th.join(timeout=60)
        elapsed = time.perf_counter() - t0
        stop_x.set()
        feeder.join(timeout=5)
        return elapsed

    elapsed = saturated_run(broker, router._c_in, router)
    total = router._c_in.value()
    out = reg.counter("transaction_outgoing_total")
    result = {
        "tx_s": round(total / elapsed, 1),
        "standard_starts": out.value(labels={"type": "standard"}),
        "fraud_starts": out.value(labels={"type": "fraud"}),
    }

    # Phase 1a — shadow-scoring overhead (lifecycle/shadow.py): the SAME
    # saturated harness with a challenger armed in the scorer's slot and
    # the router's score lane tap-wrapped. The lifecycle's hot-path
    # contract is that shadow evaluation rides a bounded queue serviced
    # off-thread (host numpy forward), so tx_s must sit within noise of
    # the baseline — overhead_pct is the acceptance number, with the
    # dropped-batch count showing where backpressure went instead.
    from ccfd_tpu.lifecycle.shadow import ShadowTap

    broker_s = Broker()
    reg_s = Registry()
    engine_s = build_engine(cfg, broker_s, reg_s, None)
    tap = ShadowTap(scorer, broker_s, cfg.shadow_topic, reg_s)
    scorer.install_challenger(1, scorer_params)
    tap.arm(1)
    router_s = Router(cfg, broker_s, tap.wrap(scorer.score), engine_s,
                      reg_s, max_batch=4096)
    shadow_thread = threading.Thread(
        target=lambda: tap.run(interval_s=0.01), daemon=True)
    shadow_thread.start()
    c_in_s = reg_s.counter("transaction_incoming_total")
    elapsed_s = saturated_run(broker_s, c_in_s, router_s)
    tap.stop()
    shadow_thread.join(timeout=5)
    tap.disarm()
    scorer.clear_challenger()
    tx_s_shadow = c_in_s.value() / elapsed_s
    result["shadow"] = {
        "tx_s": round(tx_s_shadow, 1),
        "overhead_pct": round(
            100.0 * (1.0 - tx_s_shadow / max(result["tx_s"], 1e-9)), 1),
        "rows_shadow_scored": int(reg_s.counter(
            "ccfd_lifecycle_shadow_rows_total").value()),
        "rows_dropped": int(reg_s.counter(
            "ccfd_lifecycle_shadow_dropped_total").value()),
    }

    # Phase 1b — worker-count axis (router/parallel.py ParallelRouter):
    # the SAME max_batch budget, N partition-parallel worker loops
    # sharing one coalescing batcher. Reports scaling efficiency against
    # the single-router phase above and the coalesced-dispatch fan-in
    # (dispatches < worker batches == concurrent sub-batches merged into
    # one device launch). ``cpus`` rides along because thread fan-out is
    # hardware-bounded: on a 2-core CPU host the GIL thread and the XLA
    # pool already saturate the box at workers=1, so the scaling ceiling
    # is ~1x there; the row exists to prove the machinery and to measure
    # real scaling where the cores exist. Dispatches coalesce toward an
    # 8192 bucket (2 worker polls): big enough to show fan-in, small
    # enough that the pool's finishes don't convoy behind one
    # device-batch the size of every worker's poll combined.
    import os as _os

    from ccfd_tpu.router.parallel import ParallelRouter

    result["workers"] = {"1": {"tx_s": result["tx_s"]}}
    result["workers_cpus"] = _os.cpu_count()
    scorer_w = Scorer(model_name="mlp", params=scorer_params,
                      batch_sizes=(128, 1024, 4096, 8192))
    scorer_w.warmup()
    for n_workers in (4,):
        broker_w = Broker(default_partitions=2 * n_workers)
        reg_w = Registry()
        engine_w = build_engine(cfg, broker_w, reg_w, None)
        pr = ParallelRouter(cfg, broker_w, scorer_w.score, engine_w, reg_w,
                            workers=n_workers, max_batch=4096,
                            coalesce_max_batch=8192)
        c_in_w = reg_w.counter("transaction_incoming_total")
        elapsed_w = saturated_run(broker_w, c_in_w, pr)
        shed_w = reg_w.counter("router_shed_total").value()
        # routed-only throughput: transaction_incoming_total counts shed
        # (consumed-but-dropped) rows too, and the scaling ratio must not
        # be inflatable by drops (shed stays 0 with the default budget;
        # the row reports it so a nonzero value is visible)
        total_w = c_in_w.value() - shed_w
        tx_s_w = total_w / elapsed_w
        worker_batches = reg_w.counter(
            "router_worker_batches_total").total()
        dispatches = reg_w.counter(
            "router_coalesced_dispatches_total").value()
        pr.close()
        result["workers"][str(n_workers)] = {
            "tx_s": round(tx_s_w, 1),
            "scaling_x": round(tx_s_w / max(result["tx_s"], 1e-9), 2),
            "scaling_efficiency": round(
                tx_s_w / max(result["tx_s"], 1e-9) / n_workers, 3),
            "worker_batches": int(worker_batches),
            "coalesced_dispatches": int(dispatches),
            "shed": int(shed_w),
        }

    # Phase 2 — decision latency at a PACED rate (the business SLO the
    # reference tracks as SeldonCore board quantiles): under the
    # saturated phase above, latency is just backlog depth; the SLO
    # question is producer -> process-start at a sustainable arrival
    # rate. Fresh registry/router so the histogram holds only this phase,
    # and the consumer group skips phase 1's unconsumed backlog — its
    # seconds-old timestamps would otherwise dominate the quantiles.
    broker.reset_offsets("router", cfg.kafka_topic,
                         broker.end_offsets(cfg.kafka_topic))
    reg2 = Registry()
    engine2 = build_engine(cfg, broker, reg2, None)
    router2 = Router(cfg, broker, scorer.score, engine2, reg2,
                     max_batch=4096)
    # pace AT the north-star rate when the saturated phase shows headroom
    # (capped at half of saturation so an overloaded host still measures
    # a sustainable rate, not its own backlog)
    rate = max(5_000.0, min(NORTH_STAR_TX_S, result["tx_s"] * 0.5))
    th2 = router2.start(poll_timeout_s=0.01, pipeline=True)
    t_end = time.perf_counter() + max(3.0, seconds / 2)
    # 5 ms production tick: the tick is a floor under every record's
    # queueing delay (a record waits out the rest of its burst), so a
    # coarse tick would measure the generator, not the pipeline
    tick = 0.005
    chunk = max(1, int(rate * tick))
    i = 0
    while time.perf_counter() < t_end:
        broker.produce_batch(
            cfg.kafka_topic, recs[i % 4096:i % 4096 + chunk],
            keys[i % 4096:i % 4096 + chunk],
        )
        i += chunk
        time.sleep(tick)
    # drain, then read the quantiles
    deadline = time.perf_counter() + 10
    while (router2._c_in.value() < i
           and time.perf_counter() < deadline):
        time.sleep(0.05)
    router2.stop()
    th2.join(timeout=30)
    dec = reg2.histogram("router_decision_seconds")
    result["paced_rate_tx_s"] = round(rate, 0)
    result["p50_ms"] = round(dec.quantile(0.5) * 1e3, 3)
    result["p99_ms"] = round(dec.quantile(0.99) * 1e3, 3)
    return result


def _bench_mesh(params, batch, seconds, depth):
    """devices=N scaling row (ROADMAP item 2, mirroring the PR 3
    worker-scaling row): the SAME work through the SAME
    :func:`_section_scorer` / :func:`_hop_buckets` construction at mesh
    1x1 and on the full local mesh (data-parallel partitioner,
    parallel/partition.py — the live platform's serving construction), so
    the scaling ratio isolates what sharding adds. Records per-device
    dispatch counts off the PR 10 executable inventory: on a mesh each
    dispatch is ONE SPMD launch spanning every device, so the grid's
    tallies ARE the per-device counts. Runs when >1 device is visible (or
    a virtual CPU mesh is forced — the row then stamps
    ``virtual_devices: true`` and reports ``sharding_overhead_x`` INSTEAD
    of scaling_x/efficiency: all N virtual devices share the same host
    cores, so a speedup claim there would be a scheduler artifact;
    tools/multichip_scaling.py documents the confound)."""
    import jax

    from ccfd_tpu.parallel.mesh import make_named_mesh
    from ccfd_tpu.parallel.partition import DataParallelPartitioner

    n_dev = len(jax.devices())
    if n_dev < 2:
        return None
    from ccfd_tpu.data.ccfd import synthetic_dataset

    # feed depth x batch rows per call: with a top (batch,) bucket each
    # call then splits into `depth` chunks whose dispatches actually
    # overlap — one bucket-sized call would drain before returning and
    # the pipelining knob would be inert
    x = synthetic_dataset(n=depth * batch, fraud_rate=0.01, seed=2).X

    def rate(scorer):
        n_rows = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            scorer.score_pipelined(x, depth=depth)
            n_rows += depth * batch
        return n_rows / (time.perf_counter() - t0)

    tx_single = rate(_section_scorer("mlp", params, batch))
    part = DataParallelPartitioner(make_named_mesh(jax.devices()))
    sharded = _section_scorer("mlp", params, batch, partitioner=part)
    tx_mesh = rate(sharded)
    grid = sharded.executable_grid()
    scaling = tx_mesh / max(tx_single, 1e-9)
    # virtual devices (forced CPU mesh) all share the same host cores, so
    # a "speedup" column would claim parallel scaling that physically
    # cannot exist — at fixed cores the honest number is the sharding
    # overhead ratio (tools/multichip_scaling.py measures it at fixed
    # global work); scaling_x/efficiency are emitted only on real chips
    virtual = jax.default_backend() == "cpu"
    row = {
        "devices": n_dev,
        "mesh_axes": grid.get("mesh_axes"),
        "tx_s": round(tx_mesh, 1),
        "single_tx_s": round(tx_single, 1),
        "virtual_devices": virtual,
        "per_device_dispatches": grid["dispatches"],
    }
    if virtual:
        row["sharding_overhead_x"] = round(
            tx_single / max(tx_mesh, 1e-9), 2)
    else:
        row["scaling_x"] = round(scaling, 2)
        row["efficiency"] = round(scaling / n_dev, 3)
    return row


def _bench_retrain(seconds):
    """Online-retrain throughput (BASELINE.json configs[4]): labels -> one
    SGD step per batch, the loop the engine's label topic feeds — sharded
    over a data mesh when more than one device is visible, single-device
    otherwise (the ``devices`` field records which)."""
    import jax
    import numpy as np

    from ccfd_tpu.data.ccfd import synthetic_dataset
    from ccfd_tpu.models import mlp
    from ccfd_tpu.parallel.train import TrainConfig, init_state, make_train_step

    n_dev = len(jax.devices())
    partitioner = None
    if n_dev > 1:
        # the live platform's retrain construction (parallel/partition.py):
        # donated sharded state over the named data-parallel mesh
        from ccfd_tpu.parallel.mesh import make_named_mesh
        from ccfd_tpu.parallel.partition import DataParallelPartitioner

        partitioner = DataParallelPartitioner(make_named_mesh())
    ds = synthetic_dataset(n=4096, fraud_rate=0.2, seed=3)
    tc = TrainConfig(compute_dtype="bfloat16")
    params = mlp.init(jax.random.PRNGKey(0))
    params = mlp.set_normalizer(params, ds.X.mean(0), ds.X.std(0))
    state = init_state(params, tc)
    step = make_train_step(tc, partitioner=partitioner)
    x = ds.X[:1024]
    y = ds.y[:1024].astype(np.float32)
    state, loss = step(state, x, y)  # compile
    jax.block_until_ready(loss)
    steps = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        state, loss = step(state, x, y)
        steps += 1
    jax.block_until_ready(loss)
    elapsed = time.perf_counter() - t0
    return {
        "steps_s": round(steps / elapsed, 1),
        "labels_s": round(steps * 1024 / elapsed, 1),
        "batch": 1024,
        "devices": n_dev,
        "final_loss": round(float(loss), 4),
    }


def _scorer_hop_rate(name, params, x, seconds, use_fused=False):
    """Time the REAL scorer hop for one model: numpy in, probabilities on
    host out, full H2D + dispatch + D2H per call through the Scorer (host
    tier forced off so the number is the device path) — the same surface
    the headline MLP metric measures, so the zoo ranks comparably.
    Built through :func:`_section_scorer`, so zoo/quant compile the SAME
    bucket ladder the rest section serves."""
    s = _section_scorer(name, params, x.shape[0], use_fused=use_fused)
    if use_fused and not s.fused:
        # warmup fell back (lowering failure): recording the XLA rate
        # under a fused label would corrupt the A/B this exists to settle
        return None
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        s.score(x)
        if use_fused and not s.fused:
            # the scorer degraded mid-loop (runtime fused failure): the
            # rest of the window would measure the XLA graph under a
            # fused label — bail NOW and give the heal window's scarce
            # seconds to the next section
            return None
        n += x.shape[0]
    return round(n / (time.perf_counter() - t0), 1)


def _bench_zoo(seconds, batch=16384):
    """Scorer-hop throughput for the rest of the model zoo (the headline
    number is the flagship MLP): logreg (reference modelfull parity family)
    and the tensorized GBT ensemble, each through the same Scorer hop as
    the headline."""
    import jax
    import numpy as np

    from ccfd_tpu.data.ccfd import synthetic_dataset
    from ccfd_tpu.models import logreg, trees

    ds = synthetic_dataset(n=batch, fraud_rate=0.01, seed=4)
    rng = np.random.default_rng(0)

    def random_tree_params(n_trees, depth):
        # randomized splits so gathers hit varied nodes (an all-inf
        # threshold ensemble would descend one hot path and flatter the
        # number)
        skel = trees.init_empty(n_trees=n_trees, depth=depth)
        return {
            "feature": jax.numpy.asarray(
                rng.integers(0, 30, skel["feature"].shape), "int32"
            ),
            "threshold": jax.numpy.asarray(
                rng.normal(size=skel["threshold"].shape), "float32"
            ),
            "leaf": jax.numpy.asarray(
                rng.normal(scale=0.05, size=skel["leaf"].shape), "float32"
            ),
            "base": skel["base"],
        }

    gbt_params = random_tree_params(100, 4)
    # the servable-HGB shape (HGB_SERVABLE_r04.json best: 44 trees x
    # depth 8): the quality champion's serving cost
    hgb_like = random_tree_params(44, 8)
    out = {}
    for name, model, params in (
        ("logreg", "logreg", logreg.fit_numpy(ds.X[:2048], ds.y[:2048])),
        ("gbt", "gbt", gbt_params),          # lockstep-descent gathers
        ("gbt_mxu", "gbt_mxu", gbt_params),  # gather-free one-hot matmul
        ("gbt_hgb_shape", "gbt", hgb_like),  # 44 trees x depth 8
    ):
        out[name] = {"tx_s": _scorer_hop_rate(model, params, ds.X, seconds),
                     "batch": batch}
    return out


def _median_time(fn, k=5):
    """Median wall time of k calls — the timing primitive the roofline
    split and the seq-pipeline split share."""
    ts = []
    for _ in range(k):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def _bench_roofline(scorer, params, X, lat_batch, headline_tx_s,
                    rest, quant):
    """Roofline accounting (VERDICT r4 items 4/5): turn "wire-bound" from
    an assertion into numbers.  Records the model's FLOP/row, each measured
    section's achieved FLOP/s and wire bytes/s against the relevant peaks
    (MXU bf16/int8, HBM, and a *measured* H2D link bandwidth), plus a
    host-prep / H2D / device-compute time split for one serving batch — the
    denominators the batch-size and wire-format decisions (f32 vs bf16 vs
    int8 rows) have been made without.

    The north star (BASELINE.json) names a v5e-1; published peaks for that
    chip are used when the attached device reports a v5e kind and carried
    as "assumed" otherwise.  On the CPU fallback the peaks are null and the
    H2D figure is host memcpy — labeled, still useful as the split's
    denominator."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    flop_per_row = int(sum(
        2 * int(np.asarray(l["w"]).shape[0]) * int(np.asarray(l["w"]).shape[1])
        for l in params["layers"]
    ) + 2 * int(np.asarray(params["norm"]["mu"]).shape[0]))

    backend = jax.default_backend()
    kind = getattr(jax.devices()[0], "device_kind", backend)
    # published per-chip peaks (dense): bf16 GFLOP/s, int8 GOP/s, HBM GB/s
    peak_table = {
        "v5e": (197_000.0, 394_000.0, 819.0),
        "v5 lite": (197_000.0, 394_000.0, 819.0),
        "v5p": (459_000.0, 918_000.0, 2765.0),
        "v4": (275_000.0, 275_000.0, 1228.0),
        "v3": (123_000.0, 123_000.0, 900.0),
    }
    peaks = None
    peaks_assumed = False
    if backend == "tpu":
        for tag, (bf16, int8, hbm) in peak_table.items():
            if tag in str(kind).lower():
                peaks = {"mxu_bf16_gflop_s": bf16, "mxu_int8_gop_s": int8,
                         "hbm_gb_s": hbm}
                break
        if peaks is None:  # tunnel may report an opaque kind: assume the
            peaks_assumed = True  # north star's chip rather than nothing
            bf16, int8, hbm = peak_table["v5e"]
            peaks = {"mxu_bf16_gflop_s": bf16, "mxu_int8_gop_s": int8,
                     "hbm_gb_s": hbm}

    # measured H2D link: one bulk transfer for bandwidth, one small for
    # per-dispatch overhead (through a tunneled attachment the fixed cost
    # dominates small batches — that IS the host-tier policy's regime)
    def _h2d_s(nbytes):
        arr = np.zeros(nbytes // 4, np.float32)
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(jax.device_put(arr))
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2]

    bulk_bytes = 32 * 1024 * 1024
    small_bytes = 256 * 1024
    h2d_bulk_s = _h2d_s(bulk_bytes)
    h2d = {
        "mb_s_measured": round(bulk_bytes / h2d_bulk_s / 1e6, 1),
        "dispatch_ms_small": round(_h2d_s(small_bytes) * 1e3, 3),
        "bulk_mib": 32,
    }

    # host-prep / H2D / device-compute split for one latency batch through
    # the live scorer's own wire dtype and apply fn
    use_fused = bool(scorer.fused and scorer._fused_params is not None)
    wire_dtype = np.dtype(scorer._fused_in_dtype) if use_fused \
        else np.dtype(np.float32)
    n_feat = int(np.asarray(params["norm"]["mu"]).shape[0])
    bytes_per_row = n_feat * wire_dtype.itemsize
    chunk = np.ascontiguousarray(X[:lat_batch], np.float32)

    prep = lambda: chunk.astype(wire_dtype)  # noqa: E731
    wired = chunk.astype(wire_dtype)
    put = lambda: jax.block_until_ready(jax.device_put(wired))  # noqa: E731
    xdev = jax.device_put(wired)
    jax.block_until_ready(xdev)
    apply_fn = scorer._fused_apply if use_fused else scorer._apply
    wparams = scorer._fused_params if use_fused else scorer._params
    jax.block_until_ready(apply_fn(wparams, xdev))  # compile outside timing
    compute = lambda: jax.block_until_ready(apply_fn(wparams, xdev))  # noqa: E731
    split = {
        "batch": lat_batch,
        "host_prep_ms": round(_median_time(prep, k=7) * 1e3, 3),
        "h2d_ms": round(_median_time(put, k=7) * 1e3, 3),
        "device_compute_ms": round(_median_time(compute, k=7) * 1e3, 3),
    }

    def section(tx_s, row_bytes, int8_math=False):
        if tx_s is None:
            return None
        out = {
            "tx_s": round(tx_s, 1),
            "bytes_per_row": row_bytes,
            "achieved_gflop_s": round(tx_s * flop_per_row / 1e9, 2),
            "wire_mb_s": round(tx_s * row_bytes / 1e6, 2),
        }
        out["h2d_link_util_pct"] = round(
            100.0 * out["wire_mb_s"] / max(h2d["mb_s_measured"], 1e-9), 2)
        if peaks:
            peak = peaks["mxu_int8_gop_s"] if int8_math \
                else peaks["mxu_bf16_gflop_s"]
            out["mfu_pct"] = round(100.0 * out["achieved_gflop_s"] / peak, 4)
            out["hbm_util_pct"] = round(
                100.0 * out["wire_mb_s"] / 1e3 / peaks["hbm_gb_s"], 4)
        return out

    sections = {}
    if headline_tx_s:
        sections["scorer_hop"] = section(headline_tx_s, bytes_per_row)
    if isinstance(rest, dict) and "tx_s" in rest:
        # REST rows land as JSON text host-side; the H2D wire is still the
        # scorer's dtype — host decode cost shows in the split, not here
        sections["rest"] = section(rest["tx_s"], bytes_per_row)
    if isinstance(quant, dict):
        q_tx = quant.get("preq_tx_s") or quant.get("tx_s")
        if q_tx:
            # int8 wire: n_feat int8 + one f32 scale per row
            sections["quant_int8_wire"] = section(
                q_tx, n_feat + 4, int8_math=True)

    head = sections.get("scorer_hop") or next(
        (s for s in sections.values() if s), None)
    if head is None:
        bound = "unmeasured"
        head = {}
    else:
        utils = {"h2d_wire": head["h2d_link_util_pct"]}
        if peaks:
            utils["mxu"] = head.get("mfu_pct", 0.0)
            utils["hbm"] = head.get("hbm_util_pct", 0.0)
        # the bound is whichever resource the headline hop uses the
        # largest fraction of; "host" when nothing device-side is >1%
        # busy — the time goes to host prep/dispatch, which the split
        # quantifies
        bound = max(utils, key=lambda k: utils[k])
        if utils[bound] < 1.0:
            bound = "host"
    return {
        "flop_per_row": flop_per_row,
        "device_kind": str(kind),
        "peaks": peaks,
        "peaks_assumed": peaks_assumed,
        "h2d": h2d,
        "split_ms": split,
        "wire_dtype": wire_dtype.name,
        "sections": sections,
        "bound": bound,
        # headline copies for the compact summary line
        "wire_mb_s": head.get("wire_mb_s"),
        "mfu_pct": head.get("mfu_pct"),
        "h2d_mb_s_measured": h2d["mb_s_measured"],
    }


def _bench_quant(params, x, seconds):
    """Int8 vs the bf16 headline on the SAME Scorer hop: per-channel int8
    weights + per-row dynamic activations ride the MXU at twice the bf16
    rate and halve the wire bytes (ops/quant.py); measuring through the
    full H2D/D2H round trip is what lets the wire half show."""
    import jax

    from ccfd_tpu.ops import quant as quantlib

    qp = quantlib.quantize_mlp(params)
    out = {
        "tx_s": _scorer_hop_rate("mlp_q8", qp, x, seconds),
        "batch": int(x.shape[0]),
        "dtype": "int8",
    }
    if jax.default_backend() == "tpu":
        # Three-way ablation, each isolating ONE effect:
        #   tx_s       — XLA q8 graph, f32 wire
        #   fused_tx_s — Pallas kernel, f32 wire (kernel effect alone;
        #                CCFD_Q8_WIRE=f32 pins the wire because the int8
        #                wire is the scorer's default now)
        #   preq_tx_s  — Pallas kernel + int8 wire (the serving default)
        # TPU-only: the CPU interpreter would record noise. None/error =
        # the kernel failed to lower, distinct from "no effect".
        prev = os.environ.get("CCFD_Q8_WIRE")
        os.environ["CCFD_Q8_WIRE"] = "f32"
        try:
            fused_rate = _scorer_hop_rate(
                "mlp_q8", qp, x, seconds, use_fused=True
            )
        finally:
            if prev is None:
                os.environ.pop("CCFD_Q8_WIRE", None)
            else:
                os.environ["CCFD_Q8_WIRE"] = prev
        out["fused_tx_s"] = fused_rate
        if fused_rate is not None:
            out["preq_tx_s"] = _preq_hop_rate(qp, x, seconds)
    return out


def _preq_hop_rate(qp, x, seconds):
    """int8-at-the-edge wire variant: host normalize+rowquant, int8 rows
    over the wire (34 B/row vs 120 f32), kernel starts at the first MXU
    matmul. Same numpy-in/probas-out surface as _scorer_hop_rate so the
    three quant numbers rank comparably; None on any kernel failure."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ccfd_tpu.ops import fused_mlp_q8 as fq

    try:
        folded = fq.fold_for_kernel(qp)
        kp = jax.device_put(folded)
        # host copies of the SAME folded normalizer the kernel uses
        # (raw sigma; zero-sigma sanitization lives in set_normalizer)
        host_norm = {k: np.asarray(folded[k]) for k in ("mu", "sigma")}
        x = np.asarray(x, np.float32)
        # shared tiling policy — an off-tile CCFD_BENCH_BATCH must not
        # read as a kernel failure
        tile = fq.fit_tile(x.shape[0])

        def hop(xb):
            q, s = fq.prequantize_rows_numpy(host_norm, xb)
            return np.asarray(
                fq.fused_mlp_q8_score_preq(
                    kp, jnp.asarray(q), jnp.asarray(s), tile=tile
                )
            )

        hop(x)  # compile + lowering check
    except Exception as e:  # noqa: BLE001 - record WHY, don't crash the
        # capture: a lowering failure and a config artifact must be
        # distinguishable in the artifact
        return f"error: {type(e).__name__}: {e}"[:200]
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        hop(x)
        n += x.shape[0]
    return round(n / (time.perf_counter() - t0), 1)


def _bench_fused_decision(params, X, seconds, batch):
    """Staged vs fused decision on the SAME rows and the SAME Scorer.

    Staged = the pre-PR-19 serving shape: score_pipelined to host probas,
    then ``RuleSet.evaluate`` walks the rule base in numpy between score
    and route. Fused = ops/fused_decision.py: score + FRAUD_THRESHOLD
    compare + first-match rule argmax inside ONE executable, one packed
    (B, 2) transfer back. ``host_syncs_per_batch`` comes from the
    structural counters on each path (scorer.host_syncs / fds.host_syncs)
    so the "the transfer is the only sync left" claim is a recorded
    number; ``parity_bit_exact`` is measured on this box, not assumed.

    The two paths run in ALTERNATING short windows and the row records
    per-path MEDIANS: the deltas under test (host rule walk vs in-
    executable eval) are a few percent of the forward, and a sequential
    A-then-B layout folds machine drift into the ratio.

    Two shapes, because the win lives in different places:
    - ``latency``: the serving micro-batch (one bucket). What fusion
      displaces here is the per-decision FIXED cost — the extra host
      materialization plus the Python/numpy rule walk between score and
      route — which is why this is the headline ``speedup``.
    - ``throughput``: a multi-chunk batch where the fused rule work
      rides inside the depth-2 pending window. On CPU device == host so
      this is near parity by construction; on TPU the removed sync is
      the point, and the row records it either way."""
    import statistics

    import numpy as np

    from ccfd_tpu.config import Config
    from ccfd_tpu.router.rules import Condition, Rule, RuleSet
    from ccfd_tpu.serving.fused import FusedDecisionScorer

    b = int(min(batch, 65536))
    x = np.asarray(X[:b], np.float32)
    # top bucket BELOW b: the A/B wants the multi-chunk serving shape
    top = max(s for s in _hop_buckets(max(b // 4, 16)))
    scorer = _section_scorer("mlp", params, top)
    # a serving-shaped rule base (threshold route + amount band + feature
    # guards), not the 2-rule default: the staged cost being displaced is
    # the per-batch host walk over exactly this kind of table
    thr = Config().fraud_threshold
    rules = RuleSet([
        Rule("fraud_hi", process="fraud", salience=20,
             when=(Condition("proba", ">=", thr),
                   Condition("Amount", ">", 0.0))),
        Rule("fraud", process="fraud", salience=15,
             when=(Condition("proba", ">=", thr),)),
        Rule("review_band", process="standard", salience=10,
             when=(Condition("proba", "between", [thr / 2, thr]),)),
        Rule("v1_guard", process="standard", salience=5,
             when=(Condition("V1", ">", 0.0),
                   Condition("V2", "<=", 0.0))),
        Rule("standard", process="standard"),
    ])
    fds = FusedDecisionScorer(scorer, rules)
    if not fds.enabled:
        return {"error": "fused decision plane declined to arm"}
    fds.warmup()

    def staged_hop(xb):
        proba = scorer.score_pipelined(xb)
        rules.evaluate(xb, proba)

    calls = {"staged": 0, "fused": 0}

    def ab(rows, staged, fused, rounds=4):
        """Alternating windows, per-path median rows/s."""
        def window(label, hop):
            n = 0
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < seconds / (2 * rounds):
                hop(rows)
                n += rows.shape[0]
                calls[label] += 1
            return n / (time.perf_counter() - t0)

        staged(rows)
        fused(rows)
        rates: dict[str, list[float]] = {"staged": [], "fused": []}
        for _ in range(rounds):
            rates["staged"].append(window("staged", staged))
            rates["fused"].append(window("fused", fused))
        return (statistics.median(rates["staged"]),
                statistics.median(rates["fused"]))

    # latency shape: one serving micro-batch through the SAME seam the
    # router runs — np.asarray(score(x)) then the host rule walk
    def staged_lat(xb):
        rules.evaluate(xb, np.asarray(scorer.score(xb)))

    lat_b = 128
    s_lat, f_lat = ab(x[:lat_b], staged_lat, fds.decide)

    calls["staged"] = calls["fused"] = 0  # syncs/batch counts thr only
    s0_staged, s0_fused = scorer.host_syncs, fds.host_syncs
    s_thr, f_thr = ab(x, staged_hop, fds.decide)
    staged_syncs = round((scorer.host_syncs - s0_staged)
                         / max(calls["staged"], 1), 2)
    fused_syncs = round((fds.host_syncs - s0_fused)
                        / max(calls["fused"], 1), 2)

    p_s = scorer.score(x)
    p_f, f_f = fds.decide(x)
    parity = bool(
        f_f is not None
        and np.array_equal(p_f, p_s)
        and np.array_equal(f_f, rules.evaluate(x, p_s))
    )
    grid = fds.executable_grid()
    return {
        "batch": b,
        "latency_batch": lat_b,
        "rules": len(rules.rules),
        "staged_decide_us": round(lat_b / s_lat * 1e6, 1),
        "fused_decide_us": round(lat_b / f_lat * 1e6, 1),
        "speedup": round(f_lat / max(s_lat, 1e-9), 3),
        "staged_tx_s": round(s_thr, 1),
        "fused_tx_s": round(f_thr, 1),
        "throughput_speedup": round(f_thr / max(s_thr, 1e-9), 3),
        "staged_host_syncs_per_batch": staged_syncs,
        "fused_host_syncs_per_batch": fused_syncs,
        "parity_bit_exact": parity,
        "staged_fallbacks": grid["staged_fallbacks"],
        "forward": grid["forward"],
    }


def _arm_watchdog() -> None:
    """The tunnel can wedge MID-bench (after a successful probe), leaving a
    device wait blocked forever inside XLA — unkillable from Python. If the
    bench doesn't finish inside CCFD_BENCH_MAX_S, print the newest cached
    TPU result (clearly labeled) and hard-exit so the round still records
    an artifact instead of a stall."""
    import threading

    explicit = os.environ.get("CCFD_BENCH_MAX_S", "")
    if explicit:
        budget = float(explicit)
    else:
        # scale with the knobs that stretch a healthy run: the worst-case
        # probe window (all attempts + backoffs) plus every timed section
        # (~8 windows of `seconds` each: scorer + latency, 2x A/B, REST
        # incl. its seconds+120 client join, pipeline, mesh, retrain) plus
        # warmup/compile slack — a long configured run must not be killed
        # and mislabeled as a wedged accelerator
        attempts = int(os.environ.get("CCFD_BENCH_PROBE_ATTEMPTS", "5"))
        probe_s = float(os.environ.get("CCFD_BENCH_PROBE_S", "90"))
        backoff_s = float(os.environ.get("CCFD_BENCH_PROBE_BACKOFF_S", "45"))
        seconds = float(os.environ.get("CCFD_BENCH_SECONDS", "3"))
        probe_window = attempts * probe_s + max(0, attempts - 1) * backoff_s
        budget = probe_window + 10 * max(seconds, 3.0) + 120 + 600

    def fire() -> None:
        # os._exit(3) must run NO MATTER WHAT: an exception here (e.g. the
        # snapshot racing a concurrent _PARTIAL.update) would disarm the
        # watchdog and leave the wedged bench hanging forever
        try:
            snap = dict(_PARTIAL)
            if snap:
                label = ("partial (bench watchdog: accelerator wedged "
                         f"mid-run after {budget:.0f}s; sections below "
                         "completed before the wedge)")
            else:
                label = ("none (bench watchdog: accelerator wedged before "
                         f"any section completed, after {budget:.0f}s)")
            out = {
                "metric": "end_to_end_scoring_throughput_mlp_bf16",
                "value": float(snap.get("value", 0.0)),
                "unit": "tx/s",
                "vs_baseline": round(
                    float(snap.get("value", 0.0)) / NORTH_STAR_TX_S, 3
                ),
                "platform": label,
            }
            out.update({k: v for k, v in snap.items() if k != "value"})
            try:
                with open(LAST_GOOD_PATH) as f:
                    out["last_good_tpu"] = json.load(f)
            except (OSError, ValueError):
                pass
            print(json.dumps(out), flush=True)
        finally:
            os._exit(3)

    t = threading.Timer(budget, fire)
    t.daemon = True
    t.start()


def _bench_seq_pipeline(seconds):
    """The seq/history PRODUCT path end-to-end (VERDICT r4 item 6):
    producer -> bus -> router -> HistoryStore assembly -> (L, B)-bucketed
    overlapped seq dispatch — not the raw model rate (that is the ``seq``
    section).

    Round 11 reworked the path (ROADMAP item 5) and this section with it:
    traffic models the production mix the ISSUE names — most rows are
    mostly-cold (anonymous REST-style scoring, filled << L) with a warm
    repeating-customer core riding the stream — so the L-bucket ladder,
    the anonymous lock-free fast path and the async double-buffering all
    carry load. Alongside the headline tx/s it records: the
    assembly-vs-dispatch split on a warm full-L bucket (the BENCH_r05
    1412-vs-13 ms number, through the striped store), overlap efficiency
    (sync wall / overlapped wall on the same mixed batch, same
    executables), per-L-bucket row occupancy, the measured rate of the
    OLD path (full-L, synchronous) on the same box and mix — the honest
    speedup denominator — and the quantized ``seq_q8`` variant's row.
    The scorer builds on the shared ``_hop_buckets`` B ladder, so CPU
    and TPU captures stay comparable with the rest/zoo/quant sections."""
    import threading

    import jax
    import numpy as np

    from ccfd_tpu.bus.broker import Broker
    from ccfd_tpu.config import Config
    from ccfd_tpu.data.ccfd import synthetic_dataset
    from ccfd_tpu.metrics.prom import Registry
    from ccfd_tpu.models import seq as seq_mod
    from ccfd_tpu.process.fraud import build_engine
    from ccfd_tpu.router.router import Router
    from ccfd_tpu.serving.history import SeqScorer

    cfg = Config()
    broker = Broker()
    reg = Registry()
    engine = build_engine(cfg, broker, reg, None)
    L = 32
    bucket = 4096
    # L=1 serves the pure-cold (anonymous) row alone — its whole context;
    # 8 catches short histories; full L the warm core
    len_buckets = (1, 8)
    hot_customers = 2048
    cold_fraction = 0.7  # anonymous one-shot rows (the mostly-cold mix)
    params = seq_mod.init(jax.random.PRNGKey(0))
    scorer = SeqScorer(params, length=L, batch_sizes=_hop_buckets(bucket),
                       max_customers=8192, len_buckets=len_buckets,
                       inflight=2, registry=reg)
    scorer.warmup()
    # the SeqScorer OBJECT is the score_fn: the router detects
    # score_with_ids and feeds decoded records so histories key by
    # customer id (serving/history.py router contract)
    router = Router(cfg, broker, scorer, engine, reg, max_batch=bucket)

    ds = synthetic_dataset(n=8192, fraud_rate=0.01, seed=1)
    recs = [
        ",".join(f"{v:.6g}" for v in ds.X[i]).encode()
        for i in range(len(ds.X))
    ]
    rng = np.random.default_rng(0)
    cold_mask = rng.random(len(recs)) < cold_fraction
    # CSV records key histories by the bus key; a None key decodes to an
    # anonymous row (scored cold, never stored)
    keys = [None if cold_mask[i] else i % hot_customers
            for i in range(len(recs))]

    stop = threading.Event()

    def feed():
        i = 0
        while not stop.is_set():
            backlog = sum(broker.end_offsets(cfg.kafka_topic))
            if backlog - router._c_in.value() > 50_000:
                time.sleep(0.002)
                continue
            j = i % 4096
            broker.produce_batch(cfg.kafka_topic, recs[j:j + 2048],
                                 keys[j:j + 2048])
            i += 2048

    th_feed = threading.Thread(target=feed, daemon=True)
    th_feed.start()
    th = router.start(poll_timeout_s=0.01)
    budget = max(3.0, seconds)
    time.sleep(budget)
    tx = router._c_in.value()
    stop.set()
    router.stop()
    th.join(timeout=30)

    # per-L-bucket row occupancy, sampled NOW — the counters describe the
    # pipeline run's production-shaped mix; the measurement sections
    # below drive the same registry-wired scorer and would pollute them
    c_rows = reg.counter("seq_bucket_rows_total", "")
    l_bucket_rows = {
        str(lb): int(c_rows.value(labels={"l_bucket": str(lb)}))
        for lb in scorer.len_buckets
    }

    # assembly-vs-dispatch split on one warm full-L bucket through the
    # SAME (now warm) striped store: prepare() is the host-side history
    # gather, the jitted full-L apply is the device dispatch — the
    # BENCH_r05 comparison point (1412 ms dispatch / 13 ms assembly)
    ids_warm = [i % hot_customers for i in range(bucket)]
    x = np.ascontiguousarray(ds.X[:bucket], np.float32)
    assembly_s = _median_time(lambda: scorer.store.prepare(ids_warm, x))
    hist, _tok = scorer.store.prepare(ids_warm, x)
    jax.block_until_ready(scorer._apply(scorer.params, hist))  # compiled
    dispatch_s = _median_time(
        lambda: jax.block_until_ready(scorer._apply(scorer.params, hist))
    )

    # overlap efficiency on one representative MIXED batch: identical
    # executables and store, inflight toggled — sync wall / async wall
    ids_mix = [None if cold_mask[i] else i % hot_customers
               for i in range(bucket)]
    scorer.inflight = 0
    sync_s = _median_time(lambda: scorer.score(x, ids_mix))
    scorer.inflight = 2
    wall_s = _median_time(lambda: scorer.score(x, ids_mix))
    mixed_tx_s = bucket / wall_s

    # the OLD path on the same box, same mix: full-L only, synchronous —
    # the denominator that makes the rework's speedup a measured number
    full = SeqScorer(params, length=L, batch_sizes=_hop_buckets(bucket),
                     max_customers=8192, len_buckets=(), inflight=0)
    jax.block_until_ready(full._apply(full.params, hist))  # compile full L
    full.score(x, ids_mix)  # warm its store like the live one
    full_s = _median_time(lambda: full.score(x, ids_mix))

    # the r05-EQUIVALENT path: full `seq.apply` graph (no readout
    # optimization), bf16, synchronous, every row padded to full L — the
    # serving loop BENCH_r05 measured at 5,461 tx/s, reproduced on this
    # box and mix so the acceptance's >=4x is denominated honestly
    # (full_l_sync above isolates bucketing+overlap; this adds back the
    # graph-level readout win)
    import jax.numpy as jnp

    old = SeqScorer(params, length=L, batch_sizes=_hop_buckets(bucket),
                    max_customers=8192, len_buckets=(), inflight=0)
    old._apply = lambda p, xs: seq_mod.apply(p, xs, jnp.bfloat16)
    old.score(x, ids_mix)  # warm + compile the old executable set
    old_s = _median_time(lambda: old.score(x, ids_mix))

    # quantized variant (ops/seq_quant.py): same mixed batch through the
    # int8 graph — rate plus prob delta vs the champion on identical
    # cold contexts (its serving admission is the lifecycle shadow gate,
    # tests/test_seq_lifecycle.py; CPU captures carry accuracy, TPU speed)
    from ccfd_tpu.ops.seq_quant import quantize_seq

    q8 = SeqScorer(quantize_seq(params), length=L,
                   batch_sizes=_hop_buckets(bucket), max_customers=8192,
                   len_buckets=len_buckets, inflight=2)
    q8.score(x, ids_mix)  # warm + compile
    q8_s = _median_time(lambda: q8.score(x, ids_mix), k=3)
    p_champ = scorer.host_score(x[:1024])
    p_q8 = q8.host_score(x[:1024])
    return {
        "tx_s": round(tx / budget, 1),
        "seq_len": L,
        "bucket": bucket,
        "len_buckets": list(scorer.len_buckets),
        "cold_fraction": cold_fraction,
        "customers": len(scorer.store),
        "assembly_ms": round(assembly_s * 1e3, 3),
        "dispatch_ms": round(dispatch_s * 1e3, 3),
        "dispatch_over_assembly": (round(dispatch_s / assembly_s, 1)
                                   if assembly_s else None),
        # the overlapped-batch numbers the acceptance reads
        "wall_ms": round(wall_s * 1e3, 3),
        "sync_wall_ms": round(sync_s * 1e3, 3),
        "overlap_efficiency": round(sync_s / wall_s, 3) if wall_s else None,
        "assembly_fraction": (round(assembly_s / wall_s, 3)
                              if wall_s else None),
        "mixed_batch_tx_s": round(mixed_tx_s, 1),
        "full_l_sync_tx_s": round(bucket / full_s, 1),
        "speedup_vs_full_l": round(full_s / wall_s, 2) if wall_s else None,
        "r05_path_tx_s": round(bucket / old_s, 1),
        "speedup_vs_r05_path": (round(old_s / wall_s, 2)
                                if wall_s else None),
        "l_bucket_rows": l_bucket_rows,
        "quantized": {
            "tx_s": round(bucket / q8_s, 1),
            "max_prob_delta": round(
                float(np.abs(p_champ - p_q8).max()), 4),
        },
    }


def _bench_seq(seconds):
    """Long-context member of the model zoo: the per-customer history
    transformer (models/seq.py). Scores (B, L, 30) histories; when >1
    device is visible the histories shard over the mesh and BOTH
    sequence-parallel strategies run — ring attention (ppermute rotation,
    ops/ring_attention.py) and ulysses (all-to-all head/sequence reshard,
    ops/ulysses.py) — so their tradeoff is a recorded number."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ccfd_tpu.models import seq

    n_dev = len(jax.devices())
    B, L = 256, 64
    params = seq.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(B, L, 30)), jnp.float32)

    def measure(attn, budget_s):
        @jax.jit
        def step(p, xx):
            return jax.nn.sigmoid(
                seq.logits(p, xx, jnp.bfloat16, attention_fn=attn)
            )

        out = step(params, x)
        jax.block_until_ready(out)
        n = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < budget_s:
            out = step(params, x)
            n += B
        jax.block_until_ready(out)
        return round(n / (time.perf_counter() - t0), 1)

    result = {"batch": B, "seq_len": L, "devices": n_dev}
    strategies: list = [("single_device", None)]
    if n_dev > 1 and n_dev % 2 == 0:
        from ccfd_tpu.ops.ring_attention import ring_attention
        from ccfd_tpu.ops.ulysses import ulysses_attention
        from ccfd_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(model_parallel=2)
        strategies = [
            ("ring", lambda q, k, v: ring_attention(q, k, v, mesh, "model")),
            ("ulysses",
             lambda q, k, v: ulysses_attention(q, k, v, mesh, "model")),
        ]
    budget = max(0.5, seconds / len(strategies))
    for name, attn in strategies:
        result[f"histories_s_{name}"] = measure(attn, budget)
    # headline number: the best strategy measured
    result["histories_s"] = max(
        v for k, v in result.items() if k.startswith("histories_s_")
    )
    return result


def _bench_replay(seconds):
    """Bulk replay & backtest plane (ROADMAP round 17): re-score a
    recorded window through the LIVE bus -> router -> scorer path at
    ``bulk`` priority while live traffic keeps flowing, with the
    burn-rate engine armed. The row is the sustained re-score rate over
    repeated window passes — never a single warmup-shaped pass — next to
    the live lane's fast-window breach count, which must stay zero (the
    overload plane's bulk ceiling is the mechanism under test) and the
    parity tally (every pass must re-produce the recorded verdicts
    byte-stable; a bench that scores fast but diverges measures a bug)."""
    import tempfile
    import threading

    import jax
    import numpy as np

    from ccfd_tpu.bus.broker import Broker
    from ccfd_tpu.config import Config
    from ccfd_tpu.data.ccfd import synthetic_dataset
    from ccfd_tpu.metrics.prom import Registry
    from ccfd_tpu.observability.audit import AuditLog
    from ccfd_tpu.observability.slo import SLOEngine
    from ccfd_tpu.parallel.partition import params_fingerprint
    from ccfd_tpu.process.fraud import build_engine
    from ccfd_tpu.replay.service import ReplayService, ReplayVerdictTap
    from ccfd_tpu.router.router import Router
    from ccfd_tpu.runtime.overload import OverloadControl
    from ccfd_tpu.serving.scorer import Scorer

    state = tempfile.mkdtemp(prefix="ccfd_bench_replay_")
    # short burn windows so the fast-window verdict lands inside the
    # bench budget; targets carry the replay_smoke CI-box margin — the
    # row gates on "zero breaches WHILE replay saturates bulk", not on
    # this box hitting the production latency objective
    cfg = Config(confidence_threshold=1.0, slo_windows="2,4,12",
                 slo_e2e_target_ms=250.0, slo_rest_target_ms=250.0)
    regs = {n: Registry() for n in ("router", "kie", "slo", "replay")}
    slo_engine = SLOEngine.from_config(cfg, regs, regs["slo"])

    broker = Broker(default_partitions=2)
    kie = build_engine(cfg, broker, regs["kie"], None)
    scorer = Scorer(model_name="mlp", batch_sizes=(128, 1024, 4096),
                    host_tier_rows=0)
    scorer.warmup()
    fp = params_fingerprint(jax.tree.map(np.asarray, scorer.params))
    overload = OverloadControl.from_config(cfg, regs["router"],
                                           max_batch=1024, workers=1)
    audit = AuditLog(dir=os.path.join(state, "audit"),
                     registry=regs["router"])
    audit.lineage_fn = lambda: ("bench", fp)
    tap = ReplayVerdictTap(inner=audit, registry=regs["replay"])
    router = Router(cfg, broker, scorer.score, kie, regs["router"],
                    max_batch=1024, overload=overload, audit=tap)
    svc = ReplayService(cfg, broker, audit, tap=tap,
                        registry=regs["replay"],
                        state_dir=os.path.join(state, "replay"),
                        overload=overload,
                        lineage_fn=lambda: ("bench", fp))

    # record the window through the live stack (capture armed by svc)
    n_rows = 2048
    ds = synthetic_dataset(n=n_rows, fraud_rate=0.01, seed=17)
    rows = [",".join(f"{v:.6g}" for v in ds.X[i]).encode()
            for i in range(n_rows)]
    broker.produce_batch(cfg.kafka_topic, rows,
                         [f"tx-{i:05d}" for i in range(n_rows)])
    while router.step() > 0:
        pass
    audit.flush()
    recs = audit.scan_window()
    if len(recs) != n_rows:
        return {"error": f"recorded {len(recs)}/{n_rows} rows"}
    since, until = int(recs[0]["seq"]), int(recs[-1]["seq"])

    # live lane keeps flowing for the whole re-drive; burn engine ticks
    stop = threading.Event()
    live_rows = [0]

    def drive():
        i, next_tick = 0, 0.0
        while not stop.is_set():
            broker.produce_batch(cfg.kafka_topic, rows[:16],
                                 [f"live-{i}-{j}" for j in range(16)])
            live_rows[0] += 16
            i += 1
            router.step()
            now = time.monotonic()
            if now >= next_tick:
                slo_engine.tick()
                next_tick = now + 0.3
            time.sleep(0.005)

    driver = threading.Thread(target=drive, daemon=True,
                              name="bench-replay-drive")
    driver.start()

    budget = max(2.0, seconds)
    replayed = match = divergence = passes = 0
    parity = True
    t0 = time.perf_counter()
    while passes == 0 or time.perf_counter() - t0 < budget:
        rep = svc.run_window(since, until,
                             window_id=f"bench-{passes}", resume=False)
        passes += 1
        replayed += rep["replayed"]
        match += rep["match"]
        divergence += rep["divergence"]
        parity = parity and rep["parity"]
    elapsed = time.perf_counter() - t0
    # cross the fast burn window before reading the breach verdict
    time.sleep(max(1.0, 1.5 * slo_engine.windows[0][0]))
    status = slo_engine.tick()
    stop.set()
    driver.join(timeout=10)
    svc.stop()
    router.close()
    broker.close()

    breaches = sum(int(s.get("breaches", 0))
                   for s in status["slos"].values())
    return {
        "tx_s": round(replayed / elapsed, 1),
        "window_rows": n_rows,
        "passes": passes,
        "replayed": replayed,
        "match": match,
        "divergence": divergence,
        "parity": parity,
        "bulk_ceiling": cfg.replay_bulk_ceiling,
        "bulk_ceiling_restored": overload.bulk_ceiling == 1.0,
        "live_rows": live_rows[0],
        "live_fast_breaches": breaches,
        "live_slo_green": not any(
            s.get("breaching") or s.get("breaches")
            for s in status["slos"].values()),
    }


def main() -> None:
    _arm_watchdog()
    platform_forced = os.environ.get("CCFD_BENCH_PLATFORM", "")
    fellback = False
    if os.environ.get("CCFD_BENCH_SKIP_PROBE") == "1" and not platform_forced:
        # caller (the watcher, right after a successful flash capture)
        # already KNOWS the attachment is healthy; the probe subprocess
        # would spend one of the window's scarce attachments for nothing.
        # A wedge mid-run is still bounded by the bench watchdog.
        pass
    elif not platform_forced:
        ok = _probe_backend(
            float(os.environ.get("CCFD_BENCH_PROBE_S", "90")),
            int(os.environ.get("CCFD_BENCH_PROBE_ATTEMPTS", "5")),
            float(os.environ.get("CCFD_BENCH_PROBE_BACKOFF_S", "45")),
        )
        if not ok:
            fellback = True
            platform_forced = "cpu"
    if platform_forced:
        os.environ["JAX_PLATFORMS"] = platform_forced
        import jax

        jax.config.update("jax_platforms", platform_forced)
    import jax
    import numpy as np

    from ccfd_tpu.utils.compile_cache import enable as _enable_compile_cache

    _enable_compile_cache()  # repeat bench runs skip tunnel-side compiles

    from ccfd_tpu.data.ccfd import synthetic_dataset
    from ccfd_tpu.models import mlp
    from ccfd_tpu.serving.scorer import Scorer

    batch = int(os.environ.get("CCFD_BENCH_BATCH", "131072"))
    seconds = float(os.environ.get("CCFD_BENCH_SECONDS", "3"))
    depth = int(os.environ.get("CCFD_BENCH_PIPELINE", "2"))
    lat_batch = int(os.environ.get("CCFD_BENCH_LATENCY_BATCH", "4096"))
    skip = set(os.environ.get("CCFD_BENCH_SKIP", "").split(","))
    on_tpu = jax.default_backend() == "tpu"
    # device telemetry (observability/device.py): every scorer below
    # stages through the process-default plane; sections get h2d/peak-
    # memory rows on device (CCFD_BENCH_DEVICE=1 forces rows on cpu)
    meter = _DeviceMeter(
        attach_rows=on_tpu or os.environ.get("CCFD_BENCH_DEVICE") == "1")

    ds = synthetic_dataset(n=max(batch, lat_batch, 4096), fraud_rate=0.01, seed=0)
    params = mlp.init(jax.random.PRNGKey(0))
    params = mlp.set_normalizer(params, ds.X.mean(0), ds.X.std(0))
    # push probabilities to a trained-model-like range so the pipeline
    # section's fired mix is realistic (~1% fraud), not the untrained ~50%
    import jax.numpy as jnp

    pipe_params = dict(params)
    pipe_params["layers"] = [dict(l) for l in params["layers"]]
    pipe_params["layers"][-1] = dict(pipe_params["layers"][-1])
    pipe_params["layers"][-1]["b"] = jnp.asarray([-4.0], jnp.float32)

    scorer = Scorer(
        model_name="mlp",
        params=params,
        batch_sizes=(16, 128, 1024, lat_batch, batch),
        compute_dtype="bfloat16",
    )
    scorer.warmup()
    # services tune gc AFTER warmup (cli.py) so compiled executables/params
    # land in the frozen permanent generation; the bench mirrors that
    from ccfd_tpu.utils.gctune import tune_for_service

    tune_for_service()
    tx_per_s, p50, p99 = _bench_scorer(scorer, ds.X, batch, lat_batch, seconds, depth)
    _PARTIAL.update({
        "value": round(tx_per_s, 1), "p50_ms": round(p50, 3),
        "p99_ms": round(p99, 3), "fused_active": scorer.fused,
        "platform_measured": jax.default_backend(),
    })
    meter.section(None)  # reset the per-section H2D baseline past warmup

    fused_ab = None
    if "ab" not in skip and (on_tpu or os.environ.get("CCFD_BENCH_AB")):
        # A/B the two scorer paths on identical work so the Pallas kernel's
        # effect is a recorded number, not a docstring claim
        ab = {}
        for label, use_fused in (("fused", True), ("xla", False)):
            s = Scorer(
                model_name="mlp", params=params,
                batch_sizes=(16, 128, 1024, lat_batch, batch),
                compute_dtype="bfloat16", use_fused=use_fused,
            )
            if use_fused and not s.fused:
                ab[label] = None
                continue
            s.warmup()
            if use_fused and not s.fused:
                ab[label] = None  # lowering failed; warmup fell back
                continue
            r_tx, r_p50, r_p99 = _bench_scorer(
                s, ds.X, batch, lat_batch, max(1.0, seconds / 2), depth
            )
            ab[label] = {"tx_s": round(r_tx, 1), "p50_ms": round(r_p50, 3),
                         "p99_ms": round(r_p99, 3)}
        fused_ab = ab
        _PARTIAL["fused_ab"] = fused_ab
        meter.section(None)

    rest = None
    rest_python = None
    if "rest" not in skip:
        # one read for BOTH transports: drifting defaults between the two
        # call sites would shape the native-vs-python A/B differently
        rest_clients = int(os.environ.get("CCFD_BENCH_REST_CLIENTS", "4"))
        rest_rows = int(os.environ.get("CCFD_BENCH_REST_ROWS", "128"))
        rest = _bench_rest(
            params, lat_batch, max(2.0, seconds), rest_clients, rest_rows,
        )
        _PARTIAL["rest"] = rest
        meter.section(rest)
        if rest.get("transport") == "NativeFront":
            # transport A/B: the same load through the Python server, so
            # the native front's effect is a recorded number
            rest_python = _bench_rest(
                params, lat_batch, max(2.0, seconds / 2),
                rest_clients, rest_rows, native=False,
            )
            _PARTIAL["rest_python_transport"] = rest_python
        # request-latency FLOOR: one client, one row per request — the
        # online-decision RTT a single transaction pays with zero queueing,
        # the other end of the SLO from the throughput-shaped point above
        floor = _bench_rest(params, lat_batch, max(2.0, seconds / 2),
                            n_clients=1, rows_per_req=1)
        if "error" not in floor:
            _PARTIAL["rest_latency_floor"] = {
                k: floor[k] for k in ("p50_ms", "p99_ms", "requests_s",
                                      "transport", "errors",
                                      "host_tier_rows")
                if k in floor
            }

    pipeline = None
    if "pipeline" not in skip:
        # fresh H2D baseline: the transport-A/B and latency-floor REST
        # benches above are unmetered and must not bill this section
        meter.section(None)
        pipeline = _bench_pipeline(pipe_params, max(2.0, seconds))
        _PARTIAL["pipeline"] = pipeline
        meter.section(pipeline)

    mesh_res = None
    if "mesh" not in skip:
        mesh_res = _bench_mesh(
            params, min(batch, 65536), max(1.0, seconds / 2), depth
        )
        if mesh_res is not None:
            _PARTIAL["mesh"] = mesh_res
            meter.section(mesh_res)

    retrain_res = None
    if "retrain" not in skip:
        retrain_res = _bench_retrain(max(1.0, seconds / 2))
        _PARTIAL["retrain"] = retrain_res
        meter.section(retrain_res)

    seq_res = None
    if "seq" not in skip:
        seq_res = _bench_seq(max(1.0, seconds / 2))
        _PARTIAL["seq"] = seq_res
        meter.section(seq_res)

    if "seq_pipeline" not in skip:
        _PARTIAL["seq_pipeline"] = _bench_seq_pipeline(max(3.0, seconds))
        meter.section(_PARTIAL["seq_pipeline"])

    if "replay" not in skip:
        meter.section(None)  # replay builds its own full stack: fresh H2D
        try:
            _PARTIAL["replay"] = _bench_replay(max(2.0, seconds / 2))
        except Exception as e:  # noqa: BLE001 - a red replay row must not
            _PARTIAL["replay"] = {"error": repr(e)[:200]}  # kill the bench
        meter.section(_PARTIAL["replay"])

    zoo_res = None
    if "zoo" not in skip:
        zoo_res = _bench_zoo(max(1.0, seconds / 3))
        _PARTIAL["zoo"] = zoo_res

    quant_res = None
    if "quant" not in skip and (on_tpu or os.environ.get("CCFD_BENCH_QUANT")):
        meter.section(None)  # zoo traffic is unmetered: reset the baseline
        quant_res = _bench_quant(params, ds.X[:batch], max(1.0, seconds / 2))
        _PARTIAL["quant_int8"] = quant_res
        meter.section(quant_res)

    if "fused_decision" not in skip:
        meter.section(None)  # fresh H2D baseline for the A/B
        try:
            _PARTIAL["fused_decision"] = _bench_fused_decision(
                params, ds.X, max(1.0, seconds / 2), batch,
            )
        except Exception as e:  # noqa: BLE001 - a red fused row must not
            _PARTIAL["fused_decision"] = {"error": repr(e)[:200]}  # kill it
        meter.section(_PARTIAL["fused_decision"])

    if "roofline" not in skip:
        try:
            _PARTIAL["roofline"] = _bench_roofline(
                scorer, params, ds.X, lat_batch, tx_per_s, rest, quant_res,
            )
        except Exception as e:  # noqa: BLE001 - accounting must not cost
            _PARTIAL["roofline"] = {"error": repr(e)[:200]}  # the bench run

    # the e2e p99 the north star talks about is the REST predict hop when
    # measured; the raw scorer-hop p99 otherwise (also when the REST
    # section errored — its numbers are then absent, not zero)
    p99_e2e = rest["p99_ms"] if rest and "p99_ms" in rest else p99
    result = {
        "metric": "end_to_end_scoring_throughput_mlp_bf16",
        "value": round(tx_per_s, 1),
        "unit": "tx/s",
        "vs_baseline": round(tx_per_s / NORTH_STAR_TX_S, 3),
        "p50_ms": round(p50, 3),
        "p99_ms": round(p99, 3),
        "p99_e2e_ms": round(p99_e2e, 3),
        "p99_vs_target": round(NORTH_STAR_P99_MS / max(p99_e2e, 1e-9), 3),
        "latency_batch": lat_batch,
        "fused_active": scorer.fused,
        # on probe fallback the platform string cites a LIVE triage run
        # first (tools/tpu_triage.py invoked now — the probe just failed,
        # so the verdict must describe today's wedge); only when the live
        # run itself fails does a FRESH (<24 h) cached artifact speak,
        # and the generic label is the last resort
        "platform": jax.default_backend()
        + ((" (fallback: " + (_fresh_triage() or _triage_verdict()
                              or "accelerator probe failed") + ")")
           if fellback else ""),
    }
    # section results flow through _PARTIAL (written as each completes for
    # the watchdog); the final result picks them up from ONE place instead
    # of re-enumerating every section
    headline_only = {"value", "p50_ms", "p99_ms", "fused_active",
                     "platform_measured"}
    result.update(
        {k: v for k, v in _PARTIAL.items() if k not in headline_only}
    )

    if on_tpu:
        # cache this as the round's last-good TPU number: later fallback
        # runs (wedged tunnel) attach it instead of losing the TPU evidence
        try:
            with open(LAST_GOOD_PATH, "w") as f:
                json.dump({"captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                           "result": result}, f)
        except OSError:
            pass
    elif fellback and os.path.exists(LAST_GOOD_PATH):
        try:
            with open(LAST_GOOD_PATH) as f:
                result["last_good_tpu"] = json.load(f)
        except (OSError, ValueError):
            pass

    print(json.dumps(result))
    # LAST line: a compact summary that survives the driver's capture
    # window.  BENCH_r03/r04.json both recorded "parsed": null because the
    # full record above is one multi-KB line and the driver keeps only the
    # final ~2000 chars — the tail held a fragment.  This line is the same
    # headline plus per-section extracts, bounded well under that window,
    # so the round's official artifact always ends with one complete JSON
    # object (VERDICT r4 item 3).
    print(json.dumps(compact_summary(result)), flush=True)


def compact_summary(result: dict) -> dict:
    """Headline + per-section extracts, guaranteed small (≤ ~1.2 KB).

    Keeps the keys the watcher and the driver contract read (metric /
    value / unit / vs_baseline / platform) and one-level numeric extracts
    of each measured section; drops free-form sub-trees (latency grids,
    per-client detail, attached last-good history) whose size is
    unbounded."""
    s = {k: result.get(k) for k in (
        "metric", "value", "unit", "vs_baseline", "p50_ms", "p99_ms",
        "p99_e2e_ms", "p99_vs_target", "fused_active", "platform",
    ) if k in result}
    s["summary"] = True  # full record precedes this line

    def pick(section: str, *keys: str) -> None:
        sec = result.get(section)
        if not isinstance(sec, dict):
            return
        if "error" in sec:
            s[section] = {"error": str(sec["error"])[:120]}
            return
        s[section] = {k: sec[k] for k in keys if k in sec}

    pick("rest", "tx_s", "requests_s", "p50_ms", "p99_ms", "transport",
         "rows_per_request", "host_tier_rows", "errors")
    pick("pipeline", "tx_s", "paced_rate_tx_s", "p50_ms", "p99_ms",
         "workers", "workers_cpus", "shadow")
    pick("mesh", "tx_s", "single_tx_s", "devices", "scaling_x",
         "efficiency", "virtual_devices", "sharding_overhead_x")
    pick("retrain", "steps_s", "labels_s", "final_loss")
    pick("seq", "histories_s", "batch", "seq_len")
    pick("seq_pipeline", "tx_s", "assembly_ms", "dispatch_ms",
         "assembly_fraction", "wall_ms", "overlap_efficiency",
         "speedup_vs_full_l", "full_l_sync_tx_s", "r05_path_tx_s",
         "speedup_vs_r05_path", "cold_fraction")
    pick("quant_int8", "tx_s", "fused_tx_s", "preq_tx_s", "batch")
    pick("fused_decision", "speedup", "throughput_speedup",
         "staged_decide_us", "fused_decide_us", "staged_tx_s",
         "fused_tx_s", "parity_bit_exact", "staged_fallbacks",
         "staged_host_syncs_per_batch", "fused_host_syncs_per_batch")
    pick("replay", "tx_s", "passes", "parity", "divergence",
         "live_fast_breaches", "live_slo_green", "bulk_ceiling")
    pick("roofline", "wire_mb_s", "h2d_mb_s_measured", "mfu_pct", "bound")
    zoo = result.get("zoo")
    if isinstance(zoo, dict):
        s["zoo"] = {
            name: fam.get("tx_s") for name, fam in zoo.items()
            if isinstance(fam, dict)
        }
    lg = result.get("last_good_tpu")
    if isinstance(lg, dict):
        s["last_good_tpu_at"] = lg.get("captured_at")
    return s


if __name__ == "__main__":
    main()
