"""Benchmark: end-to-end transaction-scoring throughput on the TPU scorer.

Measures the prediction hop the framework replaces (reference Seldon CPU
model, SURVEY.md §3 stack A): host-side feature matrix -> bucketed jit
dispatch (ccfd_tpu/serving/scorer.py) -> probabilities back on host. That
is the full serving round-trip the router pays per micro-batch — H2D copy,
XLA executable, D2H copy — not a device-only FLOP timing.

Prints ONE JSON line:
  {"metric": ..., "value": tx/s, "unit": "tx/s", "vs_baseline": ratio}

``vs_baseline`` is the ratio against the 50,000 tx/s north-star target
(BASELINE.json: the reference publishes no numbers of its own — the
driver-set target is the baseline to beat; >1.0 means the target is beaten).

Env knobs: CCFD_BENCH_BATCH (default 131072), CCFD_BENCH_SECONDS (default 3),
CCFD_BENCH_PIPELINE (in-flight dispatch depth, default 2),
CCFD_BENCH_PLATFORM=cpu to force CPU (local testing without the TPU tunnel).
"""

from __future__ import annotations

import json
import os
import time

NORTH_STAR_TX_S = 50_000.0  # BASELINE.json north_star: >=50k tx/s on v5e-1


def main() -> None:
    if os.environ.get("CCFD_BENCH_PLATFORM"):
        import jax

        jax.config.update("jax_platforms", os.environ["CCFD_BENCH_PLATFORM"])
    import jax
    import numpy as np

    from ccfd_tpu.data.ccfd import synthetic_dataset
    from ccfd_tpu.models import mlp
    from ccfd_tpu.serving.scorer import Scorer

    batch = int(os.environ.get("CCFD_BENCH_BATCH", "131072"))
    seconds = float(os.environ.get("CCFD_BENCH_SECONDS", "3"))
    depth = int(os.environ.get("CCFD_BENCH_PIPELINE", "2"))

    ds = synthetic_dataset(n=max(batch, 4096), fraud_rate=0.01, seed=0)
    params = mlp.init(jax.random.PRNGKey(0))
    params = mlp.set_normalizer(params, ds.X.mean(0), ds.X.std(0))
    scorer = Scorer(
        model_name="mlp",
        params=params,
        batch_sizes=(16, 128, 1024, 4096, batch),
        compute_dtype="bfloat16",
    )
    scorer.warmup()

    x = ds.X[:batch]
    # timed region: full host->device->host scoring round trips (the fused
    # Pallas kernel + bf16 wire + pipelined dispatch when depth > 1)
    n_rows = 0
    t0 = time.perf_counter()
    while True:
        proba = scorer.score_pipelined(x, depth=depth)
        n_rows += x.shape[0]
        elapsed = time.perf_counter() - t0
        if elapsed >= seconds:
            break
    assert proba.shape == (batch,)
    tx_per_s = n_rows / elapsed

    print(
        json.dumps(
            {
                "metric": "end_to_end_scoring_throughput_mlp_bf16",
                "value": round(tx_per_s, 1),
                "unit": "tx/s",
                "vs_baseline": round(tx_per_s / NORTH_STAR_TX_S, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
