"""Benchmark: end-to-end transaction-scoring throughput + latency on TPU.

Measures the prediction hop the framework replaces (reference Seldon CPU
model, SURVEY.md §3 stack A): host-side feature matrix -> bucketed jit
dispatch (ccfd_tpu/serving/scorer.py) -> probabilities back on host. That
is the full serving round-trip the router pays per micro-batch — H2D copy,
XLA executable, D2H copy — not a device-only FLOP timing.

Prints ONE JSON line:
  {"metric": ..., "value": tx/s, "unit": "tx/s", "vs_baseline": ratio,
   "p99_ms": ..., "p50_ms": ..., "platform": ...}

``vs_baseline`` is the ratio against the 50,000 tx/s north-star target
(BASELINE.json: the reference publishes no numbers of its own — the
driver-set target is the baseline to beat; >1.0 means the target is
beaten). ``p99_ms`` covers the second north-star target (p99 end-to-end
predict < 10 ms): per-dispatch latency of a router-sized micro-batch.

Robustness: the accelerator backend is probed in a SUBPROCESS with a
timeout first — a wedged TPU tunnel would otherwise hang ``jax.devices()``
forever and take the whole bench (and the driver waiting on it) with it.
On probe failure the bench runs on CPU and says so in ``platform``.

Env knobs: CCFD_BENCH_BATCH (default 131072), CCFD_BENCH_SECONDS (default 3),
CCFD_BENCH_PIPELINE (in-flight dispatch depth, default 2),
CCFD_BENCH_LATENCY_BATCH (default 4096), CCFD_BENCH_PLATFORM=cpu to force
CPU, CCFD_BENCH_PROBE_S (backend probe timeout, default 90).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

NORTH_STAR_TX_S = 50_000.0  # BASELINE.json north_star: >=50k tx/s on v5e-1
NORTH_STAR_P99_MS = 10.0  # BASELINE.json north_star: p99 e2e predict <10ms


def _probe_backend(timeout_s: float) -> bool:
    """Can this environment initialize its default jax backend? Run the
    check in a child so a wedged TPU tunnel can't hang the bench itself."""
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return r.returncode == 0
    except (subprocess.SubprocessError, OSError):
        return False


def main() -> None:
    platform_forced = os.environ.get("CCFD_BENCH_PLATFORM", "")
    fellback = False
    if not platform_forced:
        probe_s = float(os.environ.get("CCFD_BENCH_PROBE_S", "90"))
        if not _probe_backend(probe_s):
            fellback = True
            platform_forced = "cpu"
    if platform_forced:
        os.environ["JAX_PLATFORMS"] = platform_forced
        import jax

        jax.config.update("jax_platforms", platform_forced)
    import jax
    import numpy as np

    from ccfd_tpu.data.ccfd import synthetic_dataset
    from ccfd_tpu.models import mlp
    from ccfd_tpu.serving.scorer import Scorer

    batch = int(os.environ.get("CCFD_BENCH_BATCH", "131072"))
    seconds = float(os.environ.get("CCFD_BENCH_SECONDS", "3"))
    depth = int(os.environ.get("CCFD_BENCH_PIPELINE", "2"))
    lat_batch = int(os.environ.get("CCFD_BENCH_LATENCY_BATCH", "4096"))

    ds = synthetic_dataset(n=max(batch, lat_batch, 4096), fraud_rate=0.01, seed=0)
    params = mlp.init(jax.random.PRNGKey(0))
    params = mlp.set_normalizer(params, ds.X.mean(0), ds.X.std(0))
    scorer = Scorer(
        model_name="mlp",
        params=params,
        batch_sizes=(16, 128, 1024, lat_batch, batch),
        compute_dtype="bfloat16",
    )
    scorer.warmup()

    x = ds.X[:batch]
    # timed region: full host->device->host scoring round trips (the fused
    # Pallas kernel + bf16 wire + pipelined dispatch when depth > 1)
    n_rows = 0
    t0 = time.perf_counter()
    while True:
        proba = scorer.score_pipelined(x, depth=depth)
        n_rows += x.shape[0]
        elapsed = time.perf_counter() - t0
        if elapsed >= seconds:
            break
    assert proba.shape == (batch,)
    tx_per_s = n_rows / elapsed

    # latency: synchronous single-dispatch round trips on a router-sized
    # micro-batch — the p99 the SeldonCore dashboard would record
    xl = ds.X[:lat_batch]
    lat = []
    t_end = time.perf_counter() + max(1.0, seconds / 2)
    while time.perf_counter() < t_end:
        t1 = time.perf_counter()
        scorer.score(xl)
        lat.append((time.perf_counter() - t1) * 1e3)
    lat_a = np.asarray(lat)
    p99 = float(np.percentile(lat_a, 99))

    print(
        json.dumps(
            {
                "metric": "end_to_end_scoring_throughput_mlp_bf16",
                "value": round(tx_per_s, 1),
                "unit": "tx/s",
                "vs_baseline": round(tx_per_s / NORTH_STAR_TX_S, 3),
                "p50_ms": round(float(np.percentile(lat_a, 50)), 3),
                "p99_ms": round(p99, 3),
                "p99_vs_target": round(NORTH_STAR_P99_MS / max(p99, 1e-9), 3),
                "latency_batch": lat_batch,
                "platform": jax.default_backend()
                + (" (fallback: accelerator probe failed)" if fellback else ""),
            }
        )
    )


if __name__ == "__main__":
    main()
