#!/bin/sh
# Keep exactly one tpu_watch alive: the watcher is the round's only path
# to an on-TPU capture, and an uncaught crash (or OOM kill on the 1-core
# host) would otherwise silently forfeit every future heal window.
# Usage: nohup sh tools/watch_nanny.sh > /dev/null 2>&1 &
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO" || exit 1
# the nanny has its own deadline (default 11h, NANNY_HOURS overrides):
# the watcher's --max-hours exit is intentional, and resurrecting it with
# a fresh budget forever would run the watch indefinitely past the round
END=$(( $(date +%s) + ${NANNY_HOURS:-11} * 3600 ))
while [ "$(date +%s)" -lt "$END" ]; do
    if ! pgrep -f "tpu_watch.py --fast" > /dev/null 2>&1; then
        echo "[$(date -u +%H:%M:%S)] nanny: watcher dead - restarting" \
            >> tpu_watch.log
        nohup python tools/tpu_watch.py --fast-interval 10 --max-hours 11 \
            > /dev/null 2>&1 &
    fi
    sleep 60
done
echo "[$(date -u +%H:%M:%S)] nanny: deadline reached, exiting" >> tpu_watch.log
