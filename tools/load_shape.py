"""Traffic-shape SLO harness: drive the pipeline through load regimes and
assert the overload plane holds the line (ROADMAP item 3's missing piece).

Three regimes, each against a LIVE in-process pipeline (producer-shaped
feeder -> bus -> partition-parallel router pool with the overload plane
armed -> engine), with traffic stamped across the three priority classes
(bulk / normal / critical via record headers, runtime/overload.py):

- ``diurnal``  — a sinusoidal ramp around the base rate (the daily shape
  a fraud stack actually sees); nothing should shed, p99 stays flat.
- ``flash``    — a 5x step flash crowd, with a latency fault injected on
  the scorer edge during the crowd (runtime/faults.py) so the stage
  genuinely saturates: the AIMD limit must collapse toward its floor,
  shedding must take bulk traffic first and critical never, admitted
  traffic must stay inside the SLO, and the limit must recover after.
- ``hotkey``   — partition-skewed traffic (most records on one hot key,
  so one worker's partitions carry the load) proving the GLOBAL budget
  keeps a skewed worker from blowing the p99 for everyone.

Round 12 adds the OBJECTIVE side (observability/profile.py + slo.py): the
pipeline runs with the stage profiler and a burn-rate SLO engine armed —
the flash crowd must burn the e2e SLO's fast windows with the stage
profile showing the damage concentrated in the QUEUEING layer
(backpressure parks the crowd in the bus; ``slo.stage_shares`` in the
artifact), while the diurnal ramp must stay green (0 breaches).

Exit 0 only when EVERY regime holds its invariants:

1. admitted-traffic decision p99 (produce -> process start,
   ``router_decision_seconds``) within ``--slo-ms``;
2. zero accounting violations: every consumed record is routed, shed, or
   a counted start error — nothing lost, nothing double-counted, and the
   shared in-flight budget drains to exactly zero;
3. zero priority inversions: the ``ccfd_priority_inversions_total``
   tripwire stays 0 AND no sampling window served bulk work while
   shedding critical work; under the flash crowd, critical is never shed
   at all while bulk absorbs the loss.

    JAX_PLATFORMS=cpu python tools/load_shape.py                 # all regimes
    JAX_PLATFORMS=cpu python tools/load_shape.py --regime flash --short

Prints one JSON line (record it like the soak artifacts).
``tools/verify_tier1.sh --overload-smoke`` runs the short flash regime as
an exit-code-gated CI smoke.
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # hermetic: never dial a tunnel

import numpy as np  # noqa: E402

from ccfd_tpu.bus.broker import Broker  # noqa: E402
from ccfd_tpu.config import Config  # noqa: E402
from ccfd_tpu.data.ccfd import synthetic_dataset  # noqa: E402
from ccfd_tpu.metrics.prom import Registry  # noqa: E402
from ccfd_tpu.observability.profile import StageProfiler  # noqa: E402
from ccfd_tpu.observability.slo import SLOEngine, SLOSpec  # noqa: E402
from ccfd_tpu.process.fraud import build_engine  # noqa: E402
from ccfd_tpu.router.parallel import ParallelRouter  # noqa: E402
from ccfd_tpu.runtime.faults import FaultPlan, FaultSpec  # noqa: E402
from ccfd_tpu.runtime.overload import (  # noqa: E402
    PRIORITY_NAMES,
    AdaptiveInflightBudget,
    DeadlinePolicy,
    OverloadControl,
)
from ccfd_tpu.serving.scorer import Scorer  # noqa: E402

# traffic mix: the priority classes every regime stamps onto its chunks
# (bulk = re-score backfill, critical = fraud-suspect / canary-eval lane)
MIX = (("bulk", 0.2), ("normal", 0.7), ("critical", 0.1))


class Pipeline:
    """One live pipeline with the overload plane armed, plus the knobs the
    regimes drive (fault plan on the scorer edge, priority-aware feeder)."""

    def __init__(self, workers: int = 2, partitions: int = 4,
                 limit_floor: int = 2048, codel_target_ms: float = 100.0,
                 burn_target_ms: float = 150.0):
        self.cfg = Config()
        self.broker = Broker(default_partitions=partitions)
        self.reg = Registry()
        # stage profiler + burn-rate SLO over the same live run
        # (observability/profile.py + slo.py): the regimes assert the
        # OBJECTIVE side of what the overload mechanisms defend — the
        # flash crowd must burn the e2e budget with the damage
        # concentrated in the QUEUEING layer (backpressure parks the
        # crowd in the bus), diurnal must stay green. Fast windows are
        # CI-scale (2 s confirms 4 s); the burn target is a production-
        # shaped decision bound, not the regime's hard --slo-ms ceiling.
        self.profiler = StageProfiler()
        self.slo = SLOEngine(
            [SLOSpec("e2e-p99", metric="router_decision_seconds",
                     target_ms=burn_target_ms, objective=0.99)],
            {"router": self.reg}, registry=self.reg,
            windows=((2.0, 14.4), (4.0, 14.4), (12.0, 1.0)),
        )
        self.engine = build_engine(self.cfg, self.broker, self.reg, None)
        scorer = Scorer(model_name="mlp", batch_sizes=(128, 1024, 4096, 8192))
        scorer.warmup()
        # scorer-edge latency fault, storm-toggled by the flash regime so
        # the stage saturates on cue (the same injection surface the
        # breaker/ladder drills use)
        self.fault_plan = FaultPlan(
            {"scorer": FaultSpec(latency_ms=200.0)}, active=False)
        score_fn = self.fault_plan.injector("scorer", self.reg).wrap_fn(
            scorer.score)
        self.budget = AdaptiveInflightBudget(
            8192, min_limit=limit_floor, max_limit=16384,
            target_s=0.025, step=512, good_window=4,
            decrease_cooldown_s=0.2, registry=self.reg,
        )
        self.overload = OverloadControl(
            self.reg, self.budget,
            codel=DeadlinePolicy(codel_target_ms / 1e3),
        )
        self.router = ParallelRouter(
            self.cfg, self.broker, score_fn, self.engine, self.reg,
            workers=workers, max_batch=4096, coalesce_max_batch=8192,
            overload=self.overload, profiler=self.profiler,
        )
        ds = synthetic_dataset(n=8192, fraud_rate=0.01, seed=7)
        self._rows = [
            ",".join(f"{v:.6g}" for v in ds.X[i]).encode()
            for i in range(len(ds.X))
        ]
        self.produced = 0
        self._limit_min = self._limit_max = self.budget.limit
        self._thread = None

    def start(self) -> None:
        self._thread = self.router.start(poll_timeout_s=0.02)

    # -- feeder -----------------------------------------------------------
    def produce_tick(self, n_rows: int, hot_key: int | None = None) -> None:
        """Produce one tick's rows split across the priority mix — one
        chunk per class, because produce_batch stamps ONE headers dict
        per chunk (exactly how a real producer stamps its lanes)."""
        base = self.produced
        for name, frac in MIX:
            n = max(1, int(n_rows * frac))
            idx = [(base + i) % len(self._rows) for i in range(n)]
            keys = ([hot_key] * n if hot_key is not None
                    else [(base + i) % 997 for i in range(n)])
            self.broker.produce_batch(
                self.cfg.kafka_topic,
                [self._rows[i] for i in idx], keys,
                headers={"priority": name},
            )
            base += n
        self.produced = base

    def track_limit(self) -> None:
        lim = self.budget.limit
        self._limit_min = min(self._limit_min, lim)
        self._limit_max = max(self._limit_max, lim)

    # -- counters ---------------------------------------------------------
    def counts(self) -> dict:
        c = self.reg.counter
        shed_by = {
            f"{name}:{stage}": int(c("ccfd_shed_total").value(
                labels={"priority": name, "stage": stage}))
            for name in PRIORITY_NAMES.values()
            for stage in ("deadline", "budget")
        }
        admit_by = {
            name: int(c("ccfd_admission_total").value(
                labels={"stage": "bus", "priority": name,
                        "decision": "admit"}))
            for name in PRIORITY_NAMES.values()
        }
        return {
            "incoming": int(c("transaction_incoming_total").value()),
            "outgoing": int(c("transaction_outgoing_total").total()),
            "shed": int(c("router_shed_total").value()),
            "start_errors": int(
                c("router_process_start_errors_total").total()),
            "score_err": int(c("router_score_errors_total").value()),
            "inversions": int(
                c("ccfd_priority_inversions_total").value()),
            "shed_by_priority_stage": shed_by,
            "admitted_by_priority": admit_by,
        }

    def drain_and_stop(self, timeout_s: float = 30.0) -> bool:
        c_in = self.reg.counter("transaction_incoming_total")
        deadline = time.monotonic() + timeout_s
        drained = False
        while time.monotonic() < deadline:
            if c_in.value() >= self.produced:
                drained = True
                break
            time.sleep(0.1)
        self.router.stop()
        if self._thread is not None:
            self._thread.join(timeout=30)
        self.router.close()
        return drained

    def stage_shares(self) -> dict[str, float]:
        """Where the run's decision latency went, from the stage profiler:
        each component's share of the summed wall time across queueing
        (bus wait), decode, device dispatch and route/engine. The flash
        regime's claim — backpressure parks the crowd in the BUS — reads
        directly off the queue share."""
        comps = {
            "queue": ("bus", "queue"),
            "decode": ("router.decode", "service"),
            "dispatch": ("router.score", "dispatch"),
            "route": ("router.route", "service"),
        }
        sums: dict[str, float] = {}
        for name, (stage, comp) in comps.items():
            d = self.profiler.digest(stage, comp)
            sums[name] = d.sum if d is not None else 0.0
        total = sum(sums.values())
        if total <= 0:
            return {k: 0.0 for k in sums}
        return {k: round(v / total, 4) for k, v in sums.items()}

    def verdict(self, slo_ms: float, p99_robust: bool = False) -> dict:
        """Shared invariant checks every regime asserts after its drain.

        ``p99_robust`` is the in-suite (pytest) form of the admitted-p99
        claim: under full-suite host contention the raw tail flips past
        the SLO with no admission failure behind it (the PR 11 queueing-
        layer lesson — a strict threshold on a noise-coupled statistic
        flips on a busy CI box). The robust form demands the BODY of the
        distribution corroborate a tail breach before calling it a
        violation: a genuine admission failure (nothing shed, the crowd
        admitted after waiting out the backlog) inflates p50 toward the
        crowd duration right along with p99, while scheduler noise
        stretches only the tail. A tail-only breach is recorded as
        ``p99_soft_breach`` instead of a violation. The CLI regimes keep
        the strict claim — they run in isolation."""
        self.slo.tick()
        cts = self.counts()
        dec = self.reg.histogram("router_decision_seconds")
        p50 = dec.quantile(0.5) * 1e3
        p99 = dec.quantile(0.99) * 1e3
        violations = []
        p99_soft_breach = False
        # accounting conservation: consumed == routed + shed + counted
        # errors (the degrade ladder absorbs scorer faults, so scoring
        # errors only drop rows when the ladder is off — it is on here)
        routed_or_lost = (cts["outgoing"] + cts["shed"]
                          + cts["start_errors"] + cts["score_err"])
        if cts["incoming"] != routed_or_lost:
            violations.append(
                f"accounting: incoming {cts['incoming']} != outgoing "
                f"{cts['outgoing']} + shed {cts['shed']} + start_err "
                f"{cts['start_errors']} + score_err {cts['score_err']}")
        if self.budget.inflight != 0:
            violations.append(
                f"budget leak: {self.budget.inflight} rows still reserved "
                "after drain")
        if cts["inversions"] != 0:
            violations.append(
                f"priority inversions: {cts['inversions']}")
        if not math.isnan(p99) and p99 > slo_ms:
            if (p99_robust and not math.isnan(p50)
                    and p50 <= 0.5 * slo_ms):
                p99_soft_breach = True
            else:
                violations.append(
                    f"admitted p99 {p99:.1f} ms > SLO {slo_ms:.0f} ms")
        return {
            "p50_ms": round(p50, 2) if not math.isnan(p50) else None,
            "p99_ms": round(p99, 2) if not math.isnan(p99) else None,
            "p99_soft_breach": p99_soft_breach,
            "slo_ms": slo_ms,
            "counts": cts,
            "limit_min": self._limit_min,
            "limit_max": self._limit_max,
            "limit_end": self.budget.limit,
            "slo": {
                "breaches": self.slo.breaches("e2e-p99"),
                "target_ms": self.slo.specs[0].target_ms,
                "stage_shares": self.stage_shares(),
            },
            "violations": violations,
        }


def _run_windows(pipe: Pipeline, seconds: float, rate_fn,
                 hot_key_fn=None, on_window=None) -> list[dict]:
    """Drive the feeder at rate_fn(t) rows/s on a 20 ms tick, sampling
    per-window shed/admit deltas every 0.5 s for the inversion evidence."""
    tick = 0.02
    windows: list[dict] = []
    prev = pipe.counts()
    next_window = time.monotonic() + 0.5
    t0 = time.monotonic()
    next_emit = t0
    while True:
        t = time.monotonic() - t0
        if t >= seconds:
            break
        rate = rate_fn(t)
        n = max(0, int(rate * tick))
        if n:
            pipe.produce_tick(
                n, hot_key=hot_key_fn(t) if hot_key_fn else None)
        pipe.track_limit()
        if on_window is not None:
            on_window(t)
        now = time.monotonic()
        if now >= next_window:
            pipe.slo.tick()  # burn-rate evaluation rides the window clock
            cur = pipe.counts()
            win = {
                "t_s": round(t, 1),
                "shed": {k: cur["shed_by_priority_stage"].get(k, 0)
                         - prev["shed_by_priority_stage"].get(k, 0)
                         for k in set(cur["shed_by_priority_stage"])
                         | set(prev["shed_by_priority_stage"])},
                "admit": {k: cur["admitted_by_priority"][k]
                          - prev["admitted_by_priority"][k]
                          for k in cur["admitted_by_priority"]},
            }
            windows.append(win)
            prev = cur
            next_window = now + 0.5
        next_emit += tick
        sleep = next_emit - time.monotonic()
        if sleep > 0:
            time.sleep(sleep)
    return windows


def _window_inversions(windows: list[dict]) -> int:
    """Windows where a HIGHER class was budget-shed while a LOWER class
    was admitted — the window-granular form of the per-batch tripwire.

    Judged on BUDGET sheds only: a deadline (CoDel) shed is a fate, not a
    choice — the row went stale waiting (critical rows get 4x the grace),
    and serving it anyway would burn device time on work that already
    blew its SLO while live work queued behind it."""
    order = ["bulk", "normal", "critical"]
    bad = 0
    for w in windows:
        for hi in (2, 1):
            hi_shed = w["shed"].get(f"{order[hi]}:budget", 0)
            lo_admit = sum(w["admit"].get(order[lo], 0)
                           for lo in range(hi))
            if hi_shed > 0 and lo_admit > 0:
                bad += 1
                break
    return bad


# -- regimes ---------------------------------------------------------------
def run_flash(seconds: float, slo_ms: float, base_rate: float,
              p99_robust: bool = False) -> dict:
    """5x step flash crowd + injected scorer latency step: the saturation
    regime where priority shedding, AIMD collapse/recovery and the SLO
    bound all have to show up at once. ``p99_robust`` relaxes ONLY the
    admitted-p99 tail claim to its body-corroborated form (see
    ``Pipeline.verdict``) for in-suite runs under host contention."""
    pipe = Pipeline()
    pipe.start()
    warm = seconds * 0.25
    crowd = seconds * 0.5
    crowd_end = warm + crowd

    def rate(t: float) -> float:
        return base_rate * (5.0 if warm <= t < crowd_end else 1.0)

    def storm(t: float) -> None:
        if warm <= t < crowd_end:
            if not pipe.fault_plan.active:
                pipe.fault_plan.activate()
        elif pipe.fault_plan.active:
            pipe.fault_plan.deactivate()

    windows = _run_windows(pipe, seconds, rate, on_window=storm)
    pipe.fault_plan.deactivate()
    drained = pipe.drain_and_stop()
    out = pipe.verdict(slo_ms, p99_robust=p99_robust)
    out["regime"] = "flash"
    out["base_rate"] = base_rate
    out["drained"] = drained
    out["window_inversions"] = _window_inversions(windows)
    total_shed = out["counts"]["shed"]
    if not drained:
        out["violations"].append("backlog failed to drain after the crowd")
    if total_shed == 0:
        out["violations"].append(
            "flash crowd produced zero sheds — the regime did not "
            "saturate the stage; nothing was exercised")
    # budget-stage sheds are CHOICES and must never pick critical while
    # cheaper work exists (the per-batch tripwire is the strict form)
    crit_budget = out["counts"]["shed_by_priority_stage"].get(
        "critical:budget", 0)
    if crit_budget != 0:
        out["violations"].append(
            f"{crit_budget} critical rows budget-shed while bulk/normal "
            "traffic existed to shed first")
    # deadline sheds are fates, but the priority-scaled cutoffs must
    # still order them: the loss RATE per class has to fall strictly as
    # priority rises (bulk absorbs the crowd, critical barely feels it)
    frac = {}
    for name in ("bulk", "normal", "critical"):
        shed_c = sum(v for k, v in
                     out["counts"]["shed_by_priority_stage"].items()
                     if k.startswith(name + ":"))
        admitted = out["counts"]["admitted_by_priority"][name]
        frac[name] = shed_c / max(1, shed_c + admitted)
    out["shed_fraction_by_priority"] = {
        k: round(v, 3) for k, v in frac.items()}
    if not (frac["bulk"] >= frac["normal"] >= frac["critical"]):
        out["violations"].append(
            f"shed fractions not priority-ordered: {frac}")
    if frac["critical"] >= frac["bulk"] or frac["critical"] > 0.5:
        out["violations"].append(
            f"critical lost {frac['critical']:.0%} of its rows — the "
            "priority scheme failed to protect the lane it exists for")
    if out["window_inversions"] != 0:
        out["violations"].append(
            f"{out['window_inversions']} windows served low-priority "
            "work while shedding higher-priority work")
    if out["limit_min"] >= 8192:
        out["violations"].append(
            "AIMD limit never decreased under the injected latency step")
    if out["limit_end"] <= out["limit_min"]:
        out["violations"].append(
            "AIMD limit did not recover after the crowd")
    # the SLO layer's flash claims (ISSUE 9): the crowd must burn the e2e
    # fast windows, and the stage profile must show the damage living in
    # the QUEUEING layer — backpressure parked the crowd in the bus, it
    # didn't inflate service time
    if out["slo"]["breaches"] == 0:
        out["violations"].append(
            "flash crowd never burned the e2e SLO's fast windows — the "
            "burn-rate layer saw no saturation")
    shares = out["slo"]["stage_shares"]
    if sum(shares.values()) <= 0:
        # an all-zero share map means the profiler never sampled — the
        # claim below would pass vacuously on a broken feed
        out["violations"].append(
            "stage profiler recorded no samples — the queueing-layer "
            "claim has no evidence")
    elif (shares["queue"] < 0.30
          or shares["queue"] < 2.0 * (shares["decode"] + shares["route"])):
        # the claim is "backpressure parked the crowd in the BUS and the
        # service layers didn't inflate" — NOT "bus wait outweighs device
        # compute": on CPU CI the dispatch share tracks host scheduling
        # load (a strict arg-max over all four shares flips on a busy
        # machine with no backpressure failure behind it). A real failure
        # still trips this form: crowd not parked -> the queue share
        # collapses toward zero; service-time inflation -> decode/route
        # swallow the budget (and the p99 check catches the rest)
        out["violations"].append(
            f"flash budget burn not concentrated in the queueing layer: "
            f"{shares}")
    return out


def run_diurnal(seconds: float, slo_ms: float, base_rate: float) -> dict:
    """Sinusoidal daily ramp: the no-drama regime — the plane must stay
    out of the way (no sheds, flat p99) while the rate doubles and halves."""
    pipe = Pipeline()
    pipe.start()

    def rate(t: float) -> float:
        return base_rate * (1.0 + 0.6 * math.sin(2 * math.pi * t / seconds))

    windows = _run_windows(pipe, seconds, rate)
    drained = pipe.drain_and_stop()
    out = pipe.verdict(slo_ms)
    out["regime"] = "diurnal"
    out["base_rate"] = base_rate
    out["drained"] = drained
    out["window_inversions"] = _window_inversions(windows)
    if not drained:
        out["violations"].append("diurnal backlog failed to drain")
    if out["counts"]["shed"] > 0:
        out["violations"].append(
            f"diurnal ramp shed {out['counts']['shed']} rows — the plane "
            "interfered with a load it should absorb")
    if out["slo"]["breaches"] > 0:
        out["violations"].append(
            f"diurnal ramp burned the e2e SLO ({out['slo']['breaches']} "
            "breaches) — the daily shape must stay green")
    return out


def run_hotkey(seconds: float, slo_ms: float, base_rate: float) -> dict:
    """Partition-skewed hot key: ~85% of traffic rides one key (one
    partition, one worker). The shared global budget and the coalesced
    dispatch must keep the skewed worker from blowing the pool's p99."""
    pipe = Pipeline()
    pipe.start()

    def hot(t: float):
        # 85% of ticks pin the hot key; the rest spread
        return 0 if (int(t / 0.02) % 20) < 17 else None

    windows = _run_windows(pipe, seconds, lambda t: base_rate * 2,
                           hot_key_fn=hot)
    drained = pipe.drain_and_stop()
    out = pipe.verdict(slo_ms)
    out["regime"] = "hotkey"
    out["base_rate"] = base_rate * 2
    out["drained"] = drained
    out["window_inversions"] = _window_inversions(windows)
    c = pipe.reg.counter("router_worker_batches_total")
    out["worker_batches"] = {
        str(i): int(c.value(labels={"worker": str(i)}))
        for i in range(pipe.router.n_workers)
    }
    if not drained:
        out["violations"].append("hot-key backlog failed to drain")
    if out["window_inversions"] != 0:
        out["violations"].append("hot-key regime served low-priority work "
                                 "while shedding higher-priority work")
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--regime", default="all",
                    choices=("all", "flash", "diurnal", "hotkey"))
    ap.add_argument("--seconds", type=float, default=30.0,
                    help="duration per regime")
    ap.add_argument("--short", action="store_true",
                    help="CI smoke: ~8 s flash-crowd-scale regimes")
    ap.add_argument("--slo-ms", type=float, default=1200.0,
                    help="admitted-traffic decision p99 SLO. Default is "
                    "derived from the harness's own overload config: the "
                    "worst admitted bus age (4x the 100 ms CoDel target) "
                    "+ the injected 200 ms crowd dispatch latency + "
                    "routing/engine time + CI-box margin")
    ap.add_argument("--base-rate", type=float, default=4000.0,
                    help="base traffic rate, rows/s")
    args = ap.parse_args()
    seconds = 8.0 if args.short else args.seconds

    regimes = {
        "flash": run_flash, "diurnal": run_diurnal, "hotkey": run_hotkey,
    }
    names = list(regimes) if args.regime == "all" else [args.regime]
    results = {}
    ok = True
    for name in names:
        res = regimes[name](seconds, args.slo_ms, args.base_rate)
        results[name] = res
        ok = ok and not res["violations"]
        print(f"[load_shape] {name}: p99={res['p99_ms']} ms "
              f"shed={res['counts']['shed']} "
              f"violations={len(res['violations'])}", file=sys.stderr)
    print(json.dumps({
        "harness": "load_shape",
        "seconds_per_regime": seconds,
        "slo_ms": args.slo_ms,
        "ok": ok,
        "regimes": results,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
