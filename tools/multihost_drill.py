"""Real multi-process distributed run: 2 processes x 4 virtual CPU devices.

Until round 4, ``parallel/multihost.py`` had only ever executed in the
degenerate global==local case (tests/test_multihost.py is single-process
by design).  This drill runs the ACTUAL process-boundary paths —
``jax.distributed.initialize`` over a real coordinator socket,
``make_global_mesh`` spanning two processes (data axis across the process
boundary, model axis inside each process's device domain), a pjit-sharded
train step whose gradient all-reduce crosses processes, and a sharded
serving forward fed by ``process_local_batch_to_global`` with EACH process
contributing different local rows — on CPU, the same way the test suite
virtualizes multi-chip (8 devices here = 2 hosts x 4).

Checks that make it a proof rather than a smoke:
  - every process sees process_count==2, 8 global / 4 local devices
  - train losses are finite AND bit-identical across processes for every
    step (the psum really ran globally: each process feeds different data,
    so agreement is impossible without the cross-process collective)
  - the sharded serving score's global mean agrees across processes
  - a per-process input fingerprint proves the two processes fed
    DIFFERENT local batches
  - ring attention with the sequence sharded over the PROCESS-SPANNING
    data axis (ppermute edges crossing the DCN analog every rotation)
    matches dense attention computed in the same jit to <1e-4 — the
    long-context parallelism that legitimately rides DCN, exercised
    across a real process boundary (tensor-parallel stays in-process by
    design, asserted)

Artifact: MULTIHOST_r04.json.  Run:  python tools/multihost_drill.py

Reference contrast: the reference scales out with k8s replicas over
Kafka + REST (SURVEY.md §2 'distributed communication backend'); this is
the single-logical-program equivalent that a multi-host TPU slice runs.
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N_PROCESSES = 2
LOCAL_DEVICES = 4
MODEL_PARALLEL = 2
LOCAL_ROWS = 64
STEPS = 3

_CHILD = r"""
import json, os, sys, time
import jax

# the site hook forces an accelerator platform; this drill is hermetic CPU
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["CCFD_REPO"])
t0 = time.time()

import numpy as np
from ccfd_tpu.parallel import multihost
from ccfd_tpu.parallel.train import TrainConfig, init_state, make_train_step
from ccfd_tpu.parallel.sharding import batch_spec, label_spec
from ccfd_tpu.models import mlp

assert multihost.initialize() is True, "distributed init did not engage"
pid = jax.process_index()
assert jax.process_count() == int(os.environ["NUM_PROCESSES"])
assert jax.local_device_count() == int(os.environ["CCFD_LOCAL_DEVICES"])

mesh = multihost.make_global_mesh(
    model_parallel=int(os.environ["CCFD_MODEL_PARALLEL"])
)
# data axis must span processes: first and last row of the device grid
# live on different processes
procs_on_data_axis = {d.process_index for d in mesh.devices[:, 0]}
assert len(procs_on_data_axis) == jax.process_count(), (
    "data axis does not span processes"
)
# model axis must stay inside one process (tensor-parallel never over DCN)
for row in mesh.devices:
    assert len({d.process_index for d in row}) == 1, "model axis spans DCN"

local_rows = int(os.environ["CCFD_LOCAL_ROWS"])
rng = np.random.default_rng(1000 + pid)  # DIFFERENT data per process
x_local = rng.normal(size=(local_rows, 30)).astype(np.float32)
y_local = (rng.random(local_rows) < 0.5).astype(np.float32)
fingerprint = float(np.abs(x_local).sum())

x = multihost.process_local_batch_to_global(mesh, x_local)
import jax.numpy as jnp
y = jax.make_array_from_process_local_data(label_spec(mesh), y_local)
assert x.shape[0] == local_rows * jax.process_count()

params = mlp.init(jax.random.PRNGKey(0))
tc = TrainConfig()
state = init_state(params, tc)
step = make_train_step(tc, mesh)
losses = []
for _ in range(int(os.environ["CCFD_STEPS"])):
    state, loss = step(state, x, y)
    losses.append(float(loss))  # replicated scalar: gatherable everywhere

# sharded serving forward; global mean inside jit -> replicated scalar
# (no host gather needed), comparable bit-for-bit across processes
score_mean = float(jax.jit(
    lambda p, xx: mlp.apply(p, xx).mean(),
    in_shardings=(None, batch_spec(mesh)),
)(state["params"], x))

# --- sequence parallelism ACROSS the process boundary -----------------
# Ring attention's ppermute hops neighbor-to-neighbor around the data
# axis, which spans both processes here: two of the ring edges cross the
# process boundary (the DCN analog) every rotation. Tensor-parallel
# stays in-process by design (asserted above); long-context SP is the
# parallelism that legitimately rides DCN, so it is the one exercised
# cross-process. Parity vs dense attention computed IN THE SAME jit on
# the same global arrays (GSPMD gathers for the dense side), so the
# check is compiled end-to-end with the real collectives.
from jax.sharding import NamedSharding, PartitionSpec as P
from ccfd_tpu.ops.ring_attention import reference_attention, ring_attention
from ccfd_tpu.parallel.mesh import DATA_AXIS

B, H, L, D = 4, 2, 64, 16
ring_n = mesh.devices.shape[0]
assert L % ring_n == 0
rng_seq = np.random.default_rng(2000)  # SAME inputs on every process
qkv_full = [rng_seq.normal(size=(B, H, L, D)).astype(np.float32)
            for _ in range(3)]
seq_sh = NamedSharding(mesh, P(None, None, DATA_AXIS, None))
local_slice = slice(
    pid * (L // jax.process_count()), (pid + 1) * (L // jax.process_count())
)
qs, ks, vs = (
    jax.make_array_from_process_local_data(seq_sh, a[:, :, local_slice, :])
    for a in qkv_full
)

@jax.jit
def ring_vs_dense(q, k, v):
    ring = ring_attention(q, k, v, mesh, DATA_AXIS)
    dense = reference_attention(q, k, v)
    return jnp.max(jnp.abs(ring.astype(jnp.float32) -
                           dense.astype(jnp.float32)))

ring_delta = float(ring_vs_dense(qs, ks, vs))

print(json.dumps({
    "process_id": pid,
    "process_count": jax.process_count(),
    "global_devices": jax.device_count(),
    "local_devices": jax.local_device_count(),
    "mesh_shape": list(mesh.devices.shape),
    "input_fingerprint": fingerprint,
    "losses": losses,
    "score_mean": score_mean,
    "global_batch": int(x.shape[0]),
    "ring_positions": ring_n,
    "ring_vs_dense_max_delta": ring_delta,
    "wall_s": round(time.time() - t0, 1),
}))
"""


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_topology(n_processes: int, local_devices: int, model_parallel: int,
                 timeout_s: float) -> dict:
    port = free_port()
    procs = []
    for pid in range(n_processes):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": (env.get("XLA_FLAGS", "").replace(
                "--xla_force_host_platform_device_count=8", "").strip()
                + f" --xla_force_host_platform_device_count={local_devices}"
            ).strip(),
            "COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "NUM_PROCESSES": str(n_processes),
            "PROCESS_ID": str(pid),
            "CCFD_REPO": REPO,
            "CCFD_LOCAL_DEVICES": str(local_devices),
            "CCFD_MODEL_PARALLEL": str(model_parallel),
            "CCFD_LOCAL_ROWS": str(LOCAL_ROWS),
            "CCFD_STEPS": str(STEPS),
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _CHILD],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env, cwd=REPO,
        ))
    reports = []
    errors = []
    deadline = time.monotonic() + timeout_s  # ONE budget for the topology,
    # not per child: the children run concurrently, and a hung coordinator
    # hangs all of them — serial full-timeout waits would multiply the stall
    for p in procs:
        try:
            out, err = p.communicate(
                timeout=max(1.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            errors.append("timeout")
            continue
        if p.returncode != 0:
            errors.append(err.strip()[-800:])
            continue
        reports.append(json.loads(out.strip().splitlines()[-1]))

    ok = len(reports) == n_processes and not errors
    checks: dict = {}
    if ok:
        # the invariant logic lives in fleet/protocol.py as a pure
        # function over the reports, so tier-1 tests exercise it without
        # jax.distributed (tests/test_fleet_protocol.py)
        from ccfd_tpu.fleet.protocol import check_multihost_reports

        checks = check_multihost_reports(
            reports, n_processes, local_devices, model_parallel,
            local_rows=LOCAL_ROWS)
        ok = all(checks.values())
    return {
        "ok": ok,
        "processes": n_processes,
        "local_devices": local_devices,
        "model_parallel": model_parallel,
        "checks": checks,
        "reports": reports,
        "errors": errors,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--topologies", default="2x4,4x2",
                    help="comma-separated PROCxDEV pairs; every topology "
                    "keeps 8 global devices so the same program shapes run")
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--out", default=os.path.join(REPO, "MULTIHOST_r04.json"))
    args = ap.parse_args()

    # parse and validate EVERY topology before running any: a malformed
    # later entry must not discard minutes of completed subprocess work,
    # and a single-process "topology" would pass every check while
    # proving nothing crosses a process boundary
    topologies = []
    for topo in args.topologies.split(","):
        try:
            n_proc, n_dev = (int(v) for v in topo.strip().split("x"))
        except ValueError:
            ap.error(f"malformed topology {topo!r} (want PROCxDEV)")
        if n_proc < 2:
            ap.error(f"topology {topo!r}: this drill exists to prove "
                     "cross-process behavior; need >= 2 processes")
        if (n_proc * n_dev) % (2 * MODEL_PARALLEL):
            ap.error(f"topology {topo!r}: global devices must divide the "
                     f"(data={2}, model={MODEL_PARALLEL}) mesh")
        topologies.append((n_proc, n_dev))

    runs = []
    for n_proc, n_dev in topologies:
        runs.append(run_topology(n_proc, n_dev, MODEL_PARALLEL,
                                 args.timeout))
        print(json.dumps({"topology": f"{n_proc}x{n_dev}",
                          "ok": runs[-1]["ok"],
                          "errors": runs[-1]["errors"]}), flush=True)
    ok = all(r["ok"] for r in runs)
    result = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "ok": ok,
        "runs": runs,
        # canonical-topology fields kept at top level for artifact readers
        **{k: runs[0][k] for k in ("processes", "local_devices",
                                   "model_parallel", "checks", "reports",
                                   "errors")},
    }
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({"ok": ok,
                      "topologies": [f"{r['processes']}x{r['local_devices']}"
                                     for r in runs]}))
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
