"""Trace report: drive the pipeline, print the per-stage critical path.

Answers "where did this transaction's 40 ms go?" with evidence: runs the
in-process pipeline (producer → bus → router → scorer → engine, plus the
notify leg) with tracing at a configurable sample rate, collects the
retained end-to-end traces from the tail-sampling sink, and prints a
p50/p99 critical-path decomposition per stage — queueing on the bus,
decode, scorer dispatch, rule-eval + engine starts — the per-stage
visibility InferLine-style pipeline SLOs need (arXiv:1812.01776; the
"300M predictions/sec" stack's latency budget discipline,
arXiv:2109.09541).

Also verifies the full observability loop the acceptance criteria ask for:
at least one retained trace spans producer→bus→router→scorer→engine with
monotone parent/child spans, an exported latency histogram carries a
trace-id exemplar (OpenMetrics scrape of the live exporter), and that
exemplar's trace id resolves over HTTP via the exporter's /traces/<id>.

    JAX_PLATFORMS=cpu python tools/trace_report.py --transactions 3000

Prints a human table on stderr and one JSON line on stdout; exit 0 only
when an end-to-end trace was retained, spans are monotone, and the
exemplar resolved.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # hermetic: never dial a tunnel

import numpy as np  # noqa: E402

from ccfd_tpu.bus.broker import Broker  # noqa: E402
from ccfd_tpu.config import Config  # noqa: E402
from ccfd_tpu.data.ccfd import synthetic_dataset  # noqa: E402
from ccfd_tpu.metrics.exporter import MetricsExporter  # noqa: E402
from ccfd_tpu.metrics.prom import Registry  # noqa: E402
from ccfd_tpu.models import mlp  # noqa: E402
from ccfd_tpu.notify.service import NotificationService  # noqa: E402
from ccfd_tpu.observability.trace import SpanSink, Tracer  # noqa: E402
from ccfd_tpu.process.fraud import build_engine  # noqa: E402
from ccfd_tpu.producer.producer import Producer  # noqa: E402
from ccfd_tpu.router.router import Router  # noqa: E402
from ccfd_tpu.serving.scorer import Scorer  # noqa: E402

# the pipeline stages, in causal order, with how each one's wall time is
# derived from the trace's spans
STAGE_SPANS = ("producer.batch", "router.decode", "router.score",
               "router.route")


def _quantile(values: list[float], q: float) -> float:
    if not values:
        return float("nan")
    return float(np.quantile(np.asarray(values), q))


def stage_breakdown(traces: list[list[dict]]) -> dict[str, dict]:
    """Per-stage wall-time samples across traces -> p50/p99 + share.

    ``bus.queue`` is derived: router.batch start minus producer.batch end —
    the time records waited on the topic before the router polled them
    (micro-batching deadline + backlog), which no single span times."""
    samples: dict[str, list[float]] = {name: [] for name in STAGE_SPANS}
    samples["bus.queue"] = []
    for spans in traces:
        by_name = {}
        for s in spans:
            by_name.setdefault(s["name"], s)
        for name in STAGE_SPANS:
            s = by_name.get(name)
            if s is not None:
                samples[name].append(s["duration_s"])
        prod, rb = by_name.get("producer.batch"), by_name.get("router.batch")
        if prod is not None and rb is not None:
            samples["bus.queue"].append(max(
                0.0, rb["start"] - (prod["start"] + prod["duration_s"])))
    total_p50 = sum(_quantile(v, 0.5) for v in samples.values() if v)
    out = {}
    for name, vals in samples.items():
        if not vals:
            continue
        p50 = _quantile(vals, 0.5)
        out[name] = {
            "n": len(vals),
            "p50_ms": round(p50 * 1e3, 3),
            "p99_ms": round(_quantile(vals, 0.99) * 1e3, 3),
            "critical_path_share": round(p50 / total_p50, 4) if total_p50 else 0.0,
        }
    return out


def monotone_ok(spans: list[dict]) -> bool:
    """Every child starts at/after its parent (small clock-read slack)."""
    by_id = {s["span_id"]: s for s in spans}
    for s in spans:
        parent = by_id.get(s["parent_id"]) if s["parent_id"] else None
        if parent is not None and s["start"] < parent["start"] - 1e-3:
            return False
    return True


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--transactions", type=int, default=3000)
    ap.add_argument("--sample", type=float, default=1.0,
                    help="tail-sampler keep rate for boring traces "
                    "(1.0: keep everything this run retains)")
    ap.add_argument("--batch", type=int, default=256,
                    help="producer batch size == trace granularity")
    ap.add_argument("--fraud-rate", type=float, default=0.02)
    ap.add_argument("--workers", type=int, default=2,
                    help="router workers (router/parallel.py): >1 verifies "
                    "the per-stage trace decomposition survives the "
                    "partition-parallel fan-out (worker-labelled "
                    "router.batch spans); 1 = single router")
    ap.add_argument("--json", dest="json_out", default="",
                    help="also write the report as a machine-readable "
                    "artifact (crash-safe tmp+rename) — the trace-derived "
                    "sibling of the StageProfile schema family "
                    "(observability/profile.py), for CI and the "
                    "provisioning planner; exit stays nonzero when no "
                    "end-to-end trace was retained")
    args = ap.parse_args()

    cfg = Config()
    broker = Broker()
    regs = {name: Registry() for name in
            ("producer", "router", "kie", "notify", "tracing")}
    # max_retained sized to the run: at sample=1.0 every trace is kept and
    # the report must not evict the end-to-end ones mid-run
    sink = SpanSink(sample=args.sample, registry=regs["tracing"],
                    max_retained=8192)

    def tracer(name: str) -> Tracer:
        return Tracer(regs[name], component=name, sink=sink)

    engine = build_engine(cfg, broker, regs["kie"], None)
    ds = synthetic_dataset(n=max(args.transactions, 1024),
                           fraud_rate=args.fraud_rate, seed=0)
    params = mlp.init(jax.random.PRNGKey(0))
    params = mlp.set_normalizer(params, ds.X.mean(0), ds.X.std(0))
    scorer = Scorer(model_name="mlp", params=params,
                    batch_sizes=(128, 1024, 4096))
    scorer.warmup()
    if args.workers > 1:
        from ccfd_tpu.router.parallel import ParallelRouter

        router = ParallelRouter(cfg, broker, scorer.score, engine,
                                regs["router"], workers=args.workers,
                                max_batch=args.batch,
                                tracer=tracer("router"))
    else:
        router = Router(cfg, broker, scorer.score, engine, regs["router"],
                        max_batch=args.batch, tracer=tracer("router"))
    notify = NotificationService(cfg, broker, regs["notify"],
                                 tracer=tracer("notify"))
    producer_tracer = tracer("producer")
    exporter = MetricsExporter(regs, sink=sink).start()

    # chunked produce/route ping-pong: every producer batch is one trace
    produced = 0
    while produced < args.transactions:
        n = min(args.batch, args.transactions - produced)
        lo = produced
        chunk = type(ds)(X=ds.X[lo:lo + n], y=ds.y[lo:lo + n])
        produced += Producer(cfg, broker, chunk,
                             registry=regs["producer"],
                             tracer=producer_tracer).run(limit=n)
        while router.step() > 0:
            pass
        notify.step(max_records=args.batch)

    sink.flush(0.0)
    summaries = sink.traces()
    full = [sink.trace(t["trace_id"]) for t in summaries]
    e2e = [spans for spans in full
           if spans is not None
           and {"producer.batch", "router.batch", "router.score",
                "router.route"} <= {s["name"] for s in spans}]
    breakdown = stage_breakdown(e2e)
    mono = all(monotone_ok(spans) for spans in e2e) and bool(e2e)
    # parallel-router attribution: every router.batch span carries its
    # worker id, and with workers>1 more than one worker must actually
    # have contributed spans (the fan-out genuinely split the stream)
    worker_ids = sorted({
        s["attrs"].get("worker")
        for spans in full if spans is not None
        for s in spans
        if s["name"] == "router.batch" and "worker" in s.get("attrs", {})
    })
    workers_ok = (args.workers <= 1) or len(worker_ids) > 1

    # -- exemplar loop: scrape OpenMetrics, resolve the trace over HTTP ----
    req = urllib.request.Request(
        exporter.endpoint + "/prometheus",
        headers={"Accept": "application/openmetrics-text"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        scrape = resp.read().decode()
    exemplar_ids = re.findall(r'# \{trace_id="([0-9a-f]{32})"\}', scrape)
    resolved = None
    for tid in exemplar_ids:
        try:
            with urllib.request.urlopen(
                f"{exporter.endpoint}/traces/{tid}", timeout=10
            ) as resp:
                if resp.status == 200:
                    resolved = tid
                    break
        except urllib.error.HTTPError:
            continue  # exemplar from a dropped trace: try the next
    exporter.stop()
    broker.close()

    keep_counter = regs["tracing"].counter("ccfd_traces_kept_total")
    report = {
        "transactions": produced,
        "traces_retained": len(summaries),
        "end_to_end_traces": len(e2e),
        "monotone_ok": mono,
        "router_workers": args.workers,
        "worker_span_labels": worker_ids,
        "worker_labels_ok": workers_ok,
        "stages": breakdown,
        "exemplars_in_scrape": len(exemplar_ids),
        "exemplar_trace_resolved": resolved,
        "sampler": {
            "sample": args.sample,
            "kept_fraud": int(keep_counter.value({"reason": "fraud"})),
            "kept_slow": int(keep_counter.value({"reason": "slow"})),
            "kept_sampled": int(keep_counter.value({"reason": "sampled"})),
            "dropped": int(regs["tracing"].counter(
                "ccfd_traces_dropped_total").value()),
        },
    }
    print("\n== per-stage critical path (p50 / p99, ms) ==", file=sys.stderr)
    for name, st in sorted(breakdown.items(),
                           key=lambda kv: -kv[1]["critical_path_share"]):
        print(f"  {name:<16} {st['p50_ms']:>9.3f} / {st['p99_ms']:>9.3f}"
              f"   share={st['critical_path_share']:.1%}  (n={st['n']})",
              file=sys.stderr)
    print(json.dumps(report))
    ok = bool(e2e) and mono and resolved is not None and workers_ok
    if args.json_out:
        # StageProfile-family artifact: trace-derived decomposition under
        # its own schema id, stages shaped like the profile's digests so a
        # planner can consume either. Written even on failure (the "ok"
        # flag and exit code carry the verdict; CI wants the evidence).
        artifact = {
            "schema": "ccfd.stage_profile.trace.v1",
            "generated_unix": time.time(),
            "ok": ok,
            "source": "trace_report",
            "stages": {
                name: {
                    "count": st["n"],
                    "p50_ms": st["p50_ms"],
                    "p99_ms": st["p99_ms"],
                    "critical_path_share": st["critical_path_share"],
                }
                for name, st in breakdown.items()
            },
            "report": report,
        }
        from ccfd_tpu.observability.profile import write_json_crash_safe

        write_json_crash_safe(args.json_out, artifact)
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
