"""One-dial flash TPU capture for sub-minute healthy windows.

Round-4 field evidence (2026-07-31, first heal in two rounds): the relay's
pool legs opened at 03:46, a direct ``jax.devices()`` attached in 0.1 s and
ran a matmul — and by 03:54 the legs were refused again, with the REST
sweep's MAIN process wedged at backend init because its own probe
subprocess had already spent an attachment.  Healthy windows can be
~1 minute and serve very few attachments; every subprocess probe is an
attachment the measurements never get.

This runner therefore:

- pre-filters with a TCP connect to the relay legs (no attachment cost;
  ``tpu_triage.POOL_PORTS`` is the ground truth), exiting 4 when none
  listens;
- dials EXACTLY ONCE, in-process — there is no probe subprocess; the
  attach itself is the probe, bounded by a hard watchdog thread that
  flushes whatever was measured and ``os._exit``\\ s on expiry (a wedged
  PJRT init is unkillable from Python);
- runs the bench sections cheapest-fresh-value-first, FLUSHING the
  artifact after every section, so a mid-run wedge keeps everything
  measured so far (the persistent compile cache additionally banks every
  executable compiled before the wedge for the next window);
- merges completed sections into ``BENCH_TPU_LAST_GOOD.json`` (the file
  bench.py attaches to fallback runs) without destroying sections an
  older full capture measured and this flash did not reach.

Exit codes: 0 = attached on TPU and completed the priority sections;
2 = TPU but wedged mid-run (partial flushed); 3 = attach/section wedge
before any TPU evidence; 4 = no relay leg listening; 5 = attached but not
a TPU backend (nothing recorded).

Reference acceptance surface: the Seldon request-rate/latency dashboard
(/root/reference/deploy/grafana/SeldonCore.json:499-531).
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

from tpu_triage import legs_listening  # noqa: E402

# single source of truth for this round's flash artifact: tpu_watch.py's
# outer-timeout classifier reads the same file this runner flushes, and a
# drifted copy there would misreport banked partial captures as wedges
DEFAULT_OUT = os.path.join(REPO, "FLASH_TPU_r05.json")


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "ccfd_bench", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)  # type: ignore[union-attr]
    return mod


def merge_last_good(path: str, state: dict) -> None:
    """Merge this flash's completed sections into the bench's last-good
    artifact WITHOUT destroying sections an older full capture measured
    and this flash did not reach (tested: tests/test_flash_merge.py)."""
    merged: dict = {}
    try:
        with open(path) as f:
            merged = json.load(f)
    except (OSError, ValueError):
        pass
    result = merged.get("result", {})
    result.update(state["result"])
    merged["result"] = result
    merged["captured_at"] = state["ts_flush"]
    merged["flash_sections"] = {
        **merged.get("flash_sections", {}),
        **{k: state["ts_flush"] for k in state["sections"]},
    }
    with open(path + ".tmp", "w") as f:
        json.dump(merged, f)
    os.replace(path + ".tmp", path)


class Watchdog:
    """Deadline the main thread bumps before each section.  On expiry the
    state flushed so far is final: write it and hard-exit — a wedged device
    wait inside XLA cannot be interrupted any other way."""

    def __init__(self, flush, state):
        self._deadline = time.monotonic() + 60.0
        self._section = "startup"
        self._flush = flush
        self._state = state
        t = threading.Thread(target=self._run, daemon=True)
        t.start()

    def bump(self, section: str, budget_s: float) -> None:
        self._section = section
        self._deadline = time.monotonic() + budget_s

    def _run(self) -> None:
        while True:
            time.sleep(1.0)
            if time.monotonic() > self._deadline:
                try:
                    self._state["wedged_in_section"] = self._section
                    # best-effort flush, bounded: if the MAIN thread is the
                    # one wedged inside a flush (holding the lock), waiting
                    # on it would defeat the hard-exit guarantee
                    self._flush(lock_timeout_s=10.0)
                finally:
                    code = 2 if self._state.get("sections") else 3
                    os._exit(code)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=DEFAULT_OUT)
    ap.add_argument("--rest-seconds", type=float, default=6.0)
    ap.add_argument("--seconds", type=float, default=2.0,
                    help="measured window for non-REST sections")
    ap.add_argument("--attach-budget", type=float, default=150.0)
    ap.add_argument("--skip-extended", action="store_true",
                    help="stop after the priority sections (no REST grid)")
    ap.add_argument("--allow-cpu", action="store_true",
                    help="record even off-TPU (debugging the runner only)")
    ap.add_argument("--platform", default="",
                    help="force a jax platform (debugging the runner only; "
                    "default: the site hook's accelerator)")
    ap.add_argument("--force-dial", action="store_true",
                    help="skip the relay-leg pre-filter and dial anyway "
                    "(for a probe-confirmed attachment whose port set "
                    "moved away from the known legs)")
    ap.add_argument("--profile-dir", default="",
                    help="device-trace output dir for the profile section "
                    "(TensorBoard format). Default: profile_<platform>_"
                    "<YYYYMMDD> under the repo — stamped per capture, not "
                    "pinned to a round name, so the next on-TPU heal "
                    "captures cleanly instead of clobbering (or cohabiting) "
                    "an old round's trace")
    args = ap.parse_args()

    if (not args.force_dial and not legs_listening()
            and not (args.allow_cpu or args.platform)):
        print(json.dumps({"flash": "no relay leg listening"}))
        return 4

    state: dict = {"ts_start": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                             time.gmtime()),
                   "sections": {}, "result": {}}
    # the watchdog thread also flushes (on expiry, while the main thread
    # may be mid-flush); without serialization the two writers truncate
    # each other's .tmp and can publish torn JSON over the last-good merge
    flush_lock = threading.Lock()

    def _flush_locked() -> None:
        state["ts_flush"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f, indent=1)
        os.replace(tmp, args.out)
        # merge into the bench's last-good artifact so fallback bench runs
        # (and the round's BENCH_rNN.json) carry the freshest TPU evidence
        if state.get("platform") == "tpu" and state["result"]:
            merge_last_good(
                os.path.join(REPO, "BENCH_TPU_LAST_GOOD.json"), state
            )

    def flush(lock_timeout_s: float | None = None) -> None:
        if lock_timeout_s is None:
            with flush_lock:
                _flush_locked()
            return
        # watchdog path: bounded acquire — a main thread wedged mid-flush
        # holds the lock forever, and os._exit must still happen
        if flush_lock.acquire(timeout=lock_timeout_s):
            try:
                _flush_locked()
            finally:
                flush_lock.release()

    dog = Watchdog(flush, state)
    bench = _load_bench()

    # ---- attach: the ONE dial -------------------------------------------
    dog.bump("attach", args.attach_budget)
    t0 = time.monotonic()
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    else:
        os.environ.pop("JAX_PLATFORMS", None)
    from ccfd_tpu.utils.compile_cache import enable as enable_cache

    enable_cache()
    import jax

    if args.platform:
        # env alone is not enough: the site hook pins jax_platforms to
        # "axon,cpu" at interpreter start, and the axon leg hangs forever
        # on a dead relay instead of failing over — pin the config too
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    devs = jax.devices()
    state["platform"] = devs[0].platform
    state["devices"] = [str(d) for d in devs]
    y = (jnp.ones((128, 128)) @ jnp.ones((128, 128))).block_until_ready()
    assert float(y[0, 0]) == 128.0
    state["attach_s"] = round(time.monotonic() - t0, 2)
    state["sections"]["attach"] = state["attach_s"]
    print(json.dumps({"attach": state["attach_s"],
                      "platform": state["platform"]}), flush=True)
    if state["platform"] != "tpu" and not args.allow_cpu:
        return 5
    flush()

    from ccfd_tpu.data.ccfd import synthetic_dataset
    from ccfd_tpu.models import mlp
    from ccfd_tpu.serving.scorer import Scorer
    from ccfd_tpu.utils.gctune import tune_for_service

    batch = 131072
    lat_batch = 4096
    ds = synthetic_dataset(n=batch, fraud_rate=0.01, seed=0)
    params = mlp.init(jax.random.PRNGKey(0))
    params = mlp.set_normalizer(params, ds.X.mean(0), ds.X.std(0))
    tune_for_service()

    def section(name, budget_s, fn):
        dog.bump(name, budget_s)
        t = time.monotonic()
        try:
            fn()
            state["sections"][name] = round(time.monotonic() - t, 2)
        except Exception as e:  # noqa: BLE001 - record, keep capturing
            state["sections"][name] = f"error: {e!r}"[:300]
        print(json.dumps({name: state["sections"][name]}), flush=True)
        flush()

    # ---- priority sections: cheapest fresh value first ------------------
    held: dict = {}  # the headline scorer, reused by the roofline section

    def do_scorer():
        scorer = Scorer(model_name="mlp", params=params,
                        batch_sizes=(lat_batch, batch),
                        compute_dtype="bfloat16")
        scorer.warmup()
        held["scorer"] = scorer
        tx, p50, p99 = bench._bench_scorer(
            scorer, ds.X, batch, lat_batch, args.seconds, 2)
        state["result"].update({
            "metric": "end_to_end_scoring_throughput_mlp_bf16",
            "value": round(tx, 1), "unit": "tx/s",
            "vs_baseline": round(tx / bench.NORTH_STAR_TX_S, 3),
            "p50_ms": round(p50, 3), "p99_ms": round(p99, 3),
            "latency_batch": lat_batch, "fused_active": scorer.fused,
            "platform": "tpu", "capture_mode": "flash",
        })

    def do_zoo():
        state["result"]["zoo"] = bench._bench_zoo(max(1.0, args.seconds / 2))

    def do_quant():
        state["result"]["quant_int8"] = bench._bench_quant(
            params, ds.X[:batch], max(1.0, args.seconds / 2))

    def do_rest():
        r = bench._bench_rest(params, lat_batch, args.rest_seconds,
                              n_clients=4, rows_per_req=128, native=True)
        state["result"]["rest"] = r
        if "p99_ms" in r:
            state["result"]["p99_e2e_ms"] = r["p99_ms"]
            state["result"]["p99_vs_target"] = round(
                bench.NORTH_STAR_P99_MS / max(r["p99_ms"], 1e-9), 3)

    def do_roofline():
        # the denominators for "wire-bound" on the hardware that claim is
        # about: measured H2D link bandwidth, host/H2D/compute split, MFU
        # vs the v5e MXU peak (VERDICT r4 items 4/5)
        state["result"]["roofline"] = bench._bench_roofline(
            held["scorer"], params, ds.X, lat_batch,
            float(state["result"].get("value") or 0.0) or None,
            state["result"].get("rest"),
            state["result"].get("quant_int8"))

    def do_rest_python():
        state["result"]["rest_python_transport"] = bench._bench_rest(
            params, lat_batch, max(3.0, args.rest_seconds / 2),
            n_clients=4, rows_per_req=128, native=False)

    def do_seq():
        state["result"]["seq"] = bench._bench_seq(max(1.0, args.seconds / 2))

    def do_seq_pipeline():
        # the seq/history PRODUCT path (router -> HistoryStore assembly ->
        # bucketed dispatch) with its assembly-vs-dispatch split — the
        # number VERDICT r4 item 6 asks for on TPU
        state["result"]["seq_pipeline"] = bench._bench_seq_pipeline(
            max(3.0, args.seconds))

    def do_retrain():
        state["result"]["retrain"] = bench._bench_retrain(
            max(1.0, args.seconds / 2))

    def do_pipeline():
        pipe_params = dict(params)
        pipe_params["layers"] = [dict(l) for l in params["layers"]]
        pipe_params["layers"][-1]["b"] = jnp.asarray([-4.0], jnp.float32)
        state["result"]["pipeline"] = bench._bench_pipeline(
            pipe_params, args.seconds)

    def do_fused_ab():
        ab = {}
        for label, use_fused in (("fused", True), ("xla", False)):
            s = Scorer(model_name="mlp", params=params,
                       batch_sizes=(lat_batch, batch),
                       compute_dtype="bfloat16", use_fused=use_fused)
            if use_fused and not s.fused:
                ab[label] = None
                continue
            s.warmup()
            if use_fused and not s.fused:
                ab[label] = None  # lowering failed; warmup fell back
                continue
            tx, p50, p99 = bench._bench_scorer(
                s, ds.X, batch, lat_batch, max(1.0, args.seconds / 2), 2)
            ab[label] = {"tx_s": round(tx, 1), "p50_ms": round(p50, 3),
                         "p99_ms": round(p99, 3)}
        state["result"]["fused_ab"] = ab

    def do_profile():
        # a real device trace of the serving hop (TensorBoard-loadable):
        # evidence of MXU occupancy / wire-vs-compute no throughput number
        # can carry. Runs LAST of the priority sections — it risks nothing
        # the earlier flushes haven't banked.
        from ccfd_tpu.utils.tracing import Tracer

        # output dir stamped by platform + capture date (no hardcoded
        # round name): each heal's trace lands in its own dir, and the
        # artifact records the resolved platform so a fallback capture is
        # never mistaken for device evidence
        logdir = args.profile_dir or os.path.join(
            REPO,
            f"profile_{state['platform']}_"
            f"{time.strftime('%Y%m%d', time.gmtime())}",
        )
        scorer = Scorer(model_name="mlp", params=params,
                        batch_sizes=(batch,), compute_dtype="bfloat16")
        scorer.warmup()
        tracer = Tracer()
        with tracer.profile(logdir):
            for _ in range(5):
                scorer.score_pipelined(ds.X[:batch], depth=2)
        n_files = sum(len(fs) for _, _, fs in os.walk(logdir))
        state["result"]["profile"] = {"logdir": os.path.basename(logdir),
                                      "files": n_files,
                                      "platform": state["platform"]}

    section("scorer", 300, do_scorer)
    section("zoo", 300, do_zoo)
    section("quant_int8", 240, do_quant)
    section("rest_native", 300 + args.rest_seconds, do_rest)
    section("roofline", 180, do_roofline)
    section("rest_python", 240 + args.rest_seconds, do_rest_python)
    section("seq", 240, do_seq)
    section("seq_pipeline", 240, do_seq_pipeline)
    section("retrain", 240, do_retrain)
    section("pipeline", 300, do_pipeline)
    section("fused_ab", 240, do_fused_ab)
    section("profile", 240, do_profile)

    errors = [k for k, v in state["sections"].items()
              if isinstance(v, str) and v.startswith("error")]
    state["priority_complete"] = not errors

    # ---- extended: REST grid while the window lasts ---------------------
    if not args.skip_extended:
        grid = []
        for native in (True, False):
            for n_clients in (4, 8):
                for rows in (8, 32, 128):
                    if rows == 128 and n_clients == 4:
                        continue  # already measured above
                    name = f"rest_grid_{'nat' if native else 'py'}_c{n_clients}_r{rows}"

                    def do_point(native=native, n_clients=n_clients,
                                 rows=rows):
                        p = bench._bench_rest(
                            params, lat_batch, args.rest_seconds,
                            n_clients=n_clients, rows_per_req=rows,
                            native=native)
                        p.update({"native": native,
                                  "n_clients_requested": n_clients})
                        grid.append(p)
                        state["result"]["rest_grid"] = grid

                    section(name, 180 + args.rest_seconds, do_point)

    dog.bump("done", 60)
    state["ts_end"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    all_errors = [k for k, v in state["sections"].items()
                  if isinstance(v, str) and v.startswith("error")]
    flush()
    print(json.dumps({"flash": "complete",
                      "sections": list(state["sections"]),
                      "errors": all_errors}), flush=True)
    # exit contract: 0 only when every section measured — a detach that
    # RAISES (instead of hanging) error-marks sections fast, and the
    # watcher must not treat that as a full capture
    return 0 if not all_errors else 2


if __name__ == "__main__":
    sys.exit(main())
