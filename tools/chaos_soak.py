"""Chaos soak: full in-process pipeline under router kills AND a device wedge.

Round-2 soaked router kills only; this round's dispatch deadline
(serving/dispatch.py) adds the other failure domain — the accelerator
attachment wedging mid-run. This driver runs the real pipeline
(producer feed -> bus -> router micro-batches -> scorer -> process engine)
with a supervisor + seeded ChaosMonkey killing the router, and at the soak
midpoint wedges the scorer's device path for ``--wedge-s`` seconds (every
device dispatch hangs, exactly like the tunnel failure this host actually
exhibits). The pipeline must keep draining: scoring fails over to the host
tier, the deadline bounds the one dispatch that hits the wedge, and the
device path resumes after the heal.

    JAX_PLATFORMS=cpu python tools/chaos_soak.py --seconds 240

Prints one JSON line; record it in BASELINE.md.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # hermetic: never dial a tunnel

import numpy as np  # noqa: E402

from ccfd_tpu.bus.broker import Broker  # noqa: E402
from ccfd_tpu.config import Config  # noqa: E402
from ccfd_tpu.data.ccfd import FEATURE_NAMES, synthetic_dataset  # noqa: E402
from ccfd_tpu.metrics.prom import Registry  # noqa: E402
from ccfd_tpu.models import mlp  # noqa: E402
from ccfd_tpu.process.fraud import build_engine  # noqa: E402
from ccfd_tpu.router.router import Router  # noqa: E402
from ccfd_tpu.runtime.chaos import ChaosMonkey  # noqa: E402
from ccfd_tpu.runtime.supervisor import Supervisor  # noqa: E402
from ccfd_tpu.serving.scorer import Scorer  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=240.0)
    ap.add_argument("--wedge-s", type=float, default=20.0,
                    help="device-wedge duration at the soak midpoint")
    def _positive_ms(v: str) -> float:
        f = float(v)
        if f <= 0:
            raise argparse.ArgumentTypeError(
                "the soak exercises the dispatch deadline; it must be > 0"
            )
        return f

    ap.add_argument("--deadline-ms", type=_positive_ms, default=250.0)
    ap.add_argument("--feed-batch", type=int, default=2000)
    ap.add_argument("--audit", action="store_true",
                    help="run with the jBPM-analog audit stream ON "
                         "(every instance lifecycle event onto the bus)")
    args = ap.parse_args()

    cfg = Config(confidence_threshold=1.0,
                 audit_topic="ccd-audit" if args.audit else "")
    broker = Broker()
    reg_r, reg_k, reg_c = Registry(), Registry(), Registry()
    engine = build_engine(cfg, broker, reg_k, None)

    ds = synthetic_dataset(n=4096, fraud_rate=0.002, seed=0)
    params = mlp.init(jax.random.PRNGKey(0))
    params = mlp.set_normalizer(params, ds.X.mean(0), ds.X.std(0))
    scorer = Scorer(model_name="mlp", params=params,
                    batch_sizes=(128, 1024, 4096), host_tier_rows=64,
                    dispatch_deadline_ms=args.deadline_ms)
    wedged, release = threading.Event(), threading.Event()
    orig_apply = scorer._apply

    def gated(p, xx):
        if wedged.is_set():
            release.wait(timeout=120.0)
        return orig_apply(p, xx)

    scorer._apply = gated
    scorer.warmup()
    from ccfd_tpu.utils.gctune import tune_for_service

    tune_for_service()  # match the gc config services run with
    scorer._wedge._probe_interval_s = 2.0  # tight recovery for the soak

    router = Router(cfg, broker, scorer.score, engine, reg_r, max_batch=4096)
    sup = Supervisor(backoff_initial_s=0.05, backoff_cap_s=0.5)
    sup.add_thread_service(
        "router", lambda: router.run(poll_timeout_s=0.02), router.stop,
        reset=router.reset,
    )
    sup.start()
    monkey = ChaosMonkey(sup, seed=11, targets=["router"],
                         registry=reg_c, interval_s=20.0)
    monkey.start()

    # feeder: keep the topic loaded without unbounded backlog
    rows = [
        {FEATURE_NAMES[j]: float(ds.X[i, j]) for j in range(30)} | {"id": i}
        for i in range(args.feed_batch)
    ]
    stop_feed = threading.Event()
    produced = [0]

    def feed() -> None:
        while not stop_feed.is_set():
            done = router._c_in.value()
            if produced[0] - done < 200_000:
                broker.produce_batch(cfg.kafka_topic, rows)
                produced[0] += len(rows)
            else:
                time.sleep(0.01)

    feeder = threading.Thread(target=feed, daemon=True)
    feeder.start()

    t0 = time.time()
    t_wedge = t0 + args.seconds / 2
    wedge_done = False
    wedge_info = {}
    last_progress, last_in = time.time(), 0
    max_stall_s = 0.0
    while time.time() - t0 < args.seconds:
        time.sleep(1.0)
        cur = router._c_in.value()
        if cur > last_in:
            last_in, last_progress = cur, time.time()
        max_stall_s = max(max_stall_s, time.time() - last_progress)
        if not wedge_done and time.time() >= t_wedge:
            wedge_info["wedged_at_tx"] = cur
            wedged.set()
            time.sleep(args.wedge_s)
            wedged.clear()
            release.set()
            wedge_done = True
            wedge_info["healed_at_tx"] = router._c_in.value()
            # recovery: the probe should clear the wedge promptly
            t_rec = time.time()
            while scorer._wedge.wedged and time.time() - t_rec < 60:
                time.sleep(0.5)
            wedge_info["recovered_s_after_heal"] = round(time.time() - t_rec, 1)
            wedge_info["device_path_recovered"] = not scorer._wedge.wedged

    stop_feed.set()
    monkey.stop()
    elapsed = time.time() - t0
    total = router._c_in.value()
    out_std = reg_r.counter("transaction_outgoing_total").value(
        labels={"type": "standard"}
    )
    out_fraud = reg_r.counter("transaction_outgoing_total").value(
        labels={"type": "fraud"}
    )
    audit_events = None
    if args.audit:
        audit_events = sum(broker.end_offsets(cfg.audit_topic))
    result = {
        "audit": bool(args.audit),
        "audit_events": audit_events,
        "seconds": round(elapsed, 1),
        "tx_total": int(total),
        "tx_s": round(total / elapsed, 1),
        "router_kills": len(monkey.history),
        "supervisor_restarts": sup.status()["router"]["restarts"],
        "max_progress_stall_s": round(max_stall_s, 1),
        "wedge": wedge_info,
        "dispatch_timeouts": scorer.dispatch_timeouts,
        "host_fallback_scores": scorer.host_fallback_scores,
        "process_starts": int(out_std + out_fraud),
    }
    sup.stop()
    print(json.dumps(result))
    ok = (
        total > 0
        and wedge_info.get("device_path_recovered", False)
        and wedge_info.get("healed_at_tx", 0) > wedge_info.get("wedged_at_tx", 0)
    )
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
