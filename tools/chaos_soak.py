"""Chaos soak: the full pipeline under STATEFUL failures, with accounting.

Round 2 soaked router kills (the one component with no state); round 3
added a mid-soak device wedge; round 4 killed the ENGINE — the stateful
tier — with every kill a real crash-recovery (runtime/recovery.py:
aligned checkpoint restore + bus-offset rewind through the SAME live
router). Round 5 closes the last gap (VERDICT r4 items 2/weak-8): the
DURABLE BUS itself is now a ChaosMonkey target — ``Broker.crash_restart``
drops all in-memory state and replays the segment log in place with
every consumer attached mid-stream — and the bus runs with RETENTION
(segment rotation + delete-before-committed-offset), so memory stays
flat over arbitrarily long soaks. The accounting walk is therefore LIVE:
a consumer group walks the audit ledger as it flows (its committed
position is what retention trims behind), with bitmap pid-ledgers so the
walker itself is flat-memory; RSS is sampled through the run and its
drift reported. The midpoint device wedge and the crash-reopen
copy-drill (a second Broker replayed from a copied log dir must agree
on every offset) remain from earlier rounds.

At the end, the audit stream (per-partition offset order, with the
coordinator's per-partition ``engine_restored`` markers) is walked for the
accounting invariant: within each engine epoch every started instance
reaches a terminal state exactly once or is still active in the final
engine; work a dead epoch did past its last checkpoint is counted as
rolled back (at-least-once redelivery, like Kafka into a restarted KIE
pod — reference deploy/ccd-service.yaml); nothing else may be lost or
double-completed.

Round 6 adds ``--net-faults``: beyond kills, the ChaosMonkey schedules
NETWORK fault storms — by default a blackholed scorer edge
(runtime/faults.py) — and the router runs its degradation ladder
(runtime/breaker.py + router tiers). The exit criteria then also require
that storms fired, the ladder absorbed them (``router_degraded_total``),
the breaker-state gauge is exported, and the accounting walk stayed
violation-free while degraded — a sick edge must cost scoring QUALITY,
never progress or correctness.

    JAX_PLATFORMS=cpu python tools/chaos_soak.py --seconds 240
    JAX_PLATFORMS=cpu python tools/chaos_soak.py --seconds 240 --net-faults

Prints one JSON line; record it in BASELINE.md.  Exit 0 only when the
pipeline drained, the device path recovered, engine kills happened and
every accounting check passed.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # hermetic: never dial a tunnel

import numpy as np  # noqa: E402

from ccfd_tpu.bus.broker import Broker  # noqa: E402
from ccfd_tpu.config import Config  # noqa: E402
from ccfd_tpu.data.ccfd import FEATURE_NAMES, synthetic_dataset  # noqa: E402
from ccfd_tpu.metrics.prom import Registry  # noqa: E402
from ccfd_tpu.models import mlp  # noqa: E402
from ccfd_tpu.process.fraud import build_engine  # noqa: E402
from ccfd_tpu.router.router import Router  # noqa: E402
from ccfd_tpu.runtime.chaos import ChaosMonkey  # noqa: E402
from ccfd_tpu.runtime.recovery import (  # noqa: E402
    CheckpointCoordinator,
    attach_engine_service,
)
from ccfd_tpu.runtime.supervisor import Supervisor  # noqa: E402
from ccfd_tpu.serving.scorer import Scorer  # noqa: E402


def audit_accounting(broker: Broker, topic: str) -> dict:
    """Walk the audit stream for the at-least-once accounting invariant.

    Pids are partition-sticky (events keyed by pid) and the restore marker
    reaches every partition, so each partition's offset order is ground
    truth — the walk keeps PER-PARTITION state (a marker repeats once per
    partition and must only affect that partition's pids).  At an
    ``engine_restored`` marker (runtime/recovery.py) everything the dead
    epoch did past its last checkpoint rolls back: starts/completions of
    pids >= next_pid (instances born after the cut) and completions of
    pids in ``active_pids`` (instances restored as live again, whose
    post-cut terminal events are undone and may legitimately recur).
    Anything else lost or double-completed is a violation."""
    w = AccountingWalker()
    c = broker.consumer("soak-audit-check", (topic,))
    while True:
        recs = c.poll(50_000, timeout_s=0.2)
        if not recs:
            break
        for r in recs:
            w.feed(r)
    c.close()
    return w.result()


class _PidBits:
    """Membership over monotonically-assigned pids as a bitmap.

    The walker's seen/done ledgers hold one entry per process instance —
    at soak rates that is ~every transaction, and Python int-sets cost
    ~60 B/pid (a 20-minute soak would leak ~600 MB of *ledger*, defeating
    the flat-RSS claim the soak exists to prove). Engine pids are dense
    monotone ints, so a bytearray bit per pid is exact at 1/500th the
    memory and O(range/8) for the rollback sweeps markers need."""

    __slots__ = ("bits", "count")

    def __init__(self) -> None:
        self.bits = bytearray()
        self.count = 0

    def add(self, pid: int) -> None:
        byte, bit = pid >> 3, 1 << (pid & 7)
        if byte >= len(self.bits):
            self.bits.extend(b"\0" * (byte + 1 - len(self.bits)))
        if not self.bits[byte] & bit:
            self.bits[byte] |= bit
            self.count += 1

    def discard(self, pid: int) -> None:
        byte, bit = pid >> 3, 1 << (pid & 7)
        if byte < len(self.bits) and self.bits[byte] & bit:
            self.bits[byte] &= ~bit
            self.count -= 1

    def __contains__(self, pid: int) -> bool:
        byte = pid >> 3
        return byte < len(self.bits) and bool(self.bits[byte] & (1 << (pid & 7)))

    def clear_from(self, pid: int) -> int:
        """Clear every member >= pid; returns how many were cleared."""
        cleared = 0
        first = pid >> 3
        if first < len(self.bits):
            keep = (1 << (pid & 7)) - 1
            high = self.bits[first] & ~keep
            cleared += bin(high).count("1")
            self.bits[first] &= keep
            for i in range(first + 1, len(self.bits)):
                if self.bits[i]:
                    cleared += bin(self.bits[i]).count("1")
                    self.bits[i] = 0
        self.count -= cleared
        return cleared


class AccountingWalker:
    """Incremental form of :func:`audit_accounting` (round 5): the soak's
    bus now has RETENTION, so the ledger cannot be replayed whole at the
    end — a live consumer walks the stream as it flows, and the broker's
    delete-before-committed-offset retention protects every unwalked
    record by construction (the walker's committed position IS the trim
    floor for the audit topic). Same per-partition state machine, fed one
    record at a time in partition-offset order; seen/done ledgers are
    bitmaps (:class:`_PidBits`) so the walker itself stays flat-memory."""

    def __init__(self) -> None:
        self.starts = self.completes = self.rolled_back = self.markers = 0
        self.violations: list[str] = []
        self._parts: dict[int, dict] = {}

    def feed(self, rec) -> None:
        st = self._parts.setdefault(
            rec.partition,
            {"open": set(), "done": _PidBits(), "seen": _PidBits()},
        )
        open_p: set = st["open"]
        done_b: _PidBits = st["done"]
        seen_b: _PidBits = st["seen"]
        ev = rec.value
        kind = ev.get("event")
        if kind == "engine_restored":
            self.markers += 1
            # active-at-cut pids all precede next_pid, so the clear_from
            # below cannot touch them; & seen keeps partition-stickiness
            # (the marker lists every partition's actives)
            restored = {x for x in ev.get("active_pids", ()) if x in seen_b}
            void_open = {x for x in open_p if x >= ev["next_pid"]}
            n_void_done = done_b.clear_from(ev["next_pid"])
            undone = {x for x in restored if x in done_b}
            for x in undone:
                done_b.discard(x)
            self.rolled_back += len(void_open) + n_void_done + len(undone)
            st["open"] = restored
        elif kind == "process_started":
            self.starts += 1
            pid = ev["pid"]
            seen_b.add(pid)
            if pid in open_p:
                self.violations.append(f"double start pid={pid}")
            open_p.add(pid)
        elif kind == "process_completed":
            self.completes += 1
            pid = ev["pid"]
            if pid in done_b:
                self.violations.append(f"double complete pid={pid}")
            elif pid not in open_p:
                self.violations.append(f"complete without start pid={pid}")
            else:
                open_p.discard(pid)
                done_b.add(pid)

    @property
    def open_at_end(self) -> set[int]:
        out: set[int] = set()
        for st in self._parts.values():
            out |= st["open"]
        return out

    def result(self) -> dict:
        return {
            "starts": self.starts,
            "completes": self.completes,
            "rolled_back": self.rolled_back,
            "restore_markers": self.markers,
            "open_at_end": self.open_at_end,
            "violations": self.violations[:20],
            "violation_count": len(self.violations),
        }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=240.0)
    ap.add_argument("--wedge-s", type=float, default=20.0,
                    help="device-wedge duration at the soak midpoint")

    def _positive_ms(v: str) -> float:
        f = float(v)
        if f <= 0:
            raise argparse.ArgumentTypeError(
                "the soak exercises the dispatch deadline; it must be > 0"
            )
        return f

    ap.add_argument("--deadline-ms", type=_positive_ms, default=250.0)
    ap.add_argument("--feed-batch", type=int, default=2000)
    ap.add_argument("--checkpoint-s", type=float, default=3.0)
    ap.add_argument("--chaos-interval-s", type=float, default=15.0)
    ap.add_argument("--targets", default="router,engine,bus",
                    help="comma list for the ChaosMonkey")
    ap.add_argument("--retention-records", type=int, default=50_000,
                    help="per-partition bus retention cap (0 = retain "
                    "everything, the pre-round-5 behavior). With the cap "
                    "on, memory stays flat over arbitrarily long soaks "
                    "and the live accounting walker's committed position "
                    "is what keeps every unwalked ledger record safe")
    ap.add_argument("--segment-bytes", type=int, default=4 * 1024 * 1024,
                    help="on-disk segment size. Sized to the retention "
                    "window, NOT the 64 MiB production default: disk "
                    "trims whole segments, so segment size bounds how "
                    "much history a bus crash_restart must replay — the "
                    "first 20-min run with 64 MiB segments spent a 38 s "
                    "stall JSON-decoding a ~1M-record replay per kill")
    ap.add_argument("--bus-log", default="",
                    help="durable bus log dir (default: fresh tempdir)")
    ap.add_argument("--bus-drill-tx", type=int, default=40_000,
                    help="run the bus crash-reopen drill once this many "
                    "transactions have flowed (early: replaying the log is "
                    "O(records), so the drill must run on a bounded log, "
                    "not the multi-million-record end state)")
    ap.add_argument("--net-faults", action="store_true",
                    help="round 6: drill DEGRADED edges, not just kills — "
                    "the ChaosMonkey schedules fault storms on the scorer "
                    "edge (runtime/faults.py) and the router must keep "
                    "deciding every transaction through its degradation "
                    "ladder (host tier / rules-only) with zero accounting "
                    "violations")
    ap.add_argument("--fault-spec", default="scorer:blackhole,stall=300",
                    help="CCFD_FAULTS-syntax plan the storms activate "
                    "(default: a blackholed scorer edge)")
    ap.add_argument("--fault-interval-s", type=float, default=10.0)
    ap.add_argument("--fault-duration-s", type=float, default=3.0)
    ap.add_argument("--workers", type=int, default=2,
                    help="router worker loops (router/parallel.py): >1 "
                    "drills the partition-parallel fan-out — group-wide "
                    "pause barrier, shared in-flight budget, coalesced "
                    "dispatch — under the same kills; 1 = the historical "
                    "single router")
    ap.add_argument("--device-faults", action="store_true",
                    help="ISSUE 11: drill the DEVICE as the fault target "
                    "— a DeviceSupervisor (runtime/heal.py) supervises "
                    "the scorer while device-fault storms "
                    "(runtime/faults.py device_hang et al.) wedge it; "
                    "each storm must reach QUARANTINED with the host "
                    "tier serving (zero accounting violations), heal "
                    "through the ladder, and re-promote WARM (no "
                    "serving-stage XLA compiles after the flip)")
    ap.add_argument("--device-fault-spec", default="device_hang:ms=400",
                    help="CCFD_DEVICE_FAULTS-syntax plan the device "
                    "storms activate")
    ap.add_argument("--device-fault-interval-s", type=float, default=20.0,
                    help="seconds between device-fault storm windows")
    ap.add_argument("--storage-faults", action="store_true",
                    help="ISSUE 13: drill the DISK as the fault target — "
                    "storage-fault storms (runtime/faults.py torn_write/"
                    "rename_lost/bitrot/enospc/fsync_fail/slow_disk) "
                    "degrade every durable write at the durability seam "
                    "while the ChaosMonkey kills services mid-write; the "
                    "run must end with accounting exactly conserved, "
                    "serving-params fingerprint == the lineage champion's "
                    "checkpoint_hash, every detected corruption "
                    "quarantined (never served), and zero unswept tmp "
                    "debris. Implies --lifecycle (the hash-parity claim "
                    "needs the lineage) and a durable on-disk cut.")
    ap.add_argument("--storage-fault-spec",
                    default="bitrot:rate=0.25;torn_write:rate=0.25;"
                            "rename_lost:rate=0.15;fsync_fail:rate=0.1;"
                            "slow_disk:ms=2,rate=0.5",
                    help="CCFD_STORAGE_FAULTS-syntax plan the storage "
                    "storms activate")
    ap.add_argument("--lifecycle", action="store_true",
                    help="run the model-lifecycle controller (lifecycle/) "
                    "under the storm: candidates cycle through shadow/"
                    "canary/promotion while services are killed; asserts "
                    "the pool ends on a single consistent model version")
    ap.add_argument("--lifecycle-submit-s", type=float, default=15.0,
                    help="seconds between candidate submissions")
    ap.add_argument("--audit", action="store_true",
                    help="arm the decision-provenance plane "
                    "(observability/audit.py): every routed tx stamps a "
                    "DecisionRecord through kill-storms; the ok-gate "
                    "requires exact conservation (routed == recorded) "
                    "and that re-stamps only appear with crash restores")
    ap.add_argument("--replay", action="store_true",
                    help="ISSUE 17: fold verdict-parity into the ok-gate "
                    "— after the storm settles, a window recorded DURING "
                    "the storm is re-scored through the same live stack "
                    "(ccfd_tpu/replay/) at bulk priority; any drop, "
                    "ghost or unexplained divergence fails the exit "
                    "gate (champion_hash divergences are tolerated only "
                    "when --lifecycle actually promoted). Implies "
                    "--audit (the window source is the decision log).")
    ap.add_argument("--replay-rows", type=int, default=512,
                    help="size of the storm-recorded window the replay "
                    "drill re-scores")
    ap.add_argument("--lockcheck", action="store_true",
                    help="arm the runtime lock-order sanitizer (analysis/"
                    "lockcheck.py; CCFD_LOCKCHECK=1 implies it): every "
                    "lock ccfd_tpu constructs records its acquisition "
                    "order through the kill-storm, and ANY recorded "
                    "inversion fails the soak — the ccfd-lint lock-order "
                    "rule's dynamic half, under real chaos")
    args = ap.parse_args()
    lock_graph = None
    if args.lockcheck or os.environ.get("CCFD_LOCKCHECK"):
        from ccfd_tpu.analysis import lockcheck as _lockcheck

        # record-don't-raise: a soak must run to its accounting walk and
        # report, not die mid-storm — the ok-gate below fails on any
        # recorded inversion
        lock_graph = _lockcheck.install(raise_on_cycle=False)
    if args.storage_faults:
        # the end-of-run hash-parity claim (serving fingerprint ==
        # lineage champion checkpoint_hash) needs the lineage running
        args.lifecycle = True
    if args.replay:
        # the replay drill's window source is the decision log
        args.audit = True

    bus_dir = args.bus_log or tempfile.mkdtemp(prefix="ccfd_soak_bus_")
    # audit ON: it is the accounting ledger this soak asserts over
    cfg = Config(confidence_threshold=1.0, audit_topic="ccd-audit")
    broker = Broker(log_dir=bus_dir,
                    retention_records=args.retention_records or None,
                    segment_bytes=args.segment_bytes)
    reg_r, reg_k, reg_c = Registry(), Registry(), Registry()

    # live accounting walker: consumes the ledger AS IT FLOWS (retention
    # trims behind its committed position; the end-of-run walk of rounds
    # 2-4 would find the ledger's head already deleted)
    walker = AccountingWalker()
    walker_stop = threading.Event()
    audit_consumer = broker.consumer("soak-audit-check", (cfg.audit_topic,))

    def walk() -> None:
        while True:
            recs = audit_consumer.poll(50_000, timeout_s=0.2)
            for r in recs:
                walker.feed(r)
            if not recs and walker_stop.is_set():
                return

    walk_thread = threading.Thread(target=walk, daemon=True,
                                   name="soak-acct-walker")
    walk_thread.start()

    def engine_factory():
        return build_engine(cfg, broker, reg_k, None)

    engine = engine_factory()

    ds = synthetic_dataset(n=4096, fraud_rate=0.002, seed=0)
    params = mlp.init(jax.random.PRNGKey(0))
    params = mlp.set_normalizer(params, ds.X.mean(0), ds.X.std(0))
    # push probabilities to a trained-model-like range (bench.py does the
    # same): an untrained MLP fires ~half of all traffic into the fraud
    # process, which floods the engine with open investigations at a rate
    # no investigator pool could match and turns the soak into a
    # snapshot-size stress test instead of a failure drill
    import jax.numpy as jnp

    params = dict(params)
    params["layers"] = [dict(l) for l in params["layers"]]
    params["layers"][-1]["b"] = jnp.asarray([-4.0], jnp.float32)
    scorer = Scorer(model_name="mlp", params=params,
                    batch_sizes=(128, 1024, 4096), host_tier_rows=64,
                    dispatch_deadline_ms=args.deadline_ms)
    wedged, release = threading.Event(), threading.Event()
    orig_apply = scorer._apply

    def gated(p, xx):
        if wedged.is_set():
            release.wait(timeout=120.0)
        return orig_apply(p, xx)

    scorer._apply = gated
    scorer.warmup()
    from ccfd_tpu.utils.gctune import tune_for_service

    tune_for_service()  # match the gc config services run with
    scorer._wedge._probe_interval_s = 2.0  # tight recovery for the soak

    # net-fault mode: the scorer edge gets a storm-scheduled fault plan
    # (blackhole by default) and the router gets the full degradation
    # ladder — breaker-gated device tier, host numpy tier, rules-only
    # floor — so a partitioned scorer degrades quality, never progress
    fault_plan = None
    score_fn = scorer.score
    host_fn = None
    if args.net_faults:
        from ccfd_tpu.runtime.faults import FaultPlan  # noqa: E402

        fault_plan = FaultPlan.from_string(args.fault_spec, seed=13,
                                           active=False)
        net_injector = fault_plan.injector("scorer", reg_r)
        if net_injector is not None:
            score_fn = net_injector.wrap_fn(scorer.score)
    if (args.net_faults or args.device_faults) and scorer.has_host_forward:
        # both degraded-edge and sick-device drills need the ladder's
        # host tier armed: quality degrades, progress never stops
        host_fn = scorer.host_score
    # -- model lifecycle under the storm (--lifecycle) ---------------------
    # The governed-rollout machinery (lifecycle/) runs THROUGH the kills:
    # a submitter cycles perturbed candidates through shadow -> canary ->
    # promotion while the router/engine/bus die and recover around it. The
    # end-of-run assertion is the one that matters operationally: after
    # recovery the pool serves a SINGLE consistent version (serving params
    # == the champion's checkpoint; no challenger slot or canary gate left
    # dangling by a mid-canary kill).
    lifecycle = None
    lifecycle_breaker = None
    lifecycle_tap = None
    lifecycle_stats = {"canary_seen": 0}
    if args.lifecycle:
        from ccfd_tpu.lifecycle.controller import (  # noqa: E402
            Guardrails,
            LifecycleController,
        )
        from ccfd_tpu.lifecycle.evaluator import ShadowEvaluator  # noqa: E402
        from ccfd_tpu.lifecycle.shadow import ShadowTap  # noqa: E402
        from ccfd_tpu.lifecycle.versions import VersionStore  # noqa: E402
        from ccfd_tpu.parallel.checkpoint import CheckpointManager  # noqa: E402
        from ccfd_tpu.router.router import default_scorer_breaker  # noqa: E402

        lc_dir = tempfile.mkdtemp(prefix="ccfd_soak_lifecycle_")
        lifecycle_tap = ShadowTap(scorer, broker, cfg.shadow_topic, reg_r)
        lifecycle_breaker = default_scorer_breaker(reg_r)
        lifecycle = LifecycleController(
            cfg, scorer,
            store=VersionStore(os.path.join(lc_dir, "versions.json")),
            # keep enough steps that the champion's checkpoint survives a
            # storm's worth of rejected/superseded candidates saved after
            # it (the end-of-run consistency check restores it)
            checkpoints=CheckpointManager(
                os.path.join(lc_dir, "checkpoints"), keep=64),
            shadow=lifecycle_tap,
            evaluator=ShadowEvaluator(cfg, broker, scorer, reg_r),
            # labels come from the engine's investigation resolutions, a
            # trickle relative to traffic: small gates so cycles complete
            # within storm windows. Perturbed candidates rank identically,
            # so the quality gates pass and the drill exercises the
            # TRANSITIONS under kills, not the verdicts.
            # min_submit_interval_s=0: the soak WANTS supersession in the
            # mix (a mid-flight candidate replaced during a storm is one
            # of the transitions under drill)
            guardrails=Guardrails(
                min_labels=16, min_shadow_rows=256, canary_min_labels=8,
                max_score_psi=10.0, min_submit_interval_s=0.0),
            registry=reg_r, breaker=lifecycle_breaker)
        score_fn = lifecycle.wrap_score(score_fn)
    # -- incident flight recorder + dispatch watchdog (ISSUE 10) -----------
    # The router-side watchdog (runtime/overload.py bounded_dispatch) gets
    # a deadline BELOW the scorer's own, so the midpoint wedge trips
    # ccfd_dispatch_timeout_total — and every trip snapshots the system
    # state into the FlightRecorder ring: watchdog kills leave post-mortem
    # flight data, not only SLO breaches.
    from ccfd_tpu.observability.incident import FlightRecorder
    from ccfd_tpu.runtime.overload import OverloadControl

    recorder = FlightRecorder({"router": reg_r, "kie": reg_k},
                              registry=reg_r, ring=32)
    overload = OverloadControl.from_config(
        cfg, reg_r, max_batch=4096, workers=max(1, args.workers))
    if overload is not None:
        overload.dispatch_deadline_s = max(0.05,
                                           args.deadline_ms * 0.8 / 1e3)
        overload.recorder = recorder
    degrade = True if (args.net_faults or args.device_faults) else None
    # -- decision-provenance plane (--audit, ISSUE 14) ---------------------
    # One shared AuditLog across the whole pool: the ok-gate folds the
    # conservation claim (every routed tx stamped exactly once — counter
    # equality survives kill-storms because the stamp happens at the same
    # seam as transaction_outgoing_total) into the soak's accounting.
    decision_audit = None
    audit_flusher = None
    router_audit = None
    replay_tap = None
    replay_lineage = None
    if args.audit:
        from ccfd_tpu.observability.audit import AuditLog  # noqa: E402

        decision_audit = AuditLog(
            dir=tempfile.mkdtemp(prefix="ccfd_soak_audit_"),
            registry=reg_r)
        router_audit = decision_audit
        if args.replay:
            # ISSUE 17: the replay drill below re-scores a storm-recorded
            # window through THIS stack. Feature capture must be armed for
            # the whole storm (windows are only re-scorable if the route
            # seam embedded the decoded rows), and the route seam's audit
            # sink becomes the tap that diverts replay-marked verdicts to
            # the join instead of re-stamping the provenance log
            from ccfd_tpu.replay.service import (  # noqa: E402
                ReplayVerdictTap,
            )

            decision_audit.capture_rows = True
            if lifecycle is not None:
                # stamp the champion lineage on every record so a promote
                # that lands mid-storm classifies as champion_hash (an
                # explained finding), never as nondeterminism
                def replay_lineage():
                    try:
                        ch = lifecycle.store.champion()
                        return ((ch.version, ch.checkpoint_hash)
                                if ch else (None, None))
                    except Exception:  # noqa: BLE001 - probe races kills
                        return (None, None)

                decision_audit.lineage_fn = replay_lineage
            replay_tap = ReplayVerdictTap(inner=decision_audit,
                                          registry=reg_r)
            router_audit = replay_tap
        # the flusher runs for the WHOLE soak (the production shape: the
        # operator supervises it) — pending records drain to segments
        # every tick instead of accumulating in memory for the run, so
        # segment rotation and the failed-append accounting are actually
        # drilled under the storm
        audit_flusher = threading.Thread(
            target=lambda: decision_audit.run(interval_s=0.25),
            daemon=True, name="soak-audit-flush")
        audit_flusher.start()
    if args.workers > 1:
        # partition-parallel fan-out: the workers split the topic's
        # partitions, share ONE in-flight budget + breaker + coalescing
        # batcher, and the pause barrier the checkpoint coordinator takes
        # below is group-wide — the soak asserts the same 0-violation
        # accounting through kills with the whole pool in play
        from ccfd_tpu.router.parallel import ParallelRouter

        router = ParallelRouter(
            cfg, broker, score_fn, engine, reg_r, workers=args.workers,
            max_batch=4096, host_score_fn=host_fn,
            breaker=lifecycle_breaker,
            degrade=degrade,
            overload=overload, audit=router_audit)
    else:
        router = Router(cfg, broker, score_fn, engine, reg_r, max_batch=4096,
                        host_score_fn=host_fn,
                        breaker=lifecycle_breaker,
                        degrade=degrade,
                        overload=overload, audit=router_audit)
    # -- device self-healing under storms (--device-faults, ISSUE 11) ------
    # The DeviceSupervisor owns the soak's scorer: device-fault storms
    # (scheduled below, interleaved with the service kills) must drive the
    # full ladder — wedge injected -> QUARANTINED (router pinned to the
    # host tier, accounting still conserving) -> heal -> WARM re-promotion
    # (no serving-stage compiles after the flip) -> device serving again.
    healer = None
    device_plan = None
    heal_prof = None
    device_cycles: list[dict] = []
    if args.device_faults:
        from ccfd_tpu.observability.profile import StageProfiler  # noqa: E402
        from ccfd_tpu.runtime.faults import (  # noqa: E402
            DeviceFaultPlan,
            install_device_faults,
        )
        from ccfd_tpu.runtime.heal import DeviceSupervisor  # noqa: E402

        heal_prof = StageProfiler(registry=reg_r)
        heal_prof.arm_compile_listener()
        device_plan = DeviceFaultPlan.from_string(args.device_fault_spec,
                                                  seed=17, active=False)
        install_device_faults(device_plan)
        healer = DeviceSupervisor(
            scorer, registry=reg_r,
            breaker=getattr(router, "_breaker", None),
            profiler=heal_prof, recorder=recorder, overload=overload,
            canary_deadline_ms=min(150.0, args.deadline_ms * 0.6),
            suspect_strikes=2, probation_canaries=2,
            backoff_base_s=0.1, backoff_cap_s=1.0,
        )
        router.set_heal_gate(healer)
    # -- storage-fault storms (--storage-faults, ISSUE 13) ------------------
    # The durability seam (runtime/durability.py) is the fault target:
    # every lineage save, candidate checkpoint and recovery-cut write runs
    # degraded during storm windows (torn/lost/bit-flipped/failed writes)
    # while the ChaosMonkey kills services mid-write. Recovery must come
    # from quarantine + last-good generations — never from serving a
    # corrupt artifact.
    storage_plan = None
    cut_path = None
    if args.storage_faults:
        from ccfd_tpu.runtime import durability  # noqa: E402
        from ccfd_tpu.runtime.faults import (  # noqa: E402
            StorageFaultPlan,
            install_storage_faults,
        )

        durability.bind_registry(reg_r)
        storage_plan = StorageFaultPlan.from_string(args.storage_fault_spec,
                                                    seed=29, active=False)
        install_storage_faults(storage_plan)
        # a durable on-disk cut: full-process crash recovery writes ride
        # the same degraded seam (torn cuts must fall back to last-good)
        cut_path = os.path.join(tempfile.mkdtemp(prefix="ccfd_soak_cut_"),
                                "cut.json")
    coord = CheckpointCoordinator(router, broker, engine_factory,
                                  interval_s=args.checkpoint_s,
                                  path=cut_path)
    sup = Supervisor(backoff_initial_s=0.05, backoff_cap_s=0.5)
    sup.add_thread_service(
        "router", lambda: router.run(poll_timeout_s=0.02), router.stop,
        reset=router.reset,
    )
    # the durable bus as a killable service: ChaosMonkey's injection stops
    # the placeholder loop, and the supervisor's reset hook performs the
    # actual crash — Broker.crash_restart drops ALL in-memory state and
    # replays the segment log in place, with every consumer (router,
    # engine audit sink, the accounting walker) attached mid-stream
    bus_stop = threading.Event()
    bus_booted = [False]

    def bus_run() -> None:
        while not bus_stop.wait(0.5):
            pass

    def bus_reset() -> None:
        bus_stop.clear()
        if bus_booted[0]:  # first start is bring-up, not a crash
            broker.crash_restart()
        bus_booted[0] = True

    sup.add_thread_service("bus", bus_run, bus_stop.set, reset=bus_reset)
    if healer is not None:
        sup.add_thread_service(
            "heal", lambda: healer.run(interval_s=0.3), healer.stop,
            reset=healer.reset)
    if lifecycle is not None:
        sup.add_thread_service(
            "lifecycle", lambda: lifecycle.run(interval_s=0.25),
            lifecycle.stop, reset=lifecycle.reset)
        sup.add_thread_service(
            "lifecycle-shadow", lambda: lifecycle_tap.run(interval_s=0.05),
            lifecycle_tap.stop, reset=lifecycle_tap.reset)
    attach_engine_service(sup, coord)
    sup.start()
    coord.start()

    # candidate submitter: perturbed copies of the live champion cycle
    # through the lifecycle while the storm rages
    submit_stop = threading.Event()

    def submit_loop() -> None:
        rng_lc = np.random.default_rng(23)
        fraud_rows = np.flatnonzero(ds.y == 1)
        legit_rows = np.flatnonzero(ds.y == 0)
        tick = max(0.5, args.lifecycle_submit_s / 8.0)
        next_submit = time.time()
        while not submit_stop.wait(tick):
            try:
                # label trickle: the evaluator's evidence stream. In the
                # platform the fraud process emits these on resolution; the
                # soak (whose engine bias routes almost nothing to fraud in
                # short runs) feeds ground truth directly, both classes
                # represented so the AUC gate gets a verdict
                picks = np.concatenate([
                    rng_lc.choice(legit_rows, size=6),
                    rng_lc.choice(fraud_rows, size=2),
                ])
                for j in picks:
                    broker.produce(cfg.labels_topic, {
                        "transaction": dict(
                            zip(FEATURE_NAMES, map(float, ds.X[j]))),
                        "label": int(ds.y[j]),
                    })
                if time.time() < next_submit:
                    continue
                next_submit = time.time() + args.lifecycle_submit_s
                base = jax.tree.map(np.asarray,
                                    lifecycle._champion_params)
                cand = {"norm": base["norm"],
                        "layers": [dict(l) for l in base["layers"]]}
                last = dict(cand["layers"][-1])
                last["b"] = last["b"] + np.float32(
                    rng_lc.normal(0.0, 0.01))
                cand["layers"][-1] = last
                lifecycle.submit_candidate(cand, label_watermark=0)
            except Exception:  # noqa: BLE001 - submit races teardown
                pass

    submitter = None
    if lifecycle is not None:
        submitter = threading.Thread(target=submit_loop, daemon=True,
                                     name="soak-lifecycle-submit")
        submitter.start()

    # feeder: keep the topic loaded without unbounded backlog; the gate
    # lets the bus drill quiesce production without killing the thread.
    # CSV byte rows with the customer id as the record KEY — the produce
    # wire the reference producer uses (and bench.py's pipeline section):
    # ~6x smaller retained records than feature dicts, GC-untracked
    # (bus/broker.py Record note), and crash_restart replays them without
    # a JSON decode per record — the soak's flat-RSS claim is about the
    # bus, not about feeding it the fattest possible payload
    rows = [
        ",".join(f"{v:.6g}" for v in ds.X[i]).encode()
        for i in range(args.feed_batch)
    ]
    row_keys = list(range(args.feed_batch))
    stop_feed = threading.Event()
    feed_gate = threading.Event()
    feed_gate.set()
    produced = [0]

    def feed() -> None:
        while not stop_feed.is_set():
            feed_gate.wait(timeout=1.0)
            if not feed_gate.is_set():
                continue
            done = router._c_in.value()
            if produced[0] - done < 200_000:
                broker.produce_batch(cfg.kafka_topic, rows, row_keys)
                produced[0] += len(rows)
            else:
                time.sleep(0.01)

    feeder = threading.Thread(target=feed, daemon=True)
    feeder.start()

    # -- investigators: the PRODUCT service working the task queue ---------
    # Without them every flagged transaction parks an instance forever and
    # the aligned-checkpoint cost grows without bound — unrealistic (the
    # reference demo has humans working the KIE console queue) and it
    # turns the soak into a snapshot-size benchmark. The engine reference
    # follows crash-recovery swaps via the indirection below, and
    # individual completion failures (task rolled back mid-restore, dead
    # engine) are the service's normal skip path.
    from ccfd_tpu.process.investigator import InvestigatorService

    class CurrentEngine:
        """Resolve the live engine per call (restores swap it)."""

        def tasks(self, status="open"):
            return router.engine.tasks(status)

        def complete_task(self, task_id, outcome):
            return router.engine.complete_task(task_id, outcome)

    investigator = InvestigatorService(
        CurrentEngine(), Registry(), rate_per_s=0.0,  # unthrottled: soak
        trust_threshold=0.9, base_fraud_rate=0.05, seed=7,
    )
    invest_thread = threading.Thread(target=investigator.run, daemon=True)
    invest_thread.start()

    # -- bus crash-reopen drill (bounded log, under way) -------------------
    bus_check: dict = {}
    drill_deadline = time.time() + 60
    while (router._c_in.value() < args.bus_drill_tx
           and time.time() < drill_deadline):
        time.sleep(0.25)
    feed_gate.clear()
    acked = router.pause(10.0)
    try:
        live_before = {t: broker.end_offsets(t)
                       for t in (cfg.kafka_topic, cfg.audit_topic)}
        committed_before = broker.committed_offsets("router", cfg.kafka_topic)
        # Replay a COPY of the log dir, never the live one: opening a
        # Broker replays in place — offsets.log compaction would
        # os.replace() the file out from under the live broker's append
        # fd (silently killing offset durability for the rest of the
        # run), and torn-tail truncation would mutate live segments. The
        # copy is also the honest model: a crashed process's disk as the
        # restarting process finds it.
        import shutil

        copy_dir = tempfile.mkdtemp(prefix="ccfd_soak_busdrill_")
        shutil.rmtree(copy_dir)
        shutil.copytree(bus_dir, copy_dir)
        replayed = Broker(log_dir=copy_dir)
        rep_ends = {t: replayed.end_offsets(t) for t in live_before}
        rep_committed = replayed.committed_offsets("router", cfg.kafka_topic)
        replayed.close()
        shutil.rmtree(copy_dir, ignore_errors=True)
        live_after = {t: broker.end_offsets(t) for t in live_before}
        # prefix-consistency: background timers may append between the
        # live read and the copy, so the replayed view must sit between
        # the two live reads
        ends_ok = all(
            live_before[t][p] <= rep_ends[t][p] <= live_after[t][p]
            for t in live_before for p in range(len(live_before[t]))
        )
        bus_check = {
            "at_tx": int(router._c_in.value()),
            "barrier_acked": acked,
            "end_offsets_equal": ends_ok,
            "group_offsets_equal": rep_committed == committed_before,
        }
    finally:
        router.resume()
        feed_gate.set()

    targets = [t for t in args.targets.split(",") if t]
    monkey = ChaosMonkey(sup, seed=11, targets=targets,
                         registry=reg_c, interval_s=args.chaos_interval_s,
                         fault_plan=fault_plan,
                         storage_fault_plan=storage_plan,
                         fault_interval_s=(args.fault_interval_s
                                           if (args.net_faults
                                               or args.storage_faults)
                                           else None),
                         fault_duration_s=args.fault_duration_s)
    monkey.start()

    # -- device-fault storm windows (--device-faults) ----------------------
    # Interleaved with the service kills above: each window activates the
    # device plan, requires the healer to QUARANTINE, deactivates, then
    # requires a heal to HEALTHY followed by a 2 s serving window with
    # ZERO serving-stage compiles (the warm-re-promotion proof).
    df_stop = threading.Event()
    df_thread = None
    if healer is not None:
        from ccfd_tpu.runtime.heal import (  # noqa: E402
            NON_SERVING_COMPILE_STAGES,
        )

        def serving_compiles() -> int:
            return sum(v for s, v in heal_prof.compile_counts().items()
                       if s not in NON_SERVING_COMPILE_STAGES)

        def device_storm_loop() -> None:
            while not df_stop.wait(args.device_fault_interval_s):
                if wedged.is_set():
                    continue  # the midpoint wedge is its own drill
                cycle = {"at_tx": int(router._c_in.value())}
                device_plan.activate()
                t_q = time.time()
                while (healer.state != "quarantined"
                       and time.time() - t_q < 20
                       and not df_stop.is_set()):
                    time.sleep(0.1)
                cycle["quarantined"] = healer.state == "quarantined"
                device_plan.deactivate()
                t_h = time.time()
                while (healer.state != "healthy"
                       and time.time() - t_h < 60
                       and not df_stop.is_set()):
                    time.sleep(0.1)
                cycle["healed"] = healer.state == "healthy"
                base = serving_compiles()
                t_w = time.time()
                while time.time() - t_w < 2.0 and not df_stop.is_set():
                    time.sleep(0.1)
                cycle["warm"] = bool(cycle["healed"]
                                     and serving_compiles() == base)
                cycle["healed_at_tx"] = int(router._c_in.value())
                if df_stop.is_set() and not (
                        cycle["quarantined"] and cycle["healed"]):
                    # shutdown truncated this window mid-wait: the cycle
                    # never got its 20/60 s budget, so recording it would
                    # fail the exit gate on timing, not on behavior
                    break
                device_cycles.append(cycle)

        df_thread = threading.Thread(target=device_storm_loop, daemon=True,
                                     name="soak-device-storms")
        df_thread.start()

    def rss_mb() -> float:
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return round(int(line.split()[1]) / 1024.0, 1)
        except (OSError, ValueError, IndexError):
            pass
        return 0.0

    t0 = time.time()
    t_wedge = t0 + args.seconds / 2
    wedge_done = False
    wedge_info: dict = {}
    last_progress, last_in = time.time(), 0
    max_stall_s = 0.0
    rss_samples: list[list[float]] = [[0.0, rss_mb()]]
    last_rss = t0
    while time.time() - t0 < args.seconds:
        time.sleep(1.0)
        if time.time() - last_rss >= 10.0:
            last_rss = time.time()
            rss_samples.append([round(last_rss - t0, 0), rss_mb()])
        cur = router._c_in.value()
        if cur > last_in:
            last_in, last_progress = cur, time.time()
        max_stall_s = max(max_stall_s, time.time() - last_progress)
        if lifecycle is not None and lifecycle.stage == 2:
            lifecycle_stats["canary_seen"] += 1
        if not wedge_done and time.time() >= t_wedge:
            wedge_info["wedged_at_tx"] = cur
            wedged.set()
            time.sleep(args.wedge_s)
            wedged.clear()
            release.set()
            wedge_done = True
            wedge_info["healed_at_tx"] = router._c_in.value()
            # recovery: the probe should clear the wedge promptly
            t_rec = time.time()
            while scorer._wedge.wedged and time.time() - t_rec < 60:
                time.sleep(0.5)
            wedge_info["recovered_s_after_heal"] = round(time.time() - t_rec, 1)
            wedge_info["device_path_recovered"] = not scorer._wedge.wedged

    df_stop.set()
    if df_thread is not None:
        df_thread.join(timeout=10)
    if device_plan is not None:
        device_plan.deactivate()
    if storage_plan is not None:
        storage_plan.deactivate()
    stop_feed.set()
    investigator.stop()
    invest_thread.join(timeout=10)
    monkey.stop()
    coord.stop()
    elapsed = time.time() - t0
    # drain the backlog so the accounting walk sees a settled stream, then
    # park the router for the final engine-state comparison
    settle = time.time() + 20
    prev = -1
    while time.time() < settle:
        cur = router._c_in.value()
        if cur == prev:
            break
        prev = cur
        time.sleep(1.0)
    router.pause(10.0)

    # -- lifecycle consistency after recovery ------------------------------
    lifecycle_res: dict = {}
    if lifecycle is not None:
        submit_stop.set()
        if submitter is not None:
            submitter.join(timeout=5)
        # deterministic quiesce: a candidate still mid-flight (e.g. the
        # last kill landed mid-canary) rolls back, then serving must equal
        # the champion's checkpoint — ONE consistent version in the pool
        lifecycle.resolve_for_shutdown()
        champ = lifecycle.store.champion()
        served = jax.tree.map(np.asarray, scorer.params)
        from ccfd_tpu.runtime.durability import CorruptArtifactError
        try:
            restored = lifecycle.checkpoints.restore(
                served, step=champ.checkpoint_step)
        except FileNotFoundError:
            restored = None  # champion ckpt GC'd (very long soak): fail
        except CorruptArtifactError:
            # storm bitrot landed on the champion's on-disk bytes AFTER
            # the stamp: quarantined, never served — the hash-parity
            # check below (recorded fingerprint vs the tree actually
            # serving) is the integrity claim that still must hold
            restored = None
        params_match = restored is not None and all(
            np.allclose(a, b, atol=1e-6)
            for a, b in zip(jax.tree.leaves(served),
                            jax.tree.leaves(restored[0]))
        )
        stages = [v.stage for v in lifecycle.store.versions()]
        lifecycle_res = {
            "enabled": True,
            "champion_version": champ.version,
            "versions": len(stages),
            "promotions": int(reg_r.counter(
                "ccfd_lifecycle_promotions_total").value()),
            "rollbacks": int(reg_r.counter(
                "ccfd_lifecycle_rollbacks_total").value()),
            "rejections": int(reg_r.counter(
                "ccfd_lifecycle_rejections_total").value()),
            "canary_ticks_observed": lifecycle_stats["canary_seen"],
            "serving_matches_champion_checkpoint": bool(params_match),
            "serving_consistent": lifecycle.serving_consistent(),
            # a dangling challenger slot or canary gate after quiesce
            # would be the mid-canary-kill inconsistency this drill exists
            # to rule out
            "challenger_cleared": scorer.challenger_version is None,
            "gate_inactive": not lifecycle.gate.active,
        }
        if args.storage_faults:
            from ccfd_tpu.parallel.partition import params_fingerprint
            from ccfd_tpu.runtime import durability as _dur

            serving_fp = params_fingerprint(served)
            lc_events = [e["event"] for e in lifecycle.store.audit_trail()]
            lifecycle_res["storage"] = {
                "storm_windows": storage_plan.activations,
                "injected": dict(storage_plan.injected),
                "counts": {k: sum(v.values())
                           for k, v in _dur.counts().items()},
                # the integrity claim: what serves is what the lineage
                # recorded — byte-corruption on disk was quarantined (and
                # possibly recovered from a generation), never published
                "serving_fp_matches_lineage": bool(
                    champ.checkpoint_hash is not None
                    and serving_fp == champ.checkpoint_hash),
                # divergence is only legal when the audit trail explains
                # it: a fallback restore (verified older generation
                # served, re-stamped) or a rules pin (nothing verified)
                "fallback_restores": lc_events.count(
                    "storage_fallback_restore"),
                "storage_pins": lc_events.count("storage_pin"),
                "pinned_at_end": lifecycle.storage_pinned,
            }

    total = router._c_in.value()
    final_engine = router.engine
    # finalize the live walk: the thread drains whatever the ledger still
    # holds past the walker's committed position, then exits
    walker_stop.set()
    walk_thread.join(timeout=60)
    audit_consumer.close()
    acct = walker.result()
    with final_engine.state_lock:
        active_now = {i.pid for i in final_engine.instances("active")}
    # every audit-open pid must be live in the final engine and vice versa;
    # a pid open in the walked stream but terminal in the engine is just a
    # timer completion whose audit event landed after the walk (tail), not
    # a loss — verify instead of excusing blindly
    ghost = acct["open_at_end"] - active_now
    tail_completed = set()
    for pid in list(ghost):
        try:
            if final_engine.instance(pid).status != "active":
                tail_completed.add(pid)
        except KeyError:
            # audit-coupled eviction (round 8): a tail-completed instance
            # leaves the runtime store as soon as its terminal event is
            # durably produced — the bounded post-mortem ring is the
            # queryable record. A pid in NEITHER store is a real ghost.
            info = final_engine.completed_info(pid)
            if info is not None and info["status"] != "active":
                tail_completed.add(pid)
    ghost -= tail_completed
    unaudited = active_now - acct["open_at_end"]
    acct_ok = not acct["violation_count"] and not ghost and not unaudited

    # decision-record conservation (--audit): every routed tx stamped
    # exactly ONCE — the recorded counter must equal the outgoing counter
    # through every kill/restore, and duplicates (re-stamps of the same
    # bus coordinate) are only legal when a crash restore re-drove records
    audit_res: dict = {}
    if decision_audit is not None:
        decision_audit.stop()
        if audit_flusher is not None:
            audit_flusher.join(timeout=10)
        decision_audit.flush()
        routed_total = int(reg_r.counter(
            "transaction_outgoing_total").total())
        recorded_total = int(reg_r.counter(
            "ccfd_audit_records_total").value())
        a_counts = decision_audit.counts()
        audit_res = {
            "routed": routed_total,
            "recorded": recorded_total,
            "conserved": routed_total == recorded_total,
            "restamped": a_counts["restamped"],
            "ring": a_counts["ring"],
            "truncated_frames": a_counts["truncated_frames"],
            "dropped_log_write": int(reg_r.counter(
                "ccfd_audit_dropped_total").value({"reason": "log_write"})),
        }

    # -- verdict-parity replay drill (--replay, ISSUE 17) -------------------
    # Runs strictly AFTER the conservation numbers above are frozen: the
    # re-drive routes through the same stack (incrementing the routed
    # counters) but the tap diverts every replay-marked verdict away from
    # the provenance log, so routed == recorded stays exactly what the
    # storm produced. The router must be live again for the drive.
    replay_res: dict = {}
    if args.replay and decision_audit is not None:
        from ccfd_tpu.replay.service import ReplayService  # noqa: E402

        router.resume()
        recs = decision_audit.scan_window()
        # a storm-recorded window: re-scorable rows stamped on the device
        # tier (host-tier rows — small trailing poll batches — replay on
        # device and may differ in the last ulp; the drill's claim is
        # byte-parity through the SAME serving tier)
        window = [r for r in recs
                  if r.get("row") is not None
                  and r.get("tier", "device") == "device"]
        window = window[-max(1, args.replay_rows):]
        svc = ReplayService(
            cfg, broker, decision_audit, tap=replay_tap, registry=reg_r,
            state_dir=tempfile.mkdtemp(prefix="ccfd_soak_replay_"),
            overload=overload, lineage_fn=replay_lineage)
        rep = svc.run_window(window=window, window_id="soak-storm")
        svc.stop()
        promotions = int(reg_r.counter(
            "ccfd_lifecycle_promotions_total").value()) if lifecycle else 0
        # champion_hash is the one EXPLAINED cause a storm can legally
        # produce (a promote landed between the stamp and the re-drive);
        # everything else — and any drop or ghost — fails the gate
        explained = (promotions > 0
                     and set(rep["causes"]) <= {"champion_hash"})
        replay_res = {
            "window": len(window),
            "recorded_total": len(recs),
            "replayed": rep["replayed"],
            "match": rep["match"],
            "divergence": rep["divergence"],
            "drop": rep["drop"],
            "ghost": rep["ghost"],
            "dup": rep["dup"],
            "causes": rep["causes"],
            "rows_per_s": round(rep["rows_per_s"], 1),
            "parity": rep["parity"],
            "ok": bool(len(window) > 0 and not rep["stopped"]
                       and rep["drop"] == 0 and rep["ghost"] == 0
                       and (rep["parity"] or explained)),
        }

    kills: dict[str, int] = {}
    for _ts, name in monkey.history:
        kills[name] = kills.get(name, 0) + 1
    status = sup.status()
    # RSS drift: least-squares slope over the samples past the warmup
    # quartile — the flat-memory evidence VERDICT r4 item 2 asks for
    tail = rss_samples[len(rss_samples) // 4:]
    drift_mb_per_min = 0.0
    if len(tail) >= 2:
        xs = [s[0] for s in tail]
        ys = [s[1] for s in tail]
        mx, my = sum(xs) / len(xs), sum(ys) / len(ys)
        var = sum((x - mx) ** 2 for x in xs)
        if var > 0:
            drift_mb_per_min = round(
                sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / var * 60,
                3,
            )
    # memory-drift evidence (observability/memory.py): per-component
    # object counts alongside the RSS slope, so a drifting soak NAMES the
    # growing container instead of just measuring the growth
    from ccfd_tpu.observability.memory import memory_report

    mem = memory_report({
        "engine": lambda: sum(final_engine.object_counts().values()),
        "bus_retained_records": lambda: sum(
            e - b
            for t in (cfg.kafka_topic, cfg.audit_topic)
            for e, b in zip(broker.end_offsets(t),
                            broker.beginning_offsets(t))
        ),
        "walker_ledger_bytes": lambda: sum(
            len(st["done"].bits) + len(st["seen"].bits)
            for st in walker._parts.values()
        ),
    })

    result = {
        "seconds": round(elapsed, 1),
        "tx_total": int(total),
        "tx_s": round(total / elapsed, 1),
        "router_workers": args.workers,
        "coalesced": {
            "worker_batches": int(reg_r.counter(
                "router_worker_batches_total").total()),
            "dispatches": int(reg_r.counter(
                "router_coalesced_dispatches_total").value()),
        },
        # the RSS slope, top-level: THE memory-drift acceptance number
        "rss_slope_mb_per_min": drift_mb_per_min,
        "targets": targets,
        "kills": kills,
        "engine_kills": kills.get("engine", 0),
        "router_kills": kills.get("router", 0),
        "bus_kills": kills.get("bus", 0),
        "bus_crash_restarts": broker.crash_restarts,
        "retention": {
            "records_per_partition_cap": args.retention_records,
            "records_trimmed": broker.records_trimmed,
            "beginning_offsets": {
                t: broker.beginning_offsets(t)
                for t in (cfg.kafka_topic, cfg.audit_topic)
            },
            "oor_resets": broker.oor_resets,
            # who holds the trim floor per topic (diagnosis surface: a
            # group parked at a low offset is what stops trimming)
            "group_positions": {
                g: {f"{t}/{p}": off for (t, p), off in tps.items()}
                for g, tps in broker.health_snapshot()["groups"].items()
            },
        },
        "rss": {
            "start_mb": rss_samples[0][1],
            "end_mb": rss_samples[-1][1],
            "max_mb": max(s[1] for s in rss_samples),
            "drift_mb_per_min": drift_mb_per_min,
            "samples": rss_samples,
        },
        "memory": mem,
        "supervisor_restarts": {n: s["restarts"] for n, s in status.items()},
        "checkpoints": coord.checkpoints,
        "checkpoint_skips": coord.skipped,
        "restores": coord.restores,
        "max_progress_stall_s": round(max_stall_s, 1),
        "wedge": wedge_info,
        "bus_reopen_check": bus_check,
        "dispatch_timeouts": scorer.dispatch_timeouts,
        "host_fallback_scores": scorer.host_fallback_scores,
        # flight-recorder evidence (observability/incident.py): every
        # router-watchdog kill must have snapshotted into the ring
        "flight_recorder": {
            "watchdog_timeouts": int(reg_r.counter(
                "ccfd_dispatch_timeout_total").value()),
            "ring_snapshots": len(recorder.ring),
            "dispatch_timeout_snapshots": sum(
                1 for s in recorder.ring
                if s.get("reason") == "dispatch_timeout"),
        },
        "lifecycle": lifecycle_res,
        "audit": audit_res,
        "replay": replay_res,
        # device heal evidence (runtime/heal.py): each storm cycle must
        # have quarantined, healed and re-promoted WARM
        "device_heal": {
            "enabled": bool(args.device_faults),
            "spec": args.device_fault_spec if args.device_faults else "",
            "cycles": device_cycles,
            "quarantines": healer.quarantines if healer else 0,
            "repromotions": healer.repromotions if healer else 0,
            "canary_failures": healer.canary_failures if healer else 0,
            "final_state": healer.state if healer else "",
            "health_gauge_exported": (
                "ccfd_device_health" in reg_r.render()
                if healer else False),
        },
        "tasks_completed_by_investigators": investigator.completed,
        "net_faults": {
            "enabled": bool(args.net_faults),
            "spec": args.fault_spec if args.net_faults else "",
            "windows": len(monkey.fault_windows),
            "degraded_host": reg_r.counter(
                "router_degraded_total").value({"tier": "host"}),
            "degraded_rules": reg_r.counter(
                "router_degraded_total").value({"tier": "rules"}),
            "shed": reg_r.counter("router_shed_total").value(),
            "scorer_edge_failures": reg_r.counter(
                "router_score_errors_total").value(),
            "breaker_opens": (router._breaker.opens
                              if router._breaker is not None else 0),
            # the acceptance surface: breaker-state gauges reach /metrics
            # through the same registry the exporter scrapes
            "breaker_gauge_exported": "ccfd_breaker_state" in reg_r.render(),
        },
        "lockcheck": {
            "enabled": lock_graph is not None,
            "violations": (len(lock_graph.violations)
                           if lock_graph is not None else 0),
            "cycles": ([v["cycle"] for v in lock_graph.violations]
                       if lock_graph is not None else []),
        },
        "accounting": {
            "starts": acct["starts"],
            "completes": acct["completes"],
            "rolled_back": acct["rolled_back"],
            "restore_markers": acct["restore_markers"],
            "still_active": len(active_now),
            "ghost_open": len(ghost),
            "tail_completions": len(tail_completed),
            "unaudited_active": len(unaudited),
            "violations": acct["violations"],
            "violation_count": acct["violation_count"],
            "ok": acct_ok,
        },
    }
    router.resume()
    sup.stop()
    broker.close()
    print(json.dumps(result))
    fr = result["flight_recorder"]
    ok = (
        total > 0
        and (lock_graph is None or not lock_graph.violations)
        and wedge_info.get("device_path_recovered", False)
        # a watchdog kill without a ring snapshot would be exactly the
        # un-post-mortem-able kill ISSUE 10 closes
        and (fr["watchdog_timeouts"] == 0
             or fr["dispatch_timeout_snapshots"] > 0)
        and wedge_info.get("healed_at_tx", 0) > wedge_info.get("wedged_at_tx", 0)
        and result["engine_kills"] > 0
        and coord.restores > 0
        and bus_check.get("end_offsets_equal", False)
        and bus_check.get("group_offsets_equal", False)
        and ("bus" not in targets
             or (result["bus_kills"] > 0 and broker.crash_restarts > 0))
        and acct_ok
        and (
            not args.audit
            or (
                # decision-record conservation through the storm: routed
                # == recorded exactly, nothing silently lost to the audit
                # disk, and re-stamped coordinates only where a crash
                # restore legitimately re-drove the stream
                audit_res.get("conserved", False)
                and audit_res.get("dropped_log_write", 0) == 0
                and (audit_res.get("restamped", 0) == 0
                     or coord.restores > 0)
            )
        )
        # verdict-parity conservation (--replay): the storm-recorded
        # window re-scored through the same stack with zero drops, zero
        # ghosts and no divergence a lifecycle promote doesn't explain
        and (not args.replay or replay_res.get("ok", False))
        and (
            not args.lifecycle
            or (
                # the pool ends on ONE consistent model version: serving
                # params equal the champion checkpoint, no challenger slot
                # or canary gate dangling, and transitions actually cycled
                # under the storm. Under --storage-faults the on-disk
                # champion bytes may be storm-corrupt (quarantined, never
                # served) — the recorded-fingerprint parity or an audited
                # fallback/pin then carries the consistency claim.
                (lifecycle_res.get("serving_matches_champion_checkpoint")
                 or (args.storage_faults and (
                     lifecycle_res["storage"]["serving_fp_matches_lineage"]
                     or lifecycle_res["storage"]["fallback_restores"] > 0
                     or lifecycle_res["storage"]["storage_pins"] > 0)))
                and lifecycle_res.get("serving_consistent")
                and lifecycle_res.get("challenger_cleared")
                and lifecycle_res.get("gate_inactive")
                and lifecycle_res.get("versions", 0) > 1
            )
        )
        and (
            not args.storage_faults
            or (
                # storage storms actually fired and injected, writes
                # failed LOUDLY (counted) or corruption was quarantined —
                # and the run survived them all with the accounting claim
                # (acct_ok above) intact: zero corrupt artifacts served
                lifecycle_res["storage"]["storm_windows"] > 0
                and sum(lifecycle_res["storage"]["injected"].values()) > 0
            )
        )
        and (
            not args.device_faults
            or (
                # the full heal ladder, end to end, every storm window:
                # wedge injected -> QUARANTINED (host tier serving, the
                # acct_ok above proving zero violations) -> healed ->
                # WARM re-promotion (no serving-stage compiles after the
                # flip) -> device serving again at the end
                len(device_cycles) > 0
                and all(c["quarantined"] and c["healed"] and c["warm"]
                        for c in device_cycles)
                and result["device_heal"]["final_state"] == "healthy"
                and result["device_heal"]["health_gauge_exported"]
            )
        )
        and (
            not args.net_faults
            or (
                # degraded edges drilled AND absorbed: storms fired, the
                # ladder scored through them (host tier and/or rules
                # floor), the breaker surface is on /metrics, and — via
                # acct_ok above — accounting stayed violation-free while
                # degraded
                result["net_faults"]["windows"] > 0
                and (result["net_faults"]["degraded_host"]
                     + result["net_faults"]["degraded_rules"]) > 0
                and result["net_faults"]["breaker_gauge_exported"]
            )
        )
    )
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
