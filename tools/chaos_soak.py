"""Chaos soak: the full pipeline under STATEFUL failures, with accounting.

Round 2 soaked router kills (the one component with no state); round 3
added a mid-soak device wedge. This round the ChaosMonkey also kills the
ENGINE — the stateful tier — and every kill is a real crash-recovery:
the supervisor's reset hook restores the last aligned checkpoint
(runtime/recovery.py: engine snapshot + bus-offset rewind) and the
re-driven records flow through the SAME live router.  The durable bus
(segment log) underpins the replay; at the soak midpoint the scorer's
device path additionally wedges for ``--wedge-s`` (dispatch-deadline
failover), and a bus crash-reopen drill verifies a second Broker replayed
from the same log agrees with the live one on every end offset and
committed group offset.

At the end, the audit stream (per-partition offset order, with the
coordinator's per-partition ``engine_restored`` markers) is walked for the
accounting invariant: within each engine epoch every started instance
reaches a terminal state exactly once or is still active in the final
engine; work a dead epoch did past its last checkpoint is counted as
rolled back (at-least-once redelivery, like Kafka into a restarted KIE
pod — reference deploy/ccd-service.yaml); nothing else may be lost or
double-completed.

    JAX_PLATFORMS=cpu python tools/chaos_soak.py --seconds 240

Prints one JSON line; record it in BASELINE.md.  Exit 0 only when the
pipeline drained, the device path recovered, engine kills happened and
every accounting check passed.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # hermetic: never dial a tunnel

import numpy as np  # noqa: E402

from ccfd_tpu.bus.broker import Broker  # noqa: E402
from ccfd_tpu.config import Config  # noqa: E402
from ccfd_tpu.data.ccfd import FEATURE_NAMES, synthetic_dataset  # noqa: E402
from ccfd_tpu.metrics.prom import Registry  # noqa: E402
from ccfd_tpu.models import mlp  # noqa: E402
from ccfd_tpu.process.fraud import build_engine  # noqa: E402
from ccfd_tpu.router.router import Router  # noqa: E402
from ccfd_tpu.runtime.chaos import ChaosMonkey  # noqa: E402
from ccfd_tpu.runtime.recovery import (  # noqa: E402
    CheckpointCoordinator,
    attach_engine_service,
)
from ccfd_tpu.runtime.supervisor import Supervisor  # noqa: E402
from ccfd_tpu.serving.scorer import Scorer  # noqa: E402


def audit_accounting(broker: Broker, topic: str) -> dict:
    """Walk the audit stream for the at-least-once accounting invariant.

    Pids are partition-sticky (events keyed by pid) and the restore marker
    reaches every partition, so each partition's offset order is ground
    truth — the walk keeps PER-PARTITION state (a marker repeats once per
    partition and must only affect that partition's pids).  At an
    ``engine_restored`` marker (runtime/recovery.py) everything the dead
    epoch did past its last checkpoint rolls back: starts/completions of
    pids >= next_pid (instances born after the cut) and completions of
    pids in ``active_pids`` (instances restored as live again, whose
    post-cut terminal events are undone and may legitimately recur).
    Anything else lost or double-completed is a violation."""
    starts = completes = rolled_back = markers = 0
    violations: list[str] = []
    c = broker.consumer("soak-audit-check", (topic,))
    by_part: dict[int, list] = {}
    while True:
        recs = c.poll(50_000, timeout_s=0.2)
        if not recs:
            break
        for r in recs:
            by_part.setdefault(r.partition, []).append(r.value)
    c.close()
    open_at_end: set[int] = set()
    for events in by_part.values():
        open_p: set[int] = set()
        done_p: set[int] = set()
        seen_p: set[int] = set()
        for ev in events:
            kind = ev.get("event")
            if kind == "engine_restored":
                markers += 1
                restored = set(ev.get("active_pids", ())) & seen_p
                void_open = {x for x in open_p if x >= ev["next_pid"]}
                void_done = {x for x in done_p if x >= ev["next_pid"]}
                undone = done_p & restored
                rolled_back += len(void_open) + len(void_done) + len(undone)
                open_p = restored
                done_p -= void_done | undone
            elif kind == "process_started":
                starts += 1
                seen_p.add(ev["pid"])
                if ev["pid"] in open_p:
                    violations.append(f"double start pid={ev['pid']}")
                open_p.add(ev["pid"])
            elif kind == "process_completed":
                completes += 1
                if ev["pid"] in done_p:
                    violations.append(f"double complete pid={ev['pid']}")
                elif ev["pid"] not in open_p:
                    violations.append(f"complete without start pid={ev['pid']}")
                else:
                    open_p.discard(ev["pid"])
                    done_p.add(ev["pid"])
        open_at_end |= open_p
    return {
        "starts": starts,
        "completes": completes,
        "rolled_back": rolled_back,
        "restore_markers": markers,
        "open_at_end": open_at_end,
        "violations": violations[:20],
        "violation_count": len(violations),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=240.0)
    ap.add_argument("--wedge-s", type=float, default=20.0,
                    help="device-wedge duration at the soak midpoint")

    def _positive_ms(v: str) -> float:
        f = float(v)
        if f <= 0:
            raise argparse.ArgumentTypeError(
                "the soak exercises the dispatch deadline; it must be > 0"
            )
        return f

    ap.add_argument("--deadline-ms", type=_positive_ms, default=250.0)
    ap.add_argument("--feed-batch", type=int, default=2000)
    ap.add_argument("--checkpoint-s", type=float, default=3.0)
    ap.add_argument("--chaos-interval-s", type=float, default=15.0)
    ap.add_argument("--targets", default="router,engine",
                    help="comma list for the ChaosMonkey")
    ap.add_argument("--bus-log", default="",
                    help="durable bus log dir (default: fresh tempdir)")
    ap.add_argument("--bus-drill-tx", type=int, default=40_000,
                    help="run the bus crash-reopen drill once this many "
                    "transactions have flowed (early: replaying the log is "
                    "O(records), so the drill must run on a bounded log, "
                    "not the multi-million-record end state)")
    args = ap.parse_args()

    bus_dir = args.bus_log or tempfile.mkdtemp(prefix="ccfd_soak_bus_")
    # audit ON: it is the accounting ledger this soak asserts over
    cfg = Config(confidence_threshold=1.0, audit_topic="ccd-audit")
    broker = Broker(log_dir=bus_dir)
    reg_r, reg_k, reg_c = Registry(), Registry(), Registry()

    def engine_factory():
        return build_engine(cfg, broker, reg_k, None)

    engine = engine_factory()

    ds = synthetic_dataset(n=4096, fraud_rate=0.002, seed=0)
    params = mlp.init(jax.random.PRNGKey(0))
    params = mlp.set_normalizer(params, ds.X.mean(0), ds.X.std(0))
    # push probabilities to a trained-model-like range (bench.py does the
    # same): an untrained MLP fires ~half of all traffic into the fraud
    # process, which floods the engine with open investigations at a rate
    # no investigator pool could match and turns the soak into a
    # snapshot-size stress test instead of a failure drill
    import jax.numpy as jnp

    params = dict(params)
    params["layers"] = [dict(l) for l in params["layers"]]
    params["layers"][-1]["b"] = jnp.asarray([-4.0], jnp.float32)
    scorer = Scorer(model_name="mlp", params=params,
                    batch_sizes=(128, 1024, 4096), host_tier_rows=64,
                    dispatch_deadline_ms=args.deadline_ms)
    wedged, release = threading.Event(), threading.Event()
    orig_apply = scorer._apply

    def gated(p, xx):
        if wedged.is_set():
            release.wait(timeout=120.0)
        return orig_apply(p, xx)

    scorer._apply = gated
    scorer.warmup()
    from ccfd_tpu.utils.gctune import tune_for_service

    tune_for_service()  # match the gc config services run with
    scorer._wedge._probe_interval_s = 2.0  # tight recovery for the soak

    router = Router(cfg, broker, scorer.score, engine, reg_r, max_batch=4096)
    coord = CheckpointCoordinator(router, broker, engine_factory,
                                  interval_s=args.checkpoint_s)
    sup = Supervisor(backoff_initial_s=0.05, backoff_cap_s=0.5)
    sup.add_thread_service(
        "router", lambda: router.run(poll_timeout_s=0.02), router.stop,
        reset=router.reset,
    )
    attach_engine_service(sup, coord)
    sup.start()
    coord.start()

    # feeder: keep the topic loaded without unbounded backlog; the gate
    # lets the bus drill quiesce production without killing the thread
    rows = [
        {FEATURE_NAMES[j]: float(ds.X[i, j]) for j in range(30)} | {"id": i}
        for i in range(args.feed_batch)
    ]
    stop_feed = threading.Event()
    feed_gate = threading.Event()
    feed_gate.set()
    produced = [0]

    def feed() -> None:
        while not stop_feed.is_set():
            feed_gate.wait(timeout=1.0)
            if not feed_gate.is_set():
                continue
            done = router._c_in.value()
            if produced[0] - done < 200_000:
                broker.produce_batch(cfg.kafka_topic, rows)
                produced[0] += len(rows)
            else:
                time.sleep(0.01)

    feeder = threading.Thread(target=feed, daemon=True)
    feeder.start()

    # -- investigators: the PRODUCT service working the task queue ---------
    # Without them every flagged transaction parks an instance forever and
    # the aligned-checkpoint cost grows without bound — unrealistic (the
    # reference demo has humans working the KIE console queue) and it
    # turns the soak into a snapshot-size benchmark. The engine reference
    # follows crash-recovery swaps via the indirection below, and
    # individual completion failures (task rolled back mid-restore, dead
    # engine) are the service's normal skip path.
    from ccfd_tpu.process.investigator import InvestigatorService

    class CurrentEngine:
        """Resolve the live engine per call (restores swap it)."""

        def tasks(self, status="open"):
            return router.engine.tasks(status)

        def complete_task(self, task_id, outcome):
            return router.engine.complete_task(task_id, outcome)

    investigator = InvestigatorService(
        CurrentEngine(), Registry(), rate_per_s=0.0,  # unthrottled: soak
        trust_threshold=0.9, base_fraud_rate=0.05, seed=7,
    )
    invest_thread = threading.Thread(target=investigator.run, daemon=True)
    invest_thread.start()

    # -- bus crash-reopen drill (bounded log, under way) -------------------
    bus_check: dict = {}
    drill_deadline = time.time() + 60
    while (router._c_in.value() < args.bus_drill_tx
           and time.time() < drill_deadline):
        time.sleep(0.25)
    feed_gate.clear()
    acked = router.pause(10.0)
    try:
        live_before = {t: broker.end_offsets(t)
                       for t in (cfg.kafka_topic, cfg.audit_topic)}
        committed_before = broker.committed_offsets("router", cfg.kafka_topic)
        # Replay a COPY of the log dir, never the live one: opening a
        # Broker replays in place — offsets.log compaction would
        # os.replace() the file out from under the live broker's append
        # fd (silently killing offset durability for the rest of the
        # run), and torn-tail truncation would mutate live segments. The
        # copy is also the honest model: a crashed process's disk as the
        # restarting process finds it.
        import shutil

        copy_dir = tempfile.mkdtemp(prefix="ccfd_soak_busdrill_")
        shutil.rmtree(copy_dir)
        shutil.copytree(bus_dir, copy_dir)
        replayed = Broker(log_dir=copy_dir)
        rep_ends = {t: replayed.end_offsets(t) for t in live_before}
        rep_committed = replayed.committed_offsets("router", cfg.kafka_topic)
        replayed.close()
        shutil.rmtree(copy_dir, ignore_errors=True)
        live_after = {t: broker.end_offsets(t) for t in live_before}
        # prefix-consistency: background timers may append between the
        # live read and the copy, so the replayed view must sit between
        # the two live reads
        ends_ok = all(
            live_before[t][p] <= rep_ends[t][p] <= live_after[t][p]
            for t in live_before for p in range(len(live_before[t]))
        )
        bus_check = {
            "at_tx": int(router._c_in.value()),
            "barrier_acked": acked,
            "end_offsets_equal": ends_ok,
            "group_offsets_equal": rep_committed == committed_before,
        }
    finally:
        router.resume()
        feed_gate.set()

    targets = [t for t in args.targets.split(",") if t]
    monkey = ChaosMonkey(sup, seed=11, targets=targets,
                         registry=reg_c, interval_s=args.chaos_interval_s)
    monkey.start()

    t0 = time.time()
    t_wedge = t0 + args.seconds / 2
    wedge_done = False
    wedge_info: dict = {}
    last_progress, last_in = time.time(), 0
    max_stall_s = 0.0
    while time.time() - t0 < args.seconds:
        time.sleep(1.0)
        cur = router._c_in.value()
        if cur > last_in:
            last_in, last_progress = cur, time.time()
        max_stall_s = max(max_stall_s, time.time() - last_progress)
        if not wedge_done and time.time() >= t_wedge:
            wedge_info["wedged_at_tx"] = cur
            wedged.set()
            time.sleep(args.wedge_s)
            wedged.clear()
            release.set()
            wedge_done = True
            wedge_info["healed_at_tx"] = router._c_in.value()
            # recovery: the probe should clear the wedge promptly
            t_rec = time.time()
            while scorer._wedge.wedged and time.time() - t_rec < 60:
                time.sleep(0.5)
            wedge_info["recovered_s_after_heal"] = round(time.time() - t_rec, 1)
            wedge_info["device_path_recovered"] = not scorer._wedge.wedged

    stop_feed.set()
    investigator.stop()
    invest_thread.join(timeout=10)
    monkey.stop()
    coord.stop()
    elapsed = time.time() - t0
    # drain the backlog so the accounting walk sees a settled stream, then
    # park the router for the final engine-state comparison
    settle = time.time() + 20
    prev = -1
    while time.time() < settle:
        cur = router._c_in.value()
        if cur == prev:
            break
        prev = cur
        time.sleep(1.0)
    router.pause(10.0)

    total = router._c_in.value()
    final_engine = router.engine
    acct = audit_accounting(broker, cfg.audit_topic)
    with final_engine.state_lock:
        active_now = {i.pid for i in final_engine.instances("active")}
    # every audit-open pid must be live in the final engine and vice versa;
    # a pid open in the walked stream but terminal in the engine is just a
    # timer completion whose audit event landed after the walk (tail), not
    # a loss — verify instead of excusing blindly
    ghost = acct["open_at_end"] - active_now
    tail_completed = set()
    for pid in list(ghost):
        try:
            if final_engine.instance(pid).status != "active":
                tail_completed.add(pid)
        except KeyError:
            pass  # evicted == long-terminal: still a real ghost
    ghost -= tail_completed
    unaudited = active_now - acct["open_at_end"]
    acct_ok = not acct["violation_count"] and not ghost and not unaudited

    kills: dict[str, int] = {}
    for _ts, name in monkey.history:
        kills[name] = kills.get(name, 0) + 1
    status = sup.status()
    result = {
        "seconds": round(elapsed, 1),
        "tx_total": int(total),
        "tx_s": round(total / elapsed, 1),
        "targets": targets,
        "kills": kills,
        "engine_kills": kills.get("engine", 0),
        "router_kills": kills.get("router", 0),
        "supervisor_restarts": {n: s["restarts"] for n, s in status.items()},
        "checkpoints": coord.checkpoints,
        "checkpoint_skips": coord.skipped,
        "restores": coord.restores,
        "max_progress_stall_s": round(max_stall_s, 1),
        "wedge": wedge_info,
        "bus_reopen_check": bus_check,
        "dispatch_timeouts": scorer.dispatch_timeouts,
        "host_fallback_scores": scorer.host_fallback_scores,
        "tasks_completed_by_investigators": investigator.completed,
        "accounting": {
            "starts": acct["starts"],
            "completes": acct["completes"],
            "rolled_back": acct["rolled_back"],
            "restore_markers": acct["restore_markers"],
            "still_active": len(active_now),
            "ghost_open": len(ghost),
            "tail_completions": len(tail_completed),
            "unaudited_active": len(unaudited),
            "violations": acct["violations"],
            "violation_count": acct["violation_count"],
            "ok": acct_ok,
        },
    }
    router.resume()
    sup.stop()
    broker.close()
    print(json.dumps(result))
    ok = (
        total > 0
        and wedge_info.get("device_path_recovered", False)
        and wedge_info.get("healed_at_tx", 0) > wedge_info.get("wedged_at_tx", 0)
        and result["engine_kills"] > 0
        and coord.restores > 0
        and bus_check.get("end_offsets_equal", False)
        and bus_check.get("group_offsets_equal", False)
        and acct_ok
    )
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
