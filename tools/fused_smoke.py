"""Fused-decision smoke: the live operator routes through ONE executable
(ISSUE 19).

Exit-code-gated drill for ``tools/verify_tier1.sh --fused-smoke``:

1. **Arm**: a CR with ``scorer.fused_decision: true`` (and the lifecycle
   lane off — the canary gate would override scores after the fused
   verdict fires, so the operator refuses the combination) brings up the
   full platform with the fused plane armed and precompiled.
2. **Route**: 512 produced transactions flow bus -> router -> fused
   decision executable -> engine. Accounting must conserve exactly:
   incoming == outgoing == 512, every row through the fused grid
   (``staged_fallbacks == 0``), per-bucket dispatch counters > 0.
3. **Parity**: the SAME records re-scored through the staged seam
   (``score`` + host ``RuleSet.evaluate``) must match the fused verdicts
   with ZERO delta — bit-equal probabilities, identical fired indices.
4. **HTTP**: the fused executable grid (model, buckets, per-bucket
   dispatch counts) scrapes from the exporter's ``/debug/device``
   inventory over real HTTP, and the ``fused_decision_*`` counters
   appear on ``/prometheus/router``.
5. **Warm**: zero serving-stage compiles after warmup — every compile
   the routing window triggered sits in a NON_SERVING stage
   (``fused.warm`` included), none on the serving path.

    JAX_PLATFORMS=cpu python tools/fused_smoke.py
    tools/verify_tier1.sh --fused-smoke

Prints one JSON line plus ``FUSEDSMOKE verdict=PASS|FAIL``; exit 0 only
when every check holds.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # hermetic: never dial a tunnel

import numpy as np  # noqa: E402

from ccfd_tpu.config import Config  # noqa: E402
from ccfd_tpu.data.ccfd import synthetic_dataset  # noqa: E402
from ccfd_tpu.platform.operator import Platform, PlatformSpec  # noqa: E402
from ccfd_tpu.runtime.heal import NON_SERVING_COMPILE_STAGES  # noqa: E402


def _cr() -> dict:
    return {
        "apiVersion": "ccfd.tpu/v1",
        "kind": "FraudDetectionPlatform",
        "spec": {
            "store": {"enabled": False},
            "bus": {"partitions": 2},
            "scorer": {"enabled": True, "model": "mlp", "train_steps": 0,
                       "fused_decision": True},
            # the fused plane refuses to arm next to the canary gate —
            # scores would be overridden AFTER the fused verdict fired
            "lifecycle": {"enabled": False},
            "engine": {"enabled": True},
            "notify": {"enabled": True, "seed": 0},
            "router": {"enabled": True},
            "producer": {"enabled": False},
            "monitoring": {"enabled": True},
            "health": {"enabled": False},
        },
    }


def _serving_compiles(prof) -> int:
    return sum(v for stage, v in prof.compile_counts().items()
               if stage not in NON_SERVING_COMPILE_STAGES)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=512)
    ap.add_argument("--timeout-s", type=float, default=60.0)
    args = ap.parse_args()

    checks: dict[str, bool] = {}
    detail: dict = {}

    cfg = Config(customer_reply_timeout_s=0.2)
    p = Platform(PlatformSpec.from_cr(_cr(), cfg=cfg)).up(wait_ready_s=30.0)
    try:
        fds = p.fused_decision
        checks["fused_plane_armed"] = fds is not None and fds.enabled
        if fds is None:
            raise RuntimeError("fused decision plane did not arm")

        # warmup precompiled the grid during up(); everything after this
        # point is the serving window and must not compile
        warm_serving = _serving_compiles(p.profiler) if p.profiler else 0

        ds = synthetic_dataset(n=max(args.rows, 1024), fraud_rate=0.02,
                               seed=7)
        rows = [",".join(f"{v:.6g}" for v in ds.X[i]).encode()
                for i in range(args.rows)]
        keys = [f"tx-{i:05d}" for i in range(args.rows)]
        p.broker.produce_batch(cfg.kafka_topic, rows, keys)

        reg = p.registries["router"]
        out = reg.counter("transaction_outgoing_total")

        def routed() -> int:
            return int(out.value(labels={"type": "standard"})
                       + out.value(labels={"type": "fraud"}))

        deadline = time.monotonic() + args.timeout_s
        while time.monotonic() < deadline and routed() < args.rows:
            time.sleep(0.05)

        # -- 2. conservation + every row through the fused grid ------------
        n_in = int(reg.counter("transaction_incoming_total").value())
        n_out = routed()
        dispatches = sum(fds._dispatch_counts.values())
        checks["accounting_conserved"] = (
            n_in == n_out == args.rows)
        checks["all_rows_fused"] = (
            dispatches >= 1 and fds.staged_fallbacks == 0)
        detail["accounting"] = {
            "incoming": n_in, "outgoing": n_out,
            "fused_dispatches": dispatches,
            "staged_fallbacks": fds.staged_fallbacks,
        }

        # -- 4. the grid + per-bucket counters over real HTTP --------------
        # (scraped BEFORE the parity re-decide below so the HTTP counts
        # compare against the routing window's dispatch count exactly)
        metrics = p.status()["endpoints"]["metrics"]
        with urllib.request.urlopen(metrics + "/debug/device",
                                    timeout=10) as resp:
            dev = json.loads(resp.read())
        grid = (dev.get("executables") or {}).get("fused_decision") or {}
        http_counts = {int(k): int(v)
                       for k, v in (grid.get("dispatches") or {}).items()}
        checks["grid_scraped_http"] = (
            grid.get("enabled") is True
            and grid.get("model") == "mlp"
            and sum(http_counts.values()) == dispatches
            and all(v >= 1 for v in http_counts.values()))
        detail["grid"] = {k: grid.get(k) for k in (
            "model", "forward", "rules", "batch_sizes", "dispatches")}
        with urllib.request.urlopen(metrics + "/prometheus/router",
                                    timeout=10) as resp:
            scrape = resp.read().decode()
        checks["counters_scraped_http"] = (
            "fused_decision_dispatches_total" in scrape)

        # -- 3. parity: the same records through the staged seam -----------
        x = np.asarray(
            [[float(t) for t in r.decode().split(",")] for r in rows],
            np.float32)
        p_fused, f_fused = fds.decide(x)
        p_staged = np.asarray(p.scorer.score(x), np.float32)
        f_staged = fds.rules.evaluate(x, p_staged)
        checks["parity_zero_delta"] = bool(
            f_fused is not None
            and np.array_equal(p_fused, p_staged)
            and np.array_equal(f_fused, f_staged))
        detail["parity"] = {
            "rows": int(x.shape[0]),
            "proba_max_delta": float(np.abs(p_fused - p_staged).max()),
            "fired_mismatches": (int((f_fused != f_staged).sum())
                                 if f_fused is not None else -1),
        }

        # -- 5. zero serving-stage compiles after warmup -------------------
        if p.profiler is not None:
            now_serving = _serving_compiles(p.profiler)
            checks["zero_serving_compiles_after_warmup"] = (
                now_serving == warm_serving)
            detail["compiles"] = {
                "serving_during_window": now_serving - warm_serving,
                "stages": p.profiler.compile_counts(),
            }
    finally:
        p.down()

    ok = all(checks.values())
    print(json.dumps({"checks": checks, "detail": detail}, sort_keys=True))
    print(f"FUSEDSMOKE verdict={'PASS' if ok else 'FAIL'}", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
