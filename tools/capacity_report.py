"""Render a ccfd.capacity.v1 document into the human capacity summary.

The CapacityModel (observability/capacity.py) serves its fitted queueing
model at ``/capacity``; this tool is the operator's first read — which
stage is the bottleneck and at what admitted rate, per-stage utilization
and headroom, predicted vs observed p99 with the model's own error
ratio, any service-curve regressions in flight — and, with ``--workers/
--batch/--deadline-ms/--max-inflight``, the what-if verdict for a
proposed actuator move.

    python tools/capacity_report.py --url http://host:9100
    python tools/capacity_report.py --url ... --workers 4 --batch 2048
    python tools/capacity_report.py capacity.json        # from disk
    python tools/capacity_report.py ... --json           # machine form

Exit codes: 0 rendered a valid document, 2 missing/unreadable, 3 the
document fails schema validation (still rendered best-effort).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.parse
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ccfd_tpu.observability.capacity import validate_capacity  # noqa: E402


def load_doc(args) -> dict | None:
    if args.url:
        query = {k: v for k, v in (
            ("workers", args.workers), ("batch", args.batch),
            ("deadline_ms", args.deadline_ms),
            ("max_inflight", args.max_inflight)) if v is not None}
        path = "/capacity/whatif" if query else "/capacity"
        url = args.url.rstrip("/") + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        with urllib.request.urlopen(url, timeout=10) as resp:
            return json.loads(resp.read().decode())
    try:
        with open(args.doc) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        print(f"cannot read document {args.doc!r}: {e}", file=sys.stderr)
        return None


def render(doc: dict) -> str:
    lines = []
    when = doc.get("generated_unix")
    when_s = (time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(when))
              if isinstance(when, (int, float)) else "?")
    model = doc.get("model", {})
    lines.append(f"CAPACITY [{when_s}]  model={model.get('kind', '?')}  "
                 f"window={doc.get('window_s')}s  "
                 f"refreshes={doc.get('refreshes')}")
    act = doc.get("actuators", {})
    lines.append("  actuators: " + ", ".join(
        f"{k}={v}" for k, v in act.items() if v is not None))
    bn = doc.get("bottleneck")
    if bn:
        cap = (f" of {bn.get('max_rows_per_s')} max"
               if bn.get("max_rows_per_s") else "")
        lines.append(
            f"  bottleneck: {bn.get('stage')} [{bn.get('layer')}]  "
            f"headroom {bn.get('headroom_ratio')}x  "
            f"rho={bn.get('utilization')}  "
            f"admitted {bn.get('admitted_rows_per_s')} rows/s{cap}")
    e2e = doc.get("e2e", {})
    if e2e:
        err = e2e.get("error_ratio")
        lines.append(
            f"  e2e p99: predicted {e2e.get('predicted_p99_ms')} ms vs "
            f"observed {e2e.get('observed_p99_ms')} ms"
            + (f"  (error ratio {err} — trust the model while this is "
               "small)" if err is not None else ""))
    stages = doc.get("stages", {})
    if stages:
        lines.append("  stage             layer     rows/s      rho  "
                     "headroom  pred p99    obs p99")
        for name in sorted(stages):
            e = stages[name]
            knee = e.get("knee") or {}
            lines.append(
                f"    {name:<15} {e.get('layer', '?'):<9}"
                f"{e.get('arrival_rows_per_s', 0):>9} "
                f"{e.get('utilization', 0):>8} "
                f"{e.get('headroom_ratio', 0):>8}x "
                f"{e.get('predicted_p99_ms', '-'):>9} "
                f"{e.get('observed_p99_ms', '-'):>10}"
                + (f"   knee@{knee['batch']}" if knee else ""))
        regs = {
            name: e["regression"] for name, e in sorted(stages.items())
            if (e.get("regression") or {}).get("fired_total")
            or (e.get("regression") or {}).get("in_regression")
        }
        for name, r in regs.items():
            flag = "IN REGRESSION" if r.get("in_regression") else "recovered"
            lines.append(
                f"  !! {name}: service curve {flag} — fitted/baseline "
                f"ratio {r.get('ratio')} (baseline "
                f"{r.get('baseline_mean_ms')} ms, fired "
                f"{r.get('fired_total')}x)")
    wi = doc.get("whatif")
    if wi:
        req = ", ".join(f"{k}={v}" for k, v in
                        (wi.get("requested") or {}).items())
        delta = wi.get("delta_p99_ms")
        arrow = "worsens" if (delta or 0) > 0 else "improves"
        lines.append(
            f"  what-if [{req}]: predicted e2e p99 "
            f"{wi.get('base_predicted_p99_ms')} -> "
            f"{wi.get('predicted_p99_ms')} ms ({arrow} by "
            f"{abs(delta) if delta is not None else '?'} ms)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("doc", nargs="?", help="capacity JSON path")
    ap.add_argument("--url", default="",
                    help="exporter endpoint; fetch over HTTP instead")
    ap.add_argument("--workers", type=int, default=None,
                    help="what-if: router/batcher worker count")
    ap.add_argument("--batch", type=int, default=None,
                    help="what-if: batch size")
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="what-if: batcher deadline")
    ap.add_argument("--max-inflight", type=int, default=None,
                    help="what-if: admission ceiling")
    ap.add_argument("--json", action="store_true",
                    help="print the machine summary instead of prose")
    args = ap.parse_args(argv)
    if not args.url and not args.doc:
        ap.error("need a document path or --url")
    doc = load_doc(args)
    if doc is None:
        return 2
    errs = validate_capacity(doc)
    if args.json:
        print(json.dumps({
            "bottleneck": (doc.get("bottleneck") or {}).get("stage"),
            "predicted_p99_ms": doc.get("e2e", {}).get("predicted_p99_ms"),
            "observed_p99_ms": doc.get("e2e", {}).get("observed_p99_ms"),
            "error_ratio": doc.get("e2e", {}).get("error_ratio"),
            "whatif": doc.get("whatif"),
            "valid": not errs,
            "errors": errs[:10],
        }))
    else:
        print(render(doc))
        if errs:
            print(f"schema: INVALID ({len(errs)} problems)", file=sys.stderr)
    return 3 if errs else 0


if __name__ == "__main__":
    sys.exit(main())
