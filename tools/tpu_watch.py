"""Opportunistic TPU capture loop with a relay-leg fast path.

Round-4 triage (tools/tpu_triage.py, TPU_TRIAGE_r04.json) proved the wedge
is the axon relay's pool-service legs on 127.0.0.1:{8083,8093,8103,8113}
going refused: the PJRT client retries them forever and ``jax.devices()``
hangs.  A full jax probe costs ~45 s of subprocess timeout, so the old
watcher could only afford one every few minutes — but a dead TCP connect
costs ~100 µs, so this watcher pre-filters: poll the relay legs every
``--fast-interval`` (default 10 s) and only spend the jax probe when a leg
actually listens.  Healthy windows historically last minutes
(BASELINE.md "tunnel" notes); reacting in seconds instead of minutes is
the difference between a capture and another lost round.

On a confirmed-healthy probe it fires, in order, each in its own
subprocess with a watchdog:

  1. ``bench.py``            — full bench (quant + zoo sections armed),
                               refreshes BENCH_TPU_LAST_GOOD.json
  2. ``tools/rest_sweep.py`` — the pre-scripted REST north-star sweep
  3. ``tools/tpu_triage.py`` — records the healthy-state triage snapshot

It keeps watching after a capture and re-captures at most every
``--recapture-min`` minutes while the attachment stays healthy, so the
freshest possible evidence rides the round.  Exit: 0 after at least one
full TPU capture when the budget ends, 3 if none.

    python tools/tpu_watch.py --fast-interval 10 --max-hours 11 &
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "tpu_watch.log")
sys.path.insert(0, os.path.join(REPO, "tools"))
from tpu_triage import POOL_PORTS  # noqa: E402 — triage is the ground
# truth for the relay's leg set; a drifted copy here would have the
# watcher pre-filtering dead ports and skipping every healthy window


def log(msg: str) -> None:
    line = f"[{time.strftime('%H:%M:%S')}] {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def relay_legs_listening(timeout_s: float = 0.5) -> list[int]:
    """Which pool-service legs accept a TCP connect right now (~100 us per
    refused port on loopback — cheap enough for a 10 s cadence)."""
    alive = []
    for port in POOL_PORTS:
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=timeout_s):
                alive.append(port)
        except OSError:
            pass
    return alive


def probe(timeout_s: float) -> bool:
    """True iff the accelerator answers inside timeout_s (probed in a child
    process so a wedged tunnel can't hang the watcher itself)."""
    # The site hook supplies the accelerator platform; an explicit platform
    # list here could name an unregistered plugin and fail on a healthy one.
    code = "import jax; d = jax.devices(); import sys; sys.exit(0 if d else 1)"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, timeout=timeout_s, env=env, cwd=REPO,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False
    except Exception as e:  # pragma: no cover - defensive
        log(f"probe error: {e!r}")
        return False


def run_bench(bench_timeout_s: float) -> bool:
    """Run the full bench; True iff it captured on TPU (platform == tpu)."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.setdefault("CCFD_BENCH_QUANT", "1")
    env.setdefault("CCFD_BENCH_PROBE_ATTEMPTS", "2")
    try:
        r = subprocess.run(
            [sys.executable, "bench.py"], capture_output=True, text=True,
            timeout=bench_timeout_s, env=env, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        log("bench run exceeded its own watchdog + ours; treating as wedge")
        return False
    tail = (r.stdout or "").strip().splitlines()
    if not tail:
        log(f"bench produced no output (rc={r.returncode}); stderr tail: "
            f"{(r.stderr or '')[-300:]}")
        return False
    try:
        res = json.loads(tail[-1])
    except json.JSONDecodeError:
        log(f"bench last line not JSON: {tail[-1][:200]}")
        return False
    plat = res.get("platform", "")
    log(f"bench finished: platform={plat} metric={res.get('value')}")
    return plat == "tpu"


def run_tool(argv: list[str], timeout_s: float, label: str) -> bool:
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    try:
        r = subprocess.run(argv, capture_output=True, text=True,
                           timeout=timeout_s, env=env, cwd=REPO)
        log(f"{label}: rc={r.returncode}")
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        log(f"{label}: exceeded {timeout_s:.0f}s watchdog")
        return False


def capture_pipeline(bench_timeout_s: float) -> bool:
    """The whole evidence suite, cheapest-to-lose last."""
    got_tpu = run_bench(bench_timeout_s)
    if got_tpu:
        log("TPU capture secured (BENCH_TPU_LAST_GOOD.json refreshed)")
    # The sweep runs its own probe and falls back honestly; fire it even if
    # the bench lost the window mid-run — partial evidence beats none.
    run_tool([sys.executable, "tools/rest_sweep.py"], 900.0, "rest_sweep")
    run_tool([sys.executable, "tools/tpu_triage.py", "--no-trace",
              "--probe-s", "30"], 300.0, "triage snapshot")
    return got_tpu


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast-interval", type=float, default=10.0,
                    help="seconds between TCP pre-filter polls")
    ap.add_argument("--slow-every", type=int, default=30,
                    help="full jax probe anyway every N fast polls, in case "
                    "the attachment path changes away from the known legs")
    ap.add_argument("--probe-timeout", type=float, default=45.0)
    ap.add_argument("--bench-timeout", type=float, default=2400.0)
    ap.add_argument("--recapture-min", type=float, default=30.0,
                    help="minimum minutes between captures after a full "
                    "TPU capture")
    ap.add_argument("--retry-min", type=float, default=5.0,
                    help="minimum minutes before refiring the pipeline "
                    "after an attempt that did NOT land on TPU (a flash "
                    "wedge mid-bench must not refire the whole ~hour "
                    "suite back-to-back on the 1-core host)")
    ap.add_argument("--max-hours", type=float, default=11.0)
    args = ap.parse_args()

    deadline = time.time() + args.max_hours * 3600
    attempt = 0
    captured = 0
    last_attempt = 0.0   # any pipeline firing
    wait_min = 0.0       # minutes to hold off since last_attempt
    log(f"watch v2 started (fast={args.fast_interval}s, "
        f"budget={args.max_hours}h, legs={POOL_PORTS})")
    while time.time() < deadline:
        attempt += 1
        legs = relay_legs_listening()
        slow_n = max(int(args.slow_every), 1)
        go_slow = (attempt - 1) % slow_n == 0
        if not legs and not go_slow:
            time.sleep(args.fast_interval)
            continue
        # Hold off BEFORE spending a jax-import probe subprocess: inside
        # the window the probe's only possible outcome is "wait more",
        # and on the 1-core host it costs ~2 s of site hooks per spawn.
        held_min = (time.time() - last_attempt) / 60.0
        if last_attempt and held_min < wait_min:
            if legs:
                log(f"poll #{attempt}: legs {legs} up; holding "
                    f"{wait_min - held_min:.0f} more min before refire")
            time.sleep(args.fast_interval)
            continue
        if legs:
            log(f"poll #{attempt}: relay legs LISTENING {legs} — jax probe")
        if probe(args.probe_timeout):
            log(f"poll #{attempt}: HEALTHY — firing capture pipeline")
            got = capture_pipeline(args.bench_timeout)
            # stamp AFTER the pipeline: it can run ~an hour itself, and a
            # hold-off measured from its start would already be consumed
            last_attempt = time.time()
            if got:
                captured += 1
                wait_min = args.recapture_min
            else:
                wait_min = args.retry_min
        elif legs:
            log(f"poll #{attempt}: legs listening but probe hung — "
                f"wedge is beyond the relay (see tpu_triage.py)")
        else:
            # reached at most once per slow_n fast polls (~5 min default)
            log(f"poll #{attempt}: wedged (legs refused, slow probe hung)")
        time.sleep(args.fast_interval)
    log(f"budget exhausted; captures this run: {captured}")
    return 0 if captured else 3


if __name__ == "__main__":
    sys.exit(main())
