"""Opportunistic TPU capture loop with a relay-leg fast path.

Round-4 triage (tools/tpu_triage.py, TPU_TRIAGE_r04.json) proved the wedge
is the axon relay's pool-service legs on 127.0.0.1:{8083,8093,8103,8113}
going refused: the PJRT client retries them forever and ``jax.devices()``
hangs.  A full jax probe costs ~45 s of subprocess timeout, so the old
watcher could only afford one every few minutes — but a dead TCP connect
costs ~100 µs, so this watcher pre-filters: poll the relay legs every
``--fast-interval`` (default 10 s) and only spend the jax probe when a leg
actually listens.  Healthy windows historically last minutes
(BASELINE.md "tunnel" notes); reacting in seconds instead of minutes is
the difference between a capture and another lost round.

Legs listening IS the go signal (2026-07-31 field evidence: windows can
be ~1 minute and serve very few attachments, so a jax probe subprocess
here would spend one the measurements never get).  On open legs it fires:

  1. ``tools/flash_capture.py`` — single-dial, priority-ordered sections,
                                  flushes after each, merges completed
                                  sections into BENCH_TPU_LAST_GOOD.json
  2. ``bench.py`` + ``tools/tpu_triage.py`` — only when the flash
                                  completed (rc 0) and the legs still
                                  listen: the window has proven it can
                                  afford the full suite's attachments

A slow-path jax probe still runs every ``--slow-every`` polls with no
legs open, in case the relay's port set changes; a success there fires
the flash with ``--force-dial``.  It keeps watching after a capture and
re-captures at most every ``--recapture-min`` minutes while the
attachment stays healthy.  Exit: 0 after at least one TPU capture when
the budget ends, 3 if none.

    python tools/tpu_watch.py --fast-interval 10 --max-hours 11 &
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "tpu_watch.log")
sys.path.insert(0, os.path.join(REPO, "tools"))
# triage owns the relay's leg set AND the probe helper; a drifted copy
# here would have the watcher and the flash capture disagreeing on what
# 'window open' means
from tpu_triage import POOL_PORTS, legs_listening as relay_legs_listening  # noqa: E402
from flash_capture import DEFAULT_OUT as FLASH_OUT  # noqa: E402


def log(msg: str) -> None:
    line = f"[{time.strftime('%H:%M:%S')}] {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


class AvailabilityTimeline:
    """Round-long relay-leg availability record (VERDICT r4 item 8).

    Round 4 ended with one anecdote: the single observed heal coincided
    with a fresh builder session starting, and the window died ~8 minutes
    later.  This turns the watcher's existing fast polls into data: every
    sample updates counters, and transitions (closed<->open) are always
    persisted along with a heartbeat every ``heartbeat_every`` samples,
    so the round ends with an artifact that supports or refutes the
    session-start correlation instead of folklore.  Downsampling keeps
    the file small (~150 heartbeats over 12 h at the default cadence)
    while open windows are recorded exactly, with start/end timestamps.
    """

    def __init__(self, path: str, heartbeat_every: int = 30):
        self.path = path
        self.heartbeat_every = max(int(heartbeat_every), 1)
        self.started = time.time()
        self.n = 0
        self.n_open = 0
        self.last_open: bool | None = None
        self.samples: list[dict] = []
        self.windows: list[dict] = []   # one per observed open window

    @staticmethod
    def _iso(ts: float) -> str:
        return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))

    def record(self, legs: list[int]) -> None:
        """One fast-poll sample: updates the availability counters and the
        open-window ledger.  Only the loop's regular polls come here so
        open_fraction stays a poll statistic (events don't skew it)."""
        now = time.time()
        self.n += 1
        is_open = bool(legs)
        if is_open:
            self.n_open += 1
        transition = self.last_open is None or self.last_open != is_open
        if transition and is_open:
            self.windows.append({"opened": self._iso(now), "legs": legs})
        if transition and not is_open and self.windows \
                and "closed" not in self.windows[-1]:
            self.windows[-1]["closed"] = self._iso(now)
        self.last_open = is_open
        if transition or (self.n - 1) % self.heartbeat_every == 0:
            self.samples.append({"t": self._iso(now), "legs": legs})
            self.flush()

    def note(self, event: str, legs: list[int]) -> None:
        """Timestamped event sample (capture fired/done, budget end) —
        appended without touching the poll counters or window ledger."""
        self.samples.append({"t": self._iso(time.time()), "legs": legs,
                             "event": event})
        self.flush()

    def flush(self) -> None:
        doc = {
            "watcher_started": self._iso(self.started),
            "written": self._iso(time.time()),
            "poll_count": self.n,
            "open_poll_count": self.n_open,
            "open_fraction": round(self.n_open / max(self.n, 1), 5),
            "open_windows": self.windows,
            "note": "transitions always recorded; heartbeat every "
                    f"{self.heartbeat_every} fast polls",
            "samples": self.samples,
        }
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, self.path)
        except OSError as e:  # pragma: no cover - disk-full etc.
            log(f"availability flush failed: {e!r}")


def probe(timeout_s: float) -> bool:
    """True iff the accelerator answers inside timeout_s (probed in a child
    process so a wedged tunnel can't hang the watcher itself)."""
    # The site hook supplies the accelerator platform; an explicit platform
    # list here could name an unregistered plugin and fail on a healthy one.
    code = "import jax; d = jax.devices(); import sys; sys.exit(0 if d else 1)"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, timeout=timeout_s, env=env, cwd=REPO,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False
    except Exception as e:  # pragma: no cover - defensive
        log(f"probe error: {e!r}")
        return False


def run_bench(bench_timeout_s: float) -> bool:
    """Run the full bench; True iff it captured on TPU (platform == tpu)."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.setdefault("CCFD_BENCH_QUANT", "1")
    env.setdefault("CCFD_BENCH_PROBE_ATTEMPTS", "2")
    # fired only right after a successful flash: the window is proven
    # healthy, and the probe subprocess would spend an attachment
    env.setdefault("CCFD_BENCH_SKIP_PROBE", "1")
    try:
        r = subprocess.run(
            [sys.executable, "bench.py"], capture_output=True, text=True,
            timeout=bench_timeout_s, env=env, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        log("bench run exceeded its own watchdog + ours; treating as wedge")
        return False
    tail = (r.stdout or "").strip().splitlines()
    if not tail:
        log(f"bench produced no output (rc={r.returncode}); stderr tail: "
            f"{(r.stderr or '')[-300:]}")
        return False
    try:
        res = json.loads(tail[-1])
    except json.JSONDecodeError:
        log(f"bench last line not JSON: {tail[-1][:200]}")
        return False
    plat = res.get("platform", "")
    log(f"bench finished: platform={plat} metric={res.get('value')}")
    return plat == "tpu"


def run_tool(argv: list[str], timeout_s: float, label: str) -> bool:
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    try:
        r = subprocess.run(argv, capture_output=True, text=True,
                           timeout=timeout_s, env=env, cwd=REPO)
        log(f"{label}: rc={r.returncode}")
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        log(f"{label}: exceeded {timeout_s:.0f}s watchdog")
        return False


def run_flash(timeout_s: float, force_dial: bool = False) -> int:
    """One-dial flash capture (tools/flash_capture.py): the attach IS the
    probe, sections flush as they complete, and no subprocess probe spends
    an attachment first.  Returns its exit code (0 full TPU capture,
    2 partial TPU, 3 wedge, 4 legs closed, 5 non-TPU backend)."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    argv = [sys.executable, "tools/flash_capture.py"]
    if force_dial:
        argv.append("--force-dial")
    started = time.time()
    try:
        r = subprocess.run(
            argv, capture_output=True, text=True, timeout=timeout_s,
            env=env, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        # the flash flushes after every section, so classify an outer
        # timeout from the artifact instead of writing the window off —
        # but only if THIS run wrote it: a stale file from an earlier
        # window must not turn a total wedge into a "partial capture"
        path = FLASH_OUT
        try:
            fresh = os.path.getmtime(path) >= started
            with open(path) as f:
                snap = json.load(f)
            if fresh and snap.get("platform") == "tpu" and snap.get("result"):
                log("flash exceeded outer watchdog with sections banked "
                    f"({sorted(snap.get('sections', {}))}) — partial")
                return 2
        except (OSError, ValueError):
            pass
        log("flash capture exceeded outer watchdog")
        return 3
    tail = (r.stdout or "").strip().splitlines()
    log(f"flash capture: rc={r.returncode} last={tail[-1][:200] if tail else ''}")
    if r.returncode not in (0, 2, 4):
        log(f"flash stderr tail: {(r.stderr or '')[-300:]}")
    return r.returncode


def capture_pipeline(bench_timeout_s: float,
                     force_dial: bool = False) -> int | None:
    """The whole evidence suite. 2026-07-31 field evidence: healthy windows
    can be ~1 min and serve very few attachments, so the single-dial flash
    runs FIRST and banks sections incrementally; the full bench (mesh
    section + canonical artifact) and triage snapshot only spend further
    attachments when the flash proves the window is alive."""
    # outer cap must exceed the SUM of the flash's internal section budgets
    # (~2.3k s priority + ~1.7k s grid): a slow-but-progressing run through
    # a high-RTT attachment is the internal watchdog's job to bound, and
    # killing it early would misreport a near-complete capture as a wedge
    rc = run_flash(6000.0, force_dial=force_dial)
    if rc == 4:
        return None  # legs closed before the dial: not an attempt at all
    if rc in (0, 2):
        log("flash TPU capture secured (BENCH_TPU_LAST_GOOD.json merged)")
    if rc == 0 and relay_legs_listening():
        # window survived the whole flash: afford the full bench suite
        run_bench(bench_timeout_s)
        run_tool([sys.executable, "tools/tpu_triage.py", "--no-trace",
                  "--probe-s", "30"], 300.0, "triage snapshot")
    return rc


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast-interval", type=float, default=10.0,
                    help="seconds between TCP pre-filter polls")
    ap.add_argument("--slow-every", type=int, default=30,
                    help="full jax probe anyway every N fast polls, in case "
                    "the attachment path changes away from the known legs")
    ap.add_argument("--probe-timeout", type=float, default=45.0)
    ap.add_argument("--bench-timeout", type=float, default=2400.0)
    ap.add_argument("--recapture-min", type=float, default=30.0,
                    help="minimum minutes between captures after a full "
                    "TPU capture")
    ap.add_argument("--retry-min", type=float, default=5.0,
                    help="minimum minutes before refiring the pipeline "
                    "after an attempt that did NOT land on TPU (a flash "
                    "wedge mid-bench must not refire the whole ~hour "
                    "suite back-to-back on the 1-core host)")
    ap.add_argument("--max-hours", type=float, default=11.0)
    args = ap.parse_args()

    deadline = time.time() + args.max_hours * 3600
    attempt = 0
    captured = 0
    last_attempt = 0.0   # any pipeline firing
    wait_min = 0.0       # minutes to hold off since last_attempt
    avail = AvailabilityTimeline(os.path.join(REPO, "TPU_AVAILABILITY_r05.json"))
    log(f"watch v3 started (fast={args.fast_interval}s, "
        f"budget={args.max_hours}h, legs={POOL_PORTS})")
    while time.time() < deadline:
        attempt += 1
        legs = relay_legs_listening()
        avail.record(legs)
        slow_n = max(int(args.slow_every), 1)
        go_slow = (attempt - 1) % slow_n == 0
        if not legs and not go_slow:
            time.sleep(args.fast_interval)
            continue
        # Hold off BEFORE spending a jax-import probe subprocess: inside
        # the window the probe's only possible outcome is "wait more",
        # and on the 1-core host it costs ~2 s of site hooks per spawn.
        held_min = (time.time() - last_attempt) / 60.0
        if last_attempt and held_min < wait_min:
            if legs:
                log(f"poll #{attempt}: legs {legs} up; holding "
                    f"{wait_min - held_min:.0f} more min before refire")
            time.sleep(args.fast_interval)
            continue
        if legs:
            # Legs listening IS the go signal: a jax probe subprocess here
            # would spend one of the window's few attachments (2026-07-31:
            # the sweep's probe attached fine and its main process got
            # nothing) — the flash capture's own attach is the probe.
            log(f"poll #{attempt}: relay legs LISTENING {legs} — "
                f"firing capture pipeline")
            avail.note("capture_fired", legs)
            rc = capture_pipeline(args.bench_timeout)
            avail.note(f"capture_done rc={rc}", relay_legs_listening())
            if rc is not None:  # None: legs closed pre-dial, keep polling
                last_attempt = time.time()
                # rc 2 (wedged mid-run, sections banked) takes the SHORT
                # hold-off: the unmeasured sections should fire into the
                # next window, not wait out the full recapture pause
                wait_min = (args.recapture_min if rc == 0
                            else args.retry_min)
                captured += rc in (0, 2)
        elif probe(args.probe_timeout):
            # slow path: attachment healthy without any known leg open —
            # the relay's port set changed; capture anyway
            log(f"poll #{attempt}: HEALTHY without legs — firing pipeline")
            avail.note("probe_healthy_no_legs capture_fired", legs)
            rc = capture_pipeline(args.bench_timeout, force_dial=True)
            avail.note(f"capture_done rc={rc}", relay_legs_listening())
            if rc is not None:
                last_attempt = time.time()
                wait_min = (args.recapture_min if rc == 0
                            else args.retry_min)
                captured += rc in (0, 2)
        else:
            # reached at most once per slow_n fast polls (~5 min default)
            log(f"poll #{attempt}: wedged (legs refused, slow probe hung)")
        time.sleep(args.fast_interval)
    avail.note("budget_exhausted", relay_legs_listening())
    log(f"budget exhausted; captures this run: {captured}")
    return 0 if captured else 3


if __name__ == "__main__":
    sys.exit(main())
