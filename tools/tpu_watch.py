"""Opportunistic TPU bench capture loop.

The TPU attachment wedges intermittently for hours (see BASELINE.md "tunnel"
notes); ``jax.devices()`` hangs forever when it does.  This watcher probes the
backend in a short-timeout subprocess and, the moment a probe succeeds, fires a
full ``bench.py`` run (which refreshes ``BENCH_TPU_LAST_GOOD.json`` on any
successful on-device capture).  Run it in the background for the whole round:

    python tools/tpu_watch.py --interval 240 --max-hours 11

It exits 0 after the first successful TPU capture (so a supervisor can notice
and decide whether to relaunch for a fresher capture later), or 3 when the
time budget runs out with no healthy window.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "tpu_watch.log")


def log(msg: str) -> None:
    line = f"[{time.strftime('%H:%M:%S')}] {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def probe(timeout_s: float) -> bool:
    """True iff the accelerator answers inside timeout_s (probed in a child
    process so a wedged tunnel can't hang the watcher itself)."""
    # Same probe bench.py uses: the site hook supplies the accelerator
    # platform; an explicit platform list here could name an unregistered
    # plugin and fail even on a healthy tunnel.
    code = "import jax; d = jax.devices(); import sys; sys.exit(0 if d else 1)"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, timeout=timeout_s, env=env, cwd=REPO,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False
    except Exception as e:  # pragma: no cover - defensive
        log(f"probe error: {e!r}")
        return False


def run_bench(bench_timeout_s: float) -> bool:
    """Run the full bench; True iff it captured on TPU (platform == tpu)."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.setdefault("CCFD_BENCH_QUANT", "1")
    env.setdefault("CCFD_BENCH_PROBE_ATTEMPTS", "2")
    try:
        r = subprocess.run(
            [sys.executable, "bench.py"], capture_output=True, text=True,
            timeout=bench_timeout_s, env=env, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        log("bench run exceeded its own watchdog + ours; treating as wedge")
        return False
    tail = (r.stdout or "").strip().splitlines()
    if not tail:
        log(f"bench produced no output (rc={r.returncode}); stderr tail: "
            f"{(r.stderr or '')[-300:]}")
        return False
    try:
        res = json.loads(tail[-1])
    except json.JSONDecodeError:
        log(f"bench last line not JSON: {tail[-1][:200]}")
        return False
    plat = res.get("platform", "")
    log(f"bench finished: platform={plat} metric={res.get('value')}")
    return plat == "tpu"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=240.0,
                    help="seconds between probes while wedged")
    ap.add_argument("--probe-timeout", type=float, default=45.0)
    ap.add_argument("--bench-timeout", type=float, default=2400.0)
    ap.add_argument("--max-hours", type=float, default=11.0)
    args = ap.parse_args()

    deadline = time.time() + args.max_hours * 3600
    attempt = 0
    log(f"watch started (interval={args.interval}s, budget={args.max_hours}h)")
    while time.time() < deadline:
        attempt += 1
        if probe(args.probe_timeout):
            log(f"probe #{attempt}: HEALTHY — firing bench capture")
            if run_bench(args.bench_timeout):
                log("TPU capture secured (BENCH_TPU_LAST_GOOD.json refreshed)")
                return 0
            log("bench did not land on TPU (wedged mid-run?); continuing")
        else:
            if attempt % 5 == 1:
                log(f"probe #{attempt}: wedged")
        time.sleep(args.interval)
    log("budget exhausted without a TPU capture")
    return 3


if __name__ == "__main__":
    sys.exit(main())
