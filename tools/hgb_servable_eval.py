"""Servable HistGradientBoosting on the canonical table.

BASELINE.md's AUC table lists sklearn HistGradientBoosting (0.9650) as
the strongest model family — but until round 4 it was not convertible to
the served dense-tree embedding (`from_sklearn_gbt` covers only the
classic GradientBoostingClassifier). This measures what the SERVABLE
bounded-depth variant gives up: train HGB with max_depth bounded (the
dense embedding is 2^depth nodes/tree), convert via
``trees.from_sklearn_hgb``, verify conversion parity, and record the
held-out AUC of the exact params the Scorer serves.

Protocol: cmd_train's split (seed-0 permutation, 20% test), the same as
the BASELINE AUC table and tools/ensemble_eval.py.

Artifact: HGB_SERVABLE_r04.json.
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main() -> int:
    from sklearn.ensemble import HistGradientBoostingClassifier

    from ccfd_tpu.cli import _training_dataset
    from ccfd_tpu.models import trees
    from ccfd_tpu.utils.metrics_math import roc_auc

    ds, source = _training_dataset()
    rng = np.random.default_rng(0)  # cmd_train's exact split protocol
    order = rng.permutation(ds.n)
    n_test = max(1, int(ds.n * 0.2))
    test, train = order[:n_test], order[n_test:]
    Xtr, ytr, Xte, yte = ds.X[train], ds.y[train], ds.X[test], ds.y[test]

    by_depth = []
    for max_depth in (6, 8, 10):
        t0 = time.time()
        clf = HistGradientBoostingClassifier(
            max_depth=max_depth, class_weight="balanced", random_state=0
        ).fit(Xtr, ytr)
        fit_s = time.time() - t0
        params = trees.from_sklearn_hgb(clf)
        served = np.asarray(trees.apply(params, jnp.asarray(Xte)))
        sk = clf.predict_proba(Xte)[:, 1]
        by_depth.append({
            "max_depth": max_depth,
            "n_trees": int(np.asarray(params["feature"]).shape[0]),
            "embed_depth": trees.depth_of(params),
            "fit_s": round(fit_s, 1),
            "conversion_max_prob_delta": float(np.abs(served - sk).max()),
            "auc_served_params": float(roc_auc(yte, served)),
        })
    best = max(by_depth, key=lambda r: r["auc_served_params"])
    auc_served = best["auc_served_params"]
    # the unbounded reference row of the BASELINE table, same split
    t0 = time.time()
    clf_free = HistGradientBoostingClassifier(
        class_weight="balanced", random_state=0
    ).fit(Xtr, ytr)
    auc_unbounded = float(roc_auc(yte, clf_free.predict_proba(Xte)[:, 1]))
    fit_free_s = time.time() - t0

    result = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "dataset": source,
        "rows_train": int(len(train)),
        "rows_test": int(len(test)),
        "servable_by_depth": by_depth,
        "servable_best": best,
        "unbounded_reference": {
            "auc": auc_unbounded,
            "fit_s": round(fit_free_s, 1),
            "servable_gives_up": round(auc_unbounded - auc_served, 5),
        },
    }
    with open(os.path.join(REPO, "HGB_SERVABLE_r04.json"), "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
