"""Staged TPU-attachment triage: localize WHERE the axon tunnel wedges.

The serving stack's only accelerator path is the axon PJRT plugin, which
dials a local relay (pool-service legs on 127.0.0.1:{8083,8093,8103,8113},
discovered by the connect-trace stage below).  When the relay dies,
``jax.devices()`` retries those dials forever — the "wedge" every round has
fought.  This tool answers, stage by stage, *where* the attachment fails
right now, and writes the evidence to ``TPU_TRIAGE_r05.json``:

  1. listeners      — every TCP LISTEN socket in this netns (what's alive)
  2. pool_ports     — per-port verdict for the relay's pool-service legs
  3. relay_misc     — app-layer behavior of other external-owned listeners
  4. gateway        — is the default gateway a real service or an
                      accept-everything zero-egress sinkhole?
  5. conn_trace     — LD_PRELOAD connect() audit of a live ``jax.devices()``
                      attempt: the ground truth of what the client dials
                      and with what errno (skippable: --no-trace)
  6. jax_probe      — subprocess ``jax.devices()`` with timeout (the
                      end-to-end verdict)

Verdicts: ``healthy`` (probe returned devices), ``wedged_relay_dead``
(pool legs refused ⇒ nothing this host can do until the relay returns),
``wedged_backend`` (legs listening but the probe still hangs ⇒ TPU-side),
``unknown``.

Exit code: 0 healthy, 3 wedged, 4 unknown.  Run ``--json`` for stdout-only.

Round-4 findings this automates (2026-07-30): pool legs 8083/8093/8103/8113
all ECONNREFUSED; gateway 192.0.2.1 accepts *every* port (sinkhole — its
"open" pool ports RST any payload, HTTP/1.1, TLS alike); the one external
listener (0.0.0.0:2024) EOFs every protocol; client retry loop sleeps ~5-10s
between redial rounds (nonblocking connect, errno=EINPROGRESS, failure seen
via epoll).  Conclusion: relay resurrection is harness-side only; the
watcher's job is to notice legs returning within seconds (tpu_watch.py).
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import struct
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

POOL_PORTS = (8083, 8093, 8103, 8113)
GATEWAY = os.environ.get("CCFD_AXON_GW", "192.0.2.1")
# Ports that belong to this framework / the agent harness, not the relay
OWN_PORTS = {18127, 48271}

_CONNTRACE_C = r"""
#define _GNU_SOURCE
#include <stdio.h>
#include <stdlib.h>
#include <errno.h>
#include <dlfcn.h>
#include <sys/socket.h>
#include <netinet/in.h>
#include <arpa/inet.h>
static int (*real_connect)(int, const struct sockaddr*, socklen_t) = 0;
int connect(int fd, const struct sockaddr *addr, socklen_t len) {
    if (!real_connect) real_connect = dlsym(RTLD_NEXT, "connect");
    int rc = real_connect(fd, addr, len);
    int e = errno;
    if (addr && addr->sa_family == AF_INET) {
        const struct sockaddr_in *in = (const struct sockaddr_in*)addr;
        const char *p = getenv("CCFD_CONNTRACE_OUT");
        FILE *f = p && *p ? fopen(p, "a") : 0;
        if (f) {
            fprintf(f, "%s:%d rc=%d errno=%d\n",
                    inet_ntoa(in->sin_addr), ntohs(in->sin_port), rc, e);
            fclose(f);
        }
    }
    errno = e;
    return rc;
}
"""


def legs_listening(timeout_s: float = 0.5) -> list[int]:
    """Which pool-service legs accept a TCP connect right now (~100 us per
    refused port on loopback).  Shared by the watcher's fast poll and the
    flash capture's pre-filter so both always agree on what 'window open'
    means."""
    import socket

    alive = []
    for port in POOL_PORTS:
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=timeout_s):
                alive.append(port)
        except OSError:
            pass
    return alive


def tcp_listeners() -> list[dict]:
    """Every TCP LISTEN socket in this netns, from /proc/net/tcp{,6}."""
    out = []
    for path, v6 in (("/proc/net/tcp", False), ("/proc/net/tcp6", True)):
        try:
            lines = open(path).read().splitlines()[1:]
        except OSError:
            continue
        for ln in lines:
            f = ln.split()
            if f[3] != "0A":  # LISTEN
                continue
            addr_hex, port_hex = f[1].rsplit(":", 1)
            port = int(port_hex, 16)
            if v6:
                ip = "::" if set(addr_hex) <= {"0"} else "(v6)"
            else:
                ip = socket.inet_ntoa(struct.pack("<I", int(addr_hex, 16)))
            out.append({"ip": ip, "port": port, "inode": f[9]})
    return out


def port_verdict(host: str, port: int, payload: bytes | None = None,
                 timeout: float = 2.0) -> dict:
    """Connect; optionally send payload; classify the application behavior."""
    v: dict = {"host": host, "port": port}
    t0 = time.perf_counter()
    try:
        s = socket.create_connection((host, port), timeout=timeout)
    except ConnectionRefusedError:
        v["verdict"] = "refused"
        return v
    except OSError as e:
        v["verdict"] = f"unreachable: {e}"
        return v
    v["connect_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
    try:
        s.settimeout(timeout)
        if payload:
            s.sendall(payload)
        data = s.recv(128)
        if data:
            v["verdict"] = "responds"
            v["first_bytes"] = data[:64].hex()
        else:
            v["verdict"] = "accepts_then_eof"
    except socket.timeout:
        v["verdict"] = "accepts_silent"
    except ConnectionResetError:
        v["verdict"] = "accepts_then_rst"
    except OSError as e:
        v["verdict"] = f"error: {e}"
    finally:
        s.close()
    return v


def stage_pool_ports() -> list[dict]:
    return [port_verdict("127.0.0.1", p) for p in POOL_PORTS]


def stage_gateway() -> dict:
    """Sinkhole detection: a zero-egress gateway accepts every port and
    resets on payload; a real pool service would accept only its ports."""
    pool = [port_verdict(GATEWAY, p, b"GET / HTTP/1.1\r\nHost: x\r\n\r\n")
            for p in POOL_PORTS]
    canaries = [port_verdict(GATEWAY, p) for p in (55555, 1234, 9999)]
    all_accept = all("accept" in c.get("verdict", "") or
                     c.get("verdict") == "responds" for c in canaries)
    return {
        "gateway": GATEWAY,
        "pool_ports": pool,
        "canary_ports": canaries,
        "sinkhole": all_accept,
        "note": ("gateway accepts arbitrary ports: zero-egress sinkhole, its "
                 "'open' pool ports are not the pool service" if all_accept
                 else "gateway port set is selective — may be a real service"),
    }


def stage_relay_misc(listeners: list[dict]) -> list[dict]:
    """App-layer classification of listeners that are not ours."""
    out = []
    for l in listeners:
        if l["port"] in OWN_PORTS or l["ip"].startswith("(v6)"):
            continue
        if l["port"] in POOL_PORTS:
            continue  # covered by stage_pool_ports
        out.append(port_verdict("127.0.0.1", l["port"],
                                b"GET / HTTP/1.1\r\nHost: x\r\n\r\n"))
    return out


def stage_conn_trace(trace_s: float = 20.0) -> dict:
    """Ground truth: run ``jax.devices()`` under an LD_PRELOAD connect()
    audit for ``trace_s`` seconds; report every endpoint the client dialed
    and the errno it saw.  Requires g++ (skipped gracefully without)."""
    gxx = None
    # plain C source — prefer a C compiler (g++ rejects the K&R-style casts)
    for cand in ("gcc", "cc", "g++"):
        from shutil import which

        if which(cand):
            gxx = cand
            break
    if gxx is None:
        return {"skipped": "no C compiler for the trace shim"}
    with tempfile.TemporaryDirectory() as td:
        src = os.path.join(td, "conntrace.c")
        so = os.path.join(td, "conntrace.so")
        log = os.path.join(td, "trace.txt")
        open(src, "w").write(_CONNTRACE_C)
        try:
            subprocess.run([gxx, "-shared", "-fPIC", "-O2", src, "-o", so,
                            "-ldl"], check=True, capture_output=True,
                           timeout=60)
        except subprocess.CalledProcessError as e:
            return {"skipped": "shim build failed: "
                    + (e.stderr or b"").decode("utf-8", "replace")[-300:]}
        except (OSError, subprocess.SubprocessError) as e:
            return {"skipped": f"shim build failed: {e}"}
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env["LD_PRELOAD"] = so
        env["CCFD_CONNTRACE_OUT"] = log
        try:
            subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                capture_output=True, timeout=trace_s, env=env, cwd=REPO,
            )
            completed = True
        except subprocess.TimeoutExpired:
            completed = False
        dials: dict[str, dict] = {}
        try:
            for ln in open(log).read().splitlines():
                ep, _, rest = ln.partition(" ")
                d = dials.setdefault(ep, {"count": 0, "errnos": set()})
                d["count"] += 1
                d["errnos"].add(rest)
        except OSError:
            pass
        return {
            "probe_completed_in_window": completed,
            "window_s": trace_s,
            "dials": {ep: {"count": d["count"],
                           "outcomes": sorted(d["errnos"])}
                      for ep, d in sorted(dials.items())},
        }


def stage_jax_probe(timeout_s: float = 45.0) -> dict:
    code = ("import json, time, jax\n"
            "t0 = time.perf_counter()\n"
            "d = jax.devices()\n"
            "import jax.numpy as jnp\n"
            "x = jnp.ones((128, 128), jnp.bfloat16)\n"
            "(x @ x).block_until_ready()\n"
            "print(json.dumps({'platform': jax.default_backend(),"
            " 'devices': len(d),"
            " 'first_dispatch_s': round(time.perf_counter() - t0, 2)}))\n")
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout_s, env=env, cwd=REPO)
    except subprocess.TimeoutExpired:
        return {"verdict": "hang", "timeout_s": timeout_s}
    if r.returncode == 0 and r.stdout.strip():
        try:
            out = json.loads(r.stdout.strip().splitlines()[-1])
        except json.JSONDecodeError:
            return {"verdict": "error",
                    "stdout_tail": r.stdout.strip()[-200:]}
        out["verdict"] = "ok"
        return out
    return {"verdict": "error", "stderr": (r.stderr or "")[-400:]}


def run_triage(probe_s: float = 45.0, trace: bool = True) -> dict:
    report: dict = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "host": socket.gethostname(),
    }
    listeners = tcp_listeners()
    report["listeners"] = listeners
    report["pool_ports"] = stage_pool_ports()
    report["relay_misc"] = stage_relay_misc(listeners)
    report["gateway"] = stage_gateway()
    legs_alive = [p["port"] for p in report["pool_ports"]
                  if p["verdict"] not in ("refused",) and
                  not p["verdict"].startswith("unreachable")]
    report["pool_legs_listening"] = legs_alive
    if trace and not legs_alive:
        # the interesting case: prove what the client dials while dead
        report["conn_trace"] = stage_conn_trace()
    # End-to-end only worth the wait when a leg listens (else it's a
    # guaranteed `probe_s`-second hang — still record that cheaply once)
    report["jax_probe"] = stage_jax_probe(probe_s if legs_alive else
                                          min(probe_s, 20.0))
    jp = report["jax_probe"]["verdict"]
    if jp == "ok":
        report["verdict"] = "healthy"
    elif not legs_alive:
        report["verdict"] = "wedged_relay_dead"
        report["conclusion"] = (
            "The axon pool-service legs on 127.0.0.1 are not listening; the "
            "PJRT client retries them forever, so jax.devices() hangs. The "
            "relay process is outside this container's PID namespace and "
            "the gateway is a sinkhole — recovery requires the harness-side "
            "relay to return. Keep tpu_watch running; it reacts within "
            "seconds of a leg reappearing.")
    elif jp == "hang":
        report["verdict"] = "wedged_backend"
        report["conclusion"] = (
            "Relay legs listen but the probe still hangs: the wedge is "
            "beyond the relay (claim/grant or TPU-side).")
    else:
        report["verdict"] = "unknown"
    return report


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(REPO, "TPU_TRIAGE_r05.json"))
    ap.add_argument("--probe-s", type=float, default=45.0)
    ap.add_argument("--no-trace", action="store_true",
                    help="skip the LD_PRELOAD connect audit stage")
    ap.add_argument("--json", action="store_true",
                    help="print to stdout only, do not write --out")
    args = ap.parse_args()
    report = run_triage(probe_s=args.probe_s, trace=not args.no_trace)
    text = json.dumps(report, indent=1)
    print(text)
    if not args.json:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return {"healthy": 0, "wedged_relay_dead": 3,
            "wedged_backend": 3}.get(report["verdict"], 4)


if __name__ == "__main__":
    sys.exit(main())
