"""Replay smoke: prove the bulk replay plane holds the parity law live.

Exit-code-gated drill for ``tools/verify_tier1.sh --replay-smoke``
(ISSUE 17 acceptance), against a LIVE in-process pipeline — bus →
router → scorer → KIE — with the decision-provenance plane, the
overload plane AND the SLO burn-rate engine all armed:

1. **Record** a transaction window through the live stack with feature
   capture armed (``AuditLog.capture_rows``): every routed tx stamps a
   re-scorable DecisionRecord into on-disk segments.
2. **Replay** the recorded window through the SAME path at ``bulk``
   priority while live traffic keeps flowing: byte-stable parity is
   required — every recorded verdict re-produced exactly (``match ==
   total``, zero divergence/drop/ghost), with the route-seam tap
   diverting replay verdicts so the provenance log is NOT re-stamped
   (routed grows, recorded doesn't: conservation of the live log).
3. **Inject** one divergence — a recorded row doctored to carry a
   different champion hash and score (the swapped-champion shape) —
   and require the re-drive to detect it AND classify it
   ``champion_hash`` (never ``nondeterminism``).
4. **Zero live-SLO impact**: the burn-rate gauges scraped from the live
   exporter over real HTTP must show zero fast-window breaches across
   every declared SLO while replay ran at full bulk admission, and the
   bulk ceiling must have been actuated (gauge exported) and restored.

    JAX_PLATFORMS=cpu python tools/replay_smoke.py
    tools/verify_tier1.sh --replay-smoke

Prints one JSON line on stdout; exit 0 only when every check holds.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # hermetic: never dial a tunnel

import numpy as np  # noqa: E402

from ccfd_tpu.bus.broker import Broker  # noqa: E402
from ccfd_tpu.config import Config  # noqa: E402
from ccfd_tpu.data.ccfd import synthetic_dataset  # noqa: E402
from ccfd_tpu.metrics.exporter import MetricsExporter  # noqa: E402
from ccfd_tpu.metrics.prom import Registry  # noqa: E402
from ccfd_tpu.observability.audit import AuditLog  # noqa: E402
from ccfd_tpu.observability.slo import SLOEngine  # noqa: E402
from ccfd_tpu.parallel.partition import params_fingerprint  # noqa: E402
from ccfd_tpu.platform.operator import PlatformSpec  # noqa: E402
from ccfd_tpu.process.fraud import build_engine  # noqa: E402
from ccfd_tpu.replay.service import (  # noqa: E402
    ReplayService,
    ReplayVerdictTap,
)
from ccfd_tpu.router.router import Router  # noqa: E402
from ccfd_tpu.runtime.overload import OverloadControl  # noqa: E402
from ccfd_tpu.serving.scorer import Scorer  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=512,
                    help="size of the recorded window")
    ap.add_argument("--cr", default=os.path.join(
        REPO, "deploy", "platform_cr.yaml"))
    ap.add_argument("--windows", default="2,4,12",
                    help="CI-scale burn windows in seconds")
    ap.add_argument("--e2e-target-ms", type=float, default=250.0,
                    help="CI-box margin for the e2e SLO target (the "
                    "slo_smoke precedent: this box's scheduler noise, "
                    "not production latency, is what it absorbs)")
    args = ap.parse_args()

    checks: dict[str, bool] = {}
    detail: dict = {}

    state = tempfile.mkdtemp(prefix="ccfd_replay_smoke_")
    audit_dir = os.path.join(state, "audit")

    cfg = Config(confidence_threshold=1.0, slo_windows=args.windows)
    spec = PlatformSpec.from_yaml(args.cr, cfg=cfg)
    slo_options = dict(spec.component("slo").options)
    slo_options["windows"] = args.windows
    if args.e2e_target_ms and slo_options.get("specs"):
        slo_options["specs"] = [
            ({**s, "target_ms": float(args.e2e_target_ms)}
             if s.get("name") == "e2e-p99" else s)
            for s in slo_options["specs"]
        ]

    regs = {name: Registry()
            for name in ("router", "kie", "seldon", "slo", "replay")}
    slo_engine = SLOEngine.from_config(cfg, regs, regs["slo"],
                                       options=slo_options)

    # -- the live stack: bus -> router -> scorer -> KIE, fully armed ------
    broker = Broker(default_partitions=2)
    kie = build_engine(cfg, broker, regs["kie"], None)
    scorer = Scorer(model_name="mlp", batch_sizes=(128, 1024, 4096),
                    host_tier_rows=0)
    scorer.warmup()
    fp = params_fingerprint(jax.tree.map(np.asarray, scorer.params))

    def lineage():
        return ("v1", fp)

    overload = OverloadControl.from_config(cfg, regs["router"],
                                           max_batch=1024, workers=1)
    audit = AuditLog(dir=audit_dir, registry=regs["router"])
    audit.lineage_fn = lineage
    tap = ReplayVerdictTap(inner=audit, registry=regs["replay"])
    router = Router(cfg, broker, scorer.score, kie, regs["router"],
                    max_batch=1024, overload=overload, audit=tap)
    svc = ReplayService(cfg, broker, audit, tap=tap,
                        registry=regs["replay"],
                        state_dir=os.path.join(state, "replay"),
                        overload=overload, lineage_fn=lineage)
    checks["capture_armed_by_service"] = audit.capture_rows is True
    exporter = MetricsExporter(regs).start()

    # -- 1. record the window ---------------------------------------------
    ds = synthetic_dataset(n=4096, fraud_rate=0.01, seed=17)
    rows = [",".join(f"{v:.6g}" for v in ds.X[i]).encode()
            for i in range(args.rows)]
    broker.produce_batch(cfg.kafka_topic, rows,
                         [f"tx-{i:05d}" for i in range(args.rows)])
    while router.step() > 0:
        pass
    audit.flush()
    recs = audit.scan_window()
    checks["window_recorded_rescorable"] = (
        len(recs) == args.rows
        and all(r.get("row") is not None for r in recs)
        and all(r.get("hash") == fp for r in recs))
    since = int(recs[0]["seq"]) if recs else 0
    until = int(recs[-1]["seq"]) if recs else 0
    recorded_before = int(regs["router"].counter(
        "ccfd_audit_records_total").value())

    # -- 2. replay through the live stack, live traffic still flowing -----
    stop = threading.Event()
    live_extra = [0]

    def drive() -> None:
        # the live lane replay must not starve: a trickle of live
        # (normal-priority) traffic interleaves with the bulk re-drive,
        # and the burn engine ticks throughout
        i = 0
        next_tick = 0.0
        while not stop.is_set():
            if i < 40:
                broker.produce_batch(
                    cfg.kafka_topic, rows[:16],
                    [f"live-{i}-{j}" for j in range(16)])
                live_extra[0] += 16
                i += 1
            router.step()
            now = time.monotonic()
            if now >= next_tick:
                slo_engine.tick()
                next_tick = now + 0.3
            time.sleep(0.005)

    driver = threading.Thread(target=drive, daemon=True,
                              name="replay-smoke-drive")
    driver.start()
    report = svc.run_window(since, until, window_id="smoke")

    # -- 3. one injected divergence: the swapped-champion shape -----------
    # (the driver is still pumping: the re-drive needs the live router)
    inj = [dict(r) for r in recs[:64]]
    inj[7] = dict(inj[7])
    inj[7]["proba"] = 1.0 - float(inj[7]["proba"])  # the old champion's
    inj[7]["hash"] = "0" * len(fp)                  # score, its hash
    rep2 = svc.run_window(window=inj, window_id="smoke-inject",
                          resume=False)
    # keep the live lane going long enough to cross the fast burn windows
    time.sleep(max(1.0, 1.5 * float(args.windows.split(",")[0])))
    stop.set()
    driver.join(timeout=10)
    audit.flush()

    detail["report"] = {k: report[k] for k in
                        ("window_id", "total", "replayed", "match",
                         "divergence", "drop", "ghost", "dup", "causes",
                         "rows_per_s", "parity")}
    checks["byte_stable_parity"] = (
        report["parity"] and report["match"] == report["total"] == args.rows
        and report["divergence"] == 0 and report["drop"] == 0
        and report["ghost"] == 0)
    # conservation of the live log: the re-drive routed through the same
    # stack but the tap diverted every replay verdict — recorded grew
    # only by the live trickle, never by the replay
    recorded_after = int(regs["router"].counter(
        "ccfd_audit_records_total").value())
    routed_total = int(regs["router"].counter(
        "transaction_outgoing_total").total())
    checks["replay_never_restamps_the_log"] = (
        recorded_after == recorded_before + live_extra[0]
        and routed_total >= args.rows * 2)
    detail["conservation"] = {
        "recorded_before": recorded_before,
        "recorded_after": recorded_after,
        "live_extra": live_extra[0], "routed_total": routed_total,
    }
    joined = int(regs["replay"].counter(
        "ccfd_replay_verdicts_total").value({"fate": "joined"}))
    checks["verdicts_joined_via_tap"] = joined >= args.rows
    checks["bulk_ceiling_restored"] = (
        overload is None or overload.bulk_ceiling == 1.0)

    checks["injected_divergence_detected"] = rep2["divergence"] == 1
    checks["injected_divergence_classified"] = (
        rep2["causes"] == {"champion_hash": 1}
        and rep2["match"] == len(inj) - 1
        and not any(f.get("cause") == "nondeterminism"
                    for f in rep2["findings"]))
    detail["injected"] = {"causes": rep2["causes"],
                          "findings": rep2["findings"][:2]}

    # -- 4. zero live-SLO breaches, from the scraped burn gauges ----------
    status = slo_engine.tick()
    checks["slo_engine_green"] = not any(
        s["breaching"] or s["breaches"] for s in status["slos"].values())
    with urllib.request.urlopen(exporter.endpoint + "/prometheus",
                                timeout=10) as resp:
        scrape = resp.read().decode()
    burns = re.findall(r'ccfd_slo_burn_rate\{[^}]*\} ([0-9.e+-]+)', scrape)
    breaches = re.findall(r'ccfd_slo_breach_total\{[^}]*\} ([0-9.e+-]+)',
                          scrape)
    checks["burn_gauges_scraped"] = len(burns) > 0
    checks["zero_breaches_scraped"] = all(float(b) == 0.0 for b in breaches)
    checks["bulk_ceiling_gauge_scraped"] = "ccfd_bulk_ceiling" in scrape
    checks["replay_counters_scraped"] = (
        'ccfd_replay_rows_total{outcome="match"}' in scrape
        and "ccfd_replay_rows_per_s" in scrape)
    detail["slo"] = {
        "burn_samples": len(burns),
        "max_burn": max((float(b) for b in burns), default=0.0),
        "breach_counters": [float(b) for b in breaches],
    }
    detail["throughput_rows_per_s"] = round(report["rows_per_s"], 1)

    svc.stop()
    exporter.stop()
    router.close()
    broker.close()

    ok = all(checks.values())
    print(json.dumps({"ok": ok, "checks": checks, "detail": detail}))
    print(f"REPLAYSMOKE verdict={'PASS' if ok else 'FAIL'}", flush=True)
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
