#!/usr/bin/env bash
# Machine-checked tier-1 gate (VERDICT r5 weak #1: the suite shipped red
# unnoticed because nothing parsed the pytest outcome).
#
# Wraps the ROADMAP tier-1 command, tees the log, then REQUIRES a pytest
# summary line ("== N passed[, M failed][, ...] in Xs ==") and emits one
# machine-checkable tally line:
#
#     TIER1 passed=<n> failed=<n> errors=<n> rc=<rc> verdict=<PASS|FAIL>
#
# Exit codes:
#   0  summary parsed, 0 failed, 0 errors, pytest rc 0
#   1  summary parsed but the suite is red (failures/errors/rc != 0)
#   2  summary line MISSING or clobbered — the failure mode this script
#      exists to catch: a truncated/crashed run must read as red, never
#      as silence
#
# Usage:
#   tools/verify_tier1.sh                  run the suite, then tally
#   tools/verify_tier1.sh --parse-only F   tally an existing log file F
#                                          (used by tests/test_verify_tier1.py)
#   tools/verify_tier1.sh --lint           machine-checked invariant gate
#                                          (`ccfd_tpu lint`, ccfd_tpu/
#                                          analysis/): AST rules encoding
#                                          14 PRs of review findings —
#                                          durability-seam, monotonic-
#                                          durations, counted-drops,
#                                          metric-naming, breaker-outcome,
#                                          hot-path-sync, lock-order.
#                                          Exit non-zero on any
#                                          unsuppressed finding:
#                                          LINT verdict=PASS|FAIL
#   tools/verify_tier1.sh --lint-smoke     runtime lock-order sanitizer
#                                          deflake gate (CCFD_LOCKCHECK=1,
#                                          analysis/lockcheck.py): the
#                                          lint + parallel-router suites
#                                          and a short kill-storm chaos
#                                          soak with every ccfd_tpu lock
#                                          order-checked must stay
#                                          violation-free:
#                                          LINTSMOKE verdict=PASS|FAIL
#   tools/verify_tier1.sh --overload-smoke run the traffic-shape SLO
#                                          harness's short flash-crowd
#                                          regime (tools/load_shape.py)
#                                          and gate on its exit code:
#                                          OVERLOAD verdict=PASS|FAIL
#   tools/verify_tier1.sh --seq-smoke      exit-code-gated smoke of the
#                                          overlapped seq dataflow
#                                          (tools/seq_smoke.py): overlap
#                                          active + accounting conserves +
#                                          restore-replay rebuilds
#                                          byte-identical histories:
#                                          SEQSMOKE verdict=PASS|FAIL
#   tools/verify_tier1.sh --slo-smoke      exit-code-gated smoke of the
#                                          SLO plane (tools/slo_smoke.py):
#                                          CR-loaded specs, a fault-
#                                          injected latency step breaches
#                                          ONLY the REST SLO, the budget
#                                          ledger attributes the added
#                                          latency to the dispatch layer,
#                                          and the StageProfile artifact
#                                          round-trips through /profile:
#                                          SLOSMOKE verdict=PASS|FAIL
#   tools/verify_tier1.sh --incident-smoke exit-code-gated smoke of the
#                                          incident plane
#                                          (tools/incident_smoke.py): a
#                                          fault-injected 200 ms scorer
#                                          step breaches the rest SLO and
#                                          dumps EXACTLY ONE schema-valid
#                                          incident bundle whose stage
#                                          profile blames the dispatch
#                                          layer, round-tripped over real
#                                          HTTP via /incidents/<id>, with
#                                          the h2d budget layer reporting
#                                          measured (non-placeholder)
#                                          values:
#                                          INCIDENTSMOKE verdict=PASS|FAIL
#   tools/verify_tier1.sh --mesh-smoke     exit-code-gated smoke of
#                                          multi-chip sharded serving
#                                          (tools/mesh_smoke.py): the
#                                          live operator platform on a
#                                          forced 8-device CPU mesh —
#                                          sharded serving with
#                                          accounting conserved, single-
#                                          device vs mesh score parity,
#                                          one lifecycle swap under load
#                                          riding the partitioner's
#                                          publish gate, and the mesh
#                                          gauges scraped over real
#                                          HTTP:
#                                          MESHSMOKE verdict=PASS|FAIL
#   tools/verify_tier1.sh --heal-smoke     exit-code-gated smoke of the
#                                          device self-healing plane
#                                          (tools/heal_smoke.py): an
#                                          injected device_hang reaches
#                                          QUARANTINED with the host tier
#                                          serving and accounting
#                                          conserved, the heal ladder
#                                          re-promotes WARM (zero
#                                          serving-stage XLA compiles
#                                          after the flip), one schema-
#                                          valid FlightRecorder bundle
#                                          per transition edge round-
#                                          trips over real HTTP, and the
#                                          health gauges scrape live:
#                                          HEALSMOKE verdict=PASS|FAIL
#   tools/verify_tier1.sh --storage-smoke  exit-code-gated smoke of the
#                                          durable-state integrity plane
#                                          (tools/storage_smoke.py): an
#                                          injected corrupt champion
#                                          checkpoint + torn lineage at
#                                          restart are QUARANTINED and
#                                          the newest verifiable
#                                          generation restores with
#                                          serving-params fingerprint ==
#                                          lineage checkpoint_hash; with
#                                          ALL generations corrupted the
#                                          router pins to the rules tier
#                                          instead of serving unverified
#                                          params; orphan-tmp sweep and
#                                          ccfd_storage_* gauges over
#                                          real HTTP:
#                                          STORAGESMOKE verdict=PASS|FAIL
#   tools/verify_tier1.sh --audit-smoke    exit-code-gated smoke of the
#                                          decision-provenance plane
#                                          (tools/audit_smoke.py): live
#                                          traffic stamps one Decision-
#                                          Record per routed tx (routed
#                                          == recorded, 0 duplicates,
#                                          armed overhead within CI
#                                          noise); after a torn-tail
#                                          crash + restore, `ccfd_tpu
#                                          audit <tx_id>` reconstructs a
#                                          pre-crash fraud decision with
#                                          checkpoint hash == lineage
#                                          champion hash, device tier
#                                          and open-incident linkage
#                                          intact; /decisions + counters
#                                          over real HTTP:
#                                          AUDITSMOKE verdict=PASS|FAIL
#   tools/verify_tier1.sh --fleet-smoke    exit-code-gated smoke of the
#                                          multi-host fleet plane
#                                          (tools/fleet_smoke.py): a
#                                          2-member operator fleet over
#                                          ONE real-HTTP bus, one member
#                                          SIGKILLed mid-traffic; the
#                                          survivors re-adopt its
#                                          partitions disjointly, every
#                                          produced tx is disposed in
#                                          the fleet ledger (no drop, no
#                                          same-epoch double-route),
#                                          champion fingerprint parity
#                                          holds, membership/parity
#                                          gauges scrape green over real
#                                          HTTP, and the elected
#                                          aggregator dumps EXACTLY ONE
#                                          member-kill incident bundle:
#                                          FLEETSMOKE verdict=PASS|FAIL
#   tools/verify_tier1.sh --replay-smoke   exit-code-gated smoke of the
#                                          bulk replay & backtest plane
#                                          (tools/replay_smoke.py): a
#                                          recorded window re-scored
#                                          through the SAME live stack at
#                                          bulk priority holds byte-
#                                          stable verdict parity (match
#                                          == total, 0 drop/ghost), the
#                                          tap keeps replay verdicts out
#                                          of the provenance log, one
#                                          injected swapped-champion
#                                          divergence is detected AND
#                                          classified champion_hash, and
#                                          the scraped burn gauges show
#                                          zero live-SLO fast-window
#                                          breaches at full bulk
#                                          admission:
#                                          REPLAYSMOKE verdict=PASS|FAIL
#   tools/verify_tier1.sh --capacity-smoke exit-code-gated smoke of the
#                                          capacity observatory
#                                          (tools/capacity_smoke.py):
#                                          /capacity serves a schema-
#                                          valid queueing-model doc over
#                                          real HTTP with steady-state
#                                          predicted e2e p99 within 2x
#                                          of observed and the error
#                                          gauge exported; what-if moves
#                                          p99 in the measured direction
#                                          for worker-count and batcher-
#                                          deadline changes; an injected
#                                          200 ms scorer step moves the
#                                          fitted service curve, fires
#                                          the regression sentinel
#                                          EXACTLY ONCE, and re-
#                                          attributes the bottleneck to
#                                          the dispatch layer; the
#                                          baseline run stays silent:
#                                          CAPACITYSMOKE verdict=PASS|FAIL
#   tools/verify_tier1.sh --fused-smoke    exit-code-gated smoke of the
#                                          fused decision kernel
#                                          (tools/fused_smoke.py): the
#                                          live operator platform routes
#                                          512 tx through the fused path
#                                          with accounting exactly
#                                          conserved, proba/fired-rule/
#                                          branch parity 0 delta vs the
#                                          staged path on the same
#                                          records, the fused (L,B) grid
#                                          in the executable inventory
#                                          with per-bucket dispatch
#                                          counts scraped over real HTTP,
#                                          and zero serving-stage
#                                          compiles after warmup:
#                                          FUSEDSMOKE verdict=PASS|FAIL
#   tools/verify_tier1.sh --bench-compare  normalize BENCH_r*.json
#                                          captures into the append-only
#                                          BENCH_HISTORY.jsonl ledger
#                                          (tools/bench_compare.py) and
#                                          gate on the per-row verdict
#                                          vs the last SAME-PLATFORM
#                                          capture: exit 1 iff a newly
#                                          appended row regressed
#                                          (throughput < 0.7x or p99 >
#                                          1.3x its prior)
set -u

REPO_DIR="$(cd "$(dirname "$0")/.." && pwd)"
LOG="${TIER1_LOG:-/tmp/_t1.log}"

if [ "${1:-}" = "--lint" ]; then
    # machine-checked invariant gate (ccfd_tpu/analysis/): exit non-zero
    # on ANY unsuppressed, unbaselined finding. jax-free by design — this
    # gate must run even when the accelerator attachment is wedged.
    cd "$REPO_DIR" || exit 2
    if python -m ccfd_tpu lint; then
        echo "LINT verdict=PASS"
        exit 0
    fi
    echo "LINT verdict=FAIL"
    exit 1
fi

if [ "${1:-}" = "--lint-smoke" ]; then
    # dynamic half of the lock-order rule: the healthy tree must stay
    # SILENT under the sanitizer — (a) the parallel-router suite (the
    # densest real lock interleavings: coalesced dispatch, pause
    # barriers, crash recycle) and (b) a short kill-storm chaos soak,
    # both with every ccfd_tpu lock order-checked. A deliberate
    # inversion failing is tests/test_lint.py's job; this gate proves
    # the absence of false positives where it matters.
    cd "$REPO_DIR" || exit 2
    if ! CCFD_LOCKCHECK=1 JAX_PLATFORMS=cpu python -m pytest \
            tests/test_lint.py tests/test_parallel_router.py \
            -o addopts= -q -p no:cacheprovider; then
        echo "LINTSMOKE verdict=FAIL stage=lockcheck-pytest"
        exit 1
    fi
    if ! JAX_PLATFORMS=cpu python tools/chaos_soak.py --lockcheck \
            --seconds 30 --wedge-s 4 --chaos-interval-s 6 \
            --checkpoint-s 1.5; then
        echo "LINTSMOKE verdict=FAIL stage=lockcheck-soak"
        exit 1
    fi
    echo "LINTSMOKE verdict=PASS"
    exit 0
fi

if [ "${1:-}" = "--overload-smoke" ]; then
    # exit-code-gated smoke of the overload plane: a 5x flash crowd must
    # keep admitted p99 inside the SLO with zero accounting violations
    # and zero priority inversions (see tools/load_shape.py)
    cd "$REPO_DIR" || exit 2
    if JAX_PLATFORMS=cpu python tools/load_shape.py --regime flash --short; then
        echo "OVERLOAD verdict=PASS"
        exit 0
    fi
    echo "OVERLOAD verdict=FAIL"
    exit 1
fi

if [ "${1:-}" = "--seq-smoke" ]; then
    # exit-code-gated smoke of the round-11 seq dataflow: async overlap
    # must not change scores or lose rows, and crash restore-replay must
    # rebuild byte-identical histories (see tools/seq_smoke.py)
    cd "$REPO_DIR" || exit 2
    if JAX_PLATFORMS=cpu python tools/seq_smoke.py; then
        # the script already printed SEQSMOKE verdict=PASS
        exit 0
    fi
    exit 1
fi

if [ "${1:-}" = "--slo-smoke" ]; then
    # exit-code-gated smoke of the SLO/stage-profile plane: burn-rate
    # breach isolation + budget-ledger attribution + /profile round-trip
    # (see tools/slo_smoke.py; the script prints SLOSMOKE verdict=...)
    cd "$REPO_DIR" || exit 2
    if JAX_PLATFORMS=cpu python tools/slo_smoke.py; then
        exit 0
    fi
    exit 1
fi

if [ "${1:-}" = "--incident-smoke" ]; then
    # exit-code-gated smoke of the incident flight recorder: breach ->
    # exactly one schema-valid bundle over real HTTP, dispatch-layer
    # attribution, measured h2d ledger values (see tools/incident_smoke.py;
    # the script prints INCIDENTSMOKE verdict=...)
    cd "$REPO_DIR" || exit 2
    if JAX_PLATFORMS=cpu python tools/incident_smoke.py; then
        exit 0
    fi
    exit 1
fi

if [ "${1:-}" = "--mesh-smoke" ]; then
    # exit-code-gated smoke of multi-chip sharded serving: the operator
    # platform on a forced 8-device CPU mesh must serve sharded with
    # accounting conserved, score parity vs single-device, and a
    # lifecycle swap under load through the publish gate (see
    # tools/mesh_smoke.py; the script prints MESHSMOKE verdict=...)
    cd "$REPO_DIR" || exit 2
    if JAX_PLATFORMS=cpu python tools/mesh_smoke.py; then
        exit 0
    fi
    exit 1
fi

if [ "${1:-}" = "--heal-smoke" ]; then
    # exit-code-gated smoke of the device heal ladder: quarantine ->
    # host-tier serving -> heal -> warm re-promotion, bundles + gauges
    # over real HTTP (see tools/heal_smoke.py; prints HEALSMOKE verdict=)
    cd "$REPO_DIR" || exit 2
    if JAX_PLATFORMS=cpu python tools/heal_smoke.py; then
        exit 0
    fi
    exit 1
fi

if [ "${1:-}" = "--storage-smoke" ]; then
    # exit-code-gated smoke of the durable-state integrity plane:
    # corrupt-champion quarantine -> last-good restore + hash parity ->
    # rules-tier pin when nothing verifies, gauges over real HTTP (see
    # tools/storage_smoke.py; prints STORAGESMOKE verdict=...)
    cd "$REPO_DIR" || exit 2
    if JAX_PLATFORMS=cpu python tools/storage_smoke.py; then
        exit 0
    fi
    exit 1
fi

if [ "${1:-}" = "--audit-smoke" ]; then
    # exit-code-gated smoke of the decision-provenance plane: crash-
    # restore reconstruction by tx id, conservation, hash parity with
    # the lineage, incident linkage, /decisions over real HTTP (see
    # tools/audit_smoke.py; prints AUDITSMOKE verdict=...)
    cd "$REPO_DIR" || exit 2
    if JAX_PLATFORMS=cpu python tools/audit_smoke.py; then
        exit 0
    fi
    exit 1
fi

if [ "${1:-}" = "--fleet-smoke" ]; then
    # exit-code-gated smoke of the multi-host fleet plane: a 2-member
    # fleet over one real-HTTP bus, one member SIGKILLed mid-traffic —
    # partitions re-adopted disjointly, fleet-ledger conservation exact,
    # champion parity + membership gauges green over HTTP, exactly one
    # member-kill incident bundle (see tools/fleet_smoke.py; the script
    # prints FLEETSMOKE verdict=...)
    cd "$REPO_DIR" || exit 2
    if JAX_PLATFORMS=cpu python tools/fleet_smoke.py; then
        exit 0
    fi
    exit 1
fi

if [ "${1:-}" = "--replay-smoke" ]; then
    # exit-code-gated smoke of the replay plane: record -> re-drive at
    # bulk priority -> byte-stable parity, injected divergence detected
    # + cause-classified, zero live-SLO breaches from the scraped burn
    # gauges (see tools/replay_smoke.py; prints REPLAYSMOKE verdict=...)
    cd "$REPO_DIR" || exit 2
    if JAX_PLATFORMS=cpu python tools/replay_smoke.py; then
        exit 0
    fi
    exit 1
fi

if [ "${1:-}" = "--capacity-smoke" ]; then
    # exit-code-gated smoke of the capacity observatory: schema-valid
    # /capacity over real HTTP, steady-state prediction within 2x of
    # observed, what-if direction checks, injected 200 ms step -> curve
    # moves + sentinel fires exactly once + bottleneck re-attributed to
    # dispatch (see tools/capacity_smoke.py; prints CAPACITYSMOKE
    # verdict=...)
    cd "$REPO_DIR" || exit 2
    if JAX_PLATFORMS=cpu python tools/capacity_smoke.py; then
        exit 0
    fi
    exit 1
fi

if [ "${1:-}" = "--fused-smoke" ]; then
    # exit-code-gated smoke of the fused decision kernel: one device
    # dispatch -> routed verdict, conservation exact, bit parity vs the
    # staged path, fused grid + per-bucket dispatch counters over real
    # HTTP (see tools/fused_smoke.py; prints FUSEDSMOKE verdict=...)
    cd "$REPO_DIR" || exit 2
    if JAX_PLATFORMS=cpu python tools/fused_smoke.py; then
        exit 0
    fi
    exit 1
fi

if [ "${1:-}" = "--bench-compare" ]; then
    # bench trajectory gate: fold fresh BENCH_r*.json captures into the
    # append-only, platform-labeled BENCH_HISTORY.jsonl ledger and fail
    # iff a newly appended row regressed against the last same-platform
    # capture (see tools/bench_compare.py)
    cd "$REPO_DIR" || exit 2
    python tools/bench_compare.py
    rc=$?
    if [ "$rc" -eq 0 ]; then
        echo "BENCHCOMPARE verdict=PASS"
        exit 0
    fi
    echo "BENCHCOMPARE verdict=FAIL rc=${rc}"
    exit 1
fi

if [ "${1:-}" = "--parse-only" ]; then
    LOG="${2:?--parse-only needs a log file}"
    rc_cmd=0
    [ -r "$LOG" ] || { echo "TIER1 verdict=UNPARSEABLE reason=missing-log"; exit 2; }
else
    cd "$REPO_DIR" || exit 2
    set -o pipefail
    rm -f "$LOG"
    # -o addopts= : pyproject already bakes in -q, and the ROADMAP
    # command adds another — at -qq pytest SUPPRESSES the final
    # "N passed/failed in Xs" line entirely, which is precisely the
    # unparseable-summary failure mode this gate exists to catch. Same
    # tests, same plugins, single -q, machine-parseable summary.
    timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ \
        -o addopts= -q \
        -m 'not slow' --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee "$LOG"
    rc_cmd=${PIPESTATUS[0]}
    set +o pipefail
fi

# The pytest summary is the LAST line matching the "counts in seconds"
# shape. `grep -a` because a crashed worker can splice binary into the log.
summary=$(grep -aE '^=* ?([0-9]+ [a-z]+, )*[0-9]+ [a-z]+(, [0-9]+ [a-z]+)* in [0-9.]+s' "$LOG" | tail -1)
if [ -z "$summary" ]; then
    # fall back: pytest writes "no tests ran" with the same terminator
    summary=$(grep -aE 'no tests ran in [0-9.]+s' "$LOG" | tail -1)
fi
if [ -z "$summary" ]; then
    # still emit the dot/FAILED tallies: when the 870 s budget clips the
    # run mid-summary (this suite rides that edge), the dots are the only
    # honest progress count — but a missing summary is STILL a loud 2,
    # never a silent pass
    dots=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)
    failed=$(grep -ac '^FAILED' "$LOG")
    echo "TIER1 verdict=UNPARSEABLE reason=no-pytest-summary dots=${dots} failed_lines=${failed} rc=${rc_cmd} log=${LOG}"
    exit 2
fi

count() {  # count <word> -> numeric count from the summary line, 0 if absent
    echo "$summary" | grep -oE "[0-9]+ $1" | tail -1 | grep -oE '^[0-9]+' || echo 0
}
passed=$(count passed)
failed=$(count failed)
errors=$(count "errors?")

# cross-check the dot tally the ROADMAP command counts: a summary claiming
# N passed with far fewer progress dots means the log was clobbered (e.g.
# a stale summary line spliced from a nested pytest run). Loose bound —
# warning lines interleaving progress output legitimately eat some dots —
# but a PASS verdict standing on a summary the progress stream doesn't
# even half-support is exactly the silent-red this gate must refuse.
dots=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)

verdict=PASS
[ "$failed" -gt 0 ] && verdict=FAIL
[ "$errors" -gt 0 ] && verdict=FAIL
[ "$rc_cmd" -ne 0 ] && verdict=FAIL

if [ "$verdict" = "PASS" ] && [ "$passed" -gt 0 ] \
        && [ "$dots" -lt $(( passed / 2 )) ]; then
    echo "TIER1 verdict=UNPARSEABLE reason=summary-dots-mismatch passed=${passed} dots=${dots} rc=${rc_cmd} log=${LOG}"
    exit 2
fi

echo "TIER1 passed=${passed} failed=${failed} errors=${errors} dots=${dots} rc=${rc_cmd} verdict=${verdict}"
[ "$verdict" = "PASS" ] && exit 0 || exit 1
