"""Exit-code-gated smoke of the overlapped sequence-serving dataflow.

Run by ``tools/verify_tier1.sh --seq-smoke``. Drives the REAL path —
producer -> bus -> router -> striped HistoryStore -> (L, B)-bucketed async
seq dispatch -> engine — and asserts the three properties the round-11
rework must never lose:

1. **Overlap is active and exact**: the async path (inflight > 0) scores a
   mixed cold/warm batch no slower than the synchronous loop over the same
   executables, bit-identical probabilities, and the batch's host assembly
   stays a small fraction of overlapped wall (the dispatch-bound split
   that motivated the rework).
2. **Accounting conserves**: every record produced is consumed and every
   consumed record gets a decision (process starts + start errors == in),
   with zero router sheds/drops — the async dispatch window must not leak
   or double-route rows.
3. **Crash-restore correctness under the async path**: after a
   checkpoint + post-cut traffic + restore, the rewound bus re-drives the
   gap and rebuilds BYTE-IDENTICAL histories, and a commit from a dispatch
   in flight across the restore is a no-op (stale generation).

Prints ``SEQSMOKE <check> ...`` lines; exits 0 only when every check
holds.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def log(msg: str) -> None:
    print(f"SEQSMOKE {msg}", flush=True)


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import numpy as np

    from ccfd_tpu.bus.broker import Broker
    from ccfd_tpu.config import Config
    from ccfd_tpu.data.ccfd import FEATURE_NAMES, synthetic_dataset
    from ccfd_tpu.metrics.prom import Registry
    from ccfd_tpu.models import seq as seq_mod
    from ccfd_tpu.process.fraud import build_engine
    from ccfd_tpu.router.router import Router
    from ccfd_tpu.runtime.recovery import CheckpointCoordinator
    from ccfd_tpu.serving.history import SeqScorer

    ok = True
    L = 16
    params = seq_mod.init(jax.random.PRNGKey(0))
    ds = synthetic_dataset(n=2048, fraud_rate=0.01, seed=0)
    params = seq_mod.set_normalizer(params, ds.X.mean(0), ds.X.std(0))

    # -- 1. overlap: async vs sync on one mixed batch ----------------------
    scorer = SeqScorer(params, length=L, batch_sizes=(64, 256),
                       compute_dtype="float32", max_customers=512,
                       len_buckets=(1, 8), inflight=2)
    scorer.warmup()
    rng = np.random.default_rng(0)
    x = ds.X[:512].astype(np.float32)
    ids = [None if rng.random() < 0.7 else int(i % 64)
           for i in range(len(x))]
    # warm the hot customers so the mix carries real ring-buffer work
    scorer.score(x, ids)

    def median(fn, k=3):
        ts = []
        for _ in range(k):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[k // 2]

    # every score() COMMITS (histories grow): pin the store to one cut so
    # sync and async read identical contexts
    cut = scorer.store.snapshot()
    scorer.inflight = 0
    sync_s = median(lambda: scorer.score(x, ids))
    scorer.store.restore(cut)
    p_sync = scorer.score(x, ids)
    scorer.store.restore(cut)
    scorer.inflight = 2
    async_s = median(lambda: scorer.score(x, ids))
    scorer.store.restore(cut)
    p_async = scorer.score(x, ids)
    # assembly share of the overlapped wall: prepare on the warm store
    asm_s = median(lambda: scorer.store.prepare(ids, x))
    same = bool(np.array_equal(p_sync, p_async))
    # the async window must never serialize SLOWER than sync (tolerance
    # for 1-core boxes where XLA and the host contend for the same core),
    # and host assembly must stay a minority share of overlapped wall
    overlap_ok = async_s <= sync_s * 1.10 and asm_s < 0.5 * async_s
    log(f"overlap sync_ms={sync_s*1e3:.1f} async_ms={async_s*1e3:.1f} "
        f"assembly_ms={asm_s*1e3:.1f} identical_scores={same} "
        f"ok={overlap_ok and same}")
    ok &= overlap_ok and same

    # -- 2. accounting through the live router -----------------------------
    cfg = Config(fraud_threshold=0.99)
    broker = Broker()
    reg = Registry()
    factory = lambda: build_engine(cfg, broker, reg)  # noqa: E731
    scorer2 = SeqScorer(params, length=L, batch_sizes=(64, 256),
                        compute_dtype="float32", max_customers=512,
                        len_buckets=(1, 8), inflight=2, registry=reg)
    router = Router(cfg, broker, scorer2, factory(), reg, max_batch=256)
    n_records = 1024
    rows = [
        {name: float(v) for name, v in zip(FEATURE_NAMES, ds.X[i])}
        | ({"id": int(i % 64), "customer_id": int(i % 64)}
           if i % 3 else {})
        for i in range(n_records)
    ]
    broker.produce_batch(cfg.kafka_topic, rows,
                         keys=[r.get("customer_id") for r in rows])
    t = router.start(poll_timeout_s=0.01)
    deadline = time.time() + 60
    while router._c_in.value() < n_records and time.time() < deadline:
        time.sleep(0.05)
    router.pause(10.0)
    consumed = int(router._c_in.value())
    started = int(reg.counter("transaction_outgoing_total", "").total())
    start_err = int(
        reg.counter("router_process_start_errors_total", "").total())
    shed = int(reg.counter("router_shed_total", "").total())
    score_err = int(reg.counter("router_score_errors_total", "").total())
    acct_ok = (consumed == n_records
               and started + start_err == n_records
               and shed == 0 and score_err == 0)
    log(f"accounting produced={n_records} consumed={consumed} "
        f"started={started} start_errors={start_err} shed={shed} "
        f"score_errors={score_err} ok={acct_ok}")
    ok &= acct_ok

    # -- 3. restore-replay rebuilds identical histories --------------------
    coord = CheckpointCoordinator(router, broker, factory, interval_s=999.0)
    coord.register_state("history", scorer2.store.snapshot,
                         scorer2.store.restore)
    router.resume()
    assert coord.checkpoint() is not None
    post = [
        {name: float(v) for name, v in zip(FEATURE_NAMES, ds.X[1024 + i])}
        | {"id": int(i % 16), "customer_id": int(i % 16)}
        for i in range(256)
    ]
    broker.produce_batch(cfg.kafka_topic, post,
                         keys=[r["customer_id"] for r in post])
    deadline = time.time() + 60
    while router._c_in.value() < n_records + 256 and time.time() < deadline:
        time.sleep(0.05)
    router.pause(10.0)
    final_before = scorer2.store.snapshot()
    router.resume()
    coord.restore(reason="seq-smoke drill")
    deadline = time.time() + 60
    while (router._c_in.value() < n_records + 512
           and time.time() < deadline):
        time.sleep(0.05)
    router.pause(10.0)
    final_after = scorer2.store.snapshot()
    router.resume()
    router.stop()
    t.join(timeout=10)

    def as_map(snap):
        return {c[0]: (np.asarray(c[1], np.float32), int(c[2]))
                for c in snap["customers"]}

    a, b = as_map(final_before), as_map(final_after)
    replay_ok = set(a) == set(b) and all(
        a[k][1] == b[k][1] and np.array_equal(a[k][0], b[k][0]) for k in a)
    stale = int(reg.counter("seq_stale_commits_total", "").total())
    log(f"restore_replay customers={len(a)} byte_identical={replay_ok} "
        f"stale_commits_counted={stale}")
    ok &= replay_ok

    # -- 3b. a dispatch in flight across the restore commits as a no-op ----
    from ccfd_tpu.serving.history import HistoryStore

    st = HistoryStore(length=4, num_features=2, stripes=4)
    st.commit(st.prepare(["k"], np.ones((1, 2), np.float32))[1])
    snap = st.snapshot()
    _, token = st.prepare(["k"], np.full((1, 2), 9.0, np.float32))
    st.restore(snap)
    stale_noop = st.commit(token) is False
    unchanged = st.snapshot()["customers"][0][2] == 1
    log(f"stale_commit noop={stale_noop} state_unchanged={unchanged}")
    ok &= stale_noop and unchanged

    log(f"verdict={'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
