"""Mesh smoke: sharded serving through the LIVE operator platform (ISSUE 12).

Exit-code-gated drill for ``tools/verify_tier1.sh --mesh-smoke``: on a
forced 8-device virtual CPU mesh (the same CI substrate as the multichip
dryrun), the platform operator brings up the full pipeline with the
``mesh:`` component armed — named (data, fsdp, tp) mesh, partitioner-
sharded Scorer behind the router pool, publish gate through the pool's
pause barrier — and must prove:

1. **Sharded serving end to end**: the producer's transactions flow
   bus -> ParallelRouter workers -> the SPMD scorer, with accounting
   exactly conserved (incoming == outgoing + shed + start_errors) and
   every produced row consumed.
2. **Score parity**: the mesh scorer's probabilities match a fresh
   single-device scorer holding the same params.
3. **One lifecycle swap under load**: with traffic in flight, the
   lifecycle controller re-asserts the champion checkpoint
   (``restore_champion`` — the same publish surface promotions and
   rollbacks use). The swap must ride the partitioner's publish gate
   (pause acknowledged by every worker, zero timeouts), record a
   checkpoint hash in the audit trail, and leave scores unchanged.
4. **Mesh telemetry over real HTTP**: ``ccfd_mesh_devices`` /
   ``ccfd_mesh_axis_size`` / ``ccfd_mesh_publishes_total`` scrape live
   (the Device board's Mesh row).

    JAX_PLATFORMS=cpu python tools/mesh_smoke.py
    tools/verify_tier1.sh --mesh-smoke

Prints one JSON line on stdout; exit 0 only when every check holds.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the forced mesh must exist BEFORE jax initializes (same as tests/conftest)
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # hermetic: never dial a tunnel

import numpy as np  # noqa: E402

from ccfd_tpu.config import Config  # noqa: E402
from ccfd_tpu.platform.operator import Platform, PlatformSpec  # noqa: E402
from ccfd_tpu.serving.scorer import Scorer  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--transactions", type=int, default=1500)
    ap.add_argument("--drain-s", type=float, default=45.0)
    args = ap.parse_args()

    checks: dict[str, bool] = {}
    detail: dict = {}

    cr = {"spec": {
        "mesh": {"enabled": True, "devices": args.devices},
        "scorer": {"enabled": True, "model": "mlp"},
        "bus": {"partitions": 4},
        "router": {"workers": 2},
        "engine": {"enabled": True},
        "retrain": {"enabled": True, "interval_s": 0.2},
        "lifecycle": {"enabled": True},
        "producer": {"enabled": True,
                     "transactions": args.transactions},
        "monitoring": {"enabled": True, "port": 0},
        "health": {"enabled": False},
        "notify": {"enabled": False},
        "investigator": {"enabled": False},
        "analytics": {"enabled": False},
        "chaos": {"enabled": False},
    }}
    p = Platform(PlatformSpec.from_cr(cr, cfg=Config())).up()
    try:
        # -- 1. the live platform serves SHARDED -------------------------
        mesh_st = p.status().get("mesh") or {}
        detail["mesh"] = mesh_st
        checks["mesh_armed"] = mesh_st.get("devices") == args.devices
        checks["scorer_sharded"] = (
            p.scorer.mesh is p.mesh and p.partitioner is not None)
        checks["publish_gate_armed"] = (
            p.partitioner is not None
            and p.partitioner.gate is not None
            and p.partitioner.gate.barrier is p.router)

        checks["producer_done"] = p.wait_producer(timeout_s=120.0)
        reg = p.registries["router"]
        c_in = reg.counter("transaction_incoming_total")
        c_out = reg.counter("transaction_outgoing_total")
        c_shed = reg.counter("router_shed_total")
        c_err = reg.counter("router_process_start_errors_total")
        deadline = time.monotonic() + args.drain_s
        while (c_in.total() < args.transactions
               and time.monotonic() < deadline):
            time.sleep(0.1)
        checks["all_rows_consumed"] = c_in.total() == args.transactions

        # -- 2. single-device vs mesh score parity -----------------------
        host_params = jax.tree.map(np.asarray, p.scorer.params)
        single = Scorer(model_name="mlp", params=host_params,
                        compute_dtype=p.cfg.compute_dtype,
                        batch_sizes=(512,), host_tier_rows=0,
                        use_fused=False)
        rng = np.random.default_rng(12)
        probe = rng.standard_normal((512, 30)).astype(np.float32)
        ref = single.score(probe)
        got = p.scorer.score_pipelined(probe, depth=1)
        delta = float(np.max(np.abs(ref - got)))
        detail["parity_max_delta"] = delta
        checks["score_parity_vs_single_device"] = delta < 2e-2

        # -- 3. one lifecycle swap UNDER LOAD through the publish gate ---
        gate = p.partitioner.gate
        pubs_before = gate.publishes
        # fresh traffic in flight while the swap publishes
        feed = [",".join("0.1" for _ in range(30)).encode()] * 256
        p.broker.produce_batch(p.cfg.kafka_topic, feed, list(range(256)))
        p.lifecycle.restore_champion()
        checks["swap_rode_publish_gate"] = gate.publishes > pubs_before
        checks["swap_pause_acked_by_pool"] = gate.pause_timeouts == 0
        events = [e for e in p.lifecycle.store.audit_trail()
                  if e["event"] == "heal_respawn_restore"]
        checks["swap_recorded_checkpoint_hash"] = bool(
            events and events[-1]["detail"].get("checkpoint_hash"))
        total = args.transactions + len(feed)
        deadline = time.monotonic() + args.drain_s
        while c_in.total() < total and time.monotonic() < deadline:
            time.sleep(0.1)
        got2 = p.scorer.score_pipelined(probe, depth=1)
        delta2 = float(np.max(np.abs(ref - got2)))
        detail["parity_after_swap_max_delta"] = delta2
        checks["scores_unchanged_after_swap"] = delta2 < 2e-2

        # -- accounting conserved through the whole drill ----------------
        detail["accounting"] = {
            "incoming": c_in.total(), "outgoing": c_out.total(),
            "shed": c_shed.total(), "start_errors": c_err.total(),
        }
        checks["accounting_conserved"] = (
            c_in.total()
            == c_out.total() + c_shed.total() + c_err.total()
            and c_in.total() == total)

        # -- 4. mesh telemetry over real HTTP ----------------------------
        with urllib.request.urlopen(p.exporter.endpoint + "/prometheus",
                                    timeout=10) as resp:
            scrape = resp.read().decode()
        m = re.search(r"ccfd_mesh_devices ([0-9.e+-]+)", scrape)
        checks["mesh_gauge_scraped_http"] = (
            m is not None and float(m.group(1)) == float(args.devices))
        checks["mesh_axis_and_publish_counters_scraped"] = (
            "ccfd_mesh_axis_size" in scrape
            and "ccfd_mesh_publishes_total" in scrape)
    finally:
        p.down()

    ok = all(checks.values())
    print(json.dumps({"ok": ok, "checks": checks, "detail": detail}))
    print(f"MESHSMOKE verdict={'PASS' if ok else 'FAIL'}", flush=True)
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
