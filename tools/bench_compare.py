"""Bench trajectory ledger: BENCH_r*.json -> append-only BENCH_HISTORY.jsonl.

Each growth round leaves a ``BENCH_r<NN>.json`` capture (run metadata +
the bench's ``parsed`` summary payload), but the captures are islands:
nothing compares round N against round N-1, and a capture taken on the
CPU fallback would compare nonsensically against a TPU capture. This
tool normalizes every capture into one schema'd JSONL ledger row —
**platform-labeled** (the ``platform`` field's first token, so
``"cpu (fallback: ...)"`` rows are ``cpu`` rows and never compare
against ``tpu`` rows) — and emits a per-row **regression verdict**
against the last SAME-PLATFORM capture before it: throughput down or
p99 up by more than the threshold = regressed.

The ledger is append-only: captures already present (by capture name)
are never rewritten, so history survives re-runs byte for byte and the
diff of a new round is exactly its own rows. A capture whose ``parsed``
payload is null (the bench printed no parseable summary — rc may still
be 0) becomes an ``unparseable`` row with no verdict: the gap is
RECORDED, not skipped silently.

    python tools/bench_compare.py                  # update + report
    python tools/bench_compare.py --check          # no writes, verdicts only
    tools/verify_tier1.sh --bench-compare          # CI gate

Exit codes: 0 ledger updated / verified and no NEW regression, 1 a
newly-appended row regressed against its platform's prior capture, 2
unreadable input.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

HISTORY_SCHEMA = "ccfd.bench_history.v1"

# numeric fields lifted verbatim from the parsed payload into the row
_FIELDS = ("value", "vs_baseline", "p50_ms", "p99_ms", "p99_e2e_ms",
           "p99_vs_target", "latency_batch")

# fused-decision A/B numerics (PR 19): lifted from the section subdict
# with a ``fused_`` prefix collision guard — the section's own
# ``fused_tx_s`` keeps its name, ``speedup`` becomes ``fused_speedup``
_FUSED_FIELDS = {
    "speedup": "fused_speedup",
    "throughput_speedup": "fused_throughput_speedup",
    "staged_decide_us": "staged_decide_us",
    "fused_decide_us": "fused_decide_us",
    "parity_bit_exact": "fused_parity_bit_exact",
}


def normalize_platform(raw) -> str | None:
    """First token of the bench's platform string: ``"cpu (fallback:
    accelerator probe failed)"`` -> ``cpu``; ``tpu`` -> ``tpu``."""
    if not isinstance(raw, str) or not raw.strip():
        return None
    return raw.strip().split()[0].lower()


def normalize_capture(path: str) -> dict:
    """One BENCH_r*.json -> one ledger row (without the verdict)."""
    name = os.path.splitext(os.path.basename(path))[0]
    with open(path, encoding="utf-8") as f:
        cap = json.load(f)
    if not isinstance(cap, dict):
        raise ValueError(f"{path}: capture is not a mapping")
    parsed = cap.get("parsed")
    rc = cap.get("rc")
    row: dict = {
        "schema": HISTORY_SCHEMA,
        "capture": name,
        "round": cap.get("n"),
        "rc": rc,
    }
    if rc not in (0, None):
        row["status"] = "failed"
        row["platform"] = None
        return row
    if not isinstance(parsed, dict):
        # the bench ran but printed no parseable summary line; the hole
        # in the trajectory is recorded instead of silently dropped
        row["status"] = "unparseable"
        row["platform"] = None
        return row
    row["status"] = "ok"
    row["platform"] = normalize_platform(parsed.get("platform"))
    row["metric"] = parsed.get("metric")
    row["unit"] = parsed.get("unit")
    for k in _FIELDS:
        v = parsed.get(k)
        if isinstance(v, (int, float)):
            row[k] = v
    fd = parsed.get("fused_decision")
    if isinstance(fd, dict) and "error" not in fd:
        for src, dst in _FUSED_FIELDS.items():
            v = fd.get(src)
            if isinstance(v, (int, float, bool)):
                row[dst] = v
    return row


def verdict(row: dict, prior: dict | None, threshold: float) -> dict:
    """Per-row regression verdict vs the last same-platform capture."""
    if prior is None:
        return {"vs": None, "verdict": "no_prior"}
    out: dict = {"vs": prior["capture"], "verdict": "ok"}
    regressed = []
    v0, v1 = prior.get("value"), row.get("value")
    if isinstance(v0, (int, float)) and isinstance(v1, (int, float)) and v0:
        ratio = v1 / v0
        out["throughput_ratio"] = round(ratio, 4)
        if ratio < 1.0 - threshold:
            regressed.append(f"throughput x{ratio:.3f}")
    p0, p1 = prior.get("p99_ms"), row.get("p99_ms")
    if isinstance(p0, (int, float)) and isinstance(p1, (int, float)) and p0:
        ratio = p1 / p0
        out["p99_ratio"] = round(ratio, 4)
        if ratio > 1.0 + threshold:
            regressed.append(f"p99 x{ratio:.3f}")
    f0, f1 = prior.get("fused_speedup"), row.get("fused_speedup")
    if isinstance(f0, (int, float)) and isinstance(f1, (int, float)) and f0:
        # the fused-decision win eroding across rounds is a regression of
        # this PR's tentpole even when the headline throughput holds
        ratio = f1 / f0
        out["fused_speedup_ratio"] = round(ratio, 4)
        if ratio < 1.0 - threshold:
            regressed.append(f"fused_decision speedup x{ratio:.3f}")
    if row.get("fused_parity_bit_exact") is False:
        # parity is a hard invariant, not a trend: a capture that measured
        # drift between the fused and staged verdicts always regresses
        regressed.append("fused_decision parity broken")
    if regressed:
        out["verdict"] = "regressed"
        out["causes"] = regressed
    return out


def _round_key(row: dict):
    m = re.search(r"(\d+)$", row["capture"])
    return int(m.group(1)) if m else 0


def load_history(path: str) -> list[dict]:
    rows = []
    if not os.path.exists(path):
        return rows
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError as e:
                raise ValueError(f"{path}:{i + 1}: corrupt ledger line "
                                 f"({e})") from e
            if row.get("schema") != HISTORY_SCHEMA:
                raise ValueError(f"{path}:{i + 1}: unexpected schema "
                                 f"{row.get('schema')!r}")
            rows.append(row)
    return rows


def main(argv=None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--captures", default=os.path.join(repo, "BENCH_r*.json"),
                    help="glob of bench captures")
    ap.add_argument("--history", default=os.path.join(
        repo, "BENCH_HISTORY.jsonl"))
    ap.add_argument("--threshold", type=float, default=0.3,
                    help="regression band: throughput below (1-t)x or p99 "
                    "above (1+t)x the prior same-platform capture")
    ap.add_argument("--check", action="store_true",
                    help="verify + report only; write nothing")
    args = ap.parse_args(argv)

    try:
        history = load_history(args.history)
    except ValueError as e:
        print(f"bench_compare: {e}", file=sys.stderr)
        return 2
    seen = {r["capture"] for r in history}

    captures = sorted(glob.glob(args.captures))
    if not captures:
        print(f"bench_compare: no captures match {args.captures!r}",
              file=sys.stderr)
        return 2
    fresh: list[dict] = []
    for path in captures:
        name = os.path.splitext(os.path.basename(path))[0]
        if name in seen:
            continue
        try:
            fresh.append(normalize_capture(path))
        except (OSError, ValueError) as e:
            print(f"bench_compare: {e}", file=sys.stderr)
            return 2
    fresh.sort(key=_round_key)

    # verdicts: each fresh row vs the last SAME-PLATFORM row before it
    # (ledger rows first, then earlier fresh rows), never cross-platform
    last_by_platform: dict[str, dict] = {}
    for row in sorted(history, key=_round_key):
        if row.get("status") == "ok" and row.get("platform"):
            last_by_platform[row["platform"]] = row
    new_regressions = []
    for row in fresh:
        if row["status"] != "ok" or not row.get("platform"):
            continue
        prior = last_by_platform.get(row["platform"])
        row["baseline"] = verdict(row, prior, args.threshold)
        if row["baseline"]["verdict"] == "regressed":
            new_regressions.append(row)
        last_by_platform[row["platform"]] = row

    if fresh and not args.check:
        with open(args.history, "a", encoding="utf-8") as f:
            for row in fresh:
                f.write(json.dumps(row, sort_keys=True) + "\n")

    for row in history + fresh:
        b = row.get("baseline") or {}
        mark = {"regressed": "!!", "ok": "  ", "no_prior": "--"}.get(
            b.get("verdict"), "~~")
        line = (f"{mark} {row['capture']:<12} {row.get('status'):<12} "
                f"platform={row.get('platform')}")
        if row.get("status") == "ok":
            line += (f" value={row.get('value')} {row.get('unit', '')}"
                     f" p99={row.get('p99_ms')}ms")
            if b.get("vs"):
                line += (f"  vs {b['vs']}:"
                         f" tp x{b.get('throughput_ratio', '?')}"
                         f" p99 x{b.get('p99_ratio', '?')}"
                         f" -> {b['verdict'].upper()}")
        print(line)
    print(f"bench_compare: {len(fresh)} new row(s), "
          f"{len(new_regressions)} regression(s), ledger "
          f"{'unchanged (--check)' if args.check else args.history}",
          file=sys.stderr)
    return 1 if new_regressions else 0


if __name__ == "__main__":
    sys.exit(main())
