#!/usr/bin/env python
"""Model-lifecycle drill: reject a degraded challenger, promote a good one,
then force a mid-canary guardrail breach and assert auto-rollback.

The governed-rollout acceptance run (lifecycle/):

1. **Degraded challenger** — trained on label-flipped data (the bad-batch
   failure mode the lifecycle exists to catch: one poisoned label window
   must not reach production). Asserts it is REJECTED at the SHADOW gate
   and serving never changed.
2. **Improved challenger** — trained longer on the true labels. Asserts it
   passes SHADOW, serves a canary slice (both arms observed), and is
   PROMOTED to champion with serving actually swapped.
3. **Canary breach** — a third candidate reaches CANARY, then the
   scorer-edge circuit breaker is driven open (the degraded-edge signal
   the router's ladder also watches). Asserts auto-ROLLBACK to the
   champion checkpoint, serving restored bit-for-bit to the promoted
   champion.

Every transition is checked against the persisted audit trail, and the
``ccfd_lifecycle_stage`` / ``ccfd_lifecycle_promotions_total`` /
``ccfd_lifecycle_rollbacks_total`` series are asserted observable through
a live MetricsExporter scrape. Writes LIFECYCLE_DRILL.json (lineage +
audit + metrics) and exits 0 on success.

Usage:  python tools/lifecycle_drill.py [--out LIFECYCLE_DRILL.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="LIFECYCLE_DRILL.json")
    ap.add_argument("--state-dir", default="",
                    help="lifecycle state dir (default: a temp dir)")
    args = ap.parse_args()

    t_start = time.time()
    from ccfd_tpu.bus.broker import Broker
    from ccfd_tpu.config import Config
    from ccfd_tpu.data.ccfd import FEATURE_NAMES, synthetic_dataset
    from ccfd_tpu.lifecycle.controller import (
        STAGE_CANARY,
        STAGE_IDLE,
        Guardrails,
        LifecycleController,
    )
    from ccfd_tpu.lifecycle.evaluator import ShadowEvaluator
    from ccfd_tpu.lifecycle.shadow import ShadowTap
    from ccfd_tpu.lifecycle.versions import VersionStore
    from ccfd_tpu.metrics.exporter import MetricsExporter
    from ccfd_tpu.metrics.prom import Registry
    from ccfd_tpu.parallel.checkpoint import CheckpointManager
    from ccfd_tpu.parallel.train import TrainConfig, fit_mlp
    from ccfd_tpu.router.router import default_scorer_breaker
    from ccfd_tpu.serving.scorer import Scorer

    cfg = Config()
    broker = Broker()
    reg = Registry()
    state_dir = args.state_dir or tempfile.mkdtemp(prefix="ccfd_lifecycle_drill_")

    ds = synthetic_dataset(n=4096, fraud_rate=0.05, seed=0)
    tc = TrainConfig(compute_dtype="float32")
    print("[drill] training champion (true labels, 150 steps)...")
    champion = fit_mlp(ds.X, ds.y, steps=150, seed=0, tc=tc)
    scorer = Scorer(model_name="mlp", params=champion,
                    batch_sizes=(16, 128, 1024, 4096),
                    compute_dtype="float32")

    store = VersionStore(os.path.join(state_dir, "versions.json"))
    ckpt = CheckpointManager(os.path.join(state_dir, "checkpoints"), keep=8)
    shadow = ShadowTap(scorer, broker, cfg.shadow_topic, reg)
    evaluator = ShadowEvaluator(cfg, broker, scorer, reg)
    breaker = default_scorer_breaker(reg)
    guardrails = Guardrails(
        min_labels=64, min_shadow_rows=512, canary_min_labels=32,
        # AUC + alert-rate carry the degraded-challenger verdict here; the
        # PSI ceiling stays wide because the drill's two champions are
        # trained from different seeds (their absolute score scales differ
        # more than a production parent->child retrain's would)
        max_score_psi=10.0, canary_weight=0.2,
    )
    ctl = LifecycleController(
        cfg, scorer, store=store, checkpoints=ckpt, shadow=shadow,
        evaluator=evaluator, guardrails=guardrails, registry=reg,
        breaker=breaker)
    served = ctl.wrap_score(scorer.score)
    exporter = MetricsExporter({"lifecycle": reg}, port=0).start()

    probe = ds.X[:128]
    baseline = scorer.score(probe).copy()
    rng = np.random.default_rng(0)

    def pump(with_labels: bool = True, until=None, max_iters: int = 64) -> None:
        """Feed live batches through the serving lane + labels, stepping
        the shadow worker and controller, until ``until()`` or budget."""
        for _ in range(max_iters):
            idx = rng.integers(0, len(ds.X), size=512)
            served(ds.X[idx])
            shadow.step()
            if with_labels:
                for j in rng.integers(0, len(ds.X), size=24):
                    broker.produce(cfg.labels_topic, {
                        "transaction": dict(
                            zip(FEATURE_NAMES, map(float, ds.X[j]))),
                        "label": int(ds.y[j]),
                    })
            ctl.step()
            if until is not None and until():
                return
        raise AssertionError("drill pump exhausted its budget before the "
                             "expected transition")

    checks: dict = {}

    # -- phase 1: degraded challenger must die in SHADOW -------------------
    print("[drill] phase 1: label-flipped challenger (degraded)...")
    degraded = fit_mlp(ds.X, 1.0 - ds.y, steps=150, seed=1, tc=tc)
    v_bad = ctl.submit_candidate(degraded, label_watermark=0)
    pump(until=lambda: store.get(v_bad).stage != "SHADOW")
    bad = store.get(v_bad)
    assert bad.stage == "REJECTED", f"degraded candidate ended {bad.stage}"
    assert np.allclose(scorer.score(probe), baseline, atol=1e-5), \
        "serving changed while rejecting the degraded challenger"
    assert scorer.challenger_version is None and not ctl.gate.active
    checks["degraded_rejected_in_shadow"] = True
    checks["degraded_reject_metrics"] = bad.metrics
    print(f"[drill]   v{v_bad} REJECTED: "
          f"auc_challenger={bad.metrics.get('auc_challenger'):.3f} vs "
          f"champion={bad.metrics.get('auc_champion'):.3f}")

    # -- phase 2: improved challenger promotes through CANARY --------------
    print("[drill] phase 2: improved challenger (600 steps)...")
    improved = fit_mlp(ds.X, ds.y, steps=600, seed=2, tc=tc)
    v_good = ctl.submit_candidate(improved, label_watermark=int(
        reg.counter("retrain_labels_total").value() or 0))
    saw_canary = [False]

    def good_resolved():
        if ctl.stage == STAGE_CANARY:
            saw_canary[0] = True
        return store.get(v_good).stage in ("CHAMPION", "REJECTED",
                                           "ROLLED_BACK")

    pump(until=good_resolved)
    good = store.get(v_good)
    assert good.stage == "CHAMPION", f"improved candidate ended {good.stage}"
    assert saw_canary[0], "promotion skipped the canary phase"
    c_rows = reg.counter("ccfd_lifecycle_canary_rows_total")
    assert c_rows.value(labels={"arm": "champion"}) > 0
    assert c_rows.value(labels={"arm": "challenger"}) > 0
    promoted = scorer.score(probe).copy()
    assert not np.allclose(promoted, baseline, atol=1e-5), \
        "promotion did not change serving"
    assert ctl.champion == v_good and store.champion().version == v_good
    checks["promoted_through_canary"] = True
    checks["canary_rows"] = {
        "champion": int(c_rows.value(labels={"arm": "champion"})),
        "challenger": int(c_rows.value(labels={"arm": "challenger"})),
    }
    print(f"[drill]   v{v_good} PROMOTED (canary rows: "
          f"{checks['canary_rows']})")

    # -- phase 3: canary guardrail breach auto-rolls back ------------------
    print("[drill] phase 3: third candidate + forced breaker-open breach...")
    third = fit_mlp(ds.X, ds.y, steps=650, seed=3, tc=tc)
    v_third = ctl.submit_candidate(third, label_watermark=0)
    pump(until=lambda: ctl.stage == STAGE_CANARY)
    assert store.get(v_third).stage == "CANARY"
    # degraded scorer edge mid-canary: drive the breaker open exactly as
    # the router's ladder would under a blackholed device
    for _ in range(8):
        breaker.record_failure(0.1)
    assert breaker.state == "open"
    pump(with_labels=False, until=lambda: ctl.stage == STAGE_IDLE,
         max_iters=4)
    rolled = store.get(v_third)
    assert rolled.stage == "ROLLED_BACK", f"breach ended {rolled.stage}"
    assert np.allclose(scorer.score(probe), promoted, atol=1e-5), \
        "rollback did not restore the champion checkpoint"
    assert ctl.serving_consistent()
    checks["canary_breach_rolled_back"] = True
    reasons = [e["detail"].get("reason", "")
               for e in store.audit_trail(v_third) if e["event"] == "stage"]
    assert any("breaker" in r for r in reasons), reasons
    print(f"[drill]   v{v_third} ROLLED_BACK: {reasons[-1]}")

    # -- observability: the acceptance metrics through a live scrape -------
    with urllib.request.urlopen(f"{exporter.endpoint}/metrics") as resp:
        body = resp.read().decode()
    for metric, want in (
        ("ccfd_lifecycle_stage", None),
        ("ccfd_lifecycle_promotions_total", 1.0),
        ("ccfd_lifecycle_rollbacks_total", 1.0),
        ("ccfd_lifecycle_rejections_total", 1.0),
    ):
        line = next((ln for ln in body.splitlines()
                     if ln.startswith(metric + " ")), None)
        assert line is not None, f"{metric} not exported"
        if want is not None:
            assert float(line.split()[-1]) == want, line
    checks["metrics_scraped_via_exporter"] = True

    audit = store.audit_trail()
    artifact = {
        "seconds": round(time.time() - t_start, 1),
        "state_dir": state_dir,
        "checks": checks,
        "versions": [v.to_dict() for v in store.versions()],
        "audit_trail": audit,
        "metrics": {
            "promotions": reg.counter(
                "ccfd_lifecycle_promotions_total").value(),
            "rollbacks": reg.counter(
                "ccfd_lifecycle_rollbacks_total").value(),
            "rejections": reg.counter(
                "ccfd_lifecycle_rejections_total").value(),
            "candidates": reg.counter(
                "ccfd_lifecycle_candidates_total").value(),
            "shadow_rows": reg.counter(
                "ccfd_lifecycle_shadow_rows_total").value(),
            "stage": reg.gauge("ccfd_lifecycle_stage").value(),
            "champion_version": reg.gauge(
                "ccfd_lifecycle_champion_version").value(),
        },
        "ok": True,
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1)
    exporter.stop()
    ctl.close()
    broker.close()
    print(f"[drill] OK: {len(audit)} audit events; artifact -> {args.out}")
    print(json.dumps({k: artifact[k] for k in ("seconds", "checks",
                                               "metrics")}, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
