"""Capacity smoke: prove the queueing-model plane predicts, attributes, alerts.

Exit-code-gated drill for ``tools/verify_tier1.sh --capacity-smoke``
(ISSUE 18 acceptance). Reuses the slo_smoke harness shape — live
pipeline + REST serving lanes over a real StageProfiler — with the
CAPACITY MODEL armed as its own supervised-style refresh loop:

1. Steady phase: traffic on both lanes while the model fits. Required
   outcome, all over REAL HTTP from the live exporter:
   - ``/capacity`` round-trips schema-valid (``ccfd.capacity.v1``);
   - predicted e2e p99 is within 2x of observed (CI-box margin) and the
     ``ccfd_capacity_model_error_ratio`` gauge is exported;
   - the regression sentinel stays SILENT (a baseline run must not
     alert).
2. What-if phase: ``/capacity/whatif`` must move predicted p99 in the
   measured direction — fewer workers => higher p99 (the drain stages'
   W_q grows), a longer batcher deadline => higher p99 (the coalescing
   wait scales with it).
3. Step drill: a fault-injected 200 ms scorer-latency step on the REST
   lane (runtime/faults.py — the same injection surface every other
   drill uses). Required outcome:
   - the fitted service curve for ``rest.dispatch`` MOVES (delta-based
     fitting: cumulative digests alone would take minutes to drift);
   - the regression sentinel fires EXACTLY ONCE for that stage
     (edge-triggered with hysteresis) and for no other stage;
   - bottleneck attribution flips to the dispatch layer.

    JAX_PLATFORMS=cpu python tools/capacity_smoke.py
    tools/verify_tier1.sh --capacity-smoke

Prints one JSON line on stdout; exit 0 only when every check holds.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
import tempfile
import time
import urllib.parse
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # hermetic: never dial a tunnel

import numpy as np  # noqa: E402

from ccfd_tpu.bus.broker import Broker  # noqa: E402
from ccfd_tpu.config import Config  # noqa: E402
from ccfd_tpu.data.ccfd import synthetic_dataset  # noqa: E402
from ccfd_tpu.metrics.exporter import MetricsExporter  # noqa: E402
from ccfd_tpu.metrics.prom import Registry  # noqa: E402
from ccfd_tpu.observability.capacity import (  # noqa: E402
    CapacityModel,
    validate_capacity,
)
from ccfd_tpu.observability.profile import StageProfiler  # noqa: E402
from ccfd_tpu.process.fraud import build_engine  # noqa: E402
from ccfd_tpu.router.router import Router  # noqa: E402
from ccfd_tpu.runtime.faults import FaultPlan, FaultSpec  # noqa: E402
from ccfd_tpu.serving.batcher import DynamicBatcher  # noqa: E402
from ccfd_tpu.serving.scorer import Scorer  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class Harness:
    def __init__(self, fault_ms: float, baseline_path: str,
                 tolerance: float, min_samples: int):
        self.cfg = Config()
        self.regs = {name: Registry()
                     for name in ("router", "kie", "seldon", "slo",
                                  "capacity")}
        self.profiler = StageProfiler(registry=self.regs["slo"],
                                      overload_registry=self.regs["router"])
        self.model = CapacityModel(
            self.profiler, registry=self.regs["capacity"],
            baseline_path=baseline_path,
            # CI-box margin: queue-wait means jitter window to window on a
            # busy 1-core box; the injected step is a 40-100x move, so a
            # wide band keeps the baseline silent WITHOUT weakening the
            # drill (the sentinel still must fire on the step)
            regression_tolerance=tolerance,
            min_samples=min_samples,
        )

        # -- pipeline lane (bus -> router -> engine; NO faults) -----------
        self.broker = Broker(default_partitions=2)
        self.kie = build_engine(self.cfg, self.broker, self.regs["kie"], None)
        scorer = Scorer(model_name="mlp", batch_sizes=(128, 1024, 4096))
        scorer.warmup()
        self.router = Router(self.cfg, self.broker, scorer.score, self.kie,
                             self.regs["router"], max_batch=1024,
                             profiler=self.profiler)

        # -- REST serving lane (fault target) ------------------------------
        rest_scorer = Scorer(model_name="mlp", batch_sizes=(16, 128, 1024))
        rest_scorer.warmup()
        self.fault_plan = FaultPlan(
            {"scorer_rest": FaultSpec(latency_ms=fault_ms)}, active=False)
        score_rest = self.fault_plan.injector(
            "scorer_rest", self.regs["seldon"]).wrap_fn(rest_scorer.score)
        self.batcher = DynamicBatcher(score_rest, max_batch=1024,
                                      deadline_ms=1.0, workers=2,
                                      profiler=self.profiler)
        # the live actuator values every what-if delta is measured against
        self.model.set_actuators(workers=2, batch=1024, deadline_ms=1.0)

        ds = synthetic_dataset(n=4096, fraud_rate=0.01, seed=3)
        self.X = np.asarray(ds.X, np.float32)
        self._rows = [
            ",".join(f"{v:.6g}" for v in ds.X[i]).encode()
            for i in range(512)
        ]
        self.produced = 0
        self.exporter = MetricsExporter(self.regs, profiler=self.profiler,
                                        capacity=self.model).start()

    # -- drivers -----------------------------------------------------------
    def pump_pipeline(self, rows: int = 200) -> None:
        base = self.produced
        idx = [(base + i) % len(self._rows) for i in range(rows)]
        self.broker.produce_batch(
            self.cfg.kafka_topic, [self._rows[i] for i in idx],
            [(base + i) % 97 for i in range(rows)])
        self.produced = base + rows
        while self.router.step() > 0:
            pass

    def rest_request(self, rows: int = 16) -> None:
        lo = self.produced % (len(self.X) - rows)
        self.batcher.score(self.X[lo:lo + rows])

    def drive(self, seconds: float, tick_s: float = 0.4) -> None:
        end = time.monotonic() + seconds
        next_tick = 0.0
        while time.monotonic() < end:
            self.pump_pipeline()
            self.rest_request()
            now = time.monotonic()
            if now >= next_tick:
                self.model.refresh()
                next_tick = now + tick_s
            time.sleep(0.02)
        self.model.refresh()

    def fetch(self, path: str, query: dict | None = None) -> dict:
        url = self.exporter.endpoint + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        with urllib.request.urlopen(url, timeout=10) as resp:
            return json.loads(resp.read().decode())

    def scrape(self) -> str:
        with urllib.request.urlopen(
                self.exporter.endpoint + "/prometheus", timeout=10) as resp:
            return resp.read().decode()

    def close(self) -> None:
        self.batcher.stop()
        self.router.close()
        self.exporter.stop()
        self.broker.close()


def _fired_total(doc: dict) -> dict[str, int]:
    out = {}
    for stage, entry in doc.get("stages", {}).items():
        n = (entry.get("regression") or {}).get("fired_total", 0)
        if n:
            out[stage] = n
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steady-s", type=float, default=6.0)
    ap.add_argument("--fault-s", type=float, default=6.0)
    ap.add_argument("--fault-ms", type=float, default=200.0)
    ap.add_argument("--tolerance", type=float, default=3.0,
                    help="regression tolerance (fire past (1+tol)x)")
    # 20 keeps the per-bucket verdict floor (min_samples // 10) at 2: the
    # 200 ms step throttles the single-threaded driver to ~2 dispatches
    # per refresh window, and the stepped bucket must still be judged
    ap.add_argument("--min-samples", type=int, default=20)
    args = ap.parse_args()

    tmp = tempfile.mkdtemp(prefix="ccfd-capacity-smoke-")
    h = Harness(args.fault_ms, os.path.join(tmp, "baseline.json"),
                args.tolerance, args.min_samples)
    checks: dict[str, bool] = {}
    detail: dict = {}

    # -- 1. steady state: schema-valid over HTTP, bounded error, silent ----
    h.drive(args.steady_s)
    steady = h.fetch("/capacity")
    errs = validate_capacity(steady)
    checks["capacity_schema_valid_http"] = not errs
    if errs:
        detail["capacity_errors"] = errs[:5]

    e2e = steady.get("e2e", {})
    pred = float(e2e.get("predicted_p99_ms") or 0.0)
    obs = float(e2e.get("observed_p99_ms") or 0.0)
    detail["steady_e2e"] = {"predicted_p99_ms": pred, "observed_p99_ms": obs,
                            "error_ratio": e2e.get("error_ratio")}
    checks["predicted_within_2x_observed"] = (
        obs > 0.0 and 0.5 * obs <= pred <= 2.0 * obs)
    scrape = h.scrape()
    checks["error_gauge_exported"] = bool(re.search(
        r"^ccfd_capacity_model_error_ratio [0-9.e+-]+", scrape, re.M))
    steady_fired = _fired_total(steady)
    detail["steady_regressions"] = steady_fired
    checks["baseline_run_silent"] = not steady_fired
    detail["steady_bottleneck"] = steady.get("bottleneck")

    # -- 2. what-if over HTTP: deltas move in the measured direction -------
    wi_workers = h.fetch("/capacity/whatif", {"workers": 1})
    checks["whatif_schema_valid"] = not validate_capacity(wi_workers)
    dw = float(wi_workers.get("whatif", {}).get("delta_p99_ms") or 0.0)
    detail["whatif_workers1_delta_ms"] = dw
    checks["whatif_fewer_workers_raises_p99"] = dw > 0.0

    wi_deadline = h.fetch("/capacity/whatif", {"deadline_ms": 10.0})
    dd = float(wi_deadline.get("whatif", {}).get("delta_p99_ms") or 0.0)
    detail["whatif_deadline10_delta_ms"] = dd
    checks["whatif_longer_deadline_raises_p99"] = dd > 0.0

    pre_dispatch = steady.get("stages", {}).get("rest.dispatch", {})
    pre_mean = float(pre_dispatch.get("mean_service_ms") or 0.0)

    # -- 3. step drill: 200 ms latency step on the REST scorer edge -------
    h.fault_plan.activate()
    h.drive(args.fault_s)
    h.fault_plan.deactivate()
    stepped = h.fetch("/capacity")
    checks["stepped_schema_valid"] = not validate_capacity(stepped)

    post_dispatch = stepped.get("stages", {}).get("rest.dispatch", {})
    post_mean = float(post_dispatch.get("mean_service_ms") or 0.0)
    detail["dispatch_mean_ms"] = {"pre": pre_mean, "post": post_mean}
    # the fitted curve must MOVE within the drill (delta-based fitting)
    checks["fitted_curve_moved"] = (
        pre_mean > 0.0 and post_mean >= 5.0 * pre_mean
        and post_mean >= 0.5 * args.fault_ms)

    fired = _fired_total(stepped)
    detail["stepped_regressions"] = fired
    # the stepped stage fires EXACTLY once (edge semantics: the 200 ms
    # step spans many refresh windows, so a level-triggered counter would
    # machine-gun), and no stage anywhere double-fires. Other work stages
    # MAY legitimately fire once: the 200 ms sleep de-contends the CPU,
    # which is a real service-time change on a 1-core CI box.
    checks["sentinel_fired_exactly_once"] = (
        fired.get("rest.dispatch") == 1
        and all(n == 1 for n in fired.values()))
    counter = re.search(
        r'ccfd_capacity_regression_total\{stage="rest\.dispatch"\} '
        r"([0-9.]+)", h.scrape())
    checks["sentinel_counter_scraped"] = (
        counter is not None and float(counter.group(1)) == 1.0)

    bn = stepped.get("bottleneck") or {}
    detail["stepped_bottleneck"] = bn
    checks["bottleneck_flipped_to_dispatch"] = (
        bn.get("layer") == "dispatch" and bn.get("stage") == "rest.dispatch")

    h.close()
    ok = all(checks.values())
    print(json.dumps({
        "harness": "capacity_smoke",
        "ok": ok,
        "checks": checks,
        "detail": detail,
    }))
    print(f"CAPACITYSMOKE verdict={'PASS' if ok else 'FAIL'}",
          file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
