"""Settle the flagship-quality question: does the served ensemble beat
the linear baseline's AUC?  (VERDICT r3 weak #7 / next-step #7.)

Evaluates the ``deploy/model/graph_ensemble.json`` blend — MLP (the
committed ``checkpoints/step_1200`` the servers restore by default) +
logreg (the reference's ``modelfull`` family, sklearn-trained and
converted through ``models/logreg.from_sklearn`` exactly as served) — on
the canonical dataset with the SAME split protocol as ``ccfd_tpu train``
(seed-0 permutation, 20% held out).

Protocol: the blend weight is chosen on the TRAIN split only, then the
held-out AUC of that one chosen weight is reported (the full held-out
weight curve is recorded for transparency, not selection).  Both blend
spaces the CR's combiner family supports are evaluated: probability
averaging (the ``weighted`` combiner as served) and logit averaging
(``logit_weighted``).

Artifact: ENSEMBLE_r04.json.  The decided weights are maintained by hand
in ``deploy/model/graph_ensemble.json`` and the verdict recorded in
BASELINE.md's AUC table (this tool only measures; it does not edit
deploy configs).  Reference anchor: modelfull is the single
sklearn model the reference serves (/root/reference/deploy/model/
modelfull.json); an ensemble CR is this framework's beyond-reference
graph-serving surface.
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main() -> int:
    from sklearn.linear_model import LogisticRegression
    from sklearn.preprocessing import StandardScaler

    from ccfd_tpu.models import logreg as logreg_mod
    from ccfd_tpu.models import mlp as mlp_mod
    from ccfd_tpu.parallel.checkpoint import CheckpointManager
    from ccfd_tpu.utils.metrics_math import roc_auc

    # the exact canonical dataset the committed checkpoint trained on:
    # CCFD_CSV when present, else the full Kaggle-shaped surrogate
    # (cli._training_dataset — NOT the small test synthetic)
    from ccfd_tpu.cli import _training_dataset

    ds, source = _training_dataset()

    rng = np.random.default_rng(0)   # cmd_train's exact split protocol
    order = rng.permutation(ds.n)
    n_test = max(1, int(ds.n * 0.2))
    test, train = order[:n_test], order[n_test:]
    Xtr, ytr, Xte, yte = ds.X[train], ds.y[train], ds.X[test], ds.y[test]

    # -- member 1: the committed MLP checkpoint (what serve restores) ------
    mgr = CheckpointManager(os.path.join(REPO, "checkpoints"))
    like = mlp_mod.init(jax.random.PRNGKey(0))
    restored = mgr.restore(like)
    assert restored is not None, "no committed checkpoint found"
    params, step = restored
    p_mlp_tr = np.asarray(mlp_mod.apply(params, Xtr, np.float32)).ravel()
    p_mlp_te = np.asarray(mlp_mod.apply(params, Xte, np.float32)).ravel()

    # -- member 2: modelfull analog through the SERVED conversion ----------
    sc = StandardScaler().fit(Xtr)
    clf = LogisticRegression(max_iter=2000).fit(sc.transform(Xtr), ytr)
    lr_params = logreg_mod.from_sklearn(clf, scaler=sc)
    p_lr_tr = np.asarray(logreg_mod.apply(lr_params, Xtr, np.float32)).ravel()
    p_lr_te = np.asarray(logreg_mod.apply(lr_params, Xte, np.float32)).ravel()

    eps = 1e-7

    def logit(p):
        p = np.clip(p, eps, 1 - eps)
        return np.log(p / (1 - p))

    grid = np.round(np.arange(0.0, 1.01, 0.05), 2)

    # -- weight selection on an INNER validation split ---------------------
    # The committed checkpoint saw the whole train split, so its train
    # predictions are memorized and any weight chosen on them collapses
    # to w=1. Select instead with members trained on inner-train only
    # (64/16), then evaluate the chosen weight on the untouched test
    # split using the full-train members (standard two-stage protocol).
    from ccfd_tpu.parallel.train import TrainConfig, fit_mlp

    n_val = max(1, int(len(train) * 0.2))
    val, inner = train[:n_val], train[n_val:]
    Xin, yin = ds.X[inner], ds.y[inner]
    Xval, yval = ds.X[val], ds.y[val]
    inner_mlp = fit_mlp(Xin, yin, steps=1200,
                        tc=TrainConfig(compute_dtype="float32"))
    p_mlp_val = np.asarray(mlp_mod.apply(inner_mlp, Xval, np.float32)).ravel()
    sc_in = StandardScaler().fit(Xin)
    clf_in = LogisticRegression(max_iter=2000).fit(sc_in.transform(Xin), yin)
    lr_in = logreg_mod.from_sklearn(clf_in, scaler=sc_in)
    p_lr_val = np.asarray(logreg_mod.apply(lr_in, Xval, np.float32)).ravel()

    def curve(blend):
        va = {float(w): roc_auc(yval, blend(w, p_mlp_val, p_lr_val))
              for w in grid}
        te = {float(w): roc_auc(yte, blend(w, p_mlp_te, p_lr_te))
              for w in grid}
        w_star = max(va, key=va.get)  # chosen on the inner val split only
        return {
            "w_mlp_chosen_on_val": w_star,
            "val_auc_at_chosen": round(va[w_star], 5),
            "heldout_auc_at_chosen": round(te[w_star], 5),
            "heldout_curve": {str(w): round(v, 5) for w, v in te.items()},
        }

    prob = curve(lambda w, a, b: w * a + (1 - w) * b)
    lgt = curve(lambda w, a, b: w * logit(a) + (1 - w) * logit(b))

    auc_mlp = roc_auc(yte, p_mlp_te)
    auc_lr = roc_auc(yte, p_lr_te)
    # the combiner family, like the weight, is chosen on VALIDATION —
    # selecting by held-out score would re-introduce the exact test-set
    # optimism the inner split exists to remove
    best_kind, best = max((("prob_weighted", prob), ("logit_weighted", lgt)),
                          key=lambda kv: kv[1]["val_auc_at_chosen"])
    result = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "dataset": source,
        "checkpoint_step": step,
        "heldout_auc_mlp": round(auc_mlp, 5),
        "heldout_auc_logreg": round(auc_lr, 5),
        "prob_weighted": prob,
        "logit_weighted": lgt,
        "best": {
            "combiner": best_kind,
            "w_mlp": best["w_mlp_chosen_on_val"],
            "heldout_auc": best["heldout_auc_at_chosen"],
        },
        "beats_linear_baseline":
            best["heldout_auc_at_chosen"] > round(auc_lr, 5),
    }
    with open(os.path.join(REPO, "ENSEMBLE_r04.json"), "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
