"""Incident smoke: prove the SLO-breach flight recorder closes the loop.

Exit-code-gated drill for ``tools/verify_tier1.sh --incident-smoke``
(ISSUE 10 acceptance). Reuses the slo_smoke harness — live pipeline +
REST serving lanes, CR-loaded SLO specs, CI-scale burn windows — with the
DEVICE TELEMETRY plane and the FLIGHT RECORDER armed:

1. Baseline phase: every SLO green, ZERO incident bundles.
2. A fault-injected 200 ms scorer-latency step on the REST lane breaches
   the rest SLO. Required outcome:
   - EXACTLY ONE incident bundle (edge-triggered with the breach
     counter), schema-valid (``ccfd.incident.v3``), round-tripped over
     REAL HTTP via ``/incidents`` + ``/incidents/<id>`` (and an unknown
     id 404s);
   - the bundle's stage profile + budget ledger attribute the damage to
     the DISPATCH layer (>= 80% of the added REST latency);
   - with telemetry armed the ledger's ``h2d`` layer reports MEASURED
     (non-placeholder) values — per-put samples from the scorer's
     instrumented staging path — and the measured layers still sum to
     the measured REST e2e within tolerance;
   - the bundle carries flight data: a non-empty snapshot ring.
3. ``tools/incident_report.py`` renders the bundle (the human summary
   must build from the same bytes the exporter served).

    JAX_PLATFORMS=cpu python tools/incident_smoke.py
    tools/verify_tier1.sh --incident-smoke

Prints one JSON line on stdout; exit 0 only when every check holds.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import tempfile
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # hermetic: never dial a tunnel

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name: str):
    spec = importlib.util.spec_from_file_location(
        f"ccfd_{name}", os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main() -> int:
    from ccfd_tpu.observability.incident import validate_incident

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cr", default=os.path.join(
        REPO, "deploy", "platform_cr.yaml"))
    ap.add_argument("--baseline-s", type=float, default=5.0)
    ap.add_argument("--fault-s", type=float, default=8.0)
    ap.add_argument("--fault-ms", type=float, default=200.0)
    ap.add_argument("--windows", default="3,6,20")
    ap.add_argument("--e2e-target-ms", type=float, default=250.0)
    args = ap.parse_args()

    slo_smoke = _load_tool("slo_smoke")
    inc_dir = tempfile.mkdtemp(prefix="ccfd_incident_smoke_")
    h = slo_smoke.Harness(args.cr, args.windows, args.fault_ms,
                          e2e_target_ms=args.e2e_target_ms,
                          device=True, incident_dir=inc_dir)
    checks: dict[str, bool] = {}
    detail: dict = {}

    # -- baseline: green, no bundles --------------------------------------
    h.drive(args.baseline_s)
    base_status = h.engine.tick()
    base_stats = h.phase_stats()
    checks["baseline_green"] = not any(
        s["breaching"] or s["breaches"] for s in base_status["slos"].values())
    checks["baseline_no_bundles"] = len(h.recorder.incidents()) == 0

    # -- fault phase: the breach must dump exactly one bundle -------------
    h.fault_plan.activate()
    h.drive(args.fault_s)
    h.fault_plan.deactivate()
    h.engine.tick()
    fault_stats = h.phase_stats()

    checks["rest_breached"] = h.engine.breaches("rest-p99") >= 1
    incidents = h.recorder.incidents()
    checks["exactly_one_bundle"] = len(incidents) == 1
    detail["incidents"] = [i["id"] for i in incidents]

    # -- round trip over real HTTP ----------------------------------------
    with urllib.request.urlopen(
            h.exporter.endpoint + "/incidents", timeout=10) as resp:
        listing = json.loads(resp.read().decode())
    ids = [i["id"] for i in listing.get("incidents", [])]
    checks["listing_over_http"] = ids == [i["id"] for i in incidents]
    bundle = None
    if ids:
        with urllib.request.urlopen(
                h.exporter.endpoint + f"/incidents/{ids[0]}",
                timeout=10) as resp:
            bundle = json.loads(resp.read().decode())
    errs = validate_incident(bundle) if bundle else ["no bundle fetched"]
    checks["bundle_schema_valid"] = not errs
    if errs:
        detail["bundle_errors"] = errs[:5]
    try:
        urllib.request.urlopen(
            h.exporter.endpoint + "/incidents/inc-nope", timeout=10)
        checks["unknown_id_404"] = False
    except urllib.error.HTTPError as e:
        checks["unknown_id_404"] = e.code == 404

    # -- the bundle names the guilty layer --------------------------------
    # phase-delta attribution (the slo_smoke construction): the fault
    # phase's ADDED latency must land on the dispatch layer
    def layer_added(layer: str) -> float:
        a, b = fault_stats["layers"][layer], base_stats["layers"][layer]
        n = a["count"] - b["count"]
        fault_mean = (1e3 * (a["sum_s"] - b["sum_s"]) / n) if n > 0 else 0.0
        base_mean = (1e3 * b["sum_s"] / b["count"]) if b["count"] else 0.0
        return fault_mean - base_mean

    added = {layer: layer_added(layer)
             for layer in ("batcher_wait", "dispatch", "h2d")}
    added_sum = sum(v for v in added.values() if v > 0)
    dispatch_share = (added["dispatch"] / added_sum) if added_sum > 0 else 0.0
    detail["added_ms"] = {k: round(v, 3) for k, v in added.items()}
    detail["dispatch_share"] = round(dispatch_share, 3)
    checks["bundle_blames_dispatch"] = dispatch_share >= 0.8
    # and the bundle's own stage profile shows the step on rest.dispatch
    if bundle and isinstance(bundle.get("stage_profile"), dict):
        sp = bundle["stage_profile"]["stages"].get("rest.dispatch", {})
        p99 = sp.get("dispatch", {}).get("p99_ms", 0.0)
        checks["bundle_profile_shows_step"] = p99 >= 0.8 * args.fault_ms
        detail["bundle_rest_dispatch_p99_ms"] = p99
    else:
        checks["bundle_profile_shows_step"] = False

    # -- h2d layer: measured, and the decomposition stays complete --------
    ledger = (bundle or {}).get("slo_status", {}).get("budget_ledger") or \
        h.engine.tick().get("budget_ledger")
    h2d = ledger["layers"]["h2d"]
    checks["h2d_measured"] = (not h2d.get("static")
                              and h2d.get("count", 0) > 0)
    detail["h2d_layer"] = {k: h2d.get(k)
                           for k in ("count", "spent_p99_ms",
                                     "spent_mean_ms")}

    def phase_mean(layer: str) -> float:
        a, b = fault_stats["layers"][layer], base_stats["layers"][layer]
        n = a["count"] - b["count"]
        return (1e3 * (a["sum_s"] - b["sum_s"]) / n) if n > 0 else 0.0

    fault_n = fault_stats["rest_count"] - base_stats["rest_count"]
    fault_e2e = (1e3 * (fault_stats["rest_sum_s"]
                        - base_stats["rest_sum_s"]) / max(1, fault_n))
    # NOTE: h2d rides INSIDE the dispatch layer's wall (the scorer stages
    # within the timed score call), so the completeness check adds the
    # measured h2d mean on top and the tolerance must absorb it — on this
    # CPU harness it is microseconds against a 200 ms step
    ledger_sum = (phase_mean("batcher_wait") + phase_mean("dispatch")
                  + phase_mean("h2d") + h.cfg.slo_transport_floor_ms)
    detail["ledger_sum_ms"] = round(ledger_sum, 2)
    detail["fault_e2e_ms"] = round(fault_e2e, 2)
    tol = 0.25 * fault_e2e + 2.0
    checks["ledger_sums_to_e2e"] = abs(ledger_sum - fault_e2e) <= tol

    # -- flight data + crash-safe persistence ------------------------------
    checks["bundle_has_ring"] = bool(bundle and len(bundle["ring"]) > 0)
    on_disk = [f for f in os.listdir(inc_dir) if f.endswith(".json")]
    torn = [f for f in os.listdir(inc_dir) if f.endswith(".tmp")]
    checks["bundle_on_disk_no_tmp"] = len(on_disk) == 1 and not torn

    # -- the human report renders from the served bytes --------------------
    report = _load_tool("incident_report")
    bundle_path = os.path.join(inc_dir, on_disk[0]) if on_disk else "/nope"
    checks["report_renders"] = report.main([bundle_path]) == 0

    h.close()
    ok = all(checks.values())
    print(json.dumps({
        "harness": "incident_smoke",
        "ok": ok,
        "checks": checks,
        "detail": detail,
    }))
    print(f"INCIDENTSMOKE verdict={'PASS' if ok else 'FAIL'}",
          file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
