"""Heal smoke: prove the device self-healing loop end to end (ISSUE 11).

Exit-code-gated drill for ``tools/verify_tier1.sh --heal-smoke``: against a
LIVE in-process pipeline (producer-shaped feeder → bus → router → engine)
with the degradation ladder, overload watchdog, device telemetry, flight
recorder and DeviceSupervisor all armed —

1. A baseline phase serves through the device path and must sit HEALTHY.
2. A ``device_hang`` device fault (runtime/faults.py) is injected at the
   scorer dispatch seam. Required outcome: the supervisor's canary (and
   the serving watchdog's breaker trips) drive the state machine
   HEALTHY → SUSPECT → QUARANTINED; while quarantined, every transaction
   still gets a decision through the HOST tier with accounting conserved
   (incoming == outgoing, zero sheds) and zero rows touching the device.
3. The fault deactivates; the heal ladder walks (canary retry → reinit →
   respawn as needed) into PROBATION and re-promotes WARM: after the
   flip, a traffic phase must produce ZERO XLA compile events attributed
   to serving stages (everything compiled under ``heal.warm`` /
   warmup labels), and the device tier serves again (the degraded-host
   counter stops moving).
4. One schema-valid FlightRecorder bundle exists per transition edge
   (exactly one ``device_quarantine`` and one ``device_repromote``),
   round-tripped over REAL HTTP via ``/incidents/<id>``, and the
   ``ccfd_device_health`` gauges are scraped over the live exporter.

    JAX_PLATFORMS=cpu python tools/heal_smoke.py
    tools/verify_tier1.sh --heal-smoke

Prints one JSON line on stdout; exit 0 only when every check holds.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # hermetic: never dial a tunnel

import numpy as np  # noqa: E402

from ccfd_tpu.bus.broker import Broker  # noqa: E402
from ccfd_tpu.config import Config  # noqa: E402
from ccfd_tpu.data.ccfd import synthetic_dataset  # noqa: E402
from ccfd_tpu.metrics.exporter import MetricsExporter  # noqa: E402
from ccfd_tpu.metrics.prom import Registry  # noqa: E402
from ccfd_tpu.observability.device import DeviceTelemetry  # noqa: E402
from ccfd_tpu.observability.incident import (  # noqa: E402
    FlightRecorder,
    validate_incident,
)
from ccfd_tpu.observability.profile import StageProfiler  # noqa: E402
from ccfd_tpu.process.fraud import build_engine  # noqa: E402
from ccfd_tpu.router.router import Router, default_scorer_breaker  # noqa: E402
from ccfd_tpu.runtime import faults  # noqa: E402
from ccfd_tpu.runtime.heal import (  # noqa: E402
    NON_SERVING_COMPILE_STAGES,
    DeviceSupervisor,
)
from ccfd_tpu.runtime.overload import OverloadControl  # noqa: E402
from ccfd_tpu.serving.scorer import Scorer  # noqa: E402


def serving_compiles(prof: StageProfiler) -> int:
    return sum(v for s, v in prof.compile_counts().items()
               if s not in NON_SERVING_COMPILE_STAGES)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--hang-ms", type=float, default=400.0)
    ap.add_argument("--canary-deadline-ms", type=float, default=150.0)
    ap.add_argument("--rows-per-pump", type=int, default=256)
    ap.add_argument("--quarantine-wait-s", type=float, default=20.0)
    ap.add_argument("--heal-wait-s", type=float, default=30.0)
    args = ap.parse_args()

    checks: dict[str, bool] = {}
    detail: dict = {}

    cfg = Config(confidence_threshold=1.0)
    regs = {"router": Registry(), "kie": Registry(), "heal": Registry()}
    reg = regs["router"]
    tele = DeviceTelemetry(registry=regs["heal"], sample_every=1)
    prof = StageProfiler(registry=regs["heal"],
                         overload_registry=reg)
    prof.arm_compile_listener()
    recorder = FlightRecorder(regs, registry=regs["heal"],
                              profiler=prof, telemetry=tele, ring=16)
    broker = Broker(default_partitions=2)
    engine = build_engine(cfg, broker, regs["kie"], None)
    scorer = Scorer(model_name="mlp", batch_sizes=(16, 128, 1024),
                    host_tier_rows=0, telemetry=tele)
    scorer.warmup()
    overload = OverloadControl.from_config(cfg, reg, max_batch=1024,
                                           workers=1)
    # serving watchdog: a hung dispatch is killed (breaker trip), never
    # stalls a pump — the same bound the soak runs with
    overload.dispatch_deadline_s = 0.2
    breaker = default_scorer_breaker(reg)
    sup = DeviceSupervisor(
        scorer, registry=regs["heal"], breaker=breaker, telemetry=tele,
        profiler=prof, recorder=recorder, overload=overload,
        canary_deadline_ms=args.canary_deadline_ms,
        suspect_strikes=2, probation_canaries=3,
        backoff_base_s=0.05, backoff_cap_s=0.5,
    )
    router = Router(cfg, broker, scorer.score, engine, reg,
                    max_batch=1024, host_score_fn=scorer.host_score,
                    breaker=breaker, degrade=True, overload=overload,
                    profiler=prof, heal_gate=sup)
    exporter = MetricsExporter(regs, profiler=prof, telemetry=tele,
                               recorder=recorder).start()

    ds = synthetic_dataset(n=4096, fraud_rate=0.01, seed=7)
    rows = [",".join(f"{v:.6g}" for v in ds.X[i]).encode()
            for i in range(512)]
    produced = [0]

    def pump(n=args.rows_per_pump) -> None:
        base = produced[0]
        idx = [(base + i) % len(rows) for i in range(n)]
        broker.produce_batch(cfg.kafka_topic, [rows[i] for i in idx],
                             [(base + i) % 97 for i in range(n)])
        produced[0] = base + n
        while router.step() > 0:
            pass

    c_in = reg.counter("transaction_incoming_total")
    c_out = reg.counter("transaction_outgoing_total")
    c_deg = reg.counter("router_degraded_total")
    c_shed = reg.counter("router_shed_total")
    c_err = reg.counter("router_process_start_errors_total")

    try:
        # -- 1. baseline: device serving, supervisor healthy --------------
        pump()
        pump()
        checks["baseline_healthy"] = sup.tick() == "healthy"
        checks["baseline_device_serving"] = c_deg.total() == 0

        # -- 2. inject device_hang -> quarantine with host-tier serving ---
        plan = faults.DeviceFaultPlan.from_string(
            f"device_hang:ms={args.hang_ms}", active=True)
        faults.install_device_faults(plan)
        deadline = time.monotonic() + args.quarantine_wait_s
        state = sup.state
        while state != "quarantined" and time.monotonic() < deadline:
            state = sup.tick()
        checks["reached_quarantined"] = state == "quarantined"
        detail["quarantine_status"] = sup.status()
        host_before = c_deg.value({"tier": "host"})
        in_before = c_in.total()
        pump()
        pump()
        host_served = c_deg.value({"tier": "host"}) - host_before
        detail["host_rows_while_quarantined"] = int(host_served)
        checks["host_tier_served_quarantined_traffic"] = (
            host_served == c_in.total() - in_before > 0)

        # -- 3. heal -> warm re-promotion ----------------------------------
        faults.install_device_faults(None)
        deadline = time.monotonic() + args.heal_wait_s
        while state != "healthy" and time.monotonic() < deadline:
            state = sup.tick()
            time.sleep(0.02)
        checks["healed_to_healthy"] = state == "healthy"
        checks["repromoted_once"] = sup.repromotions == 1
        compiles_at_flip = serving_compiles(prof)
        deg_at_flip = c_deg.total()
        pump()
        pump()
        checks["warm_no_serving_compiles_after_flip"] = (
            serving_compiles(prof) == compiles_at_flip)
        detail["serving_compiles_after_flip"] = (
            serving_compiles(prof) - compiles_at_flip)
        checks["device_serving_after_flip"] = c_deg.total() == deg_at_flip

        # -- accounting: every consumed row decided, nothing shed ----------
        conserved = (c_in.total()
                     == c_out.total() + c_shed.total() + c_err.total())
        checks["accounting_conserved"] = bool(conserved)
        detail["accounting"] = {
            "incoming": c_in.total(), "outgoing": c_out.total(),
            "shed": c_shed.total(), "start_errors": c_err.total(),
        }

        # -- 4. one schema-valid bundle per transition edge ----------------
        bundles = recorder.incidents()
        kinds = [b["trigger"].get("type") for b in bundles]
        checks["one_bundle_per_edge"] = sorted(kinds) == [
            "device_quarantine", "device_repromote"]
        valid = True
        for b in bundles:
            doc = recorder.incident_doc(b["id"])
            errs = validate_incident(doc)
            if errs or doc.get("validation_errors"):
                valid = False
                detail.setdefault("bundle_errors", []).extend(errs[:5])
        checks["bundles_schema_valid"] = valid and bool(bundles)

        # -- over REAL HTTP: gauges + bundle round trip --------------------
        with urllib.request.urlopen(exporter.endpoint + "/prometheus",
                                    timeout=10) as resp:
            scrape = resp.read().decode()
        m = re.search(r'ccfd_device_health\{[^}]*state="healthy"[^}]*\} '
                      r'([0-9.e+-]+)', scrape)
        checks["health_gauge_scraped_http"] = (
            m is not None and float(m.group(1)) == 1.0)
        checks["heal_counters_scraped"] = (
            "ccfd_heal_transitions_total" in scrape
            and "ccfd_heal_canary_total" in scrape)
        with urllib.request.urlopen(exporter.endpoint + "/incidents",
                                    timeout=10) as resp:
            listing = json.loads(resp.read().decode())["incidents"]
        q_id = next((b["id"] for b in listing
                     if b["trigger"].get("type") == "device_quarantine"),
                    None)
        fetched_ok = False
        if q_id:
            with urllib.request.urlopen(
                    exporter.endpoint + f"/incidents/{q_id}",
                    timeout=10) as resp:
                fetched = json.loads(resp.read().decode())
            fetched_ok = not validate_incident(fetched)
        checks["bundle_round_trips_http"] = fetched_ok
    finally:
        faults.install_device_faults(None)
        router.close()
        exporter.stop()
        broker.close()

    ok = all(checks.values())
    print(json.dumps({"ok": ok, "checks": checks, "detail": detail,
                      "supervisor": sup.status()}))
    print(f"HEALSMOKE verdict={'PASS' if ok else 'FAIL'}", flush=True)
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
