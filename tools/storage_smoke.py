"""Storage smoke: prove the durable-state integrity plane end to end
(ISSUE 13).

Exit-code-gated drill for ``tools/verify_tier1.sh --storage-smoke``:

1. **Seed** a lifecycle state dir: a controller bootstraps a genesis
   champion (v1, checkpointed + hashed in the lineage), then a second
   champion era (v2) is stamped — two checkpoint steps on disk, v2 the
   recorded champion.
2. **Corrupt champion + torn lineage**: bitrot flips bytes in v2's
   ``params.npz`` and the live ``versions.json`` is truncated mid-frame
   (a torn write that survived a crash). A restarted controller must
   (a) QUARANTINE the torn lineage and recover the FULL lineage from the
   last-good retained generation (champion still v2, counter intact),
   (b) QUARANTINE the corrupt champion checkpoint and restore the newest
   VERIFIABLE step (v1's — the parent), with the re-stamp alarm firing so
   serving-params fingerprint == lineage ``checkpoint_hash``, and (c)
   keep the device path serving (no storage pin) with accounting exactly
   conserved through a live router.
3. **All generations corrupted**: every remaining checkpoint step gets
   bitrot. The next restart must find NOTHING verifiable and pin serving
   to the RULES tier through the heal-gate seam (``StoragePinGate``):
   every transaction still gets a decision, all of them from the rules
   floor, zero from the device or host tiers, accounting conserved.
4. **Faults + sweep + HTTP**: an injected ``torn_write`` storm makes a
   lineage save fail loudly (write_errors counted, orphan tmp left); the
   next VersionStore bring-up SWEEPS the debris
   (``ccfd_storage_tmp_swept_total``); and the ``ccfd_storage_*``
   counters plus the pin gauge are scraped over REAL HTTP.

    JAX_PLATFORMS=cpu python tools/storage_smoke.py
    tools/verify_tier1.sh --storage-smoke

Prints one JSON line on stdout; exit 0 only when every check holds.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # hermetic: never dial a tunnel

import numpy as np  # noqa: E402

from ccfd_tpu.bus.broker import Broker  # noqa: E402
from ccfd_tpu.config import Config  # noqa: E402
from ccfd_tpu.data.ccfd import synthetic_dataset  # noqa: E402
from ccfd_tpu.lifecycle.controller import (  # noqa: E402
    Guardrails,
    LifecycleController,
)
from ccfd_tpu.lifecycle.evaluator import ShadowEvaluator  # noqa: E402
from ccfd_tpu.lifecycle.shadow import ShadowTap  # noqa: E402
from ccfd_tpu.lifecycle.versions import VersionStore  # noqa: E402
from ccfd_tpu.metrics.exporter import MetricsExporter  # noqa: E402
from ccfd_tpu.metrics.prom import Registry  # noqa: E402
from ccfd_tpu.models import mlp  # noqa: E402
from ccfd_tpu.parallel.checkpoint import CheckpointManager  # noqa: E402
from ccfd_tpu.parallel.partition import params_fingerprint  # noqa: E402
from ccfd_tpu.process.fraud import build_engine  # noqa: E402
from ccfd_tpu.router.router import Router  # noqa: E402
from ccfd_tpu.runtime import durability, faults  # noqa: E402
from ccfd_tpu.serving.scorer import Scorer  # noqa: E402


def _perturb(params, delta: float):
    """Same tree, shifted last-layer bias — a distinct champion era."""
    p = {"norm": params["norm"], "layers": [dict(l) for l in params["layers"]]}
    last = dict(p["layers"][-1])
    last["b"] = np.asarray(last["b"]) + np.float32(delta)
    p["layers"][-1] = last
    return p


def _controller(cfg, scorer, store, ckpts, reg, gate=None):
    broker = Broker(default_partitions=1)
    shadow = ShadowTap(scorer, broker, cfg.shadow_topic, reg)
    evaluator = ShadowEvaluator(cfg, broker, scorer, reg)
    lc = LifecycleController(
        cfg, scorer, store=store, checkpoints=ckpts, shadow=shadow,
        evaluator=evaluator, guardrails=Guardrails(), registry=reg,
        storage_pin=(gate.pin if gate is not None else None),
        storage_unpin=(gate.unpin if gate is not None else None),
    )
    return lc, broker


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=256)
    args = ap.parse_args()

    checks: dict[str, bool] = {}
    detail: dict = {}

    state = tempfile.mkdtemp(prefix="ccfd_storage_smoke_")
    lineage_path = os.path.join(state, "versions.json")
    ckpt_dir = os.path.join(state, "checkpoints")

    reg_storage = Registry()
    reg_router = Registry()
    durability.bind_registry(reg_storage)
    cfg = Config(confidence_threshold=1.0)

    params_a = _perturb(mlp.init(jax.random.PRNGKey(0)), -1.0)
    params_b = _perturb(mlp.init(jax.random.PRNGKey(0)), +2.0)

    # -- 1. seed: two champion eras on disk --------------------------------
    reg_lc = Registry()
    scorer_a = Scorer(model_name="mlp", params=params_a,
                      batch_sizes=(16, 128, 1024), host_tier_rows=0)
    store = VersionStore(lineage_path)
    # npz path: deterministic single-file artifact the drill can bitrot
    ckpts = CheckpointManager(ckpt_dir, keep=8, use_orbax=False)
    lc_a, broker_a = _controller(cfg, scorer_a, store, ckpts, reg_lc)
    checks["seed_champion_v1"] = (store.champion() is not None
                                  and store.champion().version == 1)
    # second era, stamped the way a promotion stamps it: v2 becomes the
    # recorded champion with its own checkpoint + hash (the full gated
    # promotion is lifecycle_drill's claim, not this one's)
    store.set_stage(1, "RETIRED", reason="storage-smoke era 2")
    v2 = store.create(parent=1, stage="TRAIN")
    ckpts.pinned = {v2.version}
    ckpts.save(v2.version, params_b)
    store.set_checkpoint(v2.version, v2.version,
                         checkpoint_hash=params_fingerprint(params_b))
    store.set_stage(v2.version, "CHAMPION", reason="storage-smoke era 2")
    lc_a.close()
    broker_a.close()
    hash_b = params_fingerprint(params_b)
    detail["recorded_champion_hash"] = hash_b[:12]

    # -- 2. bitrot the champion checkpoint + tear the lineage --------------
    durability.flip_bytes(os.path.join(ckpt_dir, "step_2", "params.npz"))
    with open(lineage_path, "rb") as f:
        raw = f.read()
    with open(lineage_path, "wb") as f:
        f.write(raw[: len(raw) // 2])  # torn mid-frame

    c0 = durability.counts()
    reg_lc2 = Registry()
    scorer_b = Scorer(model_name="mlp", batch_sizes=(16, 128, 1024),
                      host_tier_rows=0)  # fresh boot params
    gate = durability.StoragePinGate(registry=reg_storage)
    store2 = VersionStore(lineage_path)
    # the torn lineage quarantined; the last-good generation recovered
    # the FULL lineage — champion v2, both eras, counter intact
    checks["lineage_quarantined"] = os.path.exists(lineage_path + ".corrupt")
    champ2 = store2.champion()
    checks["lineage_recovered_last_good"] = (
        champ2 is not None and champ2.version == 2
        and champ2.checkpoint_hash == hash_b)
    ckpts2 = CheckpointManager(ckpt_dir, keep=8, use_orbax=False)
    ckpts2.pinned = {2}
    lc_b, broker_b = _controller(cfg, scorer_b, store2, ckpts2, reg_lc2,
                                 gate=gate)
    # corrupt champion checkpoint quarantined; the newest VERIFIABLE step
    # (the parent era's) restored, and the re-stamp alarm re-recorded its
    # hash — serving params fingerprint == lineage checkpoint_hash
    checks["champion_ckpt_quarantined"] = os.path.exists(
        os.path.join(ckpt_dir, "step_2.corrupt"))
    served_fp = params_fingerprint(
        jax.tree.map(np.asarray, scorer_b.params))
    checks["last_good_restored"] = served_fp == params_fingerprint(params_a)
    checks["hash_parity_with_lineage"] = (
        store2.get(2).checkpoint_hash == served_fp)
    checks["no_pin_while_verifiable"] = not gate.pinned
    events = [e["event"] for e in store2.audit_trail()]
    checks["fallback_audited"] = "storage_fallback_restore" in events

    # device path still serves through a live router, gate composed in
    engine_b = build_engine(cfg, broker_b, Registry(), None)
    router_b = Router(cfg, broker_b, scorer_b.score, engine_b, reg_router,
                      max_batch=1024, host_score_fn=scorer_b.host_score,
                      degrade=True, heal_gate=gate)
    ds = synthetic_dataset(n=2048, fraud_rate=0.01, seed=7)
    rows = [",".join(f"{v:.6g}" for v in ds.X[i]).encode()
            for i in range(args.rows)]

    def pump(router, broker):
        broker.produce_batch(cfg.kafka_topic, rows,
                             list(range(len(rows))))
        while router.step() > 0:
            pass

    c_in = reg_router.counter("transaction_incoming_total")
    c_out = reg_router.counter("transaction_outgoing_total")
    c_deg = reg_router.counter("router_degraded_total")
    c_shed = reg_router.counter("router_shed_total")
    c_err = reg_router.counter("router_process_start_errors_total")
    pump(router_b, broker_b)
    checks["device_serving_after_restore"] = (
        c_in.total() == len(rows) and c_deg.total() == 0)
    lc_b.close()
    router_b.close()
    broker_b.close()

    # -- 3. ALL generations corrupted -> rules-tier pin --------------------
    for name in os.listdir(ckpt_dir):
        npz = os.path.join(ckpt_dir, name, "params.npz")
        if name.startswith("step_") and not name.endswith(".corrupt") \
                and os.path.exists(npz):
            durability.flip_bytes(npz)
    reg_lc3 = Registry()
    reg_router3 = Registry()
    scorer_c = Scorer(model_name="mlp", batch_sizes=(16, 128, 1024),
                      host_tier_rows=0)
    gate3 = durability.StoragePinGate(registry=reg_storage)
    store3 = VersionStore(lineage_path)
    ckpts3 = CheckpointManager(ckpt_dir, keep=8, use_orbax=False)
    lc_c, broker_c = _controller(cfg, scorer_c, store3, ckpts3, reg_lc3,
                                 gate=gate3)
    checks["pinned_when_nothing_verifies"] = (gate3.pinned
                                              and lc_c.storage_pinned)
    detail["pin_reason"] = gate3.reason
    engine_c = build_engine(cfg, broker_c, Registry(), None)
    router_c = Router(cfg, broker_c, scorer_c.score, engine_c, reg_router3,
                      max_batch=1024, host_score_fn=scorer_c.host_score,
                      degrade=True, heal_gate=gate3)
    c_in3 = reg_router3.counter("transaction_incoming_total")
    c_out3 = reg_router3.counter("transaction_outgoing_total")
    c_deg3 = reg_router3.counter("router_degraded_total")
    c_shed3 = reg_router3.counter("router_shed_total")
    c_err3 = reg_router3.counter("router_process_start_errors_total")
    pump(router_c, broker_c)
    rules_rows = c_deg3.value({"tier": "rules"})
    host_rows = c_deg3.value({"tier": "host"})
    checks["rules_tier_served_everything"] = (
        c_in3.total() == len(rows) and rules_rows == len(rows)
        and host_rows == 0)
    checks["accounting_conserved"] = (
        c_in.total() == c_out.total() + c_shed.total() + c_err.total()
        and c_in3.total()
        == c_out3.total() + c_shed3.total() + c_err3.total())
    detail["accounting"] = {
        "phase2": {"in": c_in.total(), "out": c_out.total()},
        "phase3": {"in": c_in3.total(), "out": c_out3.total(),
                   "rules": int(rules_rows), "host": int(host_rows)},
    }
    lc_c.close()
    router_c.close()
    broker_c.close()

    # corruption was detected + quarantined, last-good served — counted
    c1 = durability.counts()

    def delta(metric):
        a = sum(c0.get(metric, {}).values())
        b = sum(c1.get(metric, {}).values())
        return b - a

    checks["corruption_counted"] = delta("corrupt") >= 3
    checks["fallback_counted"] = delta("fallback") >= 1
    detail["storage_counts"] = {k: sum(v.values()) for k, v in c1.items()}

    # -- 4. injected write fault -> loud error + orphan tmp -> swept -------
    plan = faults.StorageFaultPlan.from_string("torn_write", active=True)
    faults.install_storage_faults(plan)
    store3.record_event(None, "storage-smoke", {"under": "torn_write"})
    faults.install_storage_faults(None)
    orphans = [n for n in os.listdir(state) if n.endswith(".tmp")]
    checks["torn_write_left_tmp"] = bool(orphans)
    VersionStore(lineage_path)  # bring-up sweeps the debris
    c1 = durability.counts()  # re-snapshot: phase 4 moved the counters
    checks["write_error_counted"] = delta("write_errors") >= 1
    checks["tmp_swept"] = (
        not [n for n in os.listdir(state) if n.endswith(".tmp")]
        and delta("tmp_swept") >= len(orphans))
    detail["storage_counts"] = {k: sum(v.values()) for k, v in c1.items()}

    # -- gauges + counters over REAL HTTP ----------------------------------
    exporter = MetricsExporter({"storage": reg_storage,
                                "router": reg_router}).start()
    try:
        with urllib.request.urlopen(exporter.endpoint + "/prometheus",
                                    timeout=10) as resp:
            scrape = resp.read().decode()
    finally:
        exporter.stop()
    checks["corrupt_counter_scraped_http"] = bool(re.search(
        r"ccfd_storage_corrupt_total\{[^}]*\} [1-9]", scrape))
    m = re.search(r"ccfd_storage_pinned(?:\{[^}]*\})? ([0-9.e+-]+)", scrape)
    checks["pin_gauge_scraped_http"] = (m is not None
                                        and float(m.group(1)) == 1.0)
    checks["fallback_counter_scraped"] = (
        "ccfd_storage_fallback_total" in scrape
        and "ccfd_storage_tmp_swept_total" in scrape)

    ok = all(checks.values())
    print(json.dumps({"ok": ok, "checks": checks, "detail": detail}))
    print(f"STORAGESMOKE verdict={'PASS' if ok else 'FAIL'}", flush=True)
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
