"""SLO smoke: prove the burn-rate plane fires on the right SLO, and only it.

Exit-code-gated drill for ``tools/verify_tier1.sh --slo-smoke`` (ISSUE 9
acceptance): against a LIVE in-process pipeline with the stage profiler and
SLO engine armed —

1. SLO specs load from the platform CR's ``slo:`` block (the declarative
   contract, not hard-coded harness objectives); only the burn windows are
   shrunk to seconds so a CI run can cross them.
2. A baseline phase drives both the pipeline (producer-shaped feeder →
   bus → router → engine) and the REST serving lane (DynamicBatcher in
   front of a second scorer) and must stay green on every SLO.
3. A fault phase injects a 200 ms scorer-latency step on the REST lane
   ONLY (runtime/faults.py — the same injection surface the breaker and
   overload drills use). Required outcome:
   - the REST-p99 SLO's fast-window burn rate crosses the alert
     threshold within the run and ``ccfd_slo_breach_total{slo=rest-p99}``
     increments, while e2e-p99 and error-rate stay green (0 breaches);
   - the per-layer budget ledger attributes >= 80% of the ADDED REST
     latency to the scorer-dispatch layer (phase-delta means over the
     ledger's count/sum bookkeeping);
   - the ledger's measured layers sum to the measured REST e2e latency
     within tolerance (the decomposition is complete, not just ordered).
4. The burn-rate gauges are scraped over REAL HTTP from the live
   exporter, and the StageProfile JSON artifact round-trips through the
   ``/profile`` endpoint: fetched bytes validate against the schema and
   match a locally-taken snapshot stage for stage.

    JAX_PLATFORMS=cpu python tools/slo_smoke.py
    tools/verify_tier1.sh --slo-smoke

Prints one JSON line on stdout; exit 0 only when every check holds.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # hermetic: never dial a tunnel

import numpy as np  # noqa: E402

from ccfd_tpu.bus.broker import Broker  # noqa: E402
from ccfd_tpu.config import Config  # noqa: E402
from ccfd_tpu.data.ccfd import synthetic_dataset  # noqa: E402
from ccfd_tpu.metrics.exporter import MetricsExporter  # noqa: E402
from ccfd_tpu.metrics.prom import Registry  # noqa: E402
from ccfd_tpu.observability.profile import (  # noqa: E402
    StageProfiler,
    validate_profile,
)
from ccfd_tpu.observability.slo import SLOEngine  # noqa: E402
from ccfd_tpu.platform.operator import PlatformSpec  # noqa: E402
from ccfd_tpu.process.fraud import build_engine  # noqa: E402
from ccfd_tpu.router.router import Router  # noqa: E402
from ccfd_tpu.runtime.faults import FaultPlan, FaultSpec  # noqa: E402
from ccfd_tpu.serving.batcher import DynamicBatcher  # noqa: E402
from ccfd_tpu.serving.scorer import Scorer  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class Harness:
    def __init__(self, cr_path: str, windows: str, fault_ms: float,
                 e2e_target_ms: float | None = None,
                 device: bool = False,
                 incident_dir: str | None = None):
        """``device=True`` arms the DeviceTelemetry plane (both scorers
        stage through it, the ledger's h2d layer reads measured values);
        ``incident_dir`` (may be "") additionally wires a FlightRecorder
        to the engine's breach edge and the exporter's /incidents —
        the incident smoke (tools/incident_smoke.py) reuses this harness
        with both armed."""
        self.cfg = Config(slo_windows=windows)
        # the declarative SLO contract comes from the CR, not this harness
        spec = PlatformSpec.from_yaml(cr_path, cfg=self.cfg)
        self.slo_options = dict(spec.component("slo").options)
        self.slo_options["windows"] = windows  # CI-scale burn windows
        if e2e_target_ms and self.slo_options.get("specs"):
            # CI-box margin (the load_shape --slo-ms precedent): the CR's
            # production e2e target sits inside this container's scheduler
            # noise (1-3% of rows stall past 50 ms on a busy 1-core box),
            # and the smoke's claim is "the FAULTED SLO breaches, the
            # others don't" — not "this box meets production latency".
            # Only the target widens; the spec structure stays the CR's.
            self.slo_options["specs"] = [
                ({**s, "target_ms": float(e2e_target_ms)}
                 if s.get("name") == "e2e-p99" else s)
                for s in self.slo_options["specs"]
            ]

        self.regs = {name: Registry()
                     for name in ("router", "kie", "seldon", "slo")}
        self.profiler = StageProfiler(registry=self.regs["slo"],
                                      overload_registry=self.regs["router"])
        self.profiler.arm_compile_listener()
        self.telemetry = None
        if device:
            from ccfd_tpu.observability.device import DeviceTelemetry

            self.telemetry = DeviceTelemetry(registry=self.regs["slo"])
        self.engine = SLOEngine.from_config(
            self.cfg, self.regs, self.regs["slo"],
            profiler=self.profiler, options=self.slo_options,
            telemetry=self.telemetry,
        )
        self.recorder = None
        if incident_dir is not None:
            from ccfd_tpu.observability.incident import FlightRecorder

            self.regs["incident"] = Registry()
            self.recorder = FlightRecorder(
                self.regs, registry=self.regs["incident"],
                profiler=self.profiler, telemetry=self.telemetry,
                ring=16, out_dir=incident_dir or None)
            self.engine.add_breach_listener(self.recorder.on_breach)

        # -- pipeline lane (e2e-p99 + error-rate evidence; NO faults) -----
        self.broker = Broker(default_partitions=2)
        self.kie = build_engine(self.cfg, self.broker, self.regs["kie"], None)
        scorer = Scorer(model_name="mlp", batch_sizes=(128, 1024, 4096),
                        telemetry=self.telemetry)
        scorer.warmup()
        self.router = Router(self.cfg, self.broker, scorer.score, self.kie,
                             self.regs["router"], max_batch=1024,
                             profiler=self.profiler)

        # -- REST serving lane (rest-p99 evidence; fault target) ----------
        rest_scorer = Scorer(model_name="mlp", batch_sizes=(16, 128, 1024),
                             telemetry=self.telemetry)
        rest_scorer.warmup()
        self.fault_plan = FaultPlan(
            {"scorer_rest": FaultSpec(latency_ms=fault_ms)}, active=False)
        score_rest = self.fault_plan.injector(
            "scorer_rest", self.regs["seldon"]).wrap_fn(rest_scorer.score)
        self.batcher = DynamicBatcher(score_rest, max_batch=1024,
                                      deadline_ms=1.0, workers=2,
                                      profiler=self.profiler)
        self.h_rest = self.regs["seldon"].histogram(
            "seldon_api_executor_client_requests_seconds",
            "request latency by endpoint",
        )

        ds = synthetic_dataset(n=4096, fraud_rate=0.01, seed=3)
        self.X = np.asarray(ds.X, np.float32)
        self._rows = [
            ",".join(f"{v:.6g}" for v in ds.X[i]).encode()
            for i in range(512)
        ]
        self.produced = 0
        self.exporter = MetricsExporter(self.regs, profiler=self.profiler,
                                        sink=None,
                                        telemetry=self.telemetry,
                                        recorder=self.recorder).start()

    # -- drivers -----------------------------------------------------------
    def pump_pipeline(self, rows: int = 200) -> None:
        base = self.produced
        idx = [(base + i) % len(self._rows) for i in range(rows)]
        self.broker.produce_batch(
            self.cfg.kafka_topic, [self._rows[i] for i in idx],
            [(base + i) % 97 for i in range(rows)])
        self.produced = base + rows
        while self.router.step() > 0:
            pass

    def rest_request(self, rows: int = 16) -> None:
        lo = self.produced % (len(self.X) - rows)
        t0 = time.perf_counter()
        self.batcher.score(self.X[lo:lo + rows])
        self.h_rest.observe(time.perf_counter() - t0)

    def drive(self, seconds: float, tick_s: float = 0.4) -> None:
        end = time.monotonic() + seconds
        next_tick = 0.0
        while time.monotonic() < end:
            self.pump_pipeline()
            self.rest_request()
            now = time.monotonic()
            if now >= next_tick:
                self.engine.tick()
                next_tick = now + tick_s
            time.sleep(0.02)
        self.engine.tick()

    def phase_stats(self) -> dict:
        """Cumulative per-layer + e2e counters (diffed across phases)."""
        ledger = self.engine.ledger.evaluate()
        return {
            "layers": {
                name: {"count": e["count"], "sum_s": e["sum_s"]}
                for name, e in ledger["layers"].items()
            },
            "rest_count": self.h_rest.count(),
            "rest_sum_s": self.h_rest.sum(),
        }

    def close(self) -> None:
        self.batcher.stop()
        self.router.close()
        self.exporter.stop()
        self.broker.close()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cr", default=os.path.join(
        REPO, "deploy", "platform_cr.yaml"))
    ap.add_argument("--baseline-s", type=float, default=5.0)
    ap.add_argument("--fault-s", type=float, default=8.0)
    ap.add_argument("--fault-ms", type=float, default=200.0)
    ap.add_argument("--windows", default="3,6,20",
                    help="CI-scale burn windows in seconds "
                    "(fast, fast-confirm, slow)")
    ap.add_argument("--e2e-target-ms", type=float, default=250.0,
                    help="CI-box margin for the e2e SLO target (0 keeps "
                    "the CR's production value; see Harness)")
    args = ap.parse_args()

    h = Harness(args.cr, args.windows, args.fault_ms,
                e2e_target_ms=args.e2e_target_ms)
    checks: dict[str, bool] = {}
    detail: dict = {}

    # CR really is the spec source
    spec_names = [s.name for s in h.engine.specs]
    checks["specs_from_cr"] = (
        bool(h.slo_options.get("specs"))
        and spec_names == [s["name"] for s in h.slo_options["specs"]]
    )
    detail["specs"] = spec_names

    # -- baseline: everything green ---------------------------------------
    h.drive(args.baseline_s)
    base_status = h.engine.tick()
    base_stats = h.phase_stats()
    checks["baseline_green"] = not any(
        s["breaching"] or s["breaches"] for s in base_status["slos"].values())

    # -- fault phase: 200 ms latency step on the REST scorer edge only ----
    h.fault_plan.activate()
    h.drive(args.fault_s)
    h.fault_plan.deactivate()
    status = h.engine.tick()
    fault_stats = h.phase_stats()

    rest = status["slos"]["rest-p99"]
    fast_names = [w["window"] for w in status["windows"][:-1]]
    fast_thr = status["windows"][0]["threshold"]
    detail["rest_burn"] = rest["burn_rate"]
    checks["rest_burn_crossed"] = all(
        rest["burn_rate"].get(w, 0.0) >= fast_thr for w in fast_names)
    checks["rest_breached"] = h.engine.breaches("rest-p99") >= 1
    checks["others_stayed_green"] = all(
        h.engine.breaches(name) == 0
        for name in spec_names if name != "rest-p99")

    # -- ledger attribution of the ADDED latency --------------------------
    base_e2e = (1e3 * (base_stats["rest_sum_s"])
                / max(1, base_stats["rest_count"]))
    fault_n = fault_stats["rest_count"] - base_stats["rest_count"]
    fault_e2e = (1e3 * (fault_stats["rest_sum_s"] - base_stats["rest_sum_s"])
                 / max(1, fault_n))
    added_e2e = fault_e2e - base_e2e

    # per-layer phase means: fault-phase mean minus baseline-phase mean
    def layer_added(layer: str) -> float:
        a, b = fault_stats["layers"][layer], base_stats["layers"][layer]
        n = a["count"] - b["count"]
        fault_mean = (1e3 * (a["sum_s"] - b["sum_s"]) / n) if n > 0 else 0.0
        base_mean = (1e3 * b["sum_s"] / b["count"]) if b["count"] else 0.0
        return fault_mean - base_mean

    added = {layer: layer_added(layer)
             for layer in ("batcher_wait", "dispatch")}
    added_sum = sum(v for v in added.values() if v > 0)
    dispatch_share = (added["dispatch"] / added_sum) if added_sum > 0 else 0.0
    detail["added_ms"] = {k: round(v, 2) for k, v in added.items()}
    detail["added_e2e_ms"] = round(added_e2e, 2)
    detail["dispatch_share"] = round(dispatch_share, 3)
    checks["dispatch_owns_added_latency"] = (
        dispatch_share >= 0.8
        and added["dispatch"] >= 0.8 * max(added_e2e, 1e-9))

    # measured ledger layers sum to the measured e2e within tolerance
    # (fault-phase means; transport floor + h2d are static/zero and tiny)
    def phase_mean(layer: str) -> float:
        a, b = fault_stats["layers"][layer], base_stats["layers"][layer]
        n = a["count"] - b["count"]
        return (1e3 * (a["sum_s"] - b["sum_s"]) / n) if n > 0 else 0.0

    ledger_sum = (phase_mean("batcher_wait") + phase_mean("dispatch")
                  + h.cfg.slo_transport_floor_ms)
    detail["ledger_sum_ms"] = round(ledger_sum, 2)
    detail["fault_e2e_ms"] = round(fault_e2e, 2)
    tol = 0.25 * fault_e2e + 2.0
    checks["ledger_sums_to_e2e"] = abs(ledger_sum - fault_e2e) <= tol

    # -- burn gauges over real HTTP ---------------------------------------
    with urllib.request.urlopen(
            h.exporter.endpoint + "/prometheus", timeout=10) as resp:
        scrape = resp.read().decode()
    pat = re.compile(
        r'ccfd_slo_burn_rate\{slo="rest-p99",window="%s"\} ([0-9.e+-]+)'
        % re.escape(fast_names[0]))
    m = pat.search(scrape)
    checks["burn_gauge_scraped_http"] = (
        m is not None and float(m.group(1)) >= fast_thr)
    checks["breach_counter_scraped"] = (
        'ccfd_slo_breach_total{slo="rest-p99"}' in scrape)

    # -- StageProfile artifact round-trips through /profile ---------------
    local = h.profiler.snapshot()
    with urllib.request.urlopen(
            h.exporter.endpoint + "/profile", timeout=10) as resp:
        remote = json.loads(resp.read().decode())
    errs = validate_profile(remote)
    checks["profile_schema_valid"] = not errs
    same_stages = set(remote["stages"]) == set(local["stages"]) and all(
        remote["stages"][s]["rows"] == local["stages"][s]["rows"]
        for s in local["stages"]
    )
    checks["profile_roundtrip"] = same_stages
    detail["profile_stages"] = sorted(remote.get("stages", {}))
    if errs:
        detail["profile_errors"] = errs[:5]

    h.close()
    ok = all(checks.values())
    print(json.dumps({
        "harness": "slo_smoke",
        "ok": ok,
        "checks": checks,
        "detail": detail,
    }))
    print(f"SLOSMOKE verdict={'PASS' if ok else 'FAIL'}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
