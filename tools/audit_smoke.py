"""Audit smoke: prove the decision-provenance plane reconstructs after a
crash-restore (ISSUE 14).

Exit-code-gated drill for ``tools/verify_tier1.sh --audit-smoke``:

1. **Seed** a lifecycle lineage (genesis champion, checkpointed + hashed)
   and arm the full provenance plane — AuditLog with a durable segment
   dir, a keep-everything trace sink, a FlightRecorder with a bundle dir
   and the audit embed, the lineage sample and an OPEN incident — then
   route live traffic through a real Router.
2. **Conservation**: every routed tx has exactly one record (the
   ``ccfd_audit_records_total`` counter equals the summed
   ``transaction_outgoing_total``), zero duplicates.
3. **Overhead**: the same traffic through an armed vs a disarmed router —
   the armed pipeline must stay within run-to-run noise (gated at a
   generous CI-box margin; both numbers reported).
4. **Crash**: a partial frame is torn onto the newest segment (the bytes
   a crash mid-append leaves) and every live object is abandoned.
5. **Restore + reconstruct**: a fresh AuditLog truncates the torn tail
   (counted), rebuilds the ring, and ``ccfd_tpu audit <tx_id>``
   reconstructs a specific pre-crash FRAUD decision end-to-end — record
   intact, checkpoint hash EQUAL to the lineage champion's hash (which
   equals the serving params' fingerprint), device tier recorded, the
   open incident id resolving to the on-disk bundle.
6. **HTTP**: ``/decisions`` + ``/decisions/<tx_id>`` round-trip over real
   HTTP (strict JSON, unknown id 404s), the ``ccfd_audit_*`` counters
   scrape, and the ``--url`` form of the CLI joins the kept trace.

    JAX_PLATFORMS=cpu python tools/audit_smoke.py
    tools/verify_tier1.sh --audit-smoke

Prints one JSON line on stdout; exit 0 only when every check holds.
"""
from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # hermetic: never dial a tunnel

import numpy as np  # noqa: E402

from ccfd_tpu.bus.broker import Broker  # noqa: E402
from ccfd_tpu.config import Config  # noqa: E402
from ccfd_tpu.data.ccfd import synthetic_dataset  # noqa: E402
from ccfd_tpu.lifecycle.controller import (  # noqa: E402
    Guardrails,
    LifecycleController,
)
from ccfd_tpu.lifecycle.evaluator import ShadowEvaluator  # noqa: E402
from ccfd_tpu.lifecycle.shadow import ShadowTap  # noqa: E402
from ccfd_tpu.lifecycle.versions import VersionStore  # noqa: E402
from ccfd_tpu.metrics.exporter import MetricsExporter  # noqa: E402
from ccfd_tpu.metrics.prom import Registry  # noqa: E402
from ccfd_tpu.observability.audit import AuditLog  # noqa: E402
from ccfd_tpu.observability.incident import FlightRecorder  # noqa: E402
from ccfd_tpu.observability.trace import SpanSink, Tracer  # noqa: E402
from ccfd_tpu.parallel.checkpoint import CheckpointManager  # noqa: E402
from ccfd_tpu.parallel.partition import params_fingerprint  # noqa: E402
from ccfd_tpu.process.fraud import build_engine  # noqa: E402
from ccfd_tpu.router.router import Router  # noqa: E402
from ccfd_tpu.serving.scorer import Scorer  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _pump(router, broker, cfg, rows, keys) -> None:
    broker.produce_batch(cfg.kafka_topic, rows, keys)
    while router.step() > 0:
        pass


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=512)
    ap.add_argument("--bench-rows", type=int, default=8192,
                    help="rows per overhead-measurement round")
    ap.add_argument("--overhead-max-x", type=float, default=1.5,
                    help="armed/disarmed wall-clock ratio gate (CI-box "
                    "margin; the claim is 'within run-to-run noise', "
                    "measured as min-of-3 rounds)")
    args = ap.parse_args()

    checks: dict[str, bool] = {}
    detail: dict = {}

    state = tempfile.mkdtemp(prefix="ccfd_audit_smoke_")
    audit_dir = os.path.join(state, "audit")
    inc_dir = os.path.join(state, "incidents")
    lineage_path = os.path.join(state, "versions.json")
    os.makedirs(inc_dir, exist_ok=True)

    cfg = Config(confidence_threshold=1.0)
    reg = Registry()

    # -- 1. seed: genesis champion with a recorded checkpoint hash ---------
    scorer = Scorer(model_name="mlp", batch_sizes=(16, 128, 1024, 4096),
                    host_tier_rows=0)
    scorer.warmup()
    store = VersionStore(lineage_path)
    ckpts = CheckpointManager(os.path.join(state, "checkpoints"), keep=8,
                              use_orbax=False)
    lc_broker = Broker(default_partitions=1)
    lc = LifecycleController(
        cfg, scorer, store=store, checkpoints=ckpts,
        shadow=ShadowTap(scorer, lc_broker, cfg.shadow_topic, Registry()),
        evaluator=ShadowEvaluator(cfg, lc_broker, scorer, Registry()),
        guardrails=Guardrails(), registry=Registry())
    champ = store.champion()
    serving_fp = params_fingerprint(jax.tree.map(np.asarray, scorer.params))
    checks["champion_seeded_with_hash"] = (
        champ is not None and champ.checkpoint_hash == serving_fp)
    detail["champion"] = {"version": champ.version if champ else None,
                          "hash": (champ.checkpoint_hash or "")[:12]}

    # -- the provenance plane, fully armed ---------------------------------
    sink = SpanSink(sample=1.0, max_retained=256, registry=reg)
    tracer = Tracer(reg, component="router", sink=sink)
    audit = AuditLog(dir=audit_dir, registry=reg)
    recorder = FlightRecorder({"router": reg}, registry=reg, ring=8,
                              out_dir=inc_dir, audit=audit)
    audit.lineage_fn = lambda: ((champ.version, champ.checkpoint_hash)
                                if champ else (None, None))
    # an incident is OPEN for the whole traffic window: the drill bundle
    # below stands in for a breaching SLO (the operator gates the same
    # join on SLOEngine.any_breaching; tests/test_audit.py pins that)
    open_incident: dict = {"id": None}
    audit.incident_fn = lambda: open_incident["id"]
    bundle = recorder.incident({"type": "audit_drill"})
    open_incident["id"] = bundle["id"]
    checks["drill_bundle_on_disk"] = os.path.exists(
        os.path.join(inc_dir, bundle["id"] + ".json"))

    broker = Broker(default_partitions=2)
    engine = build_engine(cfg, broker, Registry(), None)
    router = Router(cfg, broker, scorer.score, engine, reg, max_batch=1024,
                    tracer=tracer, audit=audit)

    ds = synthetic_dataset(n=4096, fraud_rate=0.01, seed=11)
    rows = [",".join(f"{v:.6g}" for v in ds.X[i]).encode()
            for i in range(args.rows)]
    keys = [f"tx-{i:05d}" for i in range(args.rows)]
    _pump(router, broker, cfg, rows, keys)
    flushed = audit.flush()
    checks["flushed_to_segments"] = flushed > 0 and bool(
        os.listdir(audit_dir))

    # -- 2. conservation: routed == recorded, zero duplicates --------------
    routed = int(reg.counter("transaction_outgoing_total").total())
    recorded = int(reg.counter("ccfd_audit_records_total").value())
    c = audit.counts()
    checks["conservation_routed_eq_recorded"] = (
        routed == recorded == args.rows)
    checks["zero_duplicates"] = (c["restamped"] == 0
                                 and c["ring"] == args.rows)
    detail["conservation"] = {"routed": routed, "recorded": recorded,
                              "restamped": c["restamped"]}

    # the target: a specific FRAUD decision stamped during the open
    # incident, with the full join set
    target = None
    for s in audit.list(limit=args.rows):
        if "fraud" in str(s.get("branch", "")) and s.get("incident"):
            target = audit.get(s["tx"])
            break
    checks["fraud_decision_found"] = target is not None
    if target is None:
        print(json.dumps({"ok": False, "checks": checks, "detail": detail}))
        print("AUDITSMOKE verdict=FAIL", flush=True)
        return 3
    tx_id = str(target["tx"])
    detail["target"] = {"tx": tx_id, "uid": target["uid"],
                        "proba": target["proba"]}

    # -- 3. overhead: armed vs disarmed within CI noise --------------------
    bench_rows = [",".join(f"{v:.6g}" for v in ds.X[i % len(ds.X)]).encode()
                  for i in range(args.bench_rows)]
    bench_keys = list(range(args.bench_rows))

    def one_round(arm: bool) -> float:
        b = Broker(default_partitions=2)
        e = build_engine(cfg, b, Registry(), None)
        r = Router(cfg, b, scorer.score, e, Registry(), max_batch=4096,
                   audit=(AuditLog(dir=None, registry=None)
                          if arm else None))
        t0 = time.perf_counter()
        _pump(r, b, cfg, bench_rows, bench_keys)
        dt = time.perf_counter() - t0
        r.close()
        b.close()
        return dt

    one_round(False)  # warm both paths once (compiles, allocator)
    disarmed = min(one_round(False) for _ in range(3))
    armed = min(one_round(True) for _ in range(3))
    ratio = armed / max(disarmed, 1e-9)
    detail["overhead"] = {"disarmed_s": round(disarmed, 4),
                          "armed_s": round(armed, 4),
                          "ratio": round(ratio, 3)}
    checks["overhead_within_noise"] = ratio <= args.overhead_max_x

    # -- 4. crash: torn frame on the newest segment, objects abandoned ----
    segs = sorted(os.listdir(audit_dir))
    newest = os.path.join(audit_dir, segs[-1])
    with open(newest, "ab") as f:
        # a crash mid-append: the frame header landed, the payload didn't
        f.write(b"CCFDSUM1 " + b"ab" * 32 + b" 4096\ntorn-payload")
    router.close()
    broker.close()
    lc.close()
    lc_broker.close()

    # -- 5. restore: truncation counted, ring rebuilt, CLI reconstructs ---
    reg2 = Registry()
    audit2 = AuditLog(dir=audit_dir, registry=reg2)
    c2 = audit2.counts()
    checks["torn_tail_truncated_and_counted"] = (
        c2["truncated_frames"] >= 1
        and int(reg2.counter("ccfd_audit_dropped_total").value(
            {"reason": "torn_tail"})) >= 1)
    checks["ring_rebuilt_after_crash"] = c2["ring"] >= args.rows
    pre_crash = dict(target)
    post = audit2.get(tx_id)
    checks["record_survives_crash"] = post == pre_crash

    from ccfd_tpu.cli import main as cli_main

    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = cli_main(["audit", tx_id, "--dir", audit_dir,
                       "--lifecycle-dir", state, "--incident-dir", inc_dir,
                       "--json"])
    checks["cli_reconstructs"] = rc == 0
    doc = json.loads(out.getvalue() or "{}")
    rec = doc.get("record", {})
    lin = doc.get("lineage", {})
    inc = doc.get("incident", {})
    checks["hash_equals_lineage_champion"] = (
        rec.get("hash") == champ.checkpoint_hash == serving_fp
        and lin.get("hash_parity") is True)
    checks["tier_intact"] = rec.get("tier") == "device"
    checks["incident_linkage_intact"] = (
        rec.get("incident") == bundle["id"] and inc.get("found") is True)
    checks["lineage_events_joined"] = len(lin.get("events") or []) > 0
    detail["reconstruction"] = {
        "hash": (rec.get("hash") or "")[:12],
        "tier": rec.get("tier"),
        "incident": rec.get("incident"),
        "trace": (rec.get("trace") or "")[:16],
    }

    # -- 6. the same reconstruction over real HTTP -------------------------
    exporter = MetricsExporter({"audit": reg2}, sink=sink,
                               audit=audit2).start()
    try:
        base = exporter.endpoint
        with urllib.request.urlopen(base + f"/decisions/{tx_id}",
                                    timeout=10) as resp:
            http_rec = json.loads(resp.read().decode())
            ctype = resp.headers.get("Content-Type", "")
        checks["decision_over_http"] = (http_rec == post
                                        and "application/json" in ctype)
        with urllib.request.urlopen(base + "/decisions?limit=8",
                                    timeout=10) as resp:
            listing = json.loads(resp.read().decode())
        checks["listing_over_http"] = (
            0 < len(listing.get("decisions", [])) <= 8)
        try:
            urllib.request.urlopen(base + "/decisions/tx-nope", timeout=10)
            checks["unknown_tx_404"] = False
        except urllib.error.HTTPError as e:
            checks["unknown_tx_404"] = e.code == 404
        with urllib.request.urlopen(base + "/prometheus",
                                    timeout=10) as resp:
            scrape = resp.read().decode()
        checks["counters_scraped_http"] = (
            "ccfd_audit_records_total" in scrape
            and 'ccfd_audit_dropped_total{reason="torn_tail"}' in scrape
            and "ccfd_audit_ring_records" in scrape
            and "ccfd_audit_log_bytes" in scrape)
        # --url mode: the kept trace joins over the live sink
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = cli_main(["audit", tx_id, "--url", base,
                           "--lifecycle-dir", state,
                           "--incident-dir", inc_dir, "--json"])
        doc2 = json.loads(out.getvalue() or "{}")
        checks["cli_url_mode"] = rc == 0 and doc2.get("record") == post
        checks["kept_trace_joined"] = (
            doc2.get("trace", {}).get("kept") is True)
    finally:
        exporter.stop()

    ok = all(checks.values())
    print(json.dumps({"ok": ok, "checks": checks, "detail": detail}))
    print(f"AUDITSMOKE verdict={'PASS' if ok else 'FAIL'}", flush=True)
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
