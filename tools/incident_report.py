"""Render a ccfd.incident.v3 bundle into the human post-mortem summary.

The FlightRecorder (observability/incident.py) dumps machine-readable
incident bundles; this tool is the responder's first read — what
breached, how hard it was burning, which layer/stage ate the latency,
what the breakers/overload plane/device were doing, WHICH transactions
were in flight (the decision-record embed, schema v2), what the
capacity model believed at the breach edge (bottleneck stage, headroom,
predicted-vs-observed p99 — schema v3), and how much flight data the
ring holds.

    python tools/incident_report.py <bundle.json>          # from disk
    python tools/incident_report.py --url http://host:9100 # newest bundle
    python tools/incident_report.py --url ... --id inc-0001-rest-p99
    python tools/incident_report.py <bundle.json> --json   # machine form

Exit codes: 0 rendered a valid bundle, 2 missing/unreadable, 3 the
bundle fails schema validation (still rendered best-effort).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ccfd_tpu.observability.incident import validate_incident  # noqa: E402


def _fetch(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def load_bundle(args) -> dict | None:
    if args.url:
        inc_id = args.id
        if inc_id is None:
            listing = _fetch(args.url.rstrip("/") + "/incidents")
            incidents = listing.get("incidents", [])
            if not incidents:
                print("no incidents recorded", file=sys.stderr)
                return None
            inc_id = incidents[0]["id"]  # newest first
        return _fetch(args.url.rstrip("/") + f"/incidents/{inc_id}")
    try:
        with open(args.bundle) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        print(f"cannot read bundle {args.bundle!r}: {e}", file=sys.stderr)
        return None


def _top_stages(doc: dict, n: int = 5) -> list[tuple[str, str, float]]:
    """(stage, component, p99_ms) sorted worst-first from the bundle's
    full stage profile."""
    out = []
    sp = doc.get("stage_profile") or {}
    for stage, entry in (sp.get("stages") or {}).items():
        for comp in ("queue", "service", "dispatch"):
            d = entry.get(comp)
            if isinstance(d, dict) and d.get("count"):
                out.append((stage, comp, float(d.get("p99_ms", 0.0))))
    return sorted(out, key=lambda t: -t[2])[:n]


def render(doc: dict) -> str:
    lines = []
    trig = doc.get("trigger", {})
    when = doc.get("generated_unix")
    when_s = (time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(when))
              if isinstance(when, (int, float)) else "?")
    lines.append(f"INCIDENT {doc.get('id', '?')}  [{when_s}]")
    lines.append(f"  trigger: {trig.get('type', '?')}"
                 + (f" slo={trig['slo']}" if trig.get("slo") else ""))
    slos = doc.get("slo_status", {}).get("slos", {})
    for name, s in slos.items():
        burns = ", ".join(f"{w}={b}" for w, b in
                          (s.get("burn_rate") or {}).items())
        flag = "BREACHING" if s.get("breaching") else "ok"
        lines.append(f"  slo {name}: {flag}  burn[{burns}]  "
                     f"budget_remaining={s.get('error_budget_remaining')}")
    ledger = doc.get("slo_status", {}).get("budget_ledger")
    if ledger:
        lines.append(f"  budget ledger ({ledger.get('slo')}, "
                     f"target {ledger.get('target_ms')} ms):")
        for lname, e in (ledger.get("layers") or {}).items():
            kind = "static" if e.get("static") else f"n={e.get('count', 0)}"
            lines.append(
                f"    {lname:<13} spent p99 {e.get('spent_p99_ms', 0):>9} ms"
                f" / budget {e.get('budget_ms', 0):>7} ms"
                f"  (ratio {e.get('ratio', 0)}, {kind})")
    top = _top_stages(doc)
    if top:
        lines.append("  worst stages (p99):")
        for stage, comp, p99 in top:
            lines.append(f"    {stage:<16} {comp:<9} {p99:>10.3f} ms")
    snap = doc.get("snapshot", {})
    gauges = snap.get("gauges", {})
    breakers = gauges.get("ccfd_breaker_state")
    if breakers:
        lines.append("  breakers: " + ", ".join(
            f"{k}={int(v)}" for k, v in breakers.items()))
    dev = snap.get("device") or {}
    h2d = dev.get("h2d") or {}
    if h2d:
        t = h2d.get("transfer") or {}
        lines.append(f"  device h2d: {h2d.get('bytes_total', 0)} bytes "
                     f"staged, {t.get('count', 0)} timed puts, "
                     f"p99 {t.get('p99_ms', 'n/a')} ms")
    mem = dev.get("memory") or {}
    for device, kinds in mem.items():
        lines.append(f"  device {device}: " + ", ".join(
            f"{k}={v}" for k, v in kinds.items()))
    decisions = doc.get("decisions") or []
    if decisions:
        lines.append(f"  in-flight decisions ({len(decisions)}, newest "
                     "first):")
        for d in decisions[:8]:
            inc = f"  incident={d['incident']}" if d.get("incident") else ""
            ver = (f" v{d['version']}" if d.get("version") is not None
                   else "")
            lines.append(
                f"    tx={d.get('tx')} uid={d.get('uid')} "
                f"p={d.get('proba'):.4f} -> {d.get('branch')} "
                f"[{d.get('tier')}{ver}]{inc}")
    cap = doc.get("capacity") or {}
    if cap:
        bn = cap.get("bottleneck") or {}
        e2e = cap.get("e2e") or {}
        lines.append("  capacity model at breach:")
        if bn:
            lines.append(
                f"    bottleneck {bn.get('stage')} "
                f"[{bn.get('layer')}]  headroom "
                f"{bn.get('headroom_ratio')}x  util "
                f"{bn.get('utilization')}  admitted "
                f"{bn.get('admitted_rows_per_s')} rows/s"
                + (f" / max {bn.get('max_rows_per_s')}"
                   if bn.get("max_rows_per_s") else ""))
        if e2e:
            lines.append(
                f"    e2e p99 predicted {e2e.get('predicted_p99_ms')} ms"
                f" vs observed {e2e.get('observed_p99_ms')} ms"
                + (f"  (error ratio {e2e.get('error_ratio')})"
                   if e2e.get("error_ratio") is not None else ""))
        regs = cap.get("regressions") or {}
        if regs:
            lines.append("    service-curve regressions: " + ", ".join(
                f"{s}x{n}" for s, n in regs.items()))
    ring = doc.get("ring", [])
    reasons: dict[str, int] = {}
    for s in ring:
        reasons[s.get("reason", "?")] = reasons.get(s.get("reason", "?"), 0) + 1
    lines.append(f"  flight ring: {len(ring)} snapshots "
                 + (f"({', '.join(f'{k}x{v}' for k, v in reasons.items())})"
                    if reasons else ""))
    if doc.get("validation_errors"):
        lines.append(f"  !! validation errors: {doc['validation_errors']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("bundle", nargs="?", help="bundle JSON path")
    ap.add_argument("--url", default="",
                    help="exporter endpoint; fetch over HTTP instead")
    ap.add_argument("--id", default=None,
                    help="incident id (with --url; default: newest)")
    ap.add_argument("--json", action="store_true",
                    help="print the machine summary instead of prose")
    args = ap.parse_args(argv)
    if not args.url and not args.bundle:
        ap.error("need a bundle path or --url")
    doc = load_bundle(args)
    if doc is None:
        return 2
    errs = validate_incident(doc)
    if args.json:
        print(json.dumps({
            "id": doc.get("id"),
            "trigger": doc.get("trigger"),
            "valid": not errs,
            "errors": errs[:10],
            "ring_depth": len(doc.get("ring", [])),
            "decisions": len(doc.get("decisions") or []),
            "bottleneck": ((doc.get("capacity") or {})
                           .get("bottleneck") or {}).get("stage"),
            "slos": {n: s.get("breaching")
                     for n, s in doc.get("slo_status", {})
                     .get("slos", {}).items()},
        }))
    else:
        print(render(doc))
        if errs:
            print(f"schema: INVALID ({len(errs)} problems)", file=sys.stderr)
    return 3 if errs else 0


if __name__ == "__main__":
    sys.exit(main())
