"""Pre-scripted REST north-star sweep: one run, every point recorded.

VERDICT r3 item 2: the only on-TPU REST number ever captured (19.6k tx/s,
p99 16 ms, native < python) predates two rounds of serving work, and healthy
tunnel windows are minutes long — too short to tune interactively.  This
script sweeps the serving configuration space in one bounded pass
(~6-8 min), records EVERY point, and reports the best configuration that
meets the north star (>=50k tx/s, p99 < 10 ms, BASELINE.md:23-26) plus the
native-vs-python A/B at that configuration.

Grid: transport {native C++ front, python} x clients {4, 8} x
rows-per-request {8, 32, 128}.  GC tuning and the measured host-tier
threshold are production defaults (cli.py serve), so the sweep measures the
deployed configuration, not a bench special.

Artifact: REST_SWEEP_r04.json (or --out).  Reference acceptance surface:
the Seldon latency/request-rate dashboard
(/root/reference/deploy/grafana/SeldonCore.json:499-531).
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "ccfd_bench", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)  # type: ignore[union-attr]
    return mod


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(REPO, "REST_SWEEP_r04.json"))
    ap.add_argument("--seconds", type=float, default=12.0,
                    help="measured window per grid point")
    ap.add_argument("--clients", default="4,8")
    ap.add_argument("--rows", default="8,32,128")
    ap.add_argument("--platform", default="",
                    help="force a jax platform (default: probe, cpu fallback)")
    args = ap.parse_args()

    bench = _load_bench()

    # Platform discipline identical to bench.py: probe in a subprocess,
    # fall back to CPU with honest labeling rather than hang on the wedge.
    platform = args.platform
    fellback = False
    if not platform:
        ok = bench._probe_backend(45.0, 1, 0.0)
        if not ok:
            platform, fellback = "cpu", True
    if platform:
        os.environ["JAX_PLATFORMS"] = platform
        import jax

        jax.config.update("jax_platforms", platform)
    import jax

    from ccfd_tpu.data.ccfd import synthetic_dataset
    from ccfd_tpu.models import mlp
    from ccfd_tpu.utils.compile_cache import enable as enable_cache
    from ccfd_tpu.utils.gctune import tune_for_service

    enable_cache()
    ds = synthetic_dataset(n=8192, fraud_rate=0.01, seed=0)
    params = mlp.init(jax.random.PRNGKey(0))
    params = mlp.set_normalizer(params, ds.X.mean(0), ds.X.std(0))
    tune_for_service()
    # Resolve the platform label ONCE, up front: jax is already initialized
    # in-process by mlp.init above, so this cannot be the first tunnel
    # dial — and a flash wedge late in the sweep must not cost the label.
    platform_label = jax.default_backend() + (
        " (fallback: accelerator probe failed)" if fellback else "")

    grid = []
    t_start = time.time()

    def flush_partial() -> None:
        """Healthy tunnel windows can be shorter than the sweep: persist
        after every point so a mid-sweep wedge (or the watcher's outer
        watchdog) keeps everything measured so far."""
        with open(args.out, "w") as f:
            json.dump({"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                           time.gmtime()),
                       "platform": platform_label, "partial": True,
                       "seconds_per_point": args.seconds,
                       "grid": grid}, f, indent=1)

    for native in (True, False):
        for n_clients in [int(c) for c in args.clients.split(",")]:
            for rows in [int(r) for r in args.rows.split(",")]:
                point = bench._bench_rest(
                    params, lat_batch=4096, seconds=args.seconds,
                    n_clients=n_clients, rows_per_req=rows, native=native,
                )
                point["native"] = native
                point["n_clients_requested"] = n_clients
                grid.append(point)
                print(json.dumps(point), flush=True)
                flush_partial()

    ok_points = [p for p in grid if "error" not in p]
    meets = [p for p in ok_points if p["p99_ms"] < 10.0]
    best = max(meets, key=lambda p: p["tx_s"]) if meets else None
    # A/B at the best configuration: the native win must be a number
    ab = None
    if best is not None:
        twin = [p for p in ok_points
                if p["native"] != best["native"]
                and p["n_clients_requested"] == best["n_clients_requested"]
                and p["rows_per_request"] == best["rows_per_request"]]
        if twin:
            nat = best if best["native"] else twin[0]
            py = twin[0] if best["native"] else best
            ab = {"native_tx_s": nat["tx_s"], "python_tx_s": py["tx_s"],
                  "native_over_python": round(nat["tx_s"] /
                                              max(py["tx_s"], 1e-9), 3)}

    report = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "platform": platform_label,
        "seconds_per_point": args.seconds,
        "sweep_wall_s": round(time.time() - t_start, 1),
        "grid": grid,
        "best": best,
        "native_vs_python_at_best": ab,
        "north_star": {
            "target_tx_s": 50_000, "target_p99_ms": 10.0,
            "met": bool(best and best["tx_s"] >= 50_000),
            "best_tx_s": best["tx_s"] if best else None,
            "best_p99_ms": best["p99_ms"] if best else None,
        },
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps({"rest_sweep": report["north_star"],
                      "platform": report["platform"]}))
    return 0 if report["north_star"]["met"] else 3


if __name__ == "__main__":
    sys.exit(main())
