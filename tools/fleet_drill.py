#!/usr/bin/env python
"""Fleet kill drill: hard-kill one member of a live fleet, prove survival.

The scenario the fleet plane (ccfd_tpu/fleet/) exists for:

  1. one shared networked bus (bus/server.py over real HTTP) + N member
     processes (``python -m ccfd_tpu fleet member``), partitions split
     across members via the bus's ``router`` consumer group;
  2. traffic flows; one member is SIGKILLed MID-TRAFFIC (no atexit, no
     commit, no socket close), then the supervisor fences its idle
     consumers so the group rebalances under a bumped epoch;
  3. survivors re-adopt the dead member's partitions (disjointly — no
     partition double-owned, none orphaned), the victim respawns and the
     fleet rebalances again;
  4. the per-transaction conservation law is checked against the durable
     fleet ledger (fleet/ledger.py): every produced tx disposed, no
     ghost, no same-epoch double-route — cross-epoch redeliveries are
     counted at-least-once deliveries, not violations;
  5. champion fingerprint parity holds across survivors (nobody
     quarantined), per-member counter accounting balances, the elected
     aggregator dumped EXACTLY ONE member-kill incident bundle, and the
     survivor's exporter serves green ccfd_fleet_* gauges over HTTP;
  6. a fleet-scaling bench row (members, tx/s) is recorded.

Exit 0 iff every check passes. tools/fleet_smoke.py runs a small/fast
parameterization of this drill for `tools/verify_tier1.sh --fleet-smoke`.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import time
from urllib.request import urlopen

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _member_env() -> dict[str, str]:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",           # members are routing drills,
        "CCFD_BATCH_SIZES": "16,128,1024",  # not accelerator benches
        "CCFD_NATIVE_FRONT": "0",
    })
    return env


def _scrape(port: int) -> str:
    with urlopen(f"http://127.0.0.1:{port}/metrics", timeout=3.0) as r:
        return r.read().decode()


def _gauge(text: str, name: str) -> float | None:
    for line in text.splitlines():
        if line.startswith(name + " ") or line.startswith(name + "{"):
            try:
                return float(line.rsplit(" ", 1)[1])
            except ValueError:
                return None
    return None


def run_drill(
    members: int = 2,
    partitions: int = 4,
    txs_before: int = 300,
    txs_after: int = 300,
    ttl_s: float = 2.0,
    state_dir: str | None = None,
    drain_timeout_s: float = 90.0,
    ready_timeout_s: float = 120.0,
) -> dict:
    from ccfd_tpu.bus.broker import Broker
    from ccfd_tpu.bus.client import RemoteBroker
    from ccfd_tpu.bus.server import BrokerServer
    from ccfd_tpu.config import Config
    from ccfd_tpu.fleet.ledger import LEDGER_TOPIC, flatten_ledger
    from ccfd_tpu.fleet.protocol import (
        check_disjoint_ownership,
        check_fingerprint_parity,
        check_ledger_conservation,
        check_member_accounting,
    )
    from ccfd_tpu.fleet.supervisor import (
        FleetSupervisor,
        _free_port,
        build_member_cr,
    )

    cfg = Config.from_env()
    out: dict = {"ok": False, "checks": {}, "members": members,
                 "partitions": partitions}
    checks = out["checks"]
    state_dir = state_dir or tempfile.mkdtemp(prefix="fleet-drill-")
    out["state_dir"] = state_dir

    # the ONE shared component: a real networked bus over real HTTP
    broker = Broker(default_partitions=partitions)
    srv = BrokerServer(broker)
    bus_port = srv.start("127.0.0.1", 0)
    bus_url = f"http://127.0.0.1:{bus_port}"
    out["bus_url"] = bus_url

    names = [f"m{i:02d}" for i in range(members)]
    hb = {n: _free_port() for n in names}
    mon = {n: _free_port() for n in names}
    eps = {n: f"http://127.0.0.1:{hb[n]}" for n in names}
    sup = FleetSupervisor(bus_url, state_dir, env=_member_env())
    for n in names:
        sup.add_member(n, build_member_cr(
            n, bus_url, hb[n], [eps[o] for o in names if o != n],
            state_dir, ttl_s=ttl_s, gossip_interval_s=0.25,
            monitoring_port=mon[n],
        ))
        sup.spawn(n)

    client = RemoteBroker(bus_url)
    led = None
    produced: list[str] = []
    seq = 0

    def produce(count: int) -> None:
        nonlocal seq
        vals, keys = [], []
        for _ in range(count):
            tx = f"tx-{seq:06d}"
            seq += 1
            produced.append(tx)
            vals.append({"id": tx, "Amount": 50.0 + (seq % 400)})
            keys.append(tx)
        client.produce_batch(cfg.kafka_topic, vals, keys=keys)

    def routed_total() -> int:
        total = 0
        for n in names:
            h = sup.health(n)
            if h is not None:
                total += int(h.get("counters", {}).get("routed", 0))
        return total

    def wait_disjoint(expect_members: int, timeout_s: float = 45.0) -> list:
        deadline = time.monotonic() + timeout_s
        violations = ["never checked"]
        while time.monotonic() < deadline:
            owners = sup.ownership()
            if len(owners) == expect_members:
                violations = check_disjoint_ownership(owners, partitions)
                if not violations:
                    return []
            time.sleep(0.3)
        return violations

    try:
        sup.wait_ready(timeout_s=ready_timeout_s)
        checks["initial_ownership_disjoint"] = (
            wait_disjoint(members) == [])

        # phase 1: traffic across the whole fleet; the kill lands
        # MID-TRAFFIC (victim demonstrably routing when it dies)
        t_bench = time.monotonic()
        produce(txs_before)
        victim = names[-1]
        deadline = time.monotonic() + 60.0
        victim_routing = False
        while time.monotonic() < deadline:
            h = sup.health(victim)
            if h is not None and int(
                    h.get("counters", {}).get("routed", 0)) > 0:
                victim_routing = True
                break
            time.sleep(0.1)
        checks["victim_was_routing"] = victim_routing

        # phase 2: HARD kill + fence; survivors must re-adopt ALL
        # partitions disjointly while traffic keeps flowing
        sup.kill(victim, fence_idle_s=0.5, settle_s=1.0)
        produce(txs_after)
        survivors = [n for n in names if n != victim]
        checks["survivors_adopted_all_partitions"] = (
            wait_disjoint(len(survivors)) == [])

        # phase 3: respawn — the fleet heals back to N members
        sup.respawn(victim, timeout_s=ready_timeout_s)
        checks["rebalanced_after_respawn"] = wait_disjoint(members) == []

        # phase 4: drain the ledger until every produced tx is disposed
        led = client.consumer("fleet-drill-ledger", (LEDGER_TOPIC,))
        entries: list[dict] = []
        disposed: set[str] = set()
        deadline = time.monotonic() + drain_timeout_s
        while time.monotonic() < deadline:
            recs = led.poll(max_records=2048, timeout_s=0.5)
            if recs:
                fresh = flatten_ledger(recs)
                entries.extend(fresh)
                disposed.update(str(e["tx"]) for e in fresh)
            if set(produced) <= disposed:
                break
        bench_wall_s = time.monotonic() - t_bench

        conservation = check_ledger_conservation(produced, entries)
        out["conservation"] = {
            k: (v if not isinstance(v, list) else v[:5])
            for k, v in conservation.items()
        }
        checks["ledger_conserved"] = bool(conservation["conserved"])
        checks["ledger_covers_all_produced"] = (
            conservation["disposed"] == conservation["produced"])

        # phase 5: parity + accounting + gauges + incident evidence
        health = {n: sup.health(n) for n in names}
        live = {n: h for n, h in health.items() if h is not None}
        checks["all_members_answer_health"] = len(live) == members
        parity = check_fingerprint_parity(
            {h["member"]: h.get("fingerprint") for h in live.values()})
        out["parity"] = parity
        checks["champion_parity"] = bool(
            parity["parity"] and parity["majority"] is not None)
        checks["nobody_quarantined"] = not any(
            h.get("quarantined") for h in live.values())
        acct_violations = check_member_accounting(
            {h["member"]: h.get("counters", {}) for h in live.values()})
        out["accounting_violations"] = acct_violations
        checks["member_accounting_balances"] = not acct_violations

        # the survivor's exporter, over real HTTP: parity green, the full
        # membership back, nobody quarantined. Polled — the survivor's
        # gossip redial to the respawned victim rides a jittered backoff,
        # so its membership view converges within ~ttl, not instantly.
        gauges_green = False
        deadline = time.monotonic() + 6.0 * ttl_s
        while not gauges_green and time.monotonic() < deadline:
            try:
                text = _scrape(mon[survivors[0]])
                gauges_green = (
                    _gauge(text, "ccfd_fleet_parity") == 1.0
                    and _gauge(text, "ccfd_fleet_members") == float(members)
                    and _gauge(text, "ccfd_fleet_quarantined") == 0.0
                )
            except OSError:
                pass
            if not gauges_green:
                time.sleep(0.3)
        checks["fleet_gauges_green"] = gauges_green

        bundles = sorted(glob.glob(os.path.join(
            state_dir, "incidents-*", "inc-*-fleet_member_kill.json")))
        out["kill_bundles"] = bundles
        checks["exactly_one_kill_bundle"] = len(bundles) == 1

        # fleet-scaling bench row (tools/multichip_scaling.py analog for
        # the HOST dimension): routed throughput across the whole drill
        # window, kill and rebalance included — the survivable number
        bench = {
            "mode": "fleet_scaling",
            "members": members,
            "partitions": partitions,
            "transactions": len(produced),
            "wall_s": round(bench_wall_s, 3),
            "tx_s": round(len(produced) / max(bench_wall_s, 1e-9), 1),
            "kill_and_rejoin_included": True,
        }
        out["bench"] = bench
        with open(os.path.join(state_dir, "fleet_bench.json"), "w") as f:
            json.dump(bench, f, indent=2)
        checks["bench_row_recorded"] = True

        out["ok"] = all(checks.values())
    finally:
        if led is not None:
            led.close()
        client.close()
        sup.stop_all()
        srv.stop()
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--members", type=int, default=2)
    ap.add_argument("--partitions", type=int, default=4)
    ap.add_argument("--txs-before", type=int, default=300)
    ap.add_argument("--txs-after", type=int, default=300)
    ap.add_argument("--ttl-s", type=float, default=2.0)
    ap.add_argument("--state-dir", default=None,
                    help="keep artifacts here (default: fresh tempdir)")
    args = ap.parse_args()
    out = run_drill(
        members=args.members,
        partitions=args.partitions,
        txs_before=args.txs_before,
        txs_after=args.txs_after,
        ttl_s=args.ttl_s,
        state_dir=args.state_dir,
    )
    print(json.dumps(out, indent=2))
    print(f"FLEETDRILL verdict={'PASS' if out['ok'] else 'FAIL'}",
          file=sys.stderr)
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
