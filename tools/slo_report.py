"""StageProfile writer: produce the planner-input artifact, validated.

The machine-readable counterpart of ``tools/trace_report.py``'s human
table (ISSUE 9): emits the **StageProfile JSON artifact** — the per-stage
queueing/service/dispatch decomposition with batch-conditioned service
curves and XLA compile attribution (``observability/profile.py``,
schema ``ccfd.stage_profile.v1``) — the input contract ROADMAP item 3's
provisioning planner consumes, plus the SLO engine's burn-rate/budget
status alongside on stdout.

Two modes:

- **live** (``--url http://host:9100``): fetch ``/profile`` from a running
  platform's metrics exporter, validate it against the schema, write it
  crash-safely (tmp+rename).
- **drive** (default): bring up the in-process pipeline + REST lane with
  the profiler and SLO engine armed (the slo_smoke harness, no faults),
  run traffic for ``--seconds``, verify the document round-trips through
  the live exporter's ``/profile`` over real HTTP, and write it.

    JAX_PLATFORMS=cpu python tools/slo_report.py --out STAGE_PROFILE.json
    python tools/slo_report.py --url http://127.0.0.1:9100

Exit 0 only when the artifact validates and carries at least one stage
with samples; one JSON status line on stdout.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=os.path.join(REPO, "STAGE_PROFILE.json"))
    ap.add_argument("--url", default="",
                    help="fetch /profile from a live exporter instead of "
                    "driving an in-process pipeline")
    ap.add_argument("--seconds", type=float, default=6.0)
    ap.add_argument("--cr", default=os.path.join(
        REPO, "deploy", "platform_cr.yaml"))
    args = ap.parse_args()

    from ccfd_tpu.observability.profile import (
        validate_profile,
        write_json_crash_safe,
    )

    slo_status = None
    if args.url:
        with urllib.request.urlopen(
                args.url.rstrip("/") + "/profile", timeout=10) as resp:
            doc = json.loads(resp.read().decode())
    else:
        import jax

        jax.config.update("jax_platforms", "cpu")
        from slo_smoke import Harness

        h = Harness(args.cr, windows="5,10,30", fault_ms=0.0)
        try:
            h.drive(args.seconds)
            slo_status = h.engine.tick()
            # the artifact is read over the SAME surface the planner will
            # use: the live exporter's /profile, not a private snapshot
            with urllib.request.urlopen(
                    h.exporter.endpoint + "/profile", timeout=10) as resp:
                doc = json.loads(resp.read().decode())
        finally:
            h.close()

    errs = validate_profile(doc)
    sampled = [s for s, e in doc.get("stages", {}).items()
               if any(isinstance(e.get(c), dict) and e[c].get("count", 0)
                      for c in ("queue", "service", "dispatch"))]
    ok = not errs and bool(sampled)
    if ok:
        write_json_crash_safe(args.out, doc)
    print(json.dumps({
        "harness": "slo_report",
        "ok": ok,
        "out": args.out if ok else None,
        "schema": doc.get("schema"),
        "stages_with_samples": sorted(sampled),
        "validation_errors": errs[:5],
        "slo": (slo_status or {}).get("slos"),
        "budget_ledger": (slo_status or {}).get("budget_ledger"),
    }))
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
