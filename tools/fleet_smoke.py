#!/usr/bin/env python
"""Fleet smoke: the member-kill drill at CI size, with a one-line verdict.

Runs tools/fleet_drill.py's scenario small and fast — a 2-member fleet
over one real-HTTP bus, hard-kill one member mid-traffic, assert
partition re-adoption, exact fleet-ledger conservation, champion-parity
gauges green and exactly one member-kill incident bundle. Prints
``FLEETSMOKE verdict=PASS|FAIL`` and exits 0/1; wired into
``tools/verify_tier1.sh --fleet-smoke``.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.fleet_drill import run_drill  # noqa: E402


def main() -> int:
    out = run_drill(members=2, partitions=4, txs_before=200, txs_after=200,
                    ttl_s=2.0)
    print(json.dumps(out, indent=2))
    failed = sorted(k for k, v in out["checks"].items() if not v)
    if failed:
        print(f"FLEETSMOKE failed checks: {failed}", file=sys.stderr)
    print(f"FLEETSMOKE verdict={'PASS' if out['ok'] else 'FAIL'}")
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
