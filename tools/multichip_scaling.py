"""Sharded-path scaling curve on virtual devices -> MULTICHIP_r{N}.json.

VERDICT r2 weak #6 / next-step #7: multi-chip correctness is covered by the
dryrun and mesh tests, but no artifact records how the sharded paths BEHAVE
as the mesh grows. This tool measures sharded scoring and sharded retrain
throughput at 1/2/4/8 virtual CPU devices (one subprocess per mesh size so
each gets a fresh XLA_FLAGS device count) and writes the curve.

Read the numbers as EVIDENCE OF SCALING BEHAVIOR, not absolute perf: the
virtual devices all share this host's core(s) (the bench host has ONE), so
ideal scaling shows roughly FLAT total throughput with mesh size — the work
is genuinely partitioned N ways onto N XLA devices that each get 1/N of a
core. Collapse with device count would indicate sharding overhead
(collectives, layout churn) dominating; that is the regression this curve
exists to catch. Real-chip scaling needs real chips (the driver's bench host
exposes one).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, os, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

n = int(os.environ["CCFD_SCALE_DEVICES"])
assert len(jax.devices()) >= n, (len(jax.devices()), n)

from ccfd_tpu.parallel import multihost
from ccfd_tpu.parallel.train import TrainConfig, init_state, make_train_step
from ccfd_tpu.parallel.sharding import shard_params, replicated
from ccfd_tpu.models import mlp
from ccfd_tpu.serving.scorer import Scorer

devices = jax.devices()[:n]
mesh = multihost.make_global_mesh(model_parallel=1, devices=devices)

out = {"devices": n}

# --- sharded scoring (data-axis row sharding, replicated params) ---------
params = mlp.init(jax.random.PRNGKey(0), hidden=256)
scorer = Scorer(model_name="mlp", params=params, mesh=mesh,
                compute_dtype="float32", batch_sizes=(16384,),
                host_tier_rows=0, use_fused=False)
X = np.random.default_rng(0).standard_normal((16384, 30)).astype(np.float32)
scorer.score_pipelined(X, depth=1)  # compile
rows = 0
t0 = time.perf_counter()
while (el := time.perf_counter() - t0) < 2.0:
    scorer.score_pipelined(X, depth=2)
    rows += X.shape[0]
out["score_tx_s"] = round(rows / el, 1)

# --- sharded retrain (dp over the mesh) ----------------------------------
tc = TrainConfig(compute_dtype="float32", learning_rate=0.01)
params = mlp.init(jax.random.PRNGKey(1), hidden=256)
params = shard_params(params, jax.tree.map(lambda _: replicated(mesh), params))
state = init_state(params, tc)
step = make_train_step(tc, mesh=mesh)
xb = np.random.default_rng(1).standard_normal((4096, 30)).astype(np.float32)
yb = (np.random.default_rng(2).random(4096) < 0.1).astype(np.float32)
state, loss = step(state, xb, yb)  # compile
jax.block_until_ready(loss)
steps = 0
t0 = time.perf_counter()
while (el := time.perf_counter() - t0) < 2.0:
    state, loss = step(state, xb, yb)
    jax.block_until_ready(loss)
    steps += 1
out["retrain_steps_s"] = round(steps / el, 2)
out["retrain_labels_s"] = round(steps * 4096 / el, 1)

# --- long-context: sequence-parallel attention over the mesh -------------
# ring (ppermute rotation) and ulysses (all-to-all reshard) at sp = n:
# the curve records how the two strategies behave as the sequence axis
# shards wider (first-class long-context evidence, SURVEY beyond-reference)
from ccfd_tpu.models import seq as seq_mod

B, L = 128, 64
sparams = seq_mod.init(jax.random.PRNGKey(2))
xs = jnp.asarray(
    np.random.default_rng(3).standard_normal((B, L, 30)), jnp.float32
)

def measure_seq(attn, budget_s=2.0):
    @jax.jit
    def step(p, xx):
        return jax.nn.sigmoid(
            seq_mod.logits(p, xx, jnp.float32, attention_fn=attn)
        )
    jax.block_until_ready(step(sparams, xs))
    count = 0
    t0 = time.perf_counter()
    while True:
        # block every step: dispatch is async, and counting enqueues with
        # a frozen clock would record dispatch rate, not execution rate
        jax.block_until_ready(step(sparams, xs))
        count += B
        ell = time.perf_counter() - t0
        if ell >= budget_s:
            return round(count / ell, 1)

seq_out = {"batch": B, "seq_len": L}
if n == 1:
    seq_out["single_histories_s"] = measure_seq(None)
else:
    from ccfd_tpu.ops.ring_attention import ring_attention
    from ccfd_tpu.ops.ulysses import ulysses_attention
    from ccfd_tpu.parallel.mesh import make_mesh

    sp_mesh = make_mesh(model_parallel=n, devices=devices)
    seq_out["sp_degree"] = n
    seq_out["ring_histories_s"] = measure_seq(
        lambda q, k, v: ring_attention(q, k, v, sp_mesh, "model")
    )
    n_heads = seq_mod.N_HEADS
    if n_heads % n == 0:
        seq_out["ulysses_histories_s"] = measure_seq(
            lambda q, k, v: ulysses_attention(q, k, v, sp_mesh, "model")
        )
    else:
        # documented constraint: ulysses reshards heads over the axis and
        # needs heads % sp == 0; ring has no such bound
        seq_out["ulysses_histories_s"] = (
            f"n/a (heads {n_heads} not divisible by sp {n})"
        )
out["seq"] = seq_out
print("RESULT " + json.dumps(out))
"""


def measure(n: int, timeout_s: float = 600.0) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={n}"
    ).strip()
    env["CCFD_SCALE_DEVICES"] = str(n)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-c", _CHILD], capture_output=True, text=True,
        timeout=timeout_s, env=env, cwd=REPO,
    )
    for line in (r.stdout or "").splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise RuntimeError(
        f"n={n}: no RESULT (rc={r.returncode})\n{(r.stderr or '')[-800:]}"
    )


def main() -> int:
    sizes = [int(s) for s in (sys.argv[1:] or ["1", "2", "4", "8"])]
    curve = []
    for n in sizes:
        t0 = time.time()
        res = measure(n)
        res["wall_s"] = round(time.time() - t0, 1)
        curve.append(res)
        print(f"  devices={n}: score {res['score_tx_s']:,.0f} tx/s, "
              f"retrain {res['retrain_steps_s']} steps/s", file=sys.stderr)
    try:
        host_cores = os.cpu_count() or 1
    except Exception:  # pragma: no cover
        host_cores = 1
    out = {
        "kind": "virtual-device scaling curve (shared host cores — read as "
                "sharding-overhead evidence, not speedup; see tools/"
                "multichip_scaling.py docstring)",
        "platform": "cpu (virtual devices)",
        "host_cores": host_cores,
        "curve": curve,
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
