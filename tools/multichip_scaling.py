"""Sharding-overhead curve on virtual devices -> MULTICHIP_SCALING_r{N}.json.

VERDICT r3 weak #3: on a shared-core host, an absolute-throughput-vs-mesh
curve is confounded (all N virtual devices share the same core(s), so the
numbers wobble with scheduler noise and prove little).  What a 1-core host
CAN measure cleanly is **sharding overhead at fixed global work**: run the
SAME global batch unsharded (1 device) and sharded (N devices) in the same
process, and report the wall-time ratio.  Ideal partitioning costs ~0%
extra (same total FLOPs on the same core); growth with N isolates exactly
the partitioning/collective/layout overhead that sharding adds.

Each mesh size also records the COMM-OP COUNT from the compiled sharded
HLO (all-reduce / all-gather / reduce-scatter / collective-permute /
all-to-all) — the static evidence of what the partitioner inserted, which
is the part that translates to real chips (where those ops ride ICI
instead of a memcpy).

Sections per mesh size n: data-sharded scoring forward, dp-sharded train
step, and sequence-parallel attention (ring + ulysses at sp=n) vs the
single-device attention on the same (batch, seq) work.

The scoring/train sections build through bench.py's ``_section_scorer`` /
``_hop_buckets`` construction and the live platform's partitioner
(parallel/partition.py DataParallelPartitioner over a named mesh) — the
SAME bucket ladder, compute dtype and dispatch surface bench's devices=N
scaling row measures, so dryrun and bench numbers are directly comparable
(ISSUE 12 satellite).

Run: python tools/multichip_scaling.py [sizes...]   (default 2 4 8)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, os, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

n = int(os.environ["CCFD_SCALE_DEVICES"])
assert len(jax.devices()) >= n, (len(jax.devices()), n)

from ccfd_tpu.parallel.train import TrainConfig, init_state, make_train_step
from ccfd_tpu.models import mlp

devices = jax.devices()[:n]

COMM_OPS = ("all-reduce", "all-gather", "reduce-scatter",
            "collective-permute", "all-to-all")

def comm_counts(compiled):
    txt = compiled.as_text()
    return {op: txt.count(op) for op in COMM_OPS if txt.count(op)}

# AOT-compile so timing and comm_counts share ONE executable (a second
# implicit jit compile just to read the HLO would roughly double each
# section's compile wall time on this host)
def compile_once(fn, *args):
    return fn.lower(*args).compile()

def timed(fn, *args, budget_s=1.5):
    jax.block_until_ready(fn(*args))  # compile (no-op for AOT) + warm
    jax.block_until_ready(fn(*args))
    count = 0
    t0 = time.perf_counter()
    while True:
        jax.block_until_ready(fn(*args))
        count += 1
        el = time.perf_counter() - t0
        if el >= budget_s:
            return el / count

out = {"devices": n}

# --- scoring: same 16384-row global work through the SHARED bench
# construction (_section_scorer/_hop_buckets + the live platform's
# partitioner), unsharded vs data-sharded — dryrun and bench scaling-row
# numbers are built on one scorer surface and stay comparable
import bench
from ccfd_tpu.parallel.mesh import make_named_mesh
from ccfd_tpu.parallel.partition import DataParallelPartitioner

X = np.random.default_rng(0).standard_normal((16384, 30)).astype(np.float32)
params = mlp.init(jax.random.PRNGKey(0), hidden=256)
s_un = bench._section_scorer("mlp", params, X.shape[0], use_fused=False)
t_un = timed(lambda: s_un.score_pipelined(X, depth=1))

part = DataParallelPartitioner(make_named_mesh(devices))
s_sh = bench._section_scorer("mlp", params, X.shape[0], use_fused=False,
                             partitioner=part)
t_sh = timed(lambda: s_sh.score_pipelined(X, depth=1))
# the sharded serving executable's comm-op count: the Scorer's jitted
# apply with the partitioner's in/out shardings (same surface the row
# above serves from)
xb = s_sh._put_batch(np.zeros((s_sh.batch_sizes[-1], 30), np.float32))
out["score"] = {
    "global_rows": int(X.shape[0]),
    "construction": "bench._section_scorer (_hop_buckets ladder, "
                    "bf16, partitioner-sharded)",
    "unsharded_ms": round(t_un * 1e3, 3),
    "sharded_ms": round(t_sh * 1e3, 3),
    "overhead_pct": round((t_sh / t_un - 1) * 100, 1),
    "comm_ops": comm_counts(
        s_sh._apply.lower(s_sh.params, xb).compile()),
}

# --- train step: same 4096-row global batch, dp-sharded vs unsharded -----
tc = TrainConfig(compute_dtype="float32", learning_rate=0.01)
xb = np.random.default_rng(1).standard_normal((4096, 30)).astype(np.float32)
yb = (np.random.default_rng(2).random(4096) < 0.1).astype(np.float32)

params1 = mlp.init(jax.random.PRNGKey(1), hidden=256)
step1 = make_train_step(tc, mesh=None)
state1 = init_state(jax.device_put(params1, devices[0]), tc)
def train_once_un(s=[state1]):
    s[0], loss = step1(s[0], xb, yb)
    return loss
t_un = timed(train_once_un)

params_n = mlp.init(jax.random.PRNGKey(1), hidden=256)
# the live platform's retrain construction: the partitioner's explicit-
# sharding donated step (parallel/partition.py), same as OnlineTrainer's
step_n = make_train_step(tc, partitioner=part)
state_n = init_state(params_n, tc)
xb_sh = jax.device_put(xb, part.batch_sharding)
yb_sh = jax.device_put(yb, part.out_sharding)
def train_once_sh(s=[state_n]):
    s[0], loss = step_n(s[0], xb_sh, yb_sh)
    return loss
t_sh = timed(train_once_sh)
# make_train_step hides its jit inside a closure, so the comm count
# comes from a minimal gradient-only executable with the SAME loss
# config (pos_weight included). The optimizer update adds no
# collectives under this sharding (elementwise on replicated params /
# already-reduced grads), so the gradient all-reduce IS the step's
# comm signature; the extra small compile is the price of honesty here.
grad_jit = jax.jit(
    lambda p, x, y: jax.grad(
        lambda pp, xx, yy: mlp.loss_fn(
            pp, xx, yy, pos_weight=tc.pos_weight, compute_dtype=jnp.float32
        )
    )(p, x, y),
    in_shardings=(None, part.batch_sharding, part.out_sharding),
)
out["retrain"] = {
    "global_rows": int(xb.shape[0]),
    "unsharded_ms": round(t_un * 1e3, 3),
    "sharded_ms": round(t_sh * 1e3, 3),
    "overhead_pct": round((t_sh / t_un - 1) * 100, 1),
    # replicated params + row-sharded batch: XLA must all-reduce the
    # gradients — the partitioner's insertion count is the static
    # evidence that carries to real chips
    "grad_comm_ops": comm_counts(compile_once(grad_jit, params_n, xb_sh, yb_sh)),
}

# --- long-context: ring/ulysses at sp=n vs single-device attention -------
# Two regimes at equal token count: short windows (L=64) where the ring's
# per-hop latency dominates, and long windows (L=512) where per-chunk
# attention compute (O(L^2/sp)) amortizes the same number of hops — the
# regime sequence parallelism exists for.
from ccfd_tpu.models import seq as seq_mod

sparams = seq_mod.init(jax.random.PRNGKey(2))

def seq_step(attn):
    return jax.jit(lambda p, xx: jax.nn.sigmoid(
        seq_mod.logits(p, xx, jnp.float32, attention_fn=attn)
    ))

out["seq"] = []
for B, L in ((128, 64), (16, 512)):
    xs = jnp.asarray(
        np.random.default_rng(3).standard_normal((B, L, 30)), jnp.float32
    )
    t_single = timed(seq_step(None), sparams, xs)
    seq_out = {"batch": B, "seq_len": L,
               "single_ms": round(t_single * 1e3, 3)}
    if n > 1:
        from ccfd_tpu.ops.ring_attention import ring_attention
        from ccfd_tpu.ops.ulysses import ulysses_attention
        from ccfd_tpu.parallel.mesh import make_mesh

        sp_mesh = make_mesh(model_parallel=n, devices=devices)
        seq_out["sp_degree"] = n
        ring_fn = seq_step(
            lambda q, k, v: ring_attention(q, k, v, sp_mesh, "model")
        )
        ring_c = compile_once(ring_fn, sparams, xs)
        t_ring = timed(ring_c, sparams, xs)
        seq_out["ring_ms"] = round(t_ring * 1e3, 3)
        seq_out["ring_overhead_pct"] = round((t_ring / t_single - 1) * 100, 1)
        seq_out["ring_comm_ops"] = comm_counts(ring_c)
        n_heads = seq_mod.N_HEADS
        if n_heads % n == 0:
            uly_fn = seq_step(
                lambda q, k, v: ulysses_attention(q, k, v, sp_mesh, "model")
            )
            uly_c = compile_once(uly_fn, sparams, xs)
            t_uly = timed(uly_c, sparams, xs)
            seq_out["ulysses_ms"] = round(t_uly * 1e3, 3)
            seq_out["ulysses_overhead_pct"] = round(
                (t_uly / t_single - 1) * 100, 1
            )
            seq_out["ulysses_comm_ops"] = comm_counts(uly_c)
        else:
            # documented constraint: ulysses reshards heads over the axis
            # and needs heads % sp == 0; ring has no such bound
            seq_out["ulysses_ms"] = f"n/a (heads {n_heads} % sp {n} != 0)"
    out["seq"].append(seq_out)
print("RESULT " + json.dumps(out))
"""


def measure(n: int, timeout_s: float = 900.0) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={n}"
    ).strip()
    env["CCFD_SCALE_DEVICES"] = str(n)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-c", _CHILD], capture_output=True, text=True,
        timeout=timeout_s, env=env, cwd=REPO,
    )
    for line in (r.stdout or "").splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise RuntimeError(
        f"n={n}: no RESULT (rc={r.returncode})\n{(r.stderr or '')[-800:]}"
    )


def main() -> int:
    sizes = [int(s) for s in (sys.argv[1:] or ["2", "4", "8"])]
    curve = []
    for n in sizes:
        t0 = time.time()
        res = measure(n)
        res["wall_s"] = round(time.time() - t0, 1)
        curve.append(res)
        print(f"  devices={n}: score overhead {res['score']['overhead_pct']}%"
              f", retrain overhead {res['retrain']['overhead_pct']}%",
              file=sys.stderr)
    out = {
        "kind": "sharding-overhead curve at FIXED GLOBAL WORK on shared "
                "host cores: same batch unsharded (1 device) vs sharded "
                "(N virtual devices) in one process — overhead_pct "
                "isolates partitioning/collective cost; comm_ops is the "
                "partitioner's static insertion count (the part that "
                "carries to real chips)",
        "platform": "cpu (virtual devices)",
        "host_cores": os.cpu_count() or 1,
        "curve": curve,
    }
    print(json.dumps(out))
    # round-stamped artifact (CCFD_ROUND, default 04) so later rounds
    # don't silently overwrite this round's evidence
    rnd = os.environ.get("CCFD_ROUND", "04")
    path = os.path.join(REPO, f"MULTICHIP_SCALING_r{rnd}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
