"""Memory-drift evidence: RSS, GC pressure, per-component object counts,
and tracemalloc top allocators.

The round-5 endurance soak measured a ~23 MB/min RSS drift nobody has
located. This module is the instrumentation that names the component next
time: a process RSS reading every exporter scrape can gauge
(``ccfd_process_rss_bytes``), per-component live-object counts
(``ccfd_component_objects{component=...}`` — registered as probes by
whoever owns the container), and an on-demand ``/memory`` JSON endpoint
(metrics/exporter.py) that adds a tracemalloc top-allocators table when
allocation tracing is on.

tracemalloc costs ~2x allocation overhead while tracing, so it is OFF by
default and armed explicitly: ``GET /memory?trace=1`` (or
``ensure_tracemalloc()``) starts it; subsequent ``/memory`` reads include
the top allocation sites since then. That makes the drift workflow:
notice the slope (soak artifact / RSS gauge), arm tracing, wait, read
``/memory``, read the component name off the top of the table.
"""

from __future__ import annotations

import gc
from typing import Any, Callable, Mapping


def rss_bytes() -> int:
    """Resident set size from /proc (Linux); 0 where unavailable."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return 0


def ensure_tracemalloc(nframes: int = 5) -> bool:
    """Arm allocation tracing (idempotent); returns whether it is on."""
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start(nframes)
    return tracemalloc.is_tracing()


def tracemalloc_top(limit: int = 15) -> list[dict[str, Any]]:
    """Top allocation sites by retained bytes; [] when tracing is off."""
    import tracemalloc

    if not tracemalloc.is_tracing():
        return []
    snap = tracemalloc.take_snapshot()
    # the profiler's own frames would otherwise dominate the table
    snap = snap.filter_traces((
        tracemalloc.Filter(False, "<frozen importlib._bootstrap>"),
        tracemalloc.Filter(False, tracemalloc.__file__),
    ))
    out = []
    for stat in snap.statistics("lineno")[:limit]:
        frame = stat.traceback[0]
        out.append({
            "file": frame.filename,
            "line": frame.lineno,
            "size_bytes": stat.size,
            "count": stat.count,
        })
    return out


def memory_report(
    probes: Mapping[str, Callable[[], float]] | None = None,
    top: int = 15,
) -> dict[str, Any]:
    """One self-contained memory evidence blob (the /memory body).

    ``probes`` maps component name -> live-object-count callable; a probe
    that raises reads as -1 (a dead component is itself evidence)."""
    import tracemalloc

    components: dict[str, float] = {}
    for name, fn in (probes or {}).items():
        try:
            components[name] = float(fn())
        # ccfd-lint: disable=counted-drops -- the -1 sentinel lands in the scraped gauge: a dead component is visible evidence, not a swallow
        except Exception:  # noqa: BLE001 - a broken probe must not 500
            components[name] = -1.0
    report: dict[str, Any] = {
        "rss_bytes": rss_bytes(),
        "gc": {
            "counts": gc.get_count(),
            "garbage": len(gc.garbage),
        },
        "components": components,
        "tracemalloc": {
            "tracing": tracemalloc.is_tracing(),
            "top": tracemalloc_top(top),
        },
    }
    if tracemalloc.is_tracing():
        # gc.get_objects() materializes a list referencing EVERY tracked
        # object — at drift-incident scale that is a multi-hundred-MB
        # transient spike of exactly the signal this endpoint measures,
        # so the full object walk rides the same explicit opt-in as the
        # allocator table (?trace=1)
        report["gc"]["tracked_objects"] = len(gc.get_objects())
    if tracemalloc.is_tracing():
        cur, peak = tracemalloc.get_traced_memory()
        report["tracemalloc"]["traced_bytes"] = cur
        report["tracemalloc"]["peak_bytes"] = peak
    return report
