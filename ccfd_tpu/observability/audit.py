"""Decision provenance plane: one compact audit record per routed transaction.

Fraud serving is regulated — every score that routed a transaction must be
reconstructable after the fact ("Rethinking LLMOps for Fraud and AML",
PAPERS.md; ROADMAP item 5a). Before this plane the evidence existed but was
scattered across five other planes with no per-decision join: the trace
sink (PR 2), the lifecycle lineage (PR 4), the degradation-tier counters
(PR 1), the admission plane (PR 6) and the incident recorder (PR 10).
PRETZEL's white-box argument applies to audit too: because we own every
stage of the pipeline, provenance is STAMPED inline at the route seam for
near-zero cost instead of re-derived from logs after the fact.

The unit is the :class:`DecisionRecord` — a plain dict (wire-format
friendly, built once per routed row on the hot path) carrying:

==============  ==========================================================
key             meaning
==============  ==========================================================
``seq``         process-monotone stamp sequence
``tx``          transaction id (the record key / tx ``id`` field)
``uid``         bus coordinate ``"<partition>:<offset>"`` — unique per
                consumed record, the dedupe key under crash-replay
``ts``          the record's PRODUCE timestamp (what the decision-latency
                histogram is measured against)
``decided_ts``  when the decision was stamped
``proba``       the score that routed the row
``threshold``   the FRAUD_THRESHOLD in force
``rule``        the fired rule's name
``branch``      the routed branch (the process definition started)
``pid``         the engine process-instance id
``tier``        serving tier that produced the score:
                ``device`` | ``host`` | ``rules``
``cause``       why a degraded tier served (``quarantine`` |
                ``storage_pin`` | ``breaker_open`` | ``score_error``);
                absent on the healthy path
``events``      batch-level overload/edge events observed while scoring
                (``breaker_open``, ``watchdog_timeout``, ``score_error``)
``priority``    admission class (``bulk`` | ``normal`` | ``critical``)
``version``     champion model version id (lifecycle lineage, sampled
                once per batch — not per row)
``hash``        the champion's checkpoint hash from the same sample
``incident``    the open incident bundle id, when one is open
``trace``       trace id (joins ``/traces/<id>`` when the tail sampler
                kept it)
``worker``      router worker that routed the batch
==============  ==========================================================

Storage is two-layer and bounded:

- a **ring** (``max_records``, default 65536) keyed by ``uid`` with a
  ``tx -> uid`` index — the exporter's ``/decisions/<tx_id>`` and
  ``/decisions?since=`` answer from here in O(1)/O(k);
- a **segmented append-only log** under ``dir`` (``audit-<n>.log``):
  each flush appends ONE ``durability.frame``-checksummed block of JSON
  lines, segments rotate at ``segment_bytes`` and prune past
  ``retain_segments`` (the PR 13 generation-retention idea applied to a
  log), and recovery re-scans the segments verifying every frame — a
  torn tail (crash mid-append, injected ``torn_write``) is TRUNCATED to
  the valid prefix and counted (``ccfd_audit_dropped_total{reason=
  "torn_tail"}``), exactly the bus-log reopen contract. Storage faults
  (``runtime/faults.py`` storage class) inject at the append seam, so
  the whole failure surface drills on CPU CI.

Writes are best-effort like every PR 13 writer: the ring is authoritative
for the live process, a failed append counts
(``ccfd_audit_dropped_total{reason="log_write"}``) and serving never
stalls. A crash-restore rebuilds the ring from the verified log, so a
pre-crash decision reconstructs end-to-end (``ccfd_tpu audit <tx_id>``).

Metrics: ``ccfd_audit_records_total``, ``ccfd_audit_dropped_total{reason}``,
``ccfd_audit_log_bytes``, ``ccfd_audit_ring_records``.
"""

from __future__ import annotations

import errno
import json
import logging
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Mapping

from ccfd_tpu.runtime import durability

log = logging.getLogger(__name__)

SEGMENT_PREFIX = "audit-"
SEGMENT_SUFFIX = ".log"

# the compact listing shape (/decisions, flight-recorder embeds): enough
# to triage without shipping the full record per row
SUMMARY_KEYS = ("seq", "tx", "uid", "ts", "decided_ts", "proba", "branch",
                "tier", "priority", "version", "incident")


def summarize(rec: Mapping[str, Any]) -> dict[str, Any]:
    return {k: rec[k] for k in SUMMARY_KEYS if k in rec}


class AuditLog:
    """Bounded, crash-safe decision-record plane; see the module docstring.

    Thread-safe: every ParallelRouter worker stamps into ONE shared
    instance, the supervised flusher drains it, and the exporter queries
    it concurrently. ``lineage_fn`` (-> ``(version, checkpoint_hash)``)
    and ``incident_fn`` (-> open incident id or None) are sampled once
    per :meth:`record_batch`, never per row — the operator wires them to
    the lifecycle lineage and the flight recorder. ``readonly=True`` is
    the inspection surface (the CLI): recovery scans verify but never
    truncate or mutate the live directory.
    """

    def __init__(
        self,
        dir: str | None = None,
        max_records: int = 65536,
        segment_bytes: int = 4 * 1024 * 1024,
        retain_segments: int = 8,
        registry=None,
        fsync: bool | None = None,
        readonly: bool = False,
        lineage_fn: Callable[[], tuple[Any, Any]] | None = None,
        incident_fn: Callable[[], Any] | None = None,
        clock: Callable[[], float] = time.time,
    ):
        self.dir = dir or None
        self.max_records = max(1, int(max_records))
        self.segment_bytes = max(4096, int(segment_bytes))
        self.retain_segments = max(1, int(retain_segments))
        self._fsync = fsync
        self.readonly = bool(readonly)
        # armed by the replay plane: when True the route seam embeds the
        # decoded feature row in each record (``row`` key) so a window
        # scanned off the segments is self-contained and re-scorable
        self.capture_rows = False
        self.lineage_fn = lineage_fn
        self.incident_fn = incident_fn
        self._clock = clock
        self._mu = threading.Lock()
        self._ring: "OrderedDict[str, dict]" = OrderedDict()
        self._by_tx: dict[str, str] = {}
        self._pending: list[dict] = []
        self._seq = 0
        self._seg_index = 0
        self._seg_bytes = 0
        self.recorded = 0       # records stamped by THIS process
        self.restamped = 0      # a uid stamped again (crash-replay re-drive)
        self.recovered = 0      # records rebuilt from the log at bring-up
        self.truncated_frames = 0  # torn frames dropped at recovery
        self._stop = threading.Event()
        self._c_records = self._c_dropped = self._c_join_err = None
        self._g_log_bytes = self._g_ring = None
        if registry is not None:
            self._c_join_err = registry.counter(
                "ccfd_audit_join_errors_total",
                "provenance-join probe failures by source (lineage/"
                "incident): the records still land, but WITHOUT that "
                "join — a regulator reconstruction would come back "
                "partial, so the gap must be visible while it happens",
            )
            self._c_records = registry.counter(
                "ccfd_audit_records_total",
                "decision records stamped at the route seam (one per "
                "routed transaction; conservation: equals the sum of "
                "transaction_outgoing_total)",
            )
            self._c_dropped = registry.counter(
                "ccfd_audit_dropped_total",
                "decision-plane drops by reason — UNITS DIFFER per "
                "label: ring counts RECORDS evicted from the bounded "
                "ring (the log may still hold them), log_write counts "
                "RECORDS whose durable append failed, torn_tail counts "
                "truncation EVENTS at crash recovery (the records inside "
                "a torn frame are unparseable, so they cannot be "
                "counted)",
            )
            self._g_log_bytes = registry.gauge(
                "ccfd_audit_log_bytes",
                "total bytes across retained audit log segments",
            )
            self._g_ring = registry.gauge(
                "ccfd_audit_ring_records",
                "decision records currently held in the query ring",
            )
        if self.dir and not self.readonly:
            os.makedirs(self.dir, exist_ok=True)
        if self.dir:
            self._recover()
        self._set_gauges()

    # -- segments ----------------------------------------------------------
    def _segments(self) -> list[tuple[int, str]]:
        out: list[tuple[int, str]] = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in names:
            if (name.startswith(SEGMENT_PREFIX)
                    and name.endswith(SEGMENT_SUFFIX)):
                mid = name[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)]
                if mid.isdigit():
                    out.append((int(mid), os.path.join(self.dir, name)))
        return sorted(out)

    def _seg_path(self, index: int) -> str:
        return os.path.join(self.dir,
                            f"{SEGMENT_PREFIX}{index:08d}{SEGMENT_SUFFIX}")

    @staticmethod
    def _scan_frames(data: bytes) -> tuple[list[dict], int, bool]:
        """-> (records, valid_prefix_bytes, torn). One verified frame per
        flush; the durability seam owns the frame format
        (:func:`durability.scan_frames` — first bad frame stops the
        scan, everything after it in an append-only segment postdates
        the corruption). A frame whose payload fails to parse as JSON
        lines counts as torn from that frame on."""
        frames, valid, torn = durability.scan_frames(data)
        records: list[dict] = []
        for start, payload in frames:
            try:
                for line in payload.splitlines():
                    if line:
                        records.append(json.loads(line))
            except ValueError:
                return records, start, True
        return records, valid, torn

    def _recover(self) -> None:
        """Rebuild the ring (and the seq/segment counters) from the
        verified log. Torn tails truncate to the valid prefix (counted);
        in ``readonly`` mode the scan verifies but never mutates disk."""
        segs = self._segments()
        all_records: list[dict] = []
        poisoned: set[str] = set()
        for idx, path in segs:
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                continue
            records, valid, torn = self._scan_frames(data)
            if torn:
                self.truncated_frames += 1
                self._count_drop("torn_tail", 1)
                log.warning(
                    "audit segment %s torn at byte %d: truncated to the "
                    "valid prefix (%d records recovered)",
                    path, valid, len(records))
                if not self.readonly:
                    try:
                        with open(path, "r+b") as f:
                            f.truncate(valid)
                    except OSError:
                        # the torn bytes could not be removed (dying
                        # media): the segment must never take another
                        # append — recovery stops at the first bad
                        # frame, so anything written after the garbage
                        # would be unrecoverable
                        poisoned.add(path)
            all_records.extend(records)
        if segs:
            self._seg_index = segs[-1][0]
            newest = self._seg_path(self._seg_index)
            if newest in poisoned:
                self._seg_index += 1
                self._seg_bytes = 0
                log.error("audit segment %s kept its torn tail; rotated "
                          "to a fresh segment", newest)
            else:
                try:
                    self._seg_bytes = os.path.getsize(newest)
                except OSError:
                    self._seg_bytes = 0
        with self._mu:
            for rec in all_records[-self.max_records:]:
                self._ring_put_locked(rec, recovered=True)
            self.recovered = len(all_records)
            self._seq = max(
                (int(r.get("seq", -1)) for r in all_records), default=-1
            ) + 1

    # -- stamping (the route-seam hot path) --------------------------------
    def record_batch(
        self,
        rows: list[dict],
        *,
        tier: str = "device",
        cause: str | None = None,
        events: tuple | list = (),
        worker: int | None = None,
        trace_id: str | None = None,
        threshold: float | None = None,
    ) -> None:
        """Stamp one batch of routed rows. ``rows`` carry the per-row
        fields the router already holds (tx/uid/ts/proba/rule/branch/
        pid/priority); everything batch-granular — tier, cause, events,
        the threshold in force, the lineage sample, the open incident —
        is resolved ONCE here and shared across the batch (the
        per-batch-not-per-row contract that keeps the armed plane
        inside bench noise). The route seam owns ``threshold``: it is a
        property of the decision, not of this log."""
        if not rows:
            return
        ver = hsh = None
        if self.lineage_fn is not None:
            try:
                ver, hsh = self.lineage_fn()
            except Exception:  # noqa: BLE001 - provenance must not crash routing
                if self._c_join_err is not None:
                    self._c_join_err.inc(labels={"source": "lineage"})
        inc = None
        if self.incident_fn is not None:
            try:
                inc = self.incident_fn()
            except Exception:  # noqa: BLE001 - provenance must not crash routing
                if self._c_join_err is not None:
                    self._c_join_err.inc(labels={"source": "incident"})
        thr = threshold
        now = self._clock()
        ev = list(events) if events else None
        with self._mu:
            for r in rows:
                r["seq"] = self._seq
                self._seq += 1
                r["decided_ts"] = now
                r["tier"] = tier
                if thr is not None:
                    r["threshold"] = thr
                if cause is not None:
                    r["cause"] = cause
                if ev:
                    r["events"] = ev
                if worker is not None:
                    r["worker"] = worker
                if trace_id is not None:
                    r["trace"] = trace_id
                if ver is not None:
                    r["version"] = ver
                if hsh is not None:
                    r["hash"] = hsh
                if inc is not None:
                    r["incident"] = inc
                self._ring_put_locked(r)
                if self.dir is not None and not self.readonly:
                    self._pending.append(r)
            self.recorded += len(rows)
        if self._c_records is not None:
            self._c_records.inc(len(rows))
        self._set_ring_gauge()

    def _ring_put_locked(self, rec: dict, recovered: bool = False) -> None:
        uid = str(rec.get("uid") or f"seq-{rec.get('seq', 0)}")
        if uid in self._ring:
            # a crash-restore re-drive legitimately re-routes (and
            # re-stamps) a consumed record: latest decision wins in the
            # ring, the log keeps both stamps, and the tally makes the
            # re-drive visible to the soak's conservation gate
            del self._ring[uid]
            if not recovered:
                self.restamped += 1
        self._ring[uid] = rec
        tx = rec.get("tx")
        if tx is not None:
            self._by_tx[str(tx)] = uid
        while len(self._ring) > self.max_records:
            old_uid, old = self._ring.popitem(last=False)
            old_tx = old.get("tx")
            if old_tx is not None and self._by_tx.get(str(old_tx)) == old_uid:
                del self._by_tx[str(old_tx)]
            if not recovered:
                self._count_drop("ring", 1)

    def _count_drop(self, reason: str, n: int) -> None:
        if self._c_dropped is not None and n > 0:
            self._c_dropped.inc(n, labels={"reason": reason})

    def _set_ring_gauge(self) -> None:
        if self._g_ring is not None:
            self._g_ring.set(float(len(self._ring)))

    def _set_gauges(self) -> None:
        # the log-bytes walk stats every retained segment: flush/recovery
        # cadence only — the route-seam hot path updates just the ring
        # gauge (log bytes change only when an append lands anyway)
        self._set_ring_gauge()
        if self._g_log_bytes is not None and self.dir:
            total = 0
            for _i, path in self._segments():
                try:
                    total += os.path.getsize(path)
                except OSError:
                    pass
            self._g_log_bytes.set(float(total))

    # -- the durable append (storage faults inject here) -------------------
    def _append(self, data: bytes) -> None:
        plan = None
        try:
            from ccfd_tpu.runtime import faults

            plan = faults.storage_faults()
        # ccfd-lint: disable=counted-drops -- nothing dropped: only the fault-INJECTION overlay is absent; the append below proceeds unfaulted
        except Exception:  # noqa: BLE001 - fault plumbing must not block audit
            plan = None

        def draw(kind: str):
            return plan.draw(kind) if plan is not None else None

        s = draw("slow_disk")
        if s is not None:
            time.sleep(s.ms / 1e3)
        if draw("enospc") is not None:
            raise OSError(errno.ENOSPC, "injected ENOSPC", self.dir)
        path = self._seg_path(self._seg_index)
        torn = draw("torn_write")
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            start = os.fstat(fd).st_size
            try:
                if torn is not None:
                    # crash mid-append: a prefix of the frame lands —
                    # exactly the torn tail recovery truncates and counts
                    os.write(fd, data[: max(0, int(len(data) * torn.frac))])
                    raise OSError(errno.EIO, "injected torn write", path)
                n = os.write(fd, data)
                if n != len(data):  # short write (full disk mid-frame)
                    raise OSError(errno.EIO, f"short write {n}", path)
                fsync = (durability._defaults["fsync"]
                         if self._fsync is None else self._fsync)
                if fsync:
                    if draw("fsync_fail") is not None:
                        raise OSError(errno.EIO, "injected fsync failure",
                                      path)
                    os.fsync(fd)
            except OSError:
                # a partial frame must NOT stay at the tail: the process
                # is still alive and will append more frames after it,
                # and recovery stops at the first bad frame — every later
                # GOOD frame would be silently truncated with it. Roll
                # the segment back to its pre-append length; if even that
                # fails (dying media), abandon the segment and rotate so
                # the next flush starts a clean one.
                try:
                    os.ftruncate(fd, start)
                except OSError:
                    self._seg_index += 1
                    self._seg_bytes = 0
                    log.error("audit segment %s unrecoverable after a "
                              "failed append; rotated to a fresh segment",
                              path)
                raise
        finally:
            os.close(fd)
        self._seg_bytes += len(data)
        if self._seg_bytes >= self.segment_bytes:
            self._seg_index += 1
            self._seg_bytes = 0
            for _i, p in self._segments()[:-self.retain_segments]:
                try:
                    os.unlink(p)
                except OSError:
                    pass

    def flush(self) -> int:
        """Drain pending records into the current segment as ONE framed
        block; returns records landed. A failed append (full disk,
        injected fault) counts the loss loudly — the ring stays
        authoritative and serving never stalls on the audit disk."""
        with self._mu:
            if not self._pending:
                return 0
            pending, self._pending = self._pending, []
        payload = ("\n".join(
            json.dumps(r, separators=(",", ":"), default=str)
            for r in pending) + "\n").encode()
        try:
            self._append(durability.frame(payload))
        except OSError as e:
            self._count_drop("log_write", len(pending))
            durability.note("write_errors", artifact="audit")
            log.error("audit log append failed (%s): %d records kept only "
                      "in the ring", e, len(pending))
            return 0
        self._set_gauges()
        return len(pending)

    # -- queries -----------------------------------------------------------
    def get(self, tx_id: Any) -> dict | None:
        """Full record for a transaction id (or a ``uid`` / ``seq-<n>``
        key) — the ``/decisions/<tx_id>`` body. Latest decision wins when
        an id was re-routed (crash-replay)."""
        key = str(tx_id)
        with self._mu:
            uid = self._by_tx.get(key, key)
            rec = self._ring.get(uid)
            return dict(rec) if rec is not None else None

    def list(self, since: float | None = None,
             limit: int = 256, until: float | None = None) -> list[dict]:
        """Compact summaries, newest first — the ``/decisions?since=``
        body. ``since``/``until`` filter on ``decided_ts`` (unix
        seconds): records with ``since < decided_ts <= until``.

        The scan is bounded while holding the stamp mutex: ring order IS
        decide order (a re-stamp re-inserts at the tail), so iterating
        newest-first can STOP at the first record at/under ``since``
        instead of walking 64k older entries under the lock the route
        seam needs — and ``limit`` is clamped so an unbounded
        ``?limit=`` cannot turn a poll into a full-ring scan either.
        ``until`` records SKIPPED at the newest end still count against
        the same scan bound (limit + skips capped together), keeping the
        worst case at one bounded walk rather than a full ring."""
        limit = min(max(1, int(limit)), 4096)
        scan_cap = limit + 4096  # bounded even when `until` skips newest
        out: list[dict] = []
        scanned = 0
        with self._mu:
            for rec in reversed(self._ring.values()):
                scanned += 1
                if scanned > scan_cap:
                    break
                ts = rec.get("decided_ts", 0.0)
                if since is not None and ts <= since:
                    break
                if until is not None and ts > until:
                    continue
                out.append(summarize(rec))
                if len(out) >= limit:
                    break
        return out

    def scan_window(self, since_seq: int | None = None,
                    until_seq: int | None = None,
                    limit: int = 262_144) -> list[dict]:
        """Bounded windowed scan over the ON-DISK segments — the replay
        plane's window source. Returns full records with
        ``since_seq <= seq <= until_seq``, ascending by ``seq``, one per
        ``uid`` (a crash-replay re-stamp means a uid can appear twice in
        the log; the LATEST stamp is the decision of record, matching
        the ring's latest-wins rule).

        Read-only by construction (the PR 14 readonly-scan rule): the
        scan opens segments for reading and NEVER truncates a torn tail
        — a frame torn by a concurrent live append simply stops that
        segment's scan at the valid prefix, and the caller sees a
        shorter window rather than a mutated log. Memory is inherently
        bounded by segment retention (``retain_segments`` x
        ``segment_bytes``); ``limit`` backstops the result set."""
        if not self.dir:
            return []
        lo = None if since_seq is None else int(since_seq)
        hi = None if until_seq is None else int(until_seq)
        limit = max(1, int(limit))
        best: dict[str, dict] = {}
        for _idx, path in self._segments():
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                continue
            records, _valid, _torn = self._scan_frames(data)
            for rec in records:
                try:
                    seq = int(rec.get("seq", -1))
                except (TypeError, ValueError):
                    continue
                if (lo is not None and seq < lo) or (
                        hi is not None and seq > hi):
                    continue
                uid = str(rec.get("uid") or f"seq-{seq}")
                prev = best.get(uid)
                if prev is None or int(prev.get("seq", -1)) <= seq:
                    best[uid] = rec
        out = sorted(best.values(), key=lambda r: int(r.get("seq", -1)))
        if len(out) > limit:
            log.warning("audit scan_window clamped %d -> %d records",
                        len(out), limit)
            out = out[:limit]
        return out

    def recent_summaries(self, n: int = 16,
                         since: float | None = None) -> list[dict]:
        """The flight-recorder embed: the last ``n`` decisions (newest
        first) — which transactions were in flight when an incident
        bundle dumped."""
        return self.list(since=since, limit=n)

    @property
    def ring_size(self) -> int:
        with self._mu:
            return len(self._ring)

    def counts(self) -> dict[str, int]:
        with self._mu:
            return {
                "recorded": self.recorded,
                "ring": len(self._ring),
                "pending": len(self._pending),
                "restamped": self.restamped,
                "recovered": self.recovered,
                "truncated_frames": self.truncated_frames,
            }

    # -- supervised-service surface (the flusher) --------------------------
    def reset(self) -> None:
        self._stop.clear()

    def stop(self) -> None:
        self._stop.set()

    def run(self, interval_s: float = 0.25) -> None:
        try:
            while not self._stop.wait(interval_s):
                self.flush()
        finally:
            self.flush()  # orderly shutdown lands the tail
