"""Structured trace-correlated JSON logging.

The reference's services log free text to pod stdout; correlating a log
line with the transaction that caused it means grepping timestamps. This
layer emits one JSON object per line and stamps ``trace_id``/``span_id``
from the active span (observability/trace.py contextvar), so a retained
trace found via the exporter's ``/traces/<id>`` endpoint joins directly
against the log stream — the logging third of the trace↔metric↔log
triangle (exemplars are the metric side).

Usage::

    log = slog.get_logger("router")        # JSON handler, component field
    log.warning("scorer edge degraded", extra={"tier": "host"})

Any ``extra={...}`` keys land as top-level JSON fields (collisions with
the reserved fields are prefixed ``x_``). ``configure()`` is idempotent
per logger and never touches the root logger, so test harnesses and
embedding applications keep their own logging untouched.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Any, TextIO

from ccfd_tpu.observability.trace import current_context

_RESERVED = frozenset((
    "ts", "level", "component", "logger", "msg", "trace_id", "span_id", "exc",
))
# logging.LogRecord's own attribute names: anything else on the record came
# from extra={...} and belongs in the JSON object
_RECORD_ATTRS = frozenset(vars(
    logging.LogRecord("", 0, "", 0, "", (), None)
)) | {"message", "asctime", "taskName"}


class TraceJSONFormatter(logging.Formatter):
    def __init__(self, component: str = ""):
        super().__init__()
        self.component = component

    def format(self, record: logging.LogRecord) -> str:
        obj: dict[str, Any] = {
            "ts": round(record.created, 6),
            "level": record.levelname.lower(),
            "component": self.component or record.name,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        ctx = current_context()
        if ctx is not None:
            obj["trace_id"] = ctx.trace_id
            obj["span_id"] = ctx.span_id
        for key, value in record.__dict__.items():
            if key in _RECORD_ATTRS or key.startswith("_"):
                continue
            out_key = f"x_{key}" if key in _RESERVED else key
            try:
                json.dumps(value)
            except (TypeError, ValueError):
                value = repr(value)
            obj[out_key] = value
        if record.exc_info:
            obj["exc"] = self.formatException(record.exc_info)
        return json.dumps(obj, default=repr)


class _StructuredHandler(logging.StreamHandler):
    """Marker subclass so configure() can recognize its own handler."""


def configure(component: str = "", logger: logging.Logger | str | None = None,
              level: int = logging.INFO,
              stream: TextIO | None = None) -> logging.Logger:
    """Attach a JSON handler to ``logger`` (default: the ``ccfd_tpu``
    namespace logger). Idempotent: re-configuring replaces this module's
    own handler instead of stacking duplicates. ``propagate`` is disabled
    so lines don't double-print through the root logger."""
    if logger is None or isinstance(logger, str):
        logger = logging.getLogger(logger or "ccfd_tpu")
    for h in list(logger.handlers):
        if isinstance(h, _StructuredHandler):
            logger.removeHandler(h)
    handler = _StructuredHandler(stream if stream is not None else sys.stderr)
    handler.setFormatter(TraceJSONFormatter(component))
    logger.addHandler(handler)
    logger.setLevel(level)
    logger.propagate = False
    return logger


def get_logger(component: str, level: int = logging.INFO,
               stream: TextIO | None = None) -> logging.Logger:
    """A ``ccfd_tpu.<component>`` logger emitting trace-correlated JSON."""
    return configure(component, f"ccfd_tpu.{component}", level=level,
                     stream=stream)


def span_fields(msg: str = "", **fields: Any) -> str:
    """Render ad-hoc fields as one JSON log line body (for call sites that
    must stay on a plain logger but want machine-parseable payloads)."""
    obj: dict[str, Any] = {"msg": msg, **fields}
    ctx = current_context()
    if ctx is not None:
        obj["trace_id"] = ctx.trace_id
        obj["span_id"] = ctx.span_id
    obj["ts"] = round(time.time(), 6)
    return json.dumps(obj, default=repr)
