"""Declarative SLOs: burn-rate evaluation + a per-layer latency-budget ledger.

The overload plane (PR 6) reacts to latency; nothing yet states the
OBJECTIVE it defends. This module closes that gap with the SRE canon's
machinery, sized for this pipeline:

- :class:`SLOSpec` — a declarative objective, loadable from the platform
  CR's ``slo:`` block (or built from the ``CCFD_SLO_*`` env defaults):
  latency SLOs ("99% of decisions under 50 ms", judged from the existing
  latency histograms via interpolated ``count_le``) and error-rate SLOs
  (good = total − errors from counters). Specs name the SLO the alerts
  and gauges carry (``e2e-p99``, ``rest-p99``, ``error-rate``).

- :class:`SLOEngine` — multi-window burn-rate evaluation (Google SRE
  workbook shape): per spec, good/bad event deltas accumulate into a time
  ring; each window's **burn rate** is its bad-fraction divided by the
  error budget (1 − objective), exported as
  ``ccfd_slo_burn_rate{slo,window}``. A breach trips when EVERY fast
  window — all but the last, by default the 5 m short window confirming
  the 1 h window — exceeds ``fast_burn`` — edge-triggered into
  ``ccfd_slo_breach_total{slo}`` so one incident counts once — and
  ``ccfd_slo_error_budget_remaining{slo}`` tracks the budget left over
  the slow (6 h) window. Window lengths are configurable (the CI smoke
  shrinks them to seconds); defaults are the canonical 5m/1h fast pair +
  6h slow window.

- :class:`BudgetLedger` — the per-layer latency budget for the NativeFront
  REST path ROADMAP item 1 needs before the ≥50k tx/s on-device target
  can be decomposed: the r04 ``rest_latency_floor`` transport floor
  (0.072 ms p99, REST_SWEEP; ``CCFD_SLO_TRANSPORT_FLOOR_MS``) as a static
  layer, measured batcher wait and device dispatch from the
  :class:`~ccfd_tpu.observability.profile.StageProfiler`, and an H2D
  layer that reads the MEASURED transfer digest from the device
  telemetry plane (observability/device.py) when it is armed — the
  pre-telemetry explicit-zero reservation remains the fallback so the
  ledger's shape is stable either way. Each layer gets a slice of the
  SLO target; ``ccfd_slo_budget_spent_ratio{slo,layer}`` says which
  layer is eating the budget.

The engine runs as a default-on supervised service under the operator
(CR ``slo:`` block, ``CCFD_SLO=0`` kill switch) and is driven inline by
the CI smoke (``tools/slo_smoke.py`` / ``verify_tier1.sh --slo-smoke``).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Any, Callable, Mapping, Sequence

from ccfd_tpu.metrics.prom import Histogram, Registry

# canonical multi-window ladder: (seconds, burn threshold). The first two
# are the FAST pair (short window confirms long — the workbook's 14.4x
# page condition); the last is the slow budget-consumption window.
DEFAULT_WINDOWS = ((300.0, 14.4), (3600.0, 14.4), (21600.0, 1.0))


def window_name(seconds: float) -> str:
    if seconds >= 3600 and seconds % 3600 == 0:
        return f"{int(seconds // 3600)}h"
    if seconds >= 60 and seconds % 60 == 0:
        return f"{int(seconds // 60)}m"
    return f"{seconds:g}s"


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One declarative objective.

    ``kind="latency"``: ``metric`` names a latency histogram (seconds);
    good events are observations at/below ``target_ms``. ``objective`` is
    the good fraction promised (0.99 -> 1% error budget).

    ``kind="error_rate"``: ``metric`` names the total-events counter and
    ``error_metric`` the failures counter (both summed across label
    sets); the objective is ``1 - max_error_rate``.
    """

    name: str
    kind: str = "latency"                 # "latency" | "error_rate"
    metric: str = ""
    target_ms: float = 50.0
    objective: float = 0.99
    error_metric: str = ""

    @property
    def error_budget(self) -> float:
        return max(1e-9, 1.0 - float(self.objective))

    @staticmethod
    def from_mapping(m: Mapping[str, Any]) -> "SLOSpec":
        """CR ``slo.specs[]`` entry -> spec. Unknown keys are rejected at
        load time (a typo'd guardrail must not silently vanish)."""
        known = {f.name for f in dataclasses.fields(SLOSpec)}
        extra = set(m) - known - {"max_error_rate"}
        if extra:
            raise ValueError(f"slo spec {m.get('name')!r}: "
                             f"unknown keys {sorted(extra)}")
        kw = {k: m[k] for k in known if k in m}
        if "max_error_rate" in m:  # sugar for error-rate objectives
            kw["objective"] = 1.0 - float(m["max_error_rate"])
            kw.setdefault("kind", "error_rate")
        if not kw.get("name"):
            raise ValueError("slo spec needs a name")
        return SLOSpec(**kw)


class _Source:
    """Resolves a spec's (good, bad) cumulative totals from registries.
    Resolution is lazy and re-tried each read: component registries gain
    their metrics as traffic starts, after the engine is built."""

    def __init__(self, spec: SLOSpec,
                 registries: Mapping[str, Registry]):
        self.spec = spec
        self._registries = registries
        self._metric = None
        self._error_metric = None

    def _resolve(self, name: str):
        for reg in self._registries.values():
            m = reg.get(name)
            if m is not None:
                return m
        return None

    def totals(self) -> tuple[float, float]:
        """-> cumulative (good, bad) event counts since process start."""
        spec = self.spec
        if self._metric is None:
            self._metric = self._resolve(spec.metric)
        if self._metric is None:
            return 0.0, 0.0
        if spec.kind == "latency":
            if not isinstance(self._metric, Histogram):
                return 0.0, 0.0
            # aggregate across label sets: the serving latency series is
            # labeled by endpoint, and the objective covers all of them
            total = float(self._metric.total_count())
            good = float(self._metric.total_count_le(spec.target_ms / 1e3))
            return good, max(0.0, total - good)
        # error_rate: counters summed across label sets
        if self._error_metric is None:
            self._error_metric = self._resolve(spec.error_metric)
        total = float(self._metric.total())
        bad = (float(self._error_metric.total())
               if self._error_metric is not None else 0.0)
        return max(0.0, total - bad), bad


class _Tracker:
    """Per-spec window ring of (t, good_delta, bad_delta) samples.

    Samples closer together than ``bucket_s`` MERGE into the newest ring
    entry: the ring then holds at most ~slow_window/bucket_s entries
    regardless of how fast the engine ticks — without this, a short
    ``interval_s`` against the default 6 h slow window would silently age
    burned budget out of a fixed-size ring hours early."""

    __slots__ = ("source", "ring", "bucket_s", "last_good", "last_bad",
                 "breaching")

    def __init__(self, source: _Source, slow_window_s: float):
        self.source = source
        # <= 4096 live buckets per slow window; deque bound is a backstop
        self.bucket_s = max(1e-3, float(slow_window_s) / 4096.0)
        self.ring: collections.deque = collections.deque(maxlen=8192)
        self.last_good = 0.0
        self.last_bad = 0.0
        self.breaching = False

    def sample(self, now: float) -> None:
        good, bad = self.source.totals()
        dg, db = good - self.last_good, bad - self.last_bad
        self.last_good, self.last_bad = good, bad
        if dg < 0 or db < 0:  # registry replaced / counter reset
            dg = db = 0.0
        if not (dg or db):
            return
        if self.ring and now - self.ring[-1][0] < self.bucket_s:
            t, g, b = self.ring[-1]
            self.ring[-1] = (t, g + dg, b + db)
        else:
            self.ring.append((now, dg, db))

    def window_fractions(self, now: float,
                         seconds: float) -> tuple[float, float]:
        """-> (bad_fraction, events) over the trailing window."""
        cutoff = now - seconds
        good = bad = 0.0
        for t, dg, db in reversed(self.ring):
            if t < cutoff:
                break
            good += dg
            bad += db
        total = good + bad
        return (bad / total if total else 0.0), total


class SLOEngine:
    """Evaluates SLO specs on a tick; owns the burn/budget/breach metrics
    and (optionally) a :class:`BudgetLedger`. Thread-safe; run either as
    a supervised loop (:meth:`run`) or ticked inline (tools)."""

    def __init__(
        self,
        specs: Sequence[SLOSpec],
        registries: Mapping[str, Registry],
        registry: Registry | None = None,
        windows: Sequence[tuple[float, float]] = DEFAULT_WINDOWS,
        ledger: "BudgetLedger | None" = None,
        profiler=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if len(windows) < 2:
            raise ValueError("burn-rate evaluation needs at least one "
                             "fast window plus the slow budget window")
        self.specs = list(specs)
        self.windows = [(float(s), float(th)) for s, th in windows]
        self.ledger = ledger
        # breach-edge listeners (observability/incident.py FlightRecorder):
        # fn(slo_name, status_doc) fires once per ENTRY into the breaching
        # state, same edge semantics as ccfd_slo_breach_total
        self._breach_listeners: list[Callable[[str, dict], Any]] = []
        # the stage profiler whose ccfd_stage_latency_ms gauges this
        # engine's tick refreshes (the supervised tick is the sampling
        # clock for the SLO board's decomposition panels; /profile reads
        # and the exporter scrape refresh too)
        self.profiler = profiler
        self._clock = clock
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._trackers = {
            spec.name: _Tracker(_Source(spec, registries),
                                slow_window_s=self.windows[-1][0])
            for spec in self.specs
        }
        r = registry if registry is not None else Registry()
        self.registry = r
        self._g_burn = r.gauge(
            "ccfd_slo_burn_rate",
            "error-budget burn rate by SLO and window (1.0 = consuming "
            "exactly the budget; the fast pair alerts at its threshold)",
        )
        self._g_budget = r.gauge(
            "ccfd_slo_error_budget_remaining",
            "fraction of the SLO's error budget left over the slow window",
        )
        self._c_breach = r.counter(
            "ccfd_slo_breach_total",
            "fast-window burn-rate breaches by SLO (edge-triggered: one "
            "increment per entry into the breaching state)",
        )
        self._g_breaching = r.gauge(
            "ccfd_slo_breaching",
            "1 while the SLO's fast-window pair is above threshold",
        )
        self._c_listener_err = r.counter(
            "ccfd_slo_listener_errors_total",
            "breach-listener callbacks that raised: the breach evaluated, "
            "but its evidence capture (flight recorder, planner hook) "
            "did not run",
        )

    # -- construction helpers ---------------------------------------------
    @staticmethod
    def default_specs(cfg) -> list[SLOSpec]:
        """The stock objectives the operator arms when the CR declares
        none: end-to-end decision p-latency, REST request p-latency, and
        the process-start error rate."""
        return [
            SLOSpec("e2e-p99", kind="latency",
                    metric="router_decision_seconds",
                    target_ms=cfg.slo_e2e_target_ms,
                    objective=cfg.slo_objective),
            SLOSpec("rest-p99", kind="latency",
                    metric="seldon_api_executor_client_requests_seconds",
                    target_ms=cfg.slo_rest_target_ms,
                    objective=cfg.slo_objective),
            SLOSpec("error-rate", kind="error_rate",
                    metric="transaction_incoming_total",
                    error_metric="router_process_start_errors_total",
                    objective=1.0 - cfg.slo_max_error_rate),
        ]

    @staticmethod
    def windows_from_config(cfg,
                            override: Any = None) -> list[tuple[float, float]]:
        """``CCFD_SLO_WINDOWS``/CR ``windows`` ("300,3600,21600") +
        ``fast_burn`` -> the (seconds, threshold) ladder: every window but
        the last is a fast window at ``fast_burn``; the last is the slow
        budget window at 1.0."""
        raw = override if override is not None else cfg.slo_windows
        if isinstance(raw, str):
            secs = [float(s) for s in raw.split(",") if s.strip()]
        else:
            secs = [float(s) for s in raw]
        if len(secs) < 2:
            raise ValueError(f"slo windows need >= 2 entries, got {secs}")
        fast = float(cfg.slo_fast_burn)
        return [(s, fast) for s in secs[:-1]] + [(secs[-1], 1.0)]

    @staticmethod
    def from_config(cfg, registries: Mapping[str, Registry],
                    registry: Registry, profiler=None,
                    options: Mapping[str, Any] | None = None,
                    telemetry=None) -> "SLOEngine":
        """The operator/CLI construction path: CR ``slo:`` options overlay
        the ``CCFD_SLO_*`` env defaults; ``specs:`` replaces the stock
        objectives wholesale when declared. ``telemetry`` (the
        DeviceTelemetry plane) upgrades the ledger's ``h2d`` layer from
        the fixed reservation to the measured transfer digest."""
        opts = dict(options or {})
        raw_specs = opts.get("specs")
        specs = ([SLOSpec.from_mapping(s) for s in raw_specs]
                 if raw_specs else SLOEngine.default_specs(cfg))
        windows = SLOEngine.windows_from_config(cfg, opts.get("windows"))
        ledger = None
        if profiler is not None and any(s.name == "rest-p99" for s in specs):
            target = next(s.target_ms for s in specs
                          if s.name == "rest-p99")
            ledger = BudgetLedger.for_rest_path(
                cfg, profiler, registry, target_ms=target,
                budgets=opts.get("budget"), telemetry=telemetry)
        return SLOEngine(specs, registries, registry=registry,
                         windows=windows, ledger=ledger,
                         profiler=profiler)

    def add_breach_listener(self, fn: Callable[[str, dict], Any]) -> None:
        """``fn(slo_name, status_doc)`` fires on every breach EDGE (once
        per entry into breaching, again only after recovery + re-breach) —
        the incident flight recorder's trigger."""
        self._breach_listeners.append(fn)

    # -- evaluation --------------------------------------------------------
    def tick(self, now: float | None = None) -> dict[str, Any]:
        """One evaluation pass; returns the status document (the shape
        ``tools/slo_report.py`` embeds next to the StageProfile)."""
        now = self._clock() if now is None else now
        if self.profiler is not None:
            self.profiler.refresh_gauges()
        out: dict[str, Any] = {"slos": {}, "windows": [
            {"window": window_name(s), "seconds": s, "threshold": th}
            for s, th in self.windows
        ]}
        fired: list[str] = []
        with self._mu:
            # every window but the last is a FAST alerting window (the
            # short ones confirm the long ones); the last is the slow
            # budget-trend window and never participates in breaching
            n_fast = len(self.windows) - 1
            for spec in self.specs:
                tr = self._trackers[spec.name]
                tr.sample(now)
                burns: dict[str, float] = {}
                fast_over = 0
                for i, (seconds, threshold) in enumerate(self.windows):
                    frac, events = tr.window_fractions(now, seconds)
                    burn = frac / spec.error_budget
                    wname = window_name(seconds)
                    burns[wname] = round(burn, 4)
                    self._g_burn.set(burn, labels={
                        "slo": spec.name, "window": wname})
                    if i < n_fast and events > 0 and burn >= threshold:
                        fast_over += 1
                # slow-window budget remaining
                slow_s, _ = self.windows[-1]
                slow_frac, _ = tr.window_fractions(now, slow_s)
                remaining = max(0.0, 1.0 - slow_frac / spec.error_budget)
                self._g_budget.set(remaining, labels={"slo": spec.name})
                breaching = fast_over == n_fast
                if breaching and not tr.breaching:
                    self._c_breach.inc(labels={"slo": spec.name})
                    fired.append(spec.name)
                tr.breaching = breaching
                self._g_breaching.set(
                    1.0 if breaching else 0.0, labels={"slo": spec.name})
                out["slos"][spec.name] = {
                    "kind": spec.kind,
                    "objective": spec.objective,
                    "target_ms": (spec.target_ms
                                  if spec.kind == "latency" else None),
                    "burn_rate": burns,
                    "error_budget_remaining": round(remaining, 4),
                    "breaching": breaching,
                    "breaches": int(self._c_breach.value(
                        {"slo": spec.name})),
                }
            if self.ledger is not None:
                out["budget_ledger"] = self.ledger.evaluate()
        # listeners run OUTSIDE the engine lock: the flight recorder reads
        # registries/profiler and must never deadlock a concurrent tick
        for name in fired:
            for fn in self._breach_listeners:
                try:
                    fn(name, out)
                except Exception:  # noqa: BLE001 - evidence capture must
                    self._c_listener_err.inc()  # never fail the evaluation
        return out

    def breaches(self, slo: str) -> int:
        return int(self._c_breach.value({"slo": slo}))

    def any_breaching(self) -> bool:
        """True while ANY objective sits in the breaching state (between a
        breach edge and its recovery tick) — the decision-audit plane's
        definition of "an incident is open": routed transactions stamped
        in this window carry the newest incident bundle's id."""
        with self._mu:
            return any(tr.breaching for tr in self._trackers.values())

    # -- supervised-service surface ---------------------------------------
    def reset(self) -> None:
        self._stop.clear()

    def stop(self) -> None:
        self._stop.set()

    def run(self, interval_s: float = 5.0) -> None:
        while not self._stop.wait(interval_s):
            self.tick()


class BudgetLedger:
    """Per-layer latency budget for one SLO's path (the REST path today).

    Layers are ``(name, budget_ms, fetch)`` where ``fetch()`` returns
    either a static spent value in ms (the measured transport floor, the
    H2D placeholder) or a live
    :class:`~ccfd_tpu.observability.profile.LatencyDigest`. ``evaluate``
    exports ``ccfd_slo_budget_spent_ratio{slo,layer}`` (spent p99 /
    layer budget) and returns the ledger snapshot — whose per-layer
    ``count``/``sum_s`` let a harness attribute a latency DELTA to the
    layer that ate it (the smoke's ≥80%-to-dispatch assertion).
    """

    def __init__(self, slo: str, target_ms: float, registry: Registry,
                 layers: Sequence[tuple[str, float, Callable[[], Any]]]):
        self.slo = slo
        self.target_ms = float(target_ms)
        self.layers = list(layers)
        self._g_ratio = registry.gauge(
            "ccfd_slo_budget_spent_ratio",
            "measured p99 spend over the layer's latency-budget slice, "
            "by SLO and layer (>1 = the layer alone blows its slice)",
        )

    @staticmethod
    def for_rest_path(cfg, profiler, registry: Registry,
                      target_ms: float | None = None,
                      budgets: Mapping[str, float] | None = None,
                      telemetry=None) -> "BudgetLedger":
        """The REST-path ledger ROADMAP item 1 decomposes against:
        transport floor (static, the r04 ``rest_latency_floor`` number),
        batcher wait + device dispatch (measured via the profiler), and
        the H2D staging layer. Default budget slices: transport gets 2x
        its floor (min-clamped to 0.2 ms — the clamp binds at the shipped
        0.072 ms floor), H2D a fixed 0.5 ms slice, and the remainder
        splits 60/40 dispatch/batcher-wait; a CR ``budget:`` mapping
        overrides any slice.

        ``telemetry`` (observability/device.py DeviceTelemetry): when the
        device plane is armed, the ``h2d`` layer reads the MEASURED
        per-transfer digest from the scorer's instrumented staging path;
        without it the layer keeps the explicit-zero reservation so the
        ledger schema (and the planner's view) is stable either way."""
        target = float(target_ms if target_ms is not None
                       else cfg.slo_rest_target_ms)
        floor_ms = float(cfg.slo_transport_floor_ms)
        b = dict(budgets or {})
        transport_b = float(b.get("transport", max(2.0 * floor_ms, 0.2)))
        h2d_b = float(b.get("h2d", 0.5))
        remainder = max(target - transport_b - h2d_b, 1.0)
        dispatch_b = float(b.get("dispatch", 0.6 * remainder))
        wait_b = float(b.get("batcher_wait", 0.4 * remainder))

        def h2d_fetch():
            if telemetry is not None:
                # measured: each sample is one staging put on the scorer
                # dispatch path (ccfd_h2d_seconds' digest twin). NOTE:
                # this digest is PROCESS-WIDE — the operator arms one
                # telemetry plane and one scorer serves both the router
                # and REST lanes, so unlike the lane-scoped rest.batcher/
                # rest.dispatch digests it folds bus-lane puts in too.
                # Read it as an upper bound on the REST lane's per-put
                # staging cost until puts carry lane context.
                return telemetry.h2d_digest()
            # telemetry disarmed: the pre-telemetry reservation, an
            # explicit zero rather than an absence (regression-tested)
            return 0.0

        return BudgetLedger(
            "rest-p99", target, registry,
            layers=[
                ("transport", transport_b, lambda: floor_ms),
                ("batcher_wait", wait_b,
                 lambda: profiler.digest("rest.batcher", "queue")),
                ("dispatch", dispatch_b,
                 lambda: profiler.digest("rest.dispatch", "dispatch")),
                ("h2d", h2d_b, h2d_fetch),
            ])

    def evaluate(self) -> dict[str, Any]:
        layers: dict[str, Any] = {}
        spent_mean_sum = 0.0
        for name, budget_ms, fetch in self.layers:
            val = fetch()
            if val is None:
                entry = {"budget_ms": round(budget_ms, 4), "count": 0,
                         "sum_s": 0.0, "spent_p99_ms": 0.0,
                         "spent_mean_ms": 0.0, "ratio": 0.0}
            elif isinstance(val, (int, float)):
                entry = {"budget_ms": round(budget_ms, 4), "count": 0,
                         "sum_s": 0.0,
                         "spent_p99_ms": round(float(val), 4),
                         "spent_mean_ms": round(float(val), 4),
                         "ratio": round(float(val) / budget_ms, 4)
                         if budget_ms > 0 else 0.0,
                         "static": True}
            else:  # LatencyDigest
                d = val.to_dict()
                p99 = d.get("p99_ms", 0.0)
                entry = {
                    "budget_ms": round(budget_ms, 4),
                    "count": d["count"],
                    "sum_s": d.get("sum_s", 0.0),
                    "spent_p99_ms": p99,
                    "spent_mean_ms": d.get("mean_ms", 0.0),
                    "ratio": (round(p99 / budget_ms, 4)
                              if budget_ms > 0 else 0.0),
                }
            spent_mean_sum += entry["spent_mean_ms"]
            self._g_ratio.set(entry["ratio"],
                              labels={"slo": self.slo, "layer": name})
            layers[name] = entry
        return {
            "slo": self.slo,
            "target_ms": self.target_ms,
            "layers": layers,
            "spent_mean_sum_ms": round(spent_mean_sum, 4),
        }
