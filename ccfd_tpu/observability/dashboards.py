"""Grafana dashboard generation: the reference's metrics contract, regenerated.

The reference ships six hand-exported Grafana dashboards
(reference deploy/grafana/{KIE,Kafka,ModelPrediction,Router,SeldonCore,
SparkMetrics}.json, ~4k lines) that define its observability contract
(SURVEY.md §5). Rather than hand-maintaining 4k lines of panel JSON, this
module *generates* the equivalent dashboards from the framework's actual
metric names, one builder per board:

- Router      — transaction/notification counters (reference Router.json:88-326)
- KIE         — the four amount histograms (reference KIE.json bucket panels)
- ModelPrediction — proba_1 / Amount / V17 / V10 gauges
  (reference ModelPrediction.json:96-322)
- SeldonCore  — request rate / status codes / latency quantiles
  (reference SeldonCore.json:119-531)
- Bus         — in-process broker depth/throughput (the Kafka.json analog)
- Analytics   — mesh analytics jobs + drift PSI (the SparkMetrics.json analog:
  Spark executor panels become device-mesh worker/job panels)
- Retrain     — online-training health (new capability; no reference analog)

``write_dashboards(dir)`` emits one importable JSON file per board.
"""

from __future__ import annotations

import json
import os
from typing import Any

_PANEL_W = 12
_PANEL_H = 8


def _panel(panel_id: int, title: str, exprs: list[str], panel_type: str = "timeseries") -> dict:
    x = (panel_id % 2) * _PANEL_W
    y = (panel_id // 2) * _PANEL_H
    return {
        "id": panel_id + 1,
        "title": title,
        "type": panel_type,
        "datasource": {"type": "prometheus", "uid": "${DS_PROMETHEUS}"},
        "gridPos": {"h": _PANEL_H, "w": _PANEL_W, "x": x, "y": y},
        "targets": [
            {"expr": expr, "refId": chr(ord("A") + i), "legendFormat": "__auto"}
            for i, expr in enumerate(exprs)
        ],
    }


def _dashboard(title: str, uid: str, panels: list[dict]) -> dict:
    return {
        "title": title,
        "uid": uid,
        "schemaVersion": 39,
        "version": 1,
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "templating": {"list": []},
        "panels": panels,
        "__inputs": [
            {
                "name": "DS_PROMETHEUS",
                "label": "Prometheus",
                "type": "datasource",
                "pluginId": "prometheus",
            }
        ],
    }


def router_dashboard() -> dict:
    p = [
        _panel(0, "Incoming transactions / s",
               ["rate(transaction_incoming_total[5m])"]),
        _panel(1, "Outgoing by type / s",
               ['rate(transaction_outgoing_total{type="standard"}[5m])',
                'rate(transaction_outgoing_total{type="fraud"}[5m])']),
        _panel(2, "Customer notifications out",
               ["notifications_outgoing_total"], "stat"),
        _panel(3, "Customer responses",
               ['notifications_incoming_total{response="approved"}',
                'notifications_incoming_total{response="non_approved"}'], "stat"),
        _panel(4, "Scoring batch size p50/p95",
               ["histogram_quantile(0.5, rate(router_batch_size_bucket[5m]))",
                "histogram_quantile(0.95, rate(router_batch_size_bucket[5m]))"]),
        _panel(5, "Scorer dispatch latency p99",
               ["histogram_quantile(0.99, rate(router_score_seconds_bucket[5m]))"]),
        _panel(6, "Decode errors / s", ["rate(transaction_decode_errors_total[5m])"]),
    ]
    return _dashboard("CCFD Router", "ccfd-router", p)


def kie_dashboard() -> dict:
    hists = [
        "fraud_investigation_amount",
        "fraud_approved_low_amount",
        "fraud_approved_amount",
        "fraud_rejected_amount",
    ]
    p = []
    for i, h in enumerate(hists):
        p.append(_panel(2 * i, f"{h} rate", [f"rate({h}_count[5m])"]))
        p.append(_panel(2 * i + 1, f"{h} mean amount",
                        [f"rate({h}_sum[5m]) / rate({h}_count[5m])"]))
    p.append(_panel(8, "Process starts by definition",
                    ['rate(process_instances_started_total[5m])']))
    p.append(_panel(9, "Process completions by status",
                    ['rate(process_instances_completed_total[5m])']))
    return _dashboard("CCFD Process Engine (KIE)", "ccfd-kie", p)


def model_prediction_dashboard() -> dict:
    p = [
        _panel(0, "proba_1 (last scored)", ["proba_1"]),
        _panel(1, "Amount (last scored)", ["Amount"]),
        _panel(2, "V17", ["V17"]),
        _panel(3, "V10", ["V10"]),
    ]
    return _dashboard("CCFD Model Prediction", "ccfd-modelpred", p)


def seldon_core_dashboard() -> dict:
    h = "seldon_api_executor_client_requests_seconds"
    p = [
        _panel(0, "Request rate / s", [f"rate({h}_count[5m])"]),
        _panel(1, "Success vs error codes / s",
               ['rate(seldon_api_executor_server_requests_total{code="200"}[5m])',
                'rate(seldon_api_executor_server_requests_total{code=~"4.."}[5m])',
                'rate(seldon_api_executor_server_requests_total{code=~"5.."}[5m])']),
    ]
    for i, q in enumerate((0.5, 0.75, 0.9, 0.95, 0.99)):
        p.append(
            _panel(2 + i, f"Latency p{int(q*100)}",
                   [f"histogram_quantile({q}, rate({h}_bucket[5m]))"])
        )
    return _dashboard("CCFD Serving (SeldonCore)", "ccfd-seldon", p)


def bus_dashboard() -> dict:
    # broker-health panels mirror the reference Kafka board's shape:
    # messages-in rate, per-topic throughput, partition end offsets, and
    # consumer-group lag in place of under-replicated/offline-partition
    # stats (the single-log bus has no replication to degrade; lag is its
    # equivalent health signal) — reference deploy/grafana/Kafka.json
    p = [
        _panel(0, "Records in / s (cluster)", ["rate(bus_records_produced_total[5m])"]),
        _panel(1, "Records delivered / s", ["rate(bus_records_delivered_total[5m])"]),
        _panel(2, "Messages in by topic / s",
               ["rate(bus_topic_records_in_total[5m])"]),
        _panel(3, "Log end offset by topic/partition", ["bus_topic_end_offset"]),
        _panel(4, "Consumer-group backlog (lag)", ["bus_topic_backlog"]),
        _panel(5, "Live consumers", ["bus_consumers"], "stat"),
        _panel(6, "Producer rows / s", ["rate(producer_rows_total[5m])"]),
        _panel(7, "Notifications sent / replies",
               ["rate(notifications_sent_total[5m])",
                "rate(notifications_replied_total[5m])",
                "rate(notifications_no_reply_total[5m])"]),
    ]
    return _dashboard("CCFD Bus", "ccfd-bus", p)


def analytics_dashboard() -> dict:
    p = [
        _panel(0, "Analytics jobs / s",
               ["rate(analytics_jobs_completed_total[5m])"]),
        _panel(1, "Job duration p50/p95",
               ["histogram_quantile(0.5, rate(analytics_job_seconds_bucket[5m]))",
                "histogram_quantile(0.95, rate(analytics_job_seconds_bucket[5m]))"]),
        _panel(2, "Rows aggregated / s",
               ["rate(analytics_rows_processed_total[5m])"]),
        _panel(3, "Mesh workers", ["analytics_workers"], "stat"),
        _panel(4, "Per-feature drift PSI", ["analytics_drift_psi"]),
        _panel(5, "Worst-feature PSI", ["analytics_drift_max_psi"], "stat"),
    ]
    return _dashboard("CCFD Analytics", "ccfd-analytics", p)


def retrain_dashboard() -> dict:
    p = [
        _panel(0, "Labels ingested by class / s", ["rate(retrain_labels_total[5m])"]),
        _panel(1, "Optimizer steps / s", ["rate(retrain_steps_total[5m])"]),
        _panel(2, "Serving hot swaps", ["retrain_param_swaps_total"], "stat"),
        _panel(3, "Last training loss", ["retrain_last_loss"], "stat"),
    ]
    return _dashboard("CCFD Online Retrain", "ccfd-retrain", p)


def build_all_dashboards() -> dict[str, dict]:
    return {
        "Router": router_dashboard(),
        "KIE": kie_dashboard(),
        "ModelPrediction": model_prediction_dashboard(),
        "SeldonCore": seldon_core_dashboard(),
        "Bus": bus_dashboard(),
        "Analytics": analytics_dashboard(),
        "Retrain": retrain_dashboard(),
    }


def write_dashboards(out_dir: str) -> list[str]:
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for name, board in build_all_dashboards().items():
        path = os.path.join(out_dir, f"{name}.json")
        with open(path, "w") as f:
            json.dump(board, f, indent=2, sort_keys=True)
            f.write("\n")
        paths.append(path)
    return paths


if __name__ == "__main__":
    import sys

    out = sys.argv[1] if len(sys.argv) > 1 else "deploy/grafana"
    for p in write_dashboards(out):
        print(p)
