"""Grafana dashboard generation: the reference's metrics contract, regenerated.

The reference ships six hand-exported Grafana dashboards
(reference deploy/grafana/{KIE,Kafka,ModelPrediction,Router,SeldonCore,
SparkMetrics}.json, ~4k lines) that define its observability contract
(SURVEY.md §5). Rather than hand-maintaining 4k lines of panel JSON, this
module *generates* the equivalent dashboards from the framework's actual
metric names, one builder per board:

- Router      — transaction/notification counters (reference Router.json:88-326)
- KIE         — the four amount histograms (reference KIE.json bucket panels)
- ModelPrediction — proba_1 / Amount / V17 / V10 gauges
  (reference ModelPrediction.json:96-322)
- SeldonCore  — request rate / status codes / latency quantiles
  (reference SeldonCore.json:119-531)
- Bus         — in-process broker depth/throughput (the Kafka.json analog)
- Analytics   — mesh analytics jobs + drift PSI (the SparkMetrics.json analog:
  Spark executor panels become device-mesh worker/job panels)
- Retrain     — online-training health (new capability; no reference analog)
- Resilience  — fault-injection / circuit-breaker / degradation-ladder
  surface (new capability; no reference analog)
- ModelLifecycle — shadow/canary/promotion/rollback surface of the model
  lifecycle controller (new capability; no reference analog)
- Overload     — adaptive admission / priority shedding / backpressure
  surface of the overload-control plane (new capability; no reference
  analog)
- SeqServing   — overlapped sequence-serving dataflow: assembly/dispatch
  split, (L, B)-bucket executable mix, async in-flight depth, stale-commit
  crash-replay tripwire (new capability; no reference analog)
- SLO          — burn-rate SLO monitoring + stage-profile surface:
  multi-window error-budget burn per SLO, budget remaining, breach
  alerts, the REST per-layer latency-budget ledger, and the live
  queueing/service/dispatch stage decomposition with XLA compile
  attribution (new capability; no reference analog)
- Device       — device & transfer telemetry + incident flight recorder:
  per-device memory by kind, measured H2D bytes/latency on the scorer
  staging path, per-stage compile attribution, and the incident plane's
  snapshot/bundle economics (new capability; no reference analog)
- Heal         — device self-healing surface: per-device health state
  machine, canary outcomes, heal-ladder attempts by rung, quarantine/
  re-promotion incidents, and the warm-re-promotion compile proof
  (new capability; no reference analog)
- Fleet        — multi-host fleet surface: live membership vs lease TTL,
  per-partition ownership (sum per partition must be exactly 1),
  champion fingerprint parity + self-quarantine, per-member admission
  ceiling shares, fenced commits, fleet-ledger health, member-kill
  bundles (new capability; no reference analog)
- Capacity     — queueing-model observatory: predicted vs observed p99
  per stage and end-to-end, the model-error trust gauge, utilization/
  headroom per stage, bottleneck attribution, and the service-curve
  regression sentinel (new capability; no reference analog)

``write_dashboards(dir)`` emits one importable JSON file per board.
"""

from __future__ import annotations

import json
import os
from typing import Any

_PANEL_W = 12
_PANEL_H = 8


def _panel(panel_id: int, title: str, exprs: list[str], panel_type: str = "timeseries") -> dict:
    x = (panel_id % 2) * _PANEL_W
    y = (panel_id // 2) * _PANEL_H
    return {
        "id": panel_id + 1,
        "title": title,
        "type": panel_type,
        "datasource": {"type": "prometheus", "uid": "${DS_PROMETHEUS}"},
        "gridPos": {"h": _PANEL_H, "w": _PANEL_W, "x": x, "y": y},
        "targets": [
            {"expr": expr, "refId": chr(ord("A") + i), "legendFormat": "__auto"}
            for i, expr in enumerate(exprs)
        ],
    }


def _alert_stat(
    panel_id: int, title: str, exprs: list[str],
    red_above: float | None = None, red_below: float | None = None,
) -> dict:
    """Stat panel with alert-style threshold coloring — the shape the
    reference's Kafka board uses for its broker-health stats (Brokers
    Online / Under Replicated Partitions / Offline Partitions,
    reference deploy/grafana/Kafka.json singlestat panels): green when
    healthy, red past the threshold, so the operational signal reads at a
    glance instead of needing a query."""
    p = _panel(panel_id, title, exprs, "stat")
    if red_above is not None:
        steps = [
            {"color": "green", "value": None},
            {"color": "red", "value": red_above},
        ]
    elif red_below is not None:
        steps = [
            {"color": "red", "value": None},
            {"color": "green", "value": red_below},
        ]
    else:  # pragma: no cover - callers always pick a direction
        steps = [{"color": "green", "value": None}]
    p["fieldConfig"] = {
        "defaults": {"thresholds": {"mode": "absolute", "steps": steps}},
        "overrides": [],
    }
    return p


def _dashboard(title: str, uid: str, panels: list[dict]) -> dict:
    return {
        "title": title,
        "uid": uid,
        "schemaVersion": 39,
        "version": 1,
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "templating": {"list": []},
        "panels": panels,
        "__inputs": [
            {
                "name": "DS_PROMETHEUS",
                "label": "Prometheus",
                "type": "datasource",
                "pluginId": "prometheus",
            }
        ],
    }


def router_dashboard() -> dict:
    p = [
        _panel(0, "Incoming transactions / s",
               ["rate(transaction_incoming_total[5m])"]),
        _panel(1, "Outgoing by type / s",
               ['rate(transaction_outgoing_total{type="standard"}[5m])',
                'rate(transaction_outgoing_total{type="fraud"}[5m])']),
        _panel(2, "Customer notifications out",
               ["notifications_outgoing_total"], "stat"),
        _panel(3, "Customer responses",
               ['notifications_incoming_total{response="approved"}',
                'notifications_incoming_total{response="non_approved"}'], "stat"),
        _panel(4, "Scoring batch size p50/p95",
               ["histogram_quantile(0.5, rate(router_batch_size_bucket[5m]))",
                "histogram_quantile(0.95, rate(router_batch_size_bucket[5m]))"]),
        _panel(5, "Scorer dispatch latency p99",
               ["histogram_quantile(0.99, rate(router_score_seconds_bucket[5m]))"]),
        _panel(6, "Decode errors / s", ["rate(transaction_decode_errors_total[5m])"]),
        # business SLO quantiles (the reference tracks these on its
        # SeldonCore board, reference SeldonCore.json:499-531): wall time
        # from a record's produce timestamp to its process-start decision
        _panel(7, "Decision latency p50/p99 (produce → process start)",
               ["histogram_quantile(0.5, rate(router_decision_seconds_bucket[5m]))",
                "histogram_quantile(0.99, rate(router_decision_seconds_bucket[5m]))"]),
        # partition-parallel fan-out (router/parallel.py): batches per
        # worker loop show the partition split is actually balanced, and
        # the coalesced-dispatch rate against the pooled worker-batch rate
        # shows the fan-in onto one device (fewer dispatches than batches
        # == concurrent workers' sub-batches merged)
        _panel(8, "Batches per router worker / s",
               ["rate(router_worker_batches_total[5m])"]),
        _panel(9, "Coalesced device dispatches vs worker batches / s",
               ["rate(router_coalesced_dispatches_total[5m])",
                "sum(rate(router_worker_batches_total[5m]))"]),
        _panel(10, "Coalesced rows / s",
               ["rate(router_coalesced_rows_total[5m])"]),
        _alert_stat(11, "Load shed / s", ["rate(router_shed_total[5m])"],
                    red_above=1),
    ]
    return _dashboard("CCFD Router", "ccfd-router", p)


def kie_dashboard() -> dict:
    hists = [
        "fraud_investigation_amount",
        "fraud_approved_low_amount",
        "fraud_approved_amount",
        "fraud_rejected_amount",
    ]
    p = []
    for i, h in enumerate(hists):
        p.append(_panel(2 * i, f"{h} rate", [f"rate({h}_count[5m])"]))
        p.append(_panel(2 * i + 1, f"{h} mean amount",
                        [f"rate({h}_sum[5m]) / rate({h}_count[5m])"]))
    p.append(_panel(8, "Process starts by definition",
                    ['rate(process_instances_started_total[5m])']))
    p.append(_panel(9, "Process completions by status",
                    ['rate(process_instances_completed_total[5m])']))
    return _dashboard("CCFD Process Engine (KIE)", "ccfd-kie", p)


def model_prediction_dashboard() -> dict:
    p = [
        _panel(0, "proba_1 (last scored)", ["proba_1"]),
        _panel(1, "Amount (last scored)", ["Amount"]),
        _panel(2, "V17", ["V17"]),
        _panel(3, "V10", ["V10"]),
    ]
    return _dashboard("CCFD Model Prediction", "ccfd-modelpred", p)


def seldon_core_dashboard() -> dict:
    h = "seldon_api_executor_client_requests_seconds"
    p = [
        _panel(0, "Request rate / s", [f"rate({h}_count[5m])"]),
        _panel(1, "Success vs error codes / s",
               ['rate(seldon_api_executor_server_requests_total{code="200"}[5m])',
                'rate(seldon_api_executor_server_requests_total{code=~"4.."}[5m])',
                'rate(seldon_api_executor_server_requests_total{code=~"5.."}[5m])']),
    ]
    for i, q in enumerate((0.5, 0.75, 0.9, 0.95, 0.99)):
        p.append(
            _panel(2 + i, f"Latency p{int(q*100)}",
                   [f"histogram_quantile({q}, rate({h}_bucket[5m]))"])
        )
    # dispatch-health alerts: wedged attachment / deadline hits / requests
    # the host tier absorbed while the device was out (serving/dispatch.py)
    p.append(_alert_stat(7, "Device wedged", ["ccfd_device_wedged"], red_above=1))
    p.append(_alert_stat(8, "Dispatch timeouts",
                         ["rate(ccfd_dispatch_timeouts_total[5m])"], red_above=0.1))
    p.append(_panel(9, "Host-fallback scores / s",
                    ["rate(ccfd_host_fallback_scores_total[5m])"]))
    return _dashboard("CCFD Serving (SeldonCore)", "ccfd-seldon", p)


def bus_dashboard() -> dict:
    # broker-health panels mirror the reference Kafka board's shape:
    # messages-in rate, per-topic throughput, partition end offsets, and
    # consumer-group lag in place of under-replicated/offline-partition
    # stats (the single-log bus has no replication to degrade; lag is its
    # equivalent health signal) — reference deploy/grafana/Kafka.json
    p = [
        _panel(0, "Records in / s (cluster)", ["rate(bus_records_produced_total[5m])"]),
        _panel(1, "Records delivered / s", ["rate(bus_records_delivered_total[5m])"]),
        _panel(2, "Messages in by topic / s",
               ["rate(bus_topic_records_in_total[5m])"]),
        _panel(3, "Log end offset by topic/partition", ["bus_topic_end_offset"]),
        _panel(4, "Consumer-group backlog (lag)", ["bus_topic_backlog"]),
        # retention/log-size panels (reference Kafka.json "Log size" row):
        # retained window per partition plus the retention trim counter —
        # flat retained + rising start offset == bounded bus
        _panel(10, "Retained records by topic/partition",
               ["bus_topic_retained_records"]),
        _panel(11, "Log start offset (retention floor)",
               ["bus_topic_log_start_offset"]),
        _panel(12, "Records trimmed by retention",
               ["rate(bus_records_trimmed_total[5m])"]),
        # alert-depth health stats (the operational point of the reference
        # Kafka board): red when no consumer is attached, when backlog
        # grows past a stall-scale threshold, or when the serving side has
        # marked its device wedged
        _alert_stat(5, "Live consumers", ["bus_consumers"], red_below=1),
        _alert_stat(6, "Max consumer lag", ["max(bus_topic_backlog)"],
                    red_above=100_000),
        _alert_stat(7, "Scorer device wedged", ["max(ccfd_device_wedged)"],
                    red_above=1),
        _panel(8, "Producer rows / s", ["rate(producer_rows_total[5m])"]),
        _panel(9, "Notifications sent / replies",
               ["rate(notifications_sent_total[5m])",
                "rate(notifications_replied_total[5m])",
                "rate(notifications_no_reply_total[5m])"]),
    ]
    return _dashboard("CCFD Bus", "ccfd-bus", p)


def kafka_cluster_dashboard() -> dict:
    """Broker-health board for the REAL-Kafka deployment mode.

    When `bus/kafka_adapter.py` points the pipeline at an actual cluster
    (the reference's 3-broker Strimzi, frauddetection_cr.yaml:73-77), the
    in-proc Bus board's series don't exist — the cluster is scraped via the
    Kafka JMX exporter instead. This board carries the reference Kafka
    board's operational stat panels with the same JMX metric names and
    alert thresholds (reference deploy/grafana/Kafka.json: Brokers Online /
    Online Partitions / Under Replicated Partitions / Offline Partitions
    Count) plus throughput/lag views.
    """
    p = [
        _alert_stat(0, "Brokers Online",
                    ["count(kafka_server_replicamanager_leadercount)"],
                    red_below=3),
        _alert_stat(1, "Online Partitions",
                    ["sum(kafka_server_replicamanager_partitioncount)"],
                    red_below=1),
        _alert_stat(2, "Under Replicated Partitions",
                    ["sum(kafka_server_replicamanager_underreplicatedpartitions)"],
                    red_above=1),
        _alert_stat(3, "Offline Partitions Count",
                    ["sum(kafka_controller_kafkacontroller_offlinepartitionscount)"],
                    red_above=1),
        _panel(4, "Messages in / s",
               ["sum(rate(kafka_server_brokertopicmetrics_messagesin_total[5m]))"]),
        _panel(5, "Bytes in / out per second",
               ["sum(rate(kafka_server_brokertopicmetrics_bytesin_total[5m]))",
                "sum(rate(kafka_server_brokertopicmetrics_bytesout_total[5m]))"]),
        _panel(6, "Consumer group lag", ["sum(kafka_consumergroup_lag) by (consumergroup)"]),
        _alert_stat(7, "Adapter send failures",
                    ["rate(kafka_adapter_send_errors_total[5m])"], red_above=1),
    ]
    return _dashboard("CCFD Kafka Cluster", "ccfd-kafka", p)


def analytics_dashboard() -> dict:
    p = [
        _panel(0, "Analytics jobs / s",
               ["rate(analytics_jobs_completed_total[5m])"]),
        _panel(1, "Job duration p50/p95",
               ["histogram_quantile(0.5, rate(analytics_job_seconds_bucket[5m]))",
                "histogram_quantile(0.95, rate(analytics_job_seconds_bucket[5m]))"]),
        _panel(2, "Rows aggregated / s",
               ["rate(analytics_rows_processed_total[5m])"]),
        _panel(3, "Mesh workers", ["analytics_workers"], "stat"),
        _panel(4, "Per-feature drift PSI", ["analytics_drift_psi"]),
        _panel(5, "Worst-feature PSI", ["analytics_drift_max_psi"], "stat"),
    ]
    return _dashboard("CCFD Analytics", "ccfd-analytics", p)


def resilience_dashboard() -> dict:
    """Degraded-edge health board (round 6; no reference analog).

    Reads the fault-injection / circuit-breaker / degradation-ladder
    surface: breaker state per edge (``ccfd_breaker_state``: 0 closed,
    1 half-open, 2 open — runtime/breaker.py), per-tier degraded scoring
    and load shedding from the router's ladder (router/router.py), and the
    chaos layer's injected-fault rates (runtime/faults.py), so an operator
    can see AT A GLANCE which edge is sick, which tier is absorbing it,
    and whether the storm is injected or real.
    """
    p = [
        _alert_stat(0, "Any circuit open", ["max(ccfd_breaker_state)"],
                    red_above=2),
        _panel(1, "Breaker state by edge (0 closed / 1 half-open / 2 open)",
               ["ccfd_breaker_state"]),
        _panel(2, "Breaker transitions / s",
               ["rate(ccfd_breaker_transitions_total[5m])"]),
        _panel(3, "Degraded scoring by tier / s",
               ['rate(router_degraded_total{tier="host"}[5m])',
                'rate(router_degraded_total{tier="rules"}[5m])']),
        _alert_stat(4, "Load shedding / s", ["rate(router_shed_total[5m])"],
                    red_above=1),
        _panel(5, "Injected faults by edge+kind / s",
               ["rate(faults_injected_total[5m])"]),
        _panel(6, "Scorer-edge failures / s",
               ["rate(router_score_errors_total[5m])"]),
        _panel(7, "Chaos: service kills / fault windows per s",
               ["rate(chaos_injections_total[5m])",
                "rate(chaos_fault_windows_total[5m])"]),
        # memory-drift surface (observability/memory.py): RSS slope is the
        # endurance signal, per-component object counts name the suspect
        _panel(8, "Process RSS (bytes)", ["ccfd_process_rss_bytes"]),
        _panel(9, "Component object counts", ["ccfd_component_objects"]),
        # overload plane (runtime/overload.py): the adaptive in-flight
        # limit MOVING against its utilization is the live evidence the
        # AIMD loop is in control (the full surface is the Overload board)
        _panel(10, "Adaptive in-flight limit vs used (by stage)",
               ["ccfd_inflight_limit", "ccfd_inflight_used"]),
    ]
    return _dashboard("CCFD Resilience", "ccfd-resilience", p)


def overload_dashboard() -> dict:
    """Overload-control board (round 10; runtime/overload.py).

    The adaptive-admission surface: the AIMD in-flight limit against its
    utilization per stage (the limit visibly dropping under a latency
    step and recovering after IS the control loop working), admission
    decisions and sheds broken out by priority class and stage (bulk must
    shed first, critical last — the priority-inversion tripwire alerts if
    that ordering ever breaks), the dispatch-watchdog kill rate, REST
    429s, and the bus backlog the backpressure path parks load in instead
    of consuming it into an unbounded shed."""
    p = [
        _panel(0, "Adaptive in-flight limit vs used (by stage)",
               ["ccfd_inflight_limit", "ccfd_inflight_used"]),
        _panel(1, "Admission decisions (rows/s) by stage+priority",
               ['rate(ccfd_admission_total{decision="admit"}[5m])',
                'rate(ccfd_admission_total{decision!="admit"}[5m])']),
        _panel(2, "Shed rows / s by priority and stage",
               ["rate(ccfd_shed_total[5m])"]),
        _alert_stat(3, "Priority inversions (must be 0)",
                    ["ccfd_priority_inversions_total"], red_above=1),
        _alert_stat(4, "Dispatch watchdog kills / s",
                    ["rate(ccfd_dispatch_timeout_total[5m])"],
                    red_above=0.1),
        _panel(5, "REST admission: 429 responses / s",
               ['rate(seldon_api_executor_server_requests_total{code="429"}[5m])']),
        _panel(6, "Bus backlog under backpressure (consumer lag)",
               ["bus_topic_backlog"]),
        _panel(7, "Admitted-traffic decision latency p50/p99",
               ["histogram_quantile(0.5, rate(router_decision_seconds_bucket[5m]))",
                "histogram_quantile(0.99, rate(router_decision_seconds_bucket[5m]))"]),
        _alert_stat(8, "Router shed rate (rows/s)",
                    ["rate(router_shed_total[5m])"], red_above=1),
        _panel(9, "Batcher queue depth (serving REST / router coalescing)",
               ['ccfd_component_objects{component="serving_batcher_queue"}',
                'ccfd_component_objects{component="router_batcher_queue"}']),
    ]
    return _dashboard("CCFD Overload", "ccfd-overload", p)


def tracing_dashboard() -> dict:
    """Distributed-tracing board (round 7; observability/trace.py).

    Per-stage latency decomposition from the span histograms every
    component tracer exports (``trace_span_seconds{span=...}`` on the
    component's own scraped registry), the critical-path share each stage
    contributes (sum-of-durations normalized — the "where did this
    transaction's 40 ms go" view), and the tail sampler's keep/drop
    economics so an operator can see both what tracing shows and what it
    costs. The labelset-guard panel watches the cardinality protection
    that keeps span/edge labels from blowing up the scrape surface
    (metrics/prom.py)."""
    h = "trace_span_seconds"
    p = [
        _panel(0, "Per-stage latency p50 (by span)",
               [f"histogram_quantile(0.5, sum by (span, le) (rate({h}_bucket[5m])))"]),
        _panel(1, "Per-stage latency p99 (by span)",
               [f"histogram_quantile(0.99, sum by (span, le) (rate({h}_bucket[5m])))"]),
        _panel(2, "Critical-path share by stage",
               [f"sum by (span) (rate({h}_sum[5m])) "
                f"/ ignoring(span) group_left sum(rate({h}_sum[5m]))"]),
        _panel(3, "Spans recorded / s (by component)",
               ["rate(ccfd_trace_spans_total[5m])"]),
        _panel(4, "Sampler keep vs drop / s",
               ["rate(ccfd_traces_kept_total[5m])",
                "rate(ccfd_traces_dropped_total[5m])"]),
        _panel(5, "Forced keeps by reason / s",
               ['rate(ccfd_traces_kept_total{reason!="sampled"}[5m])']),
        _alert_stat(6, "Retained traces", ["ccfd_traces_retained"],
                    red_below=1),
        _panel(7, "Traces pending decision", ["ccfd_traces_pending"]),
        _alert_stat(8, "Label-sets folded to overflow / s",
                    ["rate(ccfd_metric_labelsets_dropped_total[5m])"],
                    red_above=1),
    ]
    return _dashboard("CCFD Tracing", "ccfd-tracing", p)


def lifecycle_dashboard() -> dict:
    """Model-lifecycle board (round 9; lifecycle/).

    The governed-rollout surface: which stage the candidate is in
    (``ccfd_lifecycle_stage``: 0 idle / 1 shadow / 2 canary), the
    promotion/rejection/rollback economics, shadow-scoring throughput and
    drops (the off-hot-path contract: drops, not latency), the evaluator's
    champion-vs-challenger evidence (label AUC, alert-rate delta,
    score-distribution PSI against its 0.25 action threshold), and the
    canary traffic split by arm. An operator reads it as: what is in
    flight, how close is the verdict, and did anything roll back."""
    p = [
        _alert_stat(0, "Candidate stage (0 idle / 1 shadow / 2 canary)",
                    ["ccfd_lifecycle_stage"], red_above=2),
        _panel(1, "Champion / candidate version",
               ["ccfd_lifecycle_champion_version",
                "ccfd_lifecycle_candidate_version"], "stat"),
        _panel(2, "Promotions / rollbacks / rejections",
               ["ccfd_lifecycle_promotions_total",
                "ccfd_lifecycle_rollbacks_total",
                "ccfd_lifecycle_rejections_total"], "stat"),
        _alert_stat(3, "Rollbacks / s",
                    ["rate(ccfd_lifecycle_rollbacks_total[5m])"],
                    red_above=0.01),
        _panel(4, "Candidates accepted vs coalesced / s",
               ["rate(ccfd_lifecycle_candidates_total[5m])",
                "rate(ccfd_lifecycle_submissions_coalesced_total[5m])"]),
        _panel(5, "Shadow rows scored / dropped per s",
               ["rate(ccfd_lifecycle_shadow_rows_total[5m])",
                "rate(ccfd_lifecycle_shadow_dropped_total[5m])"]),
        _panel(6, "Label AUC by model",
               ["ccfd_lifecycle_auc"]),
        _panel(7, "Labels / shadow rows joined for the candidate",
               ["ccfd_lifecycle_eval_labels",
                "ccfd_lifecycle_eval_shadow_rows"]),
        _alert_stat(8, "Champion vs challenger score PSI",
                    ["ccfd_lifecycle_score_psi"], red_above=0.25),
        _panel(9, "Alert-rate delta (challenger - champion)",
               ["ccfd_lifecycle_alert_rate_delta"]),
        _panel(10, "Canary rows by arm / s",
               ['rate(ccfd_lifecycle_canary_rows_total{arm="champion"}[5m])',
                'rate(ccfd_lifecycle_canary_rows_total{arm="challenger"}[5m])']),
        _alert_stat(11, "Shadow scoring errors / s",
                    ["rate(ccfd_lifecycle_shadow_errors_total[5m])",
                     "rate(ccfd_lifecycle_canary_errors_total[5m])"],
                    red_above=0.1),
    ]
    return _dashboard("CCFD Model Lifecycle", "ccfd-lifecycle", p)


def seq_serving_dashboard() -> dict:
    """Sequence Serving board (round 11; serving/history.py).

    The overlapped seq dataflow's surface: host assembly vs device
    dispatch per router batch (the BENCH_r05 1412-vs-13 ms split, now
    live numbers — dispatch here counts only the blocking waits the
    overlap failed to hide), the (L, B)-bucket executable mix (short L
    buckets firing = the cold-row fast lane actually serving), async
    in-flight depth, the anonymous lock-free fast path, live-history
    customers against the LRU cap, and the stale-generation commit
    counter — nonzero only when a dispatch was in flight across a crash
    restore, where the no-op commit is exactly what keeps replay from
    double-appending."""
    p = [
        _panel(0, "Assembly vs dispatch p50 (s / batch)",
               ["histogram_quantile(0.5, rate(seq_assembly_seconds_bucket[5m]))",
                "histogram_quantile(0.5, rate(seq_dispatch_seconds_bucket[5m]))"]),
        _panel(1, "Assembly vs dispatch p99 (s / batch)",
               ["histogram_quantile(0.99, rate(seq_assembly_seconds_bucket[5m]))",
                "histogram_quantile(0.99, rate(seq_dispatch_seconds_bucket[5m]))"]),
        _panel(2, "Dispatches by (L, B) bucket / s",
               ["rate(seq_bucket_dispatch_total[5m])"]),
        _panel(3, "Rows by L bucket / s",
               ["rate(seq_bucket_rows_total[5m])"]),
        _panel(4, "Async dispatches in flight", ["seq_inflight_dispatches"]),
        _panel(5, "Anonymous fast-path rows / s",
               ["rate(seq_anonymous_rows_total[5m])"]),
        _panel(6, "Customers with live history", ["seq_history_customers"],
               "stat"),
        _alert_stat(7, "Stale-generation commits (crash-replay no-ops)",
                    ["rate(seq_stale_commits_total[5m])"], red_above=1),
    ]
    return _dashboard("CCFD Sequence Serving", "ccfd-seq", p)


def slo_dashboard() -> dict:
    """SLO board (round 12; observability/slo.py + profile.py).

    The objective-side view the Overload board's mechanisms defend: per
    SLO, the multi-window error-budget burn rate (the fast 5m/1h pair is
    the page condition; the slow 6h window is the budget-consumption
    trend), error budget remaining, and the edge-triggered breach
    counter. Below it, the stage-profile surface: the per-layer REST
    latency-budget ledger (which layer is eating the budget — transport
    floor, batcher wait, device dispatch, H2D), the live queueing vs
    service vs dispatch decomposition per pipeline stage, and XLA
    compile-event attribution (a mid-traffic compile explains a p99
    spike no traffic change does)."""
    p = [
        _panel(0, "Error-budget burn rate by SLO and window",
               ["ccfd_slo_burn_rate"]),
        _alert_stat(1, "Fast-window burn (page at threshold)",
                    ['max(ccfd_slo_burn_rate{window="5m"})',
                     'max(ccfd_slo_burn_rate{window="1h"})'],
                    red_above=14.4),
        _alert_stat(2, "Error budget remaining by SLO",
                    ["ccfd_slo_error_budget_remaining"], red_below=0.1),
        _alert_stat(3, "SLO breaches (edge-triggered)",
                    ["ccfd_slo_breach_total"], red_above=1),
        _panel(4, "SLO breaching now (0/1)", ["ccfd_slo_breaching"]),
        _panel(5, "REST budget spent ratio by layer "
                  "(>1 = layer blows its slice)",
               ["ccfd_slo_budget_spent_ratio"]),
        _panel(6, "Stage latency p99 by component (ms)",
               ['ccfd_stage_latency_ms{quantile="p99"}']),
        _panel(7, "Stage latency p50 by component (ms)",
               ['ccfd_stage_latency_ms{quantile="p50"}']),
        _panel(8, "Queueing share: bus wait vs scorer dispatch p99 (ms)",
               ['ccfd_stage_latency_ms{stage="bus",component="queue",quantile="p99"}',
                'ccfd_stage_latency_ms{stage="router.score",component="dispatch",quantile="p99"}']),
        _alert_stat(9, "XLA compiles under traffic / s",
                    ["rate(ccfd_xla_compile_events_total[5m])"],
                    red_above=0.1),
        _panel(10, "Cumulative XLA compile seconds",
               ["ccfd_xla_compile_seconds_total"]),
    ]
    return _dashboard("CCFD SLO", "ccfd-slo", p)


def device_dashboard() -> dict:
    """Device telemetry + incident board (round 13; observability/device.py
    + observability/incident.py).

    The measured side of the H2D/HBM story: per-device memory by kind
    (allocator in-use/peak/limit where the backend reports them, live
    buffer bytes everywhere), H2D staging throughput and per-put latency
    from the scorer's instrumented dispatch path (the numbers the
    BudgetLedger's h2d layer now reads instead of a reservation),
    per-stage XLA compile attribution, and the incident flight recorder's
    economics — ring depth, snapshot reasons, and the bundle counter an
    operator checks after a page to find the post-mortem at
    ``/incidents``."""
    p = [
        _panel(0, "Device memory by kind (bytes)",
               ["ccfd_device_memory_bytes"]),
        _panel(1, "H2D staged bytes / s",
               ["rate(ccfd_h2d_bytes_total[5m])"]),
        _panel(2, "H2D put latency p50/p99",
               ["histogram_quantile(0.5, rate(ccfd_h2d_seconds_bucket[5m]))",
                "histogram_quantile(0.99, rate(ccfd_h2d_seconds_bucket[5m]))"]),
        _panel(3, "H2D puts / s",
               ["rate(ccfd_h2d_seconds_count[5m])"]),
        _panel(4, "Compile seconds by stage",
               ["ccfd_compile_stage_seconds_total"]),
        _alert_stat(5, "XLA compiles under traffic / s",
                    ["rate(ccfd_xla_compile_events_total[5m])"],
                    red_above=0.1),
        _panel(6, "Flight-recorder snapshots / s (by reason)",
               ["rate(ccfd_incident_snapshots_total[5m])"]),
        _alert_stat(7, "Incident bundles dumped",
                    ["ccfd_incidents_total"], red_above=1),
        _panel(8, "Snapshot ring depth", ["ccfd_incident_ring_size"],
               "stat"),
        _alert_stat(9, "Dispatch watchdog kills / s "
                       "(each snapshots the ring)",
                    ["rate(ccfd_dispatch_timeout_total[5m])"],
                    red_above=0.1),
        # -- Mesh row (ISSUE 12; parallel/partition.py): the multi-chip
        # serving surface — device count + named axis sizes of the live
        # mesh (absent/0 = unsharded single-device serving), and the
        # publish path's health: every sharded param swap should pause
        # the router pool at a batch boundary; a pause TIMEOUT means the
        # publish went through under double-buffering only (the pool was
        # not quiescent — investigate a wedged worker)
        _panel(10, "Mesh devices (serving mesh; 0/absent = unsharded)",
               ["ccfd_mesh_devices"], "stat"),
        _panel(11, "Mesh axis sizes (data / fsdp / tp)",
               ["ccfd_mesh_axis_size"], "stat"),
        _panel(12, "Sharded param publishes / s (through the pause gate)",
               ["rate(ccfd_mesh_publishes_total[5m])"]),
        _alert_stat(13, "Publish pause timeouts / s (pool not quiescent)",
                    ["rate(ccfd_mesh_publish_pause_timeouts_total[5m])"],
                    red_above=0.01),
    ]
    return _dashboard("CCFD Device", "ccfd-device", p)


def heal_dashboard() -> dict:
    """Device-heal board (round 14; runtime/heal.py).

    The device-as-fallible-component surface: the per-device health state
    machine (one-hot ``ccfd_device_health{device,state}`` — quarantined
    is the alert), canary dispatch outcomes, heal-ladder attempts by rung
    (canary retry → backend reinit → scorer respawn), quarantine /
    re-promotion incident bundles, and the two proofs the re-promotion
    contract makes: the host tier absorbing traffic while quarantined
    (``router_degraded_total{tier="host"}``) and zero serving-stage XLA
    compiles after the warm flip (compile-stage attribution)."""
    p = [
        _alert_stat(0, "Device quarantined now",
                    ['max(ccfd_device_health{state="quarantined"})'],
                    red_above=1),
        _panel(1, "Device health state (one-hot by device)",
               ["ccfd_device_health"]),
        _panel(2, "Health transitions / s (by target state)",
               ["rate(ccfd_heal_transitions_total[5m])"]),
        _panel(3, "Canary outcomes / s",
               ['rate(ccfd_heal_canary_total{outcome="pass"}[5m])',
                'rate(ccfd_heal_canary_total{outcome="fail"}[5m])']),
        _panel(4, "Heal-ladder attempts / s (by rung)",
               ["rate(ccfd_heal_attempts_total[5m])"]),
        _panel(5, "Quarantine / re-promotion bundles",
               ['ccfd_incidents_total{trigger="device_quarantine"}',
                'ccfd_incidents_total{trigger="device_repromote"}'],
               "stat"),
        _panel(6, "Host tier absorbing quarantined traffic (rows/s)",
               ['rate(router_degraded_total{tier="host"}[5m])',
                'rate(router_degraded_total{tier="rules"}[5m])']),
        _alert_stat(7, "Serving-stage compiles / s (warm flip ⇒ 0)",
                    # non-serving stages excluded: the warm step ITSELF
                    # emits a heal.warm compile burst (that is the
                    # contract working, not a violation) — same exclusion
                    # set as the supervisor's compile-storm signal
                    ['sum(rate(ccfd_compile_stage_seconds_total{stage!~"'
                     'total|heal\\\\..*|scorer\\\\.warmup|seq\\\\.warmup|'
                     'seq\\\\.swap"}[5m]))'],
                    red_above=0.1),
        _panel(8, "Compile seconds by stage (heal.warm = the warm step)",
               ["ccfd_compile_stage_seconds_total"]),
        _alert_stat(9, "H2D staging put failures / s",
                    ["rate(ccfd_h2d_put_failures_total[5m])"],
                    red_above=0.1),
    ]
    return _dashboard("CCFD Heal", "ccfd-heal", p)


def storage_dashboard() -> dict:
    """Durable-state integrity board (ISSUE 13; runtime/durability.py).

    The disk-as-fallible-component surface: corrupt artifacts detected
    and quarantined (the alert — every count here is a file that would
    previously have crashed bring-up or silently served garbage),
    last-good generation fallbacks, failed durable writes (full disk /
    injected storage faults; in-memory state stays authoritative and
    re-lands on the next save), verified vs legacy-unverified reads, the
    startup orphan-tmp sweep, mid-file bus-log corruption (valid records
    dropped past a corrupt frame — offset safety demands the truncation,
    this counter makes the loss loud), and the rules-tier storage pin
    (1 = NO params generation verifies; serving refuses unverified
    trees)."""
    p = [
        _alert_stat(0, "Corrupt artifacts detected (quarantined)",
                    ["sum(ccfd_storage_corrupt_total)"], red_above=1),
        _alert_stat(1, "Serving pinned to rules tier (storage)",
                    ["max(ccfd_storage_pinned)"], red_above=1),
        _panel(2, "Corruption by artifact / s",
               ["rate(ccfd_storage_corrupt_total[5m])"]),
        _panel(3, "Last-good generation fallbacks",
               ["ccfd_storage_fallback_total"], "stat"),
        _alert_stat(4, "Durable write errors / s",
                    ["sum(rate(ccfd_storage_write_errors_total[5m]))"],
                    red_above=0.1),
        _panel(5, "Reads: verified vs legacy-unverified / s",
               ["sum(rate(ccfd_storage_verified_reads_total[5m]))",
                "sum(rate(ccfd_storage_unverified_reads_total[5m]))"]),
        _panel(6, "Orphan tmp files swept at startup",
               ["ccfd_storage_tmp_swept_total"], "stat"),
        _alert_stat(7, "Bus-log records dropped past mid-file corruption",
                    ["ccfd_storage_log_truncated_records_total"],
                    red_above=1),
    ]
    return _dashboard("CCFD Storage", "ccfd-storage", p)


def audit_dashboard() -> dict:
    """Decision provenance board (ISSUE 14; observability/audit.py).

    The compliance surface: decision records stamped per routed
    transaction (the conservation claim — this rate must track the
    outgoing rate exactly), the two durable-loss alerts kept in their
    OWN units (log_write counts RECORDS whose append failed; torn_tail
    counts truncation EVENTS at crash recovery — the records inside a
    torn frame are unparseable, so an event is the honest unit), the
    segmented log's on-disk footprint, and the bounded query ring's
    depth."""
    p = [
        _panel(0, "Decision records stamped / s",
               ["rate(ccfd_audit_records_total[5m])"]),
        _panel(1, "Routed vs recorded / s (conservation: identical)",
               ["sum(rate(transaction_outgoing_total[5m]))",
                "rate(ccfd_audit_records_total[5m])"]),
        _alert_stat(2, "Records lost to failed appends",
                    ["sum(ccfd_audit_dropped_total"
                     "{reason=\"log_write\"})"],
                    red_above=1),
        _alert_stat(3, "Torn tails truncated at recovery (events)",
                    ["sum(ccfd_audit_dropped_total"
                     "{reason=\"torn_tail\"})"],
                    red_above=1),
        _panel(4, "Drops by reason / s",
               ["rate(ccfd_audit_dropped_total[5m])"]),
        _panel(5, "Audit log bytes on disk", ["ccfd_audit_log_bytes"],
               "stat"),
        _panel(6, "Query-ring depth", ["ccfd_audit_ring_records"]),
    ]
    return _dashboard("CCFD Audit", "ccfd-audit", p)


def fleet_dashboard() -> dict:
    """Multi-host fleet board (ISSUE 16; ccfd_tpu/fleet/).

    The host-as-fallible-component surface: live membership vs the lease
    TTL (a dip is a dead or partitioned member), the bus group epoch each
    member sees (divergence = a member serving a stale assignment),
    per-partition ownership (the fleet-wide sum per partition must be
    EXACTLY 1 — >1 is a double-route, 0 is an orphan), champion
    fingerprint parity with the self-quarantine alert, the per-member
    share of the fleet admission ceiling, fenced commits refused by the
    bus epoch fence (each one is an at-least-once redelivery that would
    otherwise have been a silent double-apply), fleet-ledger publish
    health, and the aggregator's member-kill incident bundles."""
    p = [
        _alert_stat(0, "Live members (lease not expired)",
                    ["min(ccfd_fleet_members)"], red_below=2),
        _alert_stat(1, "Members self-quarantined (stale champion)",
                    ["sum(ccfd_fleet_quarantined)"], red_above=1),
        _alert_stat(2, "Champion fingerprint parity (fleet-wide)",
                    ["min(ccfd_fleet_parity)"], red_below=1),
        _panel(3, "Partition ownership (sum per partition must be 1)",
               ["sum by (partition) (ccfd_fleet_partition_owner)"]),
        _panel(4, "Bus group epoch by member (divergence = stale view)",
               ["ccfd_fleet_epoch"]),
        _panel(5, "Per-member admission ceiling (AIMD share of global)",
               ["ccfd_fleet_admission_ceiling"]),
        _alert_stat(6, "Fenced commits refused (stale-epoch evidence)",
                    ["sum(router_fenced_commits_total)"], red_above=10),
        _panel(7, "Fleet-ledger entries vs publish errors / s",
               ["sum(rate(fleet_ledger_entries_total[5m]))",
                "sum(rate(fleet_ledger_publish_errors_total[5m]))"]),
        _panel(8, "Gossip dial failures / s (by peer)",
               ["rate(fleet_gossip_errors_total[5m])"]),
        _panel(9, "Member-kill incident bundles (aggregator)",
               ["sum(fleet_member_kill_bundles_total)"], "stat"),
        _panel(10, "Elected aggregator (1 on exactly one member)",
               ["ccfd_fleet_aggregator"]),
    ]
    return _dashboard("CCFD Fleet", "ccfd-fleet", p)


def replay_dashboard() -> dict:
    """Bulk replay & backtest board (ISSUE 17; ccfd_tpu/replay/).

    The conservation surface: replayed rows by outcome (match must be
    the only moving series), divergences by classified cause with the
    one alert that matters — ``nondeterminism`` must stay 0 (every other
    cause is an EXPLAINED finding: a promote, a tier change, a threshold
    move), drops/ghosts (window accounting holes), replay throughput
    next to the bulk admission ceiling actually in force (the
    zero-live-SLO-impact evidence reads alongside the SLO board's burn
    rates), verdicts diverted at the route seam, and the durable
    cursor's progress (flat while rows flow = a wedged window)."""
    p = [
        _panel(0, "Replayed rows by outcome / s",
               ["rate(ccfd_replay_rows_total[5m])"]),
        _alert_stat(1, "Unexplained divergences (nondeterminism)",
                    ["sum(ccfd_replay_divergence_total"
                     "{cause=\"nondeterminism\"})"],
                    red_above=1),
        _panel(2, "Divergences by cause / s",
               ["rate(ccfd_replay_divergence_total[5m])"]),
        _alert_stat(3, "Window rows dropped (no verdict after retries)",
                    ["sum(ccfd_replay_rows_total{outcome=\"drop\"})"],
                    red_above=1),
        _alert_stat(4, "Ghost verdicts (uid outside the window)",
                    ["sum(ccfd_replay_rows_total{outcome=\"ghost\"})"],
                    red_above=1),
        _panel(5, "Replay throughput (rows / s, last window)",
               ["ccfd_replay_rows_per_s"]),
        _panel(6, "Bulk admission ceiling in force (by stage)",
               ["ccfd_bulk_ceiling"]),
        _panel(7, "Bulk rows shed at the ceiling / s",
               ["sum(rate(ccfd_shed_total{stage=\"bulk_ceiling\"}[5m]))"]),
        _panel(8, "Replay verdicts at the route seam / s (by fate)",
               ["rate(ccfd_replay_verdicts_total[5m])"]),
        _panel(9, "Durable cursor seq", ["ccfd_replay_cursor_seq"]),
        _panel(10, "Windows completed (clean vs findings)",
               ["sum(ccfd_replay_windows_total)"], "stat"),
    ]
    return _dashboard("CCFD Replay", "ccfd-replay", p)


def capacity_dashboard() -> dict:
    """Capacity observatory board (ISSUE 18; observability/capacity.py).

    The predictive surface the item-3 planner will actuate against: the
    model's own trustworthiness SLI first (predicted-vs-observed e2e p99
    error ratio — above 1.0 the model mispredicts by more than the
    observation itself and nothing downstream should trust it), then
    predicted p99 per stage against the live observation, utilization
    and headroom per stage, the current bottleneck attribution (one-hot
    by stage), and the service-curve regression sentinel's edge counter
    — a fired regression after a lifecycle promotion or a heal
    re-promotion is the "new executable, new service curve" signal."""
    p = [
        _alert_stat(0, "Model error ratio (|pred-obs|/obs, e2e p99)",
                    ["ccfd_capacity_model_error_ratio"], red_above=1.0),
        _panel(1, "Predicted p99 by stage (ms)",
               ['ccfd_capacity_predicted_p99_ms{stage!="e2e"}']),
        _panel(2, "Predicted vs observed e2e p99 (ms)",
               ['ccfd_capacity_predicted_p99_ms{stage="e2e"}',
                'ccfd_stage_latency_ms{quantile="p99"}']),
        _panel(3, "Stage utilization (rho)",
               ["ccfd_capacity_utilization"]),
        _panel(4, "Headroom ratio by stage (capacity / admitted)",
               ["ccfd_capacity_headroom_ratio"]),
        _alert_stat(5, "Min headroom (saturation at 1.0)",
                    ["min(ccfd_capacity_headroom_ratio)"], red_below=1.2),
        _panel(6, "Bottleneck attribution (one-hot by stage)",
               ["ccfd_capacity_bottleneck"]),
        _alert_stat(7, "Service-curve regressions fired",
                    ["sum(ccfd_capacity_regression_total)"], red_above=1),
        _panel(8, "Regressions by stage / s",
               ["rate(ccfd_capacity_regression_total[5m])"]),
    ]
    return _dashboard("CCFD Capacity", "ccfd-capacity", p)


def retrain_dashboard() -> dict:
    p = [
        _panel(0, "Labels ingested by class / s", ["rate(retrain_labels_total[5m])"]),
        _panel(1, "Optimizer steps / s", ["rate(retrain_steps_total[5m])"]),
        _panel(2, "Serving hot swaps", ["retrain_param_swaps_total"], "stat"),
        _panel(3, "Last training loss", ["retrain_last_loss"], "stat"),
    ]
    return _dashboard("CCFD Online Retrain", "ccfd-retrain", p)


def build_all_dashboards() -> dict[str, dict]:
    return {
        "Router": router_dashboard(),
        "KIE": kie_dashboard(),
        "ModelPrediction": model_prediction_dashboard(),
        "SeldonCore": seldon_core_dashboard(),
        "Bus": bus_dashboard(),
        "KafkaCluster": kafka_cluster_dashboard(),
        "Analytics": analytics_dashboard(),
        "Retrain": retrain_dashboard(),
        "Resilience": resilience_dashboard(),
        "Tracing": tracing_dashboard(),
        "ModelLifecycle": lifecycle_dashboard(),
        "Overload": overload_dashboard(),
        "SeqServing": seq_serving_dashboard(),
        "SLO": slo_dashboard(),
        "Device": device_dashboard(),
        "Heal": heal_dashboard(),
        "Storage": storage_dashboard(),
        "Audit": audit_dashboard(),
        "Fleet": fleet_dashboard(),
        "Replay": replay_dashboard(),
        "Capacity": capacity_dashboard(),
    }


def write_dashboards(out_dir: str) -> list[str]:
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for name, board in build_all_dashboards().items():
        path = os.path.join(out_dir, f"{name}.json")
        with open(path, "w") as f:
            json.dump(board, f, indent=2, sort_keys=True)
            f.write("\n")
        paths.append(path)
    return paths


if __name__ == "__main__":
    import sys

    out = sys.argv[1] if len(sys.argv) > 1 else "deploy/grafana"
    for p in write_dashboards(out):
        print(p)
