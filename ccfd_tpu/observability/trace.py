"""Pipeline-wide distributed tracing: spans, context propagation, tail sampling.

The reference demo exposes per-service JVM introspection ports and nothing
application-level (SURVEY.md §5); the old ``utils/tracing.py`` recorded
process-local spans into a private registry the exporter never served. This
module replaces it with a real tracing subsystem, shaped by the needs of
pipeline-latency attribution (InferLine, arXiv:1812.01776: tight pipeline
SLOs need per-stage critical-path visibility, not endpoint histograms):

- **Context propagation** — W3C ``traceparent`` (``00-<trace>-<span>-<flags>``)
  injected by every HTTP client hop (utils/httpclient.py, serving/client.py,
  store/client.py) and extracted by every server surface (serving, engine
  REST, bus server, metrics exporter), plus carriage through bus records
  (``Broker.produce(..., headers=...)``) so one produced batch yields one
  end-to-end trace from producer through router → scorer → engine → notify.
- **Per-component tracers** — :class:`Tracer` records span durations into the
  component's SCRAPED registry (``trace_span_seconds{span=...}``; the
  operator wires each tracer to the same registry the exporter serves —
  fixing the old unscraped-private-registry bug) and feeds finished spans to
  a shared in-process :class:`SpanSink`.
- **Tail-based sampling** — the sink keeps every trace that is slow, errored
  or flagged (fraud-routed, degraded-tier, breaker-refused — callers set
  span attrs), and a deterministic hash fraction (``CCFD_TRACE_SAMPLE``) of
  the boring rest. Decisions happen at the TAIL (after spans arrive), which
  is the only way "always keep the interesting ones" can be honored.
- **Exemplars** — span trace-ids attach to the existing latency histograms
  (metrics/prom.py exemplar support), so a Grafana heat-map cell links to
  the exact retained trace via the exporter's ``/traces/<id>`` endpoint.

Span context is tracked per-thread via ``contextvars``; pipelined code that
hops threads (the router's score worker) passes ``parent=`` explicitly.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import os
import threading
import time
import zlib
from typing import Any, Iterator, Mapping, NamedTuple

from ccfd_tpu.metrics.prom import Registry

TRACEPARENT = "traceparent"
_TRACEPARENT_B = b"traceparent"


class SpanContext(NamedTuple):
    trace_id: str  # 32 lowercase hex chars
    span_id: str   # 16 lowercase hex chars
    sampled: bool = True


_current: contextvars.ContextVar[SpanContext | None] = contextvars.ContextVar(
    "ccfd_trace_ctx", default=None
)


def current_context() -> SpanContext | None:
    """The active span's context on THIS thread (None outside any span)."""
    return _current.get()


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def format_traceparent(ctx: SpanContext | None) -> str | None:
    if ctx is None:
        return None
    return f"00-{ctx.trace_id}-{ctx.span_id}-{'01' if ctx.sampled else '00'}"


def parse_traceparent(value: Any) -> SpanContext | None:
    """``00-<32 hex>-<16 hex>-<2 hex>`` -> SpanContext; anything else None.

    Tolerant by design (a malformed header from a version-skewed peer must
    start a fresh trace, never 500 the request)."""
    if isinstance(value, bytes):
        try:
            value = value.decode("ascii")
        except UnicodeDecodeError:
            return None
    if not isinstance(value, str):
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16), int(flags, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id.lower(), span_id.lower(),
                       sampled=bool(int(flags, 16) & 1))


def inject_headers(headers: dict | None = None,
                   ctx: SpanContext | None = None) -> dict:
    """Add a ``traceparent`` entry for ``ctx`` (default: the current span)
    to ``headers`` (created if None). No-op when there is no active span."""
    headers = {} if headers is None else headers
    tp = format_traceparent(ctx if ctx is not None else current_context())
    if tp is not None:
        headers[TRACEPARENT] = tp
    return headers


def extract_context(headers: Mapping | None) -> SpanContext | None:
    """Pull a SpanContext out of an HTTP-header-shaped mapping. Accepts str
    or bytes keys (the fasthttp server lowercases bytes keys; stdlib
    handlers expose case-insensitive str mappings)."""
    if not headers:
        return None
    v = headers.get(TRACEPARENT)
    if v is None and hasattr(headers, "get"):
        v = headers.get(_TRACEPARENT_B)
    if v is None:  # stdlib email.message headers are case-insensitive,
        # plain dicts are not: scan as the last resort
        for k in headers:
            name = k.decode("latin-1") if isinstance(k, bytes) else str(k)
            if name.lower() == TRACEPARENT:
                v = headers[k]
                break
    return parse_traceparent(v)


class Span:
    """One timed operation. Mutable so callers can set ``attrs`` mid-span
    (degraded tier, fraud flag, HTTP status); finished spans are handed to
    the sink and must not be mutated afterward."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "component",
                 "start", "duration_s", "status", "attrs", "_t0")

    def __init__(self, trace_id: str, span_id: str, parent_id: str | None,
                 name: str, component: str, start: float,
                 attrs: dict | None = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.component = component
        self.start = start            # wall clock: cross-process alignment
        self._t0 = time.perf_counter()  # monotonic: duration must survive
        self.duration_s = 0.0           # NTP steps/smears
        self.status = "ok"
        self.attrs = attrs if attrs is not None else {}

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def to_dict(self) -> dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "component": self.component,
            "start": self.start,
            "duration_s": self.duration_s,
            "status": self.status,
            "attrs": dict(self.attrs),
        }


# span attrs whose truthiness forces a tail-sampling KEEP: the conditions
# an operator always wants the trace for (the router sets fraud/degraded,
# clients set breaker_open on CircuitOpenError)
FLAG_ATTRS = ("fraud", "degraded", "breaker_open")


class _TraceBuf:
    __slots__ = ("spans", "last", "reason")

    def __init__(self) -> None:
        self.spans: list[Span] = []
        self.last = 0.0
        self.reason: str | None = None  # first forced-keep reason seen


class SpanSink:
    """In-process span collector with tail-based sampling.

    Spans buffer per trace; a trace is FINALIZED (keep/drop decided) when it
    has been idle for ``decision_window_s`` (flushed lazily on read/eviction
    — no background thread) or when the pending set overflows. Keep rules,
    in order: any span errored; any span >= ``slow_s``; any span carries a
    truthy flag attr (:data:`FLAG_ATTRS`); else a deterministic hash of the
    trace id keeps ``sample`` of the remainder — deterministic so every
    component of a distributed deployment makes the SAME decision without
    coordination. Retained traces live in a bounded ring (oldest evicted).
    """

    def __init__(
        self,
        sample: float = 0.01,
        slow_s: float = 0.1,
        max_pending: int = 1024,
        max_retained: int = 256,
        decision_window_s: float = 5.0,
        registry: Registry | None = None,
    ):
        self.sample = min(1.0, max(0.0, float(sample)))
        self.slow_s = float(slow_s)
        self.max_pending = int(max_pending)
        self.max_retained = int(max_retained)
        self.decision_window_s = float(decision_window_s)
        self._lock = threading.Lock()
        # span listeners (observability/profile.py StageProfiler): called
        # for EVERY finished span, before sampling — the stage profile
        # must see the full population, not the tail-sampled keeps
        self._listeners: list = []
        self._pending: "collections.OrderedDict[str, _TraceBuf]" = (
            collections.OrderedDict()
        )
        self._retained: "collections.OrderedDict[str, list[Span]]" = (
            collections.OrderedDict()
        )
        r = registry if registry is not None else Registry()
        self.registry = r
        self._c_spans = r.counter("ccfd_trace_spans_total",
                                  "spans recorded by component")
        self._c_kept = r.counter("ccfd_traces_kept_total",
                                 "tail-sampled traces kept, by reason")
        self._c_dropped = r.counter("ccfd_traces_dropped_total",
                                    "tail-sampled traces dropped")
        self._g_retained = r.gauge("ccfd_traces_retained",
                                   "traces currently held for /traces")
        self._g_pending = r.gauge("ccfd_traces_pending",
                                  "traces awaiting a sampling decision")
        self._c_listener_err = r.counter(
            "ccfd_trace_listener_errors_total",
            "span-listener callbacks that raised (the span still lands; "
            "the listener — profiler ingestion, incident taps — missed it)",
        )

    # -- ingestion ---------------------------------------------------------
    def add_listener(self, fn) -> None:
        """Subscribe ``fn(span)`` to every finished span (unsampled). A
        raising listener is the listener's bug, not a span-loss event —
        exceptions are swallowed in :meth:`add`."""
        self._listeners.append(fn)

    def add(self, span: Span) -> None:
        for fn in self._listeners:
            try:
                fn(span)
            except Exception:  # noqa: BLE001 - listener bug must not drop spans
                self._c_listener_err.inc()
        self._c_spans.inc(labels={"component": span.component})
        with self._lock:
            retained = self._retained.get(span.trace_id)
            if retained is not None:
                # decision already made for this trace: append, keep a
                # bounded span count so a runaway trace can't grow forever
                if len(retained) < 512:
                    retained.append(span)
                return
            buf = self._pending.get(span.trace_id)
            if buf is None:
                buf = self._pending[span.trace_id] = _TraceBuf()
            if len(buf.spans) < 512:
                buf.spans.append(span)
            buf.last = time.monotonic()
            if buf.reason is None:
                buf.reason = self._forced_reason(span)
            self._g_pending.set(len(self._pending))
            if len(self._pending) > self.max_pending:
                oldest, oldbuf = next(iter(self._pending.items()))
                del self._pending[oldest]
                self._decide_locked(oldest, oldbuf)

    def _forced_reason(self, span: Span) -> str | None:
        if span.status != "ok":
            return "error"
        if span.duration_s >= self.slow_s:
            return "slow"
        for flag in FLAG_ATTRS:
            if span.attrs.get(flag):
                return flag
        return None

    def _hash_keep(self, trace_id: str) -> bool:
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        return (zlib.crc32(trace_id.encode()) & 0xFFFFFFFF) < (
            self.sample * 4294967296.0
        )

    def _decide_locked(self, trace_id: str, buf: _TraceBuf) -> None:
        reason = buf.reason or ("sampled" if self._hash_keep(trace_id)
                                else None)
        if reason is None:
            self._c_dropped.inc()
            return
        self._c_kept.inc(labels={"reason": reason})
        self._retained[trace_id] = buf.spans
        while len(self._retained) > self.max_retained:
            self._retained.popitem(last=False)
        self._g_retained.set(len(self._retained))

    def flush(self, older_than_s: float | None = None) -> None:
        """Finalize pending traces idle longer than ``older_than_s``
        (default: the decision window; pass 0.0 to decide everything now)."""
        window = (self.decision_window_s if older_than_s is None
                  else float(older_than_s))
        now = time.monotonic()
        with self._lock:
            due = [tid for tid, buf in self._pending.items()
                   if now - buf.last >= window]
            for tid in due:
                self._decide_locked(tid, self._pending.pop(tid))
            self._g_pending.set(len(self._pending))

    # -- read side (the exporter's /traces endpoints; tools) ---------------
    def trace(self, trace_id: str) -> list[dict[str, Any]] | None:
        self.flush()
        with self._lock:
            spans = self._retained.get(trace_id)
            if spans is None:
                buf = self._pending.get(trace_id)
                spans = buf.spans if buf is not None else None
            if spans is None:
                return None
            return sorted((s.to_dict() for s in spans),
                          key=lambda d: d["start"])

    def traces(self) -> list[dict[str, Any]]:
        """Retained-trace summaries, newest first."""
        self.flush()
        with self._lock:
            items = list(self._retained.items())
        out = []
        for tid, spans in reversed(items):
            starts = [s.start for s in spans]
            ends = [s.start + s.duration_s for s in spans]
            roots = [s for s in spans if s.parent_id is None]
            out.append({
                "trace_id": tid,
                "spans": len(spans),
                "root": roots[0].name if roots else spans[0].name,
                "components": sorted({s.component for s in spans}),
                "start": min(starts),
                "duration_s": max(ends) - min(starts),
                "errored": any(s.status != "ok" for s in spans),
            })
        return out


class Tracer:
    """Per-component span factory.

    ``registry`` must be the component's SCRAPED registry (the operator
    wires it; span latency lands on the same scrape surface as the
    component's own series — the fix for the old global tracer whose
    private registry the exporter never served). ``sink`` is the shared
    :class:`SpanSink`; a tracer without one still times spans into the
    histogram and the debug ring, it just feeds no retained traces.
    """

    def __init__(self, registry: Registry | None = None,
                 component: str = "ccfd", sink: SpanSink | None = None,
                 ring_size: int = 1024):
        self.registry = registry or Registry()
        self.component = component
        self.sink = sink
        self._hist = self.registry.histogram(
            "trace_span_seconds", "span durations by name"
        )
        self._ring: collections.deque = collections.deque(maxlen=ring_size)
        self._lock = threading.Lock()

    # -- explicit begin/finish (thread-hopping pipelines) ------------------
    def start(self, name: str, parent: SpanContext | None = None,
              attrs: dict | None = None) -> Span:
        """Begin a span WITHOUT activating it on this thread — for
        pipelined code whose span outlives the current stack frame (the
        router's in-flight batch). Pair with :meth:`finish`."""
        if parent is None:
            parent = current_context()
        trace_id = parent.trace_id if parent is not None else new_trace_id()
        parent_id = parent.span_id if parent is not None else None
        return Span(trace_id, new_span_id(), parent_id, name,
                    self.component, time.time(), attrs)

    def finish(self, span: Span, status: str | None = None) -> None:
        span.duration_s = max(0.0, time.perf_counter() - span._t0)
        if status is not None:
            span.status = status
        self._hist.observe(span.duration_s, labels={"span": span.name},
                           exemplar={"trace_id": span.trace_id})
        with self._lock:
            self._ring.append((span.start, span.name, span.duration_s))
        if self.sink is not None:
            self.sink.add(span)

    # -- the common path ---------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, parent: SpanContext | None = None,
             attrs: dict | None = None) -> Iterator[Span]:
        sp = self.start(name, parent=parent, attrs=attrs)
        token = _current.set(sp.context)
        try:
            yield sp
        except BaseException:
            sp.status = "error"
            raise
        finally:
            _current.reset(token)
            self.finish(sp)

    @contextlib.contextmanager
    def activate(self, ctx: SpanContext | None) -> Iterator[None]:
        """Make ``ctx`` the current context on this thread without opening
        a span (consumers resuming a bus-carried context around work whose
        spans are created piecemeal)."""
        token = _current.set(ctx)
        try:
            yield
        finally:
            _current.reset(token)

    def recent(self, n: int = 50) -> list[tuple[float, str, float]]:
        with self._lock:
            return list(self._ring)[-n:]

    @contextlib.contextmanager
    def profile(self, logdir: str) -> Iterator[None]:
        """Device-level XLA trace (TensorBoard format) around a block."""
        import jax

        with jax.profiler.trace(logdir):
            yield


_GLOBAL = Tracer()


@contextlib.contextmanager
def trace_span(name: str) -> Iterator[None]:
    """Module-level convenience span on the default (ad-hoc, UNSCRAPED)
    tracer — debug use only; wired components get a registry-injected
    tracer from the operator."""
    with _GLOBAL.span(name):
        yield
