"""Device & transfer telemetry: the measured side of the H2D/HBM story.

Every latency layer the SLO plane decomposes (observability/slo.py) is
measured EXCEPT the host↔device one: PR 9's BudgetLedger ships an explicit
``h2d`` placeholder ("not separately measurable until telemetry exists"),
and ROADMAP item 1's pinned-host staging work has no number to beat. This
module is that telemetry — backend-agnostic, so CPU CI runs exercise the
identical plumbing the TPU run reports from:

- **Per-device memory gauges** — ``ccfd_device_memory_bytes{device,kind}``
  from each device's allocator stats (``bytes_in_use`` /
  ``peak_bytes_in_use`` / ``bytes_limit`` where the backend reports them)
  plus a ``live_buffer_bytes`` kind computed from ``jax.live_arrays()``
  on every backend — the HBM-density denominator ROADMAP item 4 needs.
- **Measured H2D transfer accounting** — the Scorer's staging path
  (``serving/scorer.py _put_batch`` / the fused wire) times each
  host→device put and feeds :meth:`record_h2d`:
  ``ccfd_h2d_bytes_total`` + the ``ccfd_h2d_seconds`` histogram + a
  :class:`~ccfd_tpu.observability.profile.LatencyDigest` the BudgetLedger
  reads live — the ``h2d`` budget layer stops being a reservation the
  moment a telemetry-armed scorer serves traffic.
- **Executable inventory** — registered sources (the row Scorer's bucket
  ladder, the SeqScorer's (L, B) grid with per-executable dispatch
  counts) rendered into one document, next to the per-stage compile
  attribution the profiler's ``backend_compile`` hook collects
  (:func:`~ccfd_tpu.observability.profile.compile_stage`).

One instance per platform (operator ``device:`` block, ``CCFD_DEVICE=0``
kill switch). ``set_default``/``get_default`` exist for harnesses (bench)
that build scorers deep inside helpers; the operator always passes the
instance explicitly.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Mapping

from ccfd_tpu.observability.profile import LatencyDigest

# H2D puts are µs..ms scale; the default request-latency ladder starts at
# 5 ms and would fold every transfer into the first bucket
H2D_BUCKETS = (25e-6, 1e-4, 5e-4, 1e-3, 5e-3, 0.025, 0.1, 0.5, 2.5)

_DEFAULT: "DeviceTelemetry | None" = None


def set_default(telemetry: "DeviceTelemetry | None") -> None:
    """Install a process-default telemetry plane (bench harness hook;
    scorers built with ``telemetry=None`` pick it up). Pass None to
    clear."""
    global _DEFAULT
    _DEFAULT = telemetry


def get_default() -> "DeviceTelemetry | None":
    return _DEFAULT


class DeviceTelemetry:
    """Collects device memory, H2D transfer and executable-inventory
    evidence; see the module docstring. Thread-safe; a scorer staging a
    batch pays two ``perf_counter`` reads plus one counter increment."""

    def __init__(self, registry=None, sample_every: int = 8):
        self.registry = registry
        self._mu = threading.Lock()
        self._h2d_digest = LatencyDigest()
        self._h2d_bytes = 0
        # Transfer-time sampling: device_put is ASYNC on accelerator
        # backends (it returns after enqueueing, before bytes move), so a
        # truthful transfer time requires blocking on the put. Blocking
        # every put would cost the host its H2D/compute pipelining, so
        # only every Nth put per call site is synced+timed; the rest stay
        # async and count bytes only. 1 = time every put (tests, CPU
        # harnesses).
        self.sample_every = max(1, int(sample_every))
        self._put_seq = 0
        self._put_failures = 0
        self._sources: dict[str, Callable[[], Any]] = {}
        self._g_mem = self._c_bytes = self._h_seconds = None
        self._c_put_fail = None
        if registry is not None:
            self._g_mem = registry.gauge(
                "ccfd_device_memory_bytes",
                "per-device memory by kind: allocator bytes_in_use/"
                "peak_bytes_in_use/bytes_limit where the backend reports "
                "them, plus live_buffer_bytes summed from jax.live_arrays "
                "on every backend",
            )
            self._c_bytes = registry.counter(
                "ccfd_h2d_bytes_total",
                "bytes staged host->device on the scorer dispatch path "
                "(measured, not estimated; CPU runs count the same puts)",
            )
            self._h_seconds = registry.histogram(
                "ccfd_h2d_seconds",
                "wall time of one host->device staging put on the scorer "
                "dispatch path",
                buckets=H2D_BUCKETS,
            )
            self._c_put_fail = registry.counter(
                "ccfd_h2d_put_failures_total",
                "host->device staging puts that raised (real transfer "
                "failures and injected put_fail device faults alike) — "
                "one of the DeviceSupervisor's quarantine signals",
            )

    # -- H2D transfer accounting ------------------------------------------
    def record_h2d(self, nbytes: int, seconds: float | None = None) -> None:
        """One staging transfer: ``nbytes`` always counts; ``seconds``
        (when the caller could time the put — the row scorer's explicit
        staging) additionally lands in the histogram and the ledger's
        digest. Callers that only know bytes (the seq path's implicit
        transfer inside the jitted call) pass None."""
        with self._mu:
            self._h2d_bytes += int(nbytes)
            if seconds is not None:
                self._h2d_digest.add(float(seconds))
        if self._c_bytes is not None:
            self._c_bytes.inc(int(nbytes))
            if seconds is not None:
                self._h_seconds.observe(float(seconds))

    def h2d_bytes(self) -> int:
        with self._mu:
            return self._h2d_bytes

    def h2d_count(self) -> int:
        with self._mu:
            return self._h2d_digest.count

    def h2d_digest(self) -> LatencyDigest:
        """A consistent copy of the per-transfer digest — what the
        BudgetLedger's ``h2d`` layer reads when this plane is armed."""
        with self._mu:
            return self._h2d_digest.copy()

    def record_h2d_failure(self) -> None:
        """One failed staging put (the put raised before bytes landed)."""
        with self._mu:
            self._put_failures += 1
        if self._c_put_fail is not None:
            self._c_put_fail.inc()

    def h2d_failures(self) -> int:
        with self._mu:
            return self._put_failures

    # -- device memory ------------------------------------------------------
    @staticmethod
    def device_memory() -> dict[str, dict[str, int]]:
        """Per-device memory stats. Allocator stats where the backend
        reports them (TPU/GPU); ``live_buffer_bytes`` from the live-array
        walk everywhere (CPU included), so the gauge family always has
        series and the CPU CI run exercises the full path."""
        import jax

        out: dict[str, dict[str, int]] = {}
        try:
            devices = jax.local_devices()
        # ccfd-lint: disable=counted-drops -- nothing to drop: no jax backend means no devices to report; the empty dict IS the report
        except Exception:  # noqa: BLE001 - no backend at all
            return out
        for d in devices:
            entry: dict[str, int] = {}
            try:
                stats = d.memory_stats()
            # ccfd-lint: disable=counted-drops -- CPU backends have no allocator stats by design; absent keys read as absent on the board
            except Exception:  # noqa: BLE001 - cpu raises/returns None
                stats = None
            for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
                if stats and k in stats:
                    entry[k] = int(stats[k])
            out[f"{d.platform}:{d.id}"] = entry
        try:
            for arr in jax.live_arrays():
                devs = list(arr.devices())
                share = int(arr.nbytes) // max(1, len(devs))
                for d in devs:
                    label = f"{d.platform}:{d.id}"
                    entry = out.setdefault(label, {})
                    entry["live_buffer_bytes"] = (
                        entry.get("live_buffer_bytes", 0) + share)
        # ccfd-lint: disable=counted-drops -- best-effort live-buffer attribution; the allocator gauges above still carry the load-bearing series
        except Exception:  # noqa: BLE001 - telemetry must never raise
            pass
        for entry in out.values():
            entry.setdefault("live_buffer_bytes", 0)
        # injected allocator pressure (runtime/faults.py device_oom): CPU
        # backends report no allocator stats, so the OOM-pressure signal
        # the heal supervisor watches would be undrillable in CI without
        # this overlay — the synthetic bytes ride the same keys the TPU
        # allocator reports, so the watcher's math is identical
        from ccfd_tpu.runtime.faults import device_oom_overlay

        ratio = device_oom_overlay()
        if ratio is not None:
            limit = 16 * 1024**3  # a plausible HBM size; only the RATIO
            for entry in out.values():  # matters to the pressure signal
                entry.setdefault("bytes_limit", limit)
                entry["bytes_in_use"] = int(
                    ratio * entry.get("bytes_limit", limit))
        return out

    def peak_memory_bytes(self) -> int | None:
        """Max peak_bytes_in_use across devices; None when no backend
        reports allocator stats (CPU) — bench rows record null then."""
        peaks = [e["peak_bytes_in_use"]
                 for e in self.device_memory().values()
                 if "peak_bytes_in_use" in e]
        return max(peaks) if peaks else None

    def refresh(self, mem: Mapping[str, Mapping[str, int]] | None = None,
                ) -> None:
        """Refresh the memory gauges (the exporter scrape is the sampling
        clock, same contract as the RSS gauge). ``mem`` lets a caller that
        already paid the live-array walk (``snapshot``) reuse it."""
        if self._g_mem is None:
            return
        if mem is None:
            mem = self.device_memory()
        for device, kinds in mem.items():
            for kind, val in kinds.items():
                self._g_mem.set(float(val),
                                labels={"device": device, "kind": kind})

    # -- executable inventory -----------------------------------------------
    def register_executable_source(self, name: str,
                                   fn: Callable[[], Any]) -> None:
        """``fn()`` -> a JSON-safe description of a component's compiled
        executable set (the row scorer's bucket list, the seq (L, B)
        grid with dispatch counts)."""
        with self._mu:
            self._sources[name] = fn

    def executable_inventory(self) -> dict[str, Any]:
        with self._mu:
            sources = dict(self._sources)
        out: dict[str, Any] = {}
        for name, fn in sources.items():
            try:
                out[name] = fn()
            # ccfd-lint: disable=counted-drops -- the error string lands IN the snapshot: recorded evidence, not a swallow
            except Exception as e:  # noqa: BLE001 - a dead source is evidence
                out[name] = {"error": repr(e)[:120]}
        return out

    # -- export -------------------------------------------------------------
    def snapshot(self) -> dict[str, Any]:
        """The device section of /debug, incident bundles and flight-
        recorder snapshots. Pays the live-array walk once (gauges refresh
        from the same read)."""
        mem = self.device_memory()
        self.refresh(mem)
        with self._mu:
            h2d = {
                "bytes_total": self._h2d_bytes,
                "transfer": self._h2d_digest.to_dict(),
            }
        return {
            "memory": mem,
            "h2d": h2d,
            "executables": self.executable_inventory(),
        }


def timed_put(telemetry: "DeviceTelemetry | None", nbytes: int, put_fn):
    """Run one staging put, feeding its bytes (always) and wall time
    (every ``telemetry.sample_every``-th put) to ``telemetry`` — the
    single helper every staging call site shares, so the disabled path
    costs one ``is None`` check.

    Timed samples BLOCK until the array is committed on device:
    device_put is asynchronous on accelerator backends, and timing the
    enqueue alone would report microseconds for a millisecond transfer.
    Unsampled puts stay fully async, so the host keeps its H2D/compute
    pipelining on the other N-1 of every N puts."""
    if telemetry is None:
        return put_fn()
    with telemetry._mu:
        telemetry._put_seq += 1
        timed = telemetry._put_seq % telemetry.sample_every == 0
    if not timed:
        try:
            out = put_fn()
        except Exception:
            telemetry.record_h2d_failure()
            raise
        # bytes count only after the put lands (matching the timed
        # branch): a failed put must not inflate ccfd_h2d_bytes_total
        telemetry.record_h2d(nbytes)
        return out
    import time

    import jax

    t0 = time.perf_counter()
    try:
        out = put_fn()
        jax.block_until_ready(out)
    except Exception:
        telemetry.record_h2d_failure()
        raise
    telemetry.record_h2d(nbytes, time.perf_counter() - t0)
    return out
