"""Capacity observatory: the queueing model fitted over the stage profile.

PR 9's StageProfile says what each stage's latency decomposition WAS;
ROADMAP item 3's InferLine-style planner (arXiv:1812.01776) needs what it
WILL BE: predicted latency at the current admitted rate, which stage
saturates first, and how a knob move shifts the prediction — PRETZEL's
white-box premise applied to provisioning. This module closes that gap:

- :class:`CapacityModel` — continuously fitted from the live
  :class:`~ccfd_tpu.observability.profile.StageProfiler`. Each
  :meth:`refresh` diffs the profiler's CUMULATIVE digests against the
  previous tick, giving a *windowed* per-stage arrival rate (batches/s and
  rows/s) and mean service time, EWMA-smoothed; the batch-conditioned
  service curve is fitted the same way (per-bucket deltas), so a latency
  step moves the fitted curve within one window even though the profile
  digests are cumulative.
- **Queueing approximation** — per stage, utilization rho = lambda *
  s_bar / servers (M/M/c collapsed to the M/M/1 form the planner needs);
  queue-only stages (``bus``, ``rest.batcher``) invert the M/M/1 wait
  equation from the measured wait instead. Predicted p50/p99 come from
  the fitted means with exponential-tail multipliers (ln 2 / ln 100) and
  sum to an end-to-end prediction. ``ccfd_capacity_model_error_ratio``
  (|predicted - observed| / observed on e2e p99) is the model's OWN
  trustworthiness SLI: the planner may only be trusted while it is small.
- **Bottleneck attribution** — the knee of each fitted service curve
  bounds the stage's max sustainable row rate; headroom = max rate /
  admitted rate (1/rho where no curve exists). The minimum-headroom stage
  is the bottleneck (``ccfd_capacity_bottleneck{stage}``, headroom in
  ``ccfd_capacity_headroom_ratio{stage}``): "which stage saturates first,
  and at what admitted rate".
- **What-if evaluator** — :meth:`whatif` re-evaluates the fitted model
  under the PR 6 actuator vocabulary (router/batcher ``workers``, batcher
  ``batch`` size and ``deadline_ms``, admission ``max_inflight``) without
  touching the live system; served at ``/capacity/whatif?workers=&batch=&
  deadline_ms=&max_inflight=`` next to the ``/capacity`` document (schema
  :data:`CAPACITY_SCHEMA`, validated by :func:`validate_capacity`).
- **Service-curve regression sentinel** — the first fit past
  ``min_samples`` is persisted as the per-stage baseline through the
  PR 13 durability seam (tmp+rename+sidecar); a later fit departing from
  baseline by more than ``regression_tolerance`` for
  ``regression_persistence`` consecutive windows fires
  ``ccfd_capacity_regression_total{stage}`` ONCE per excursion
  (edge-triggered with hysteresis, like the SLO breach counter) — the
  signal that a lifecycle promotion or heal re-promotion changed the
  serving cost. Curve-bearing stages are judged per batch bucket (a load
  swing changes the bucket MIX, not the per-bucket cost) on the raw
  window fit, with under-sampled buckets abstaining.

The model runs as a supervised operator service (``capacity`` component,
``CCFD_CAPACITY_*`` knobs); readers (exporter endpoints, incident
bundles) see only fitted state under the model lock — no profiler locks
are ever held together with it.
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Any, Callable, Mapping

from ccfd_tpu.observability.profile import write_json_crash_safe

CAPACITY_SCHEMA = "ccfd.capacity.v1"
BASELINE_SCHEMA = "ccfd.capacity_baseline.v1"

# exponential-tail quantile multipliers: for a mean-w exponential sojourn,
# p50 = w ln 2 and p99 = w ln 100 (the M/M/1 waiting-time tail)
_LN2 = math.log(2.0)
_LN100 = math.log(100.0)

# stages whose own digest records QUEUEING (wait) rather than work; every
# other stage's work component is service or dispatch per STAGE_LAYERS
STAGE_LAYERS: Mapping[str, str] = {
    "bus": "queue",
    "rest.batcher": "queue",
    "router.score": "dispatch",
    "rest.dispatch": "dispatch",
}

# queue stage -> the work stage that drains it: the queue's predicted wait
# scales with the DRAIN stage's utilization under what-if moves, and the
# drain stage's own prediction must NOT add a second wait term (the queue
# stage already carries it — no double counting in the e2e sum)
QUEUE_DRAINS: Mapping[str, str] = {
    "bus": "router.score",
    "rest.batcher": "rest.dispatch",
}

# queue stage -> every work stage in the consumer lane it feeds (the white
# -box topology the reference pipeline actually has). Used by bottleneck
# attribution: a fed work stage runs flat out — rho -> 1 — exactly when
# the queue ahead of it overflows, while the queue's wait-inverted rho
# asymptotes to 1 from BELOW, so raw min-headroom would always name the
# drain lane; the caller-visible backlog lives in the queue.
QUEUE_FEEDS: Mapping[str, tuple[str, ...]] = {
    "bus": ("router.decode", "router.score", "router.route"),
    "rest.batcher": ("rest.dispatch",),
}

_HEADROOM_CAP = 1000.0
_RHO_CAP = 0.98  # keep the W_q = s*rho/(1-rho) form finite past saturation


def stage_layer(stage: str) -> str:
    """Queueing layer a stage bills to: ``queue`` / ``dispatch`` /
    ``service`` (the same static map the budget ledger's shape implies)."""
    return STAGE_LAYERS.get(stage, "service")


def _rho_from_wait(lam: float, wait_s: float) -> float:
    """Invert the M/M/1 mean-wait equation for utilization: with
    W_q = rho^2 / (lambda (1 - rho)), rho solves
    rho^2 + lam*W*rho - lam*W = 0 -> the positive root below 1."""
    lw = max(0.0, lam * wait_s)
    if lw <= 0.0:
        return 0.0
    return min(1.0, (-lw + math.sqrt(lw * lw + 4.0 * lw)) / 2.0)


class _StageFit:
    """Fitted per-stage state (plain attrs; all rates in /s, times in s)."""

    __slots__ = (
        "layer", "lam_batches", "lam_rows", "mean_service_s", "mean_raw_s",
        "utilization", "servers", "curve", "curve_raw", "curve_n",
        "knee_batch", "max_rows_per_s", "headroom", "observed_p50_ms",
        "observed_p99_ms", "work_count", "active",
    )

    def __init__(self, layer: str) -> None:
        self.layer = layer
        self.lam_batches = 0.0
        self.lam_rows = 0.0
        self.mean_service_s = 0.0
        self.mean_raw_s = 0.0  # un-smoothed mean of the last window alone
        self.utilization = 0.0
        self.servers = 1
        self.curve: dict[int, float] = {}  # batch bucket -> fitted mean s
        self.curve_raw: dict[int, float] = {}  # bucket -> last-window mean s
        self.curve_n: dict[int, int] = {}  # bucket -> samples this window
        self.knee_batch: int | None = None
        self.max_rows_per_s: float | None = None
        self.headroom = _HEADROOM_CAP
        self.observed_p50_ms = 0.0
        self.observed_p99_ms = 0.0
        self.work_count = 0  # cumulative samples on the work component
        self.active = False  # saw traffic in the last fitted window


class CapacityModel:
    """Continuously fitted queueing model over a StageProfiler; see the
    module docstring. Thread-safe: the supervised refresh tick and the
    exporter's ``/capacity`` + ``/capacity/whatif`` reads interleave."""

    def __init__(self, profiler, registry=None, *,
                 baseline_path: str | None = None,
                 regression_tolerance: float = 1.0,
                 regression_persistence: int = 2,
                 min_samples: int = 50,
                 ewma_alpha: float = 0.5,
                 clock: Callable[[], float] = time.monotonic):
        self.profiler = profiler
        self.baseline_path = baseline_path or None
        self.regression_tolerance = max(0.01, float(regression_tolerance))
        self.regression_persistence = max(1, int(regression_persistence))
        self.min_samples = max(1, int(min_samples))
        self.ewma_alpha = min(1.0, max(0.01, float(ewma_alpha)))
        self._clock = clock
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._prev: dict[str, dict[str, Any]] | None = None
        self._prev_ts: float = 0.0
        self._fits: dict[str, _StageFit] = {}
        self._window_s = 0.0
        self._refreshes = 0
        self._fitted_unix: float | None = None
        self._e2e: dict[str, float] = {}
        self._bottleneck: dict[str, Any] | None = None
        # actuator base values (operator wires them; what-if deltas are
        # evaluated against these)
        self._actuators: dict[str, Any] = {
            "workers": 1, "batch": None, "deadline_ms": None,
            "max_inflight": None,
        }
        # regression sentinel state: per-stage baseline mean (ms), the
        # per-bucket baseline curve for curve-bearing stages, the
        # in-excursion flag (edge trigger), worst deviation ratio for the
        # doc, and fire counts
        self._baseline: dict[str, float] = {}
        self._baseline_curve: dict[str, dict[int, float]] = {}
        self._baseline_source: str | None = None
        self._in_regression: dict[str, bool] = {}
        self._breach_streak: dict[str, int] = {}
        self._worst_ratio: dict[str, float] = {}
        self._regressions: dict[str, int] = {}
        self._g_err = self._g_bottleneck = self._g_headroom = None
        self._g_util = self._g_pred = self._c_regress = None
        if registry is not None:
            self._g_err = registry.gauge(
                "ccfd_capacity_model_error_ratio",
                "capacity-model trustworthiness SLI: |predicted - observed|"
                " / observed on end-to-end p99 (planner may be trusted "
                "while this is small)",
            )
            self._g_bottleneck = registry.gauge(
                "ccfd_capacity_bottleneck",
                "1 on the minimum-headroom stage (the stage that saturates "
                "first at the current admitted rate), 0 elsewhere",
            )
            self._g_headroom = registry.gauge(
                "ccfd_capacity_headroom_ratio",
                "per-stage max sustainable row rate (service-curve knee) "
                "over the admitted rate; 1/utilization where no curve "
                "exists — < 1 means the stage is past saturation",
            )
            self._g_util = registry.gauge(
                "ccfd_capacity_utilization",
                "fitted per-stage utilization rho = arrival rate x mean "
                "service time / servers (wait-equation inversion for "
                "queue-only stages)",
            )
            self._g_pred = registry.gauge(
                "ccfd_capacity_predicted_p99_ms",
                "queueing-model predicted p99 per stage (stage label; "
                "stage=\"e2e\" is the end-to-end sum the error-ratio SLI "
                "compares against observation)",
            )
            self._c_regress = registry.counter(
                "ccfd_capacity_regression_total",
                "service-curve regression sentinel fires by stage: fitted "
                "mean service departed from the persisted baseline by more "
                "than the tolerance (one increment per excursion edge)",
            )
        if self.baseline_path:
            self._load_baseline()

    # -- actuator base values ----------------------------------------------
    def set_actuators(self, workers: int | None = None,
                      batch: int | None = None,
                      deadline_ms: float | None = None,
                      max_inflight: int | None = None) -> None:
        """Record the live actuator values what-if deltas are measured
        against (operator wiring; harnesses set them directly)."""
        with self._mu:
            if workers is not None:
                self._actuators["workers"] = max(1, int(workers))
            if batch is not None:
                self._actuators["batch"] = max(1, int(batch))
            if deadline_ms is not None:
                self._actuators["deadline_ms"] = float(deadline_ms)
            if max_inflight is not None:
                self._actuators["max_inflight"] = max(1, int(max_inflight))

    # -- baseline persistence (PR 13 durability seam) ----------------------
    def _load_baseline(self) -> None:
        from ccfd_tpu.runtime.durability import verify_interchange

        path = self.baseline_path
        if verify_interchange(path) is False:
            # torn/corrupt baseline: refit from live traffic rather than
            # alert against bytes the sidecar disowns
            return
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return
        if not isinstance(doc, Mapping) or doc.get("schema") != BASELINE_SCHEMA:
            return
        stages = doc.get("stages")
        if not isinstance(stages, Mapping):
            return
        loaded: dict[str, float] = {}
        curves: dict[str, dict[int, float]] = {}
        for stage, entry in stages.items():
            if not isinstance(entry, Mapping):
                continue
            mean = entry.get("mean_service_ms")
            if isinstance(mean, (int, float)) and math.isfinite(mean) \
                    and mean > 0:
                loaded[str(stage)] = float(mean)
            curve = entry.get("curve_ms")
            if isinstance(curve, Mapping):
                parsed = {
                    int(b): float(ms) for b, ms in curve.items()
                    if isinstance(ms, (int, float)) and math.isfinite(ms)
                    and ms > 0
                }
                if parsed:
                    curves[str(stage)] = parsed
        if loaded:
            self._baseline.update(loaded)
            self._baseline_curve.update(curves)
            self._baseline_source = path

    def _persist_baseline(self) -> None:
        if not self.baseline_path:
            return
        with self._mu:
            doc = {
                "schema": BASELINE_SCHEMA,
                "generated_unix": time.time(),
                "min_samples": self.min_samples,
                "stages": {
                    stage: {
                        "mean_service_ms": round(mean, 4),
                        **({"curve_ms": {
                            str(b): round(ms, 4) for b, ms in
                            sorted(self._baseline_curve[stage].items())
                        }} if self._baseline_curve.get(stage) else {}),
                    }
                    for stage, mean in sorted(self._baseline.items())
                },
            }
        try:
            write_json_crash_safe(self.baseline_path, doc)
        except OSError:
            # the sentinel keeps alerting from the in-memory baseline; a
            # restart refits instead of alerting against nothing
            self._baseline_source = None

    # -- fitting -----------------------------------------------------------
    @staticmethod
    def _cumulative(doc: Mapping[str, Any]) -> dict[str, dict[str, Any]]:
        """Per-stage cumulative (count, sum) for the work component, rows,
        and the by-batch curve — the delta basis for one fit window."""
        out: dict[str, dict[str, Any]] = {}
        for stage, entry in (doc.get("stages") or {}).items():
            # the layer names the component carrying the stage's own time
            work = entry.get(stage_layer(stage)) or {}
            curve = {}
            for b, d in (entry.get("service_by_batch") or {}).items():
                if isinstance(d, Mapping) and d.get("count"):
                    curve[int(b)] = (int(d["count"]), float(d.get("sum_s",
                                                                 0.0)))
            out[stage] = {
                "count": int(work.get("count", 0)),
                "sum_s": float(work.get("sum_s", 0.0)),
                "rows": int(entry.get("rows", 0)),
                "p50_ms": float(work.get("p50_ms", 0.0) or 0.0),
                "p99_ms": float(work.get("p99_ms", 0.0) or 0.0),
                "curve": curve,
            }
        return out

    def _ewma(self, old: float, new: float, first: bool) -> float:
        if first:
            return new
        a = self.ewma_alpha
        return a * new + (1.0 - a) * old

    def refresh(self) -> dict[str, Any] | None:
        """One fit tick: snapshot the profiler, diff against the previous
        tick, update fits/gauges/sentinel. Returns the capacity document
        (None until two ticks have bracketed a window)."""
        doc = self.profiler.snapshot()  # takes stage locks; never under _mu
        now = self._clock()
        cum = self._cumulative(doc)
        baseline_dirty = False
        with self._mu:
            prev, prev_ts = self._prev, self._prev_ts
            self._prev, self._prev_ts = cum, now
            dt = now - prev_ts
            if prev is None or dt <= 0.0:
                return None
            self._window_s = dt
            self._refreshes += 1
            self._fitted_unix = time.time()
            workers = int(self._actuators["workers"])
            for stage, c in cum.items():
                p = prev.get(stage) or {"count": 0, "sum_s": 0.0, "rows": 0,
                                        "curve": {}}
                fit = self._fits.get(stage)
                first = fit is None
                if first:
                    fit = self._fits[stage] = _StageFit(stage_layer(stage))
                fit.work_count = c["count"]
                fit.observed_p50_ms = c["p50_ms"]
                fit.observed_p99_ms = c["p99_ms"]
                dc = c["count"] - p["count"]
                drows = max(0, c["rows"] - p["rows"])
                fit.active = dc > 0
                fit.mean_raw_s = 0.0
                if dc > 0:
                    dsum = max(0.0, c["sum_s"] - p["sum_s"])
                    fit.lam_batches = self._ewma(fit.lam_batches, dc / dt,
                                                 first)
                    fit.lam_rows = self._ewma(fit.lam_rows, drows / dt,
                                              first)
                    fit.mean_raw_s = dsum / dc
                    fit.mean_service_s = self._ewma(fit.mean_service_s,
                                                    dsum / dc, first)
                fit.curve_raw = {}
                fit.curve_n = {}
                for b, (bc, bs) in c["curve"].items():
                    pb = p["curve"].get(b)
                    dbc = bc - (pb[0] if pb else 0)
                    if dbc > 0:
                        dbs = max(0.0, bs - (pb[1] if pb else 0.0))
                        old = fit.curve.get(b)
                        fit.curve[b] = self._ewma(old or 0.0, dbs / dbc,
                                                  old is None)
                        fit.curve_raw[b] = dbs / dbc
                        fit.curve_n[b] = dbc
                fit.servers = workers if fit.layer == "dispatch" else 1
                if fit.layer == "queue":
                    fit.utilization = _rho_from_wait(fit.lam_batches,
                                                     fit.mean_service_s)
                else:
                    fit.utilization = (fit.lam_batches * fit.mean_service_s
                                       / max(1, fit.servers))
                self._fit_knee(fit)
                if fit.layer != "queue":
                    # the sentinel watches fitted SERVICE time; a queue
                    # stage's wait regresses with load, not serving cost
                    baseline_dirty |= self._sentinel(stage, fit)
            self._attribute_bottleneck()
            self._predict_into_gauges()
            out = self._document_locked()
        if baseline_dirty:
            self._persist_baseline()
        return out

    def _fit_knee(self, fit: _StageFit) -> None:
        """Knee of the fitted service curve -> max sustainable row rate ->
        headroom. Queue stages and curve-less stages fall back to 1/rho;
        curve-bearing stages are ALSO clamped by 1/rho — the bucket grid
        labels a batch by its bucket ceiling, so the knee can promise
        throughput the stage only reaches at a larger batch size, while
        at the operating point it saturates at lambda/rho regardless."""
        best_b, best_tp = None, 0.0
        for b, mean_s in fit.curve.items():
            if mean_s <= 0.0:
                continue
            tp = b / mean_s
            if tp > best_tp:
                best_b, best_tp = b, tp
        rho_bound = (min(_HEADROOM_CAP, 1.0 / fit.utilization)
                     if fit.utilization > 0.0 else _HEADROOM_CAP)
        if best_b is not None and fit.layer != "queue":
            fit.knee_batch = best_b
            fit.max_rows_per_s = best_tp * max(1, fit.servers)
            if fit.lam_rows > 0.0:
                fit.headroom = min(_HEADROOM_CAP, rho_bound,
                                   fit.max_rows_per_s / fit.lam_rows)
            else:
                fit.headroom = _HEADROOM_CAP
        else:
            fit.knee_batch = None
            fit.max_rows_per_s = None
            fit.headroom = rho_bound

    def _sentinel(self, stage: str, fit: _StageFit) -> bool:
        """Regression sentinel for one stage; True when the baseline was
        (first-)captured or extended and needs persisting. Edge-triggered:
        one counter increment per excursion, re-armed only after the fit is
        back inside HALF the tolerance band (hysteresis — a mean hovering
        at the edge cannot machine-gun the counter).

        Three guards keep load and noise from masquerading as a cost
        regression:

        - Curve-bearing stages are judged per batch bucket against the
          baselined curve: the overall per-batch mean is confounded with
          the batch MIX (heavier load -> bigger batches -> bigger
          per-batch cost), so a pure load swing would read as one. A
          bucket first populated under today's load is absorbed into the
          baseline at its first fitted value, and a bucket with only a
          handful of window samples gets no verdict at all (one scheduler
          stall on a 2-batch bucket is noise). Curve-less stages fall
          back to the overall mean.
        - The verdict reads the RAW window fit, not the EWMA — the
          EWMA's memory stretches one contaminated window across several
          ticks, which would defeat the persistence guard below.
        - ``regression_persistence`` consecutive breaching windows are
          required before the counter fires (a ``for:`` clause, in
          Prometheus terms): a single contended window on a busy box is
          a transient, not a regression."""
        mean_ms = 1e3 * fit.mean_service_s
        min_n = max(2, self.min_samples // 10)
        base = self._baseline.get(stage)
        if base is None:
            if fit.work_count >= self.min_samples and mean_ms > 0.0:
                self._baseline[stage] = mean_ms
                if fit.curve:
                    self._baseline_curve[stage] = {
                        b: 1e3 * s for b, s in fit.curve.items()
                        if s > 0.0 and fit.curve_n.get(b, 0) >= min_n}
                return True
            return False
        tol = self.regression_tolerance
        dirty = False
        ratios: list[float] = []
        if fit.curve:
            bcurve = self._baseline_curve.setdefault(stage, {})
            for b, s_raw in fit.curve_raw.items():
                if s_raw <= 0.0 or fit.curve_n.get(b, 0) < min_n:
                    continue
                b_ms = bcurve.get(b)
                if b_ms is None:
                    s_fit = fit.curve.get(b)
                    if s_fit and s_fit > 0.0:
                        bcurve[b] = 1e3 * s_fit
                        dirty = True
                elif b_ms > 0:
                    ratios.append(1e3 * s_raw / b_ms)
            if not ratios:
                # nothing judgeable this window (buckets just baselined
                # or under-sampled); verdict on a later tick
                return dirty
        if not ratios:
            raw_ms = 1e3 * fit.mean_raw_s
            if raw_ms <= 0.0:
                return dirty
            ratios = [raw_ms / base if base > 0 else 1.0]
        worst = max(ratios, key=lambda r: abs(math.log(r)) if r > 0 else 0.0)
        self._worst_ratio[stage] = worst
        breach = any(
            r > 1.0 + tol or r < 1.0 / (1.0 + tol) for r in ratios)
        inside = all(
            (1.0 / (1.0 + 0.5 * tol)) <= r <= (1.0 + 0.5 * tol)
            for r in ratios)
        if breach:
            streak = self._breach_streak.get(stage, 0) + 1
            self._breach_streak[stage] = streak
            if streak >= self.regression_persistence \
                    and not self._in_regression.get(stage):
                self._in_regression[stage] = True
                self._regressions[stage] = self._regressions.get(stage, 0) + 1
                if self._c_regress is not None:
                    self._c_regress.inc(labels={"stage": stage})
        else:
            self._breach_streak[stage] = 0
            if inside and self._in_regression.get(stage):
                self._in_regression[stage] = False
        return dirty

    # -- prediction --------------------------------------------------------
    def _predict_stage(self, stage: str, fit: _StageFit,
                       fits: Mapping[str, _StageFit],
                       overrides: Mapping[str, Any] | None = None,
                       ) -> tuple[float, float]:
        """Predicted (p50_ms, p99_ms) for one stage under optional what-if
        overrides. Queue stages: the fitted mean wait, scaled by how the
        drain stage's W_q moves under the overrides, with exponential-tail
        quantiles. Work stages: the observed service quantiles (scaled
        along the service curve for a batch move), plus an own W_q term
        only when no fitted queue stage already carries the wait."""
        ov = overrides or {}
        lam_scale = self._lam_scale(ov)
        if fit.layer == "queue":
            wait_s = fit.mean_service_s
            drain = fits.get(QUEUE_DRAINS.get(stage, ""))
            if ov and drain is not None:
                wait_s *= self._wq_shift(drain, ov, lam_scale)
            if stage == "rest.batcher":
                wait_s = self._deadline_shift(wait_s, ov)
            return 1e3 * wait_s * _LN2, 1e3 * wait_s * _LN100
        p50, p99 = fit.observed_p50_ms, fit.observed_p99_ms
        scale = self._batch_scale(fit, ov)
        p50, p99 = p50 * scale, p99 * scale
        queued_elsewhere = any(
            QUEUE_DRAINS.get(q) == stage and q in fits for q in QUEUE_DRAINS)
        if not queued_elsewhere:
            rho = min(_RHO_CAP, fit.utilization * lam_scale
                      * self._server_shift(fit, ov) * scale)
            wq = fit.mean_service_s * scale * rho / (1.0 - rho)
            p50 += 1e3 * wq * _LN2
            p99 += 1e3 * wq * _LN100
        return p50, p99

    def _lam_scale(self, ov: Mapping[str, Any]) -> float:
        new = ov.get("max_inflight")
        base = self._actuators.get("max_inflight")
        if new and base:
            return min(1.0, float(new) / float(base))
        return 1.0

    def _server_shift(self, fit: _StageFit, ov: Mapping[str, Any]) -> float:
        """rho multiplier for a worker-count move on a dispatch stage."""
        new = ov.get("workers")
        if not new or fit.layer != "dispatch":
            return 1.0
        return max(1, fit.servers) / max(1, int(new))

    def _batch_scale(self, fit: _StageFit, ov: Mapping[str, Any]) -> float:
        """Service-time multiplier for a batch-size move, read off the
        FITTED service curve (bucket means): s(new bucket) / s(base)."""
        new = ov.get("batch")
        if not new or fit.layer != "dispatch" or not fit.curve:
            return 1.0
        base_b = self._actuators.get("batch")
        if base_b is None and fit.lam_batches > 0.0:
            base_b = fit.lam_rows / fit.lam_batches  # fitted mean batch
        base_s = self._curve_at(fit, base_b) if base_b else None
        new_s = self._curve_at(fit, float(new))
        if not base_s or not new_s:
            return 1.0
        return new_s / base_s

    @staticmethod
    def _curve_at(fit: _StageFit, batch: float) -> float | None:
        if not fit.curve:
            return None
        b = min(fit.curve, key=lambda k: abs(k - batch))
        return fit.curve.get(b) or None

    def _wq_shift(self, drain: _StageFit, ov: Mapping[str, Any],
                  lam_scale: float) -> float:
        """How the drain stage's W_q moves under overrides — the factor a
        queue stage's fitted wait is scaled by. Anchored to observation:
        with no overrides the factor is 1, so steady-state prediction
        stays what was measured."""
        scale = self._batch_scale(drain, ov)
        rho0 = min(_RHO_CAP, max(1e-6, drain.utilization))
        rho1 = min(_RHO_CAP, rho0 * lam_scale * self._server_shift(drain, ov)
                   * scale)
        wq0 = rho0 / (1.0 - rho0)
        wq1 = scale * rho1 / (1.0 - rho1)
        return wq1 / wq0 if wq0 > 0 else 1.0

    def _deadline_shift(self, wait_s: float, ov: Mapping[str, Any]) -> float:
        """Batcher-deadline move: the coalescing wait scales with the
        deadline and is capped by it (monotonic in the new deadline)."""
        new = ov.get("deadline_ms")
        base = self._actuators.get("deadline_ms")
        if not new or not base or base <= 0:
            return wait_s
        return min(float(new) / 1e3, wait_s * float(new) / float(base))

    def _e2e_predict(self, fits: Mapping[str, _StageFit],
                     overrides: Mapping[str, Any] | None = None,
                     ) -> tuple[dict[str, dict[str, float]],
                                dict[str, float]]:
        """Per-stage + summed predictions; the observed side sums the SAME
        stage set's digest quantiles so both sides of the error ratio are
        defined identically."""
        stages: dict[str, dict[str, float]] = {}
        pred50 = pred99 = obs50 = obs99 = 0.0
        for stage, fit in fits.items():
            if fit.work_count <= 0:
                continue
            p50, p99 = self._predict_stage(stage, fit, fits, overrides)
            stages[stage] = {
                "predicted_p50_ms": round(p50, 4),
                "predicted_p99_ms": round(p99, 4),
                "observed_p50_ms": round(fit.observed_p50_ms, 4),
                "observed_p99_ms": round(fit.observed_p99_ms, 4),
            }
            pred50 += p50
            pred99 += p99
            obs50 += fit.observed_p50_ms
            obs99 += fit.observed_p99_ms
        e2e = {
            "predicted_p50_ms": round(pred50, 4),
            "predicted_p99_ms": round(pred99, 4),
            "observed_p50_ms": round(obs50, 4),
            "observed_p99_ms": round(obs99, 4),
        }
        if obs99 > 0.0:
            e2e["error_ratio"] = round(abs(pred99 - obs99) / obs99, 6)
        return stages, e2e

    def _attribute_bottleneck(self) -> None:
        """Min-headroom stage among those carrying traffic (call under
        _mu). A fully idle window keeps the previous attribution.

        Two refinements keep the attribution caller-honest:

        - Near-saturation ties break on predicted wait contribution:
          when several stages sit inside a 1.2x band of the minimum
          headroom, the one whose predicted p99 dominates e2e latency is
          the bottleneck the caller feels.
        - A work stage fed by a BACKING-UP queue (:data:`QUEUE_FEEDS`,
          queue utilization >= 0.5) yields attribution to that queue:
          the fed lane runs flat out — rho -> 1 — exactly because the
          queue ahead of it is overflowing, and the queue's own
          wait-inverted rho asymptotes to 1 from below, so it could
          never numerically undercut its drain lane; the backlog the
          caller waits in is the queue's. A drain that saturates on its
          own (a cost step at low queue pressure) keeps the
          attribution — the sentinel names the cost change."""
        candidates = [(stage, fit) for stage, fit in self._fits.items()
                      if fit.active and fit.lam_batches > 0.0]
        if not candidates:
            return
        floor = min(fit.headroom for _, fit in candidates)
        near = [(stage, fit) for stage, fit in candidates
                if fit.headroom <= max(floor * 1.2, floor + 1e-9)]
        if len(near) > 1:
            stage, fit = max(
                near, key=lambda kv: self._predict_stage(
                    kv[0], kv[1], self._fits)[1])
        else:
            stage, fit = near[0]
        for q, fed in QUEUE_FEEDS.items():
            if stage in fed:
                qfit = self._fits.get(q)
                if qfit is not None and qfit.active \
                        and qfit.utilization >= 0.5:
                    stage, fit = q, qfit
                break
        self._bottleneck = {
            "stage": stage,
            "layer": fit.layer,
            "headroom_ratio": round(fit.headroom, 4),
            "utilization": round(fit.utilization, 4),
            "admitted_rows_per_s": round(fit.lam_rows, 3),
            "max_rows_per_s": (round(fit.max_rows_per_s, 3)
                               if fit.max_rows_per_s else None),
        }

    def _predict_into_gauges(self) -> None:
        """Refresh exported gauges from the fitted state (under _mu)."""
        stages, e2e = self._e2e_predict(self._fits)
        self._e2e = e2e
        bn = (self._bottleneck or {}).get("stage")
        for stage, fit in self._fits.items():
            labels = {"stage": stage}
            if self._g_headroom is not None:
                self._g_headroom.set(fit.headroom, labels=labels)
                self._g_util.set(fit.utilization, labels=labels)
                self._g_bottleneck.set(1.0 if stage == bn else 0.0,
                                       labels=labels)
            if self._g_pred is not None and stage in stages:
                self._g_pred.set(stages[stage]["predicted_p99_ms"],
                                 labels=labels)
        if self._g_pred is not None:
            self._g_pred.set(e2e["predicted_p99_ms"],
                             labels={"stage": "e2e"})
        if self._g_err is not None and "error_ratio" in e2e:
            self._g_err.set(e2e["error_ratio"])

    # -- documents ---------------------------------------------------------
    def _document_locked(self, overrides: Mapping[str, Any] | None = None,
                         ) -> dict[str, Any]:
        stages_pred, e2e = self._e2e_predict(self._fits, overrides)
        doc_stages: dict[str, Any] = {}
        for stage, fit in self._fits.items():
            entry: dict[str, Any] = {
                "layer": fit.layer,
                "arrival_batches_per_s": round(fit.lam_batches, 4),
                "arrival_rows_per_s": round(fit.lam_rows, 3),
                "mean_service_ms": round(1e3 * fit.mean_service_s, 4),
                "utilization": round(fit.utilization, 4),
                "servers": fit.servers,
                "headroom_ratio": round(fit.headroom, 4),
                "samples": fit.work_count,
            }
            if fit.curve:
                entry["fitted_curve_ms"] = {
                    str(b): round(1e3 * s, 4)
                    for b, s in sorted(fit.curve.items())
                }
            if fit.knee_batch is not None:
                entry["knee"] = {
                    "batch": fit.knee_batch,
                    "mean_ms": round(
                        1e3 * (fit.curve.get(fit.knee_batch) or 0.0), 4),
                    "max_rows_per_s": round(fit.max_rows_per_s or 0.0, 3),
                }
            base = self._baseline.get(stage)
            if base is not None:
                mean_ms = 1e3 * fit.mean_service_s
                # worst per-bucket deviation for curve-bearing stages
                # (what the sentinel actually judges); mean-based otherwise
                ratio = self._worst_ratio.get(
                    stage, mean_ms / base if base > 0 else 1.0)
                entry["regression"] = {
                    "baseline_mean_ms": round(base, 4),
                    "ratio": round(ratio, 4),
                    "in_regression": bool(self._in_regression.get(stage)),
                    "fired_total": self._regressions.get(stage, 0),
                }
            if stage in stages_pred:
                entry.update(stages_pred[stage])
            doc_stages[stage] = entry
        doc: dict[str, Any] = {
            "schema": CAPACITY_SCHEMA,
            "generated_unix": time.time(),
            "fitted_unix": self._fitted_unix,
            "window_s": round(self._window_s, 3),
            "refreshes": self._refreshes,
            "model": {
                "kind": "mm1-exponential-tail",
                "ewma_alpha": self.ewma_alpha,
                "min_samples": self.min_samples,
                "regression_tolerance": self.regression_tolerance,
                "baseline_source": self._baseline_source,
            },
            "actuators": dict(self._actuators),
            "stages": doc_stages,
            "e2e": e2e,
            "bottleneck": self._bottleneck,
        }
        if overrides:
            base_e2e = dict(self._e2e)
            doc["whatif"] = {
                "requested": {k: v for k, v in overrides.items()
                              if v is not None},
                "base_predicted_p99_ms": base_e2e.get("predicted_p99_ms"),
                "predicted_p99_ms": e2e["predicted_p99_ms"],
                "delta_p99_ms": round(
                    e2e["predicted_p99_ms"]
                    - (base_e2e.get("predicted_p99_ms") or 0.0), 4),
            }
        return doc

    def snapshot(self) -> dict[str, Any]:
        """The ``/capacity`` document (:data:`CAPACITY_SCHEMA`) from the
        fitted state — no profiler access, safe from any thread."""
        with self._mu:
            return self._document_locked()

    def whatif(self, workers: int | None = None, batch: int | None = None,
               deadline_ms: float | None = None,
               max_inflight: int | None = None) -> dict[str, Any]:
        """Evaluate an actuator move against the fitted model WITHOUT
        touching the live system: the same capacity document, with every
        prediction recomputed under the overrides plus a ``whatif``
        section carrying the predicted-p99 delta."""
        overrides = {"workers": workers, "batch": batch,
                     "deadline_ms": deadline_ms,
                     "max_inflight": max_inflight}
        with self._mu:
            return self._document_locked(overrides)

    def breach_summary(self) -> dict[str, Any]:
        """Compact capacity state for incident bundles (schema v3): the
        bottleneck, its headroom, and predicted-vs-observed at breach."""
        with self._mu:
            out: dict[str, Any] = {
                "bottleneck": self._bottleneck,
                "e2e": dict(self._e2e),
                "window_s": round(self._window_s, 3),
                "regressions": {
                    s: n for s, n in sorted(self._regressions.items()) if n
                },
            }
        return out

    # -- supervised-service surface ----------------------------------------
    def reset(self) -> None:
        self._stop.clear()

    def stop(self) -> None:
        self._stop.set()

    def run(self, interval_s: float = 2.0) -> None:
        while not self._stop.wait(interval_s):
            self.refresh()


def _num(doc: Mapping[str, Any], key: str) -> bool:
    v = doc.get(key)
    return isinstance(v, (int, float)) and math.isfinite(v)


def validate_capacity(doc: Any) -> list[str]:
    """Schema check for a capacity document -> list of problems ([] =
    valid). Hand-rolled like ``validate_profile``: the CI smoke gates on
    NAMED failures, not a boolean."""
    errs: list[str] = []
    if not isinstance(doc, Mapping):
        return ["document: not a mapping"]
    if doc.get("schema") != CAPACITY_SCHEMA:
        errs.append(f"schema: expected {CAPACITY_SCHEMA!r}, "
                    f"got {doc.get('schema')!r}")
    if not _num(doc, "generated_unix"):
        errs.append("generated_unix: missing")
    if not isinstance(doc.get("actuators"), Mapping):
        errs.append("actuators: missing mapping")
    stages = doc.get("stages")
    if not isinstance(stages, Mapping):
        return errs + ["stages: missing"]
    for name, entry in stages.items():
        if not isinstance(entry, Mapping):
            errs.append(f"stages.{name}: not a mapping")
            continue
        if entry.get("layer") not in ("queue", "service", "dispatch"):
            errs.append(f"stages.{name}.layer: invalid")
        for k in ("arrival_batches_per_s", "mean_service_ms",
                  "utilization", "headroom_ratio"):
            if not _num(entry, k):
                errs.append(f"stages.{name}.{k}: missing/non-finite")
    e2e = doc.get("e2e")
    if not isinstance(e2e, Mapping):
        errs.append("e2e: missing mapping")
    else:
        for k in ("predicted_p50_ms", "predicted_p99_ms"):
            if not _num(e2e, k):
                errs.append(f"e2e.{k}: missing/non-finite")
    bn = doc.get("bottleneck")
    if bn is not None:
        if not isinstance(bn, Mapping) or not isinstance(
                bn.get("stage"), str):
            errs.append("bottleneck: must carry a stage name when present")
        elif bn["stage"] not in stages:
            errs.append(f"bottleneck.stage: {bn['stage']!r} not in stages")
    wi = doc.get("whatif")
    if wi is not None:
        if not isinstance(wi, Mapping) or not isinstance(
                wi.get("requested"), Mapping):
            errs.append("whatif: must carry the requested overrides")
        elif not _num(wi, "predicted_p99_ms"):
            errs.append("whatif.predicted_p99_ms: missing")
    return errs
