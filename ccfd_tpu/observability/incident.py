"""SLO-breach incident flight recorder: the automatic post-mortem.

PR 9's burn-rate pages say WHEN an objective failed; the evidence a
responder needs — which stage ate the latency, what the breakers and the
overload plane were doing, what the device looked like — exists only in
live gauges that have moved on by the time anyone looks. PRETZEL
(PAPERS.md) calls this the black-box-serving observability gap. This
module closes the loop:

- :class:`FlightRecorder` — a bounded ring of periodic system snapshots
  (watched-counter deltas, a compact per-stage latency summary, breaker/
  overload/lifecycle gauge states, recent kept traces, device + memory
  stats). Runs as a supervised service under the operator; a dispatch-
  watchdog kill (``ccfd_dispatch_timeout_total`` trip) snapshots
  immediately, so watchdog post-mortems have flight data too.
- **Incident bundles** — the SLOEngine's breach edge-trigger calls
  :meth:`FlightRecorder.on_breach`, which dumps ONE schema-validated
  (:data:`INCIDENT_SCHEMA` = ``ccfd.incident.v3``) bundle per breach
  entry: trigger, full SLO status, the complete StageProfile document,
  the ring as it stood, a live snapshot, the device telemetry plane's
  view — and, with the decision-audit plane armed, the last N
  **decision-record summaries** from the breach window
  (``observability/audit.py``), so ``incident_report`` shows WHICH
  transactions were in flight when the objective failed, not just which
  layer ate the latency (schema v1 -> v2). With the capacity
  observatory armed, bundles also embed the queueing model's
  breach-time verdict — bottleneck stage, headroom, predicted-vs-
  observed p99 (``observability/capacity.py``; v2 -> v3), so the
  post-mortem says what the model EXPECTED, not just what happened.
  Bundles persist crash-safely
  (tmp+rename) under ``out_dir`` when configured, are bounded
  (``max_bundles``, oldest pruned), and are served by the exporter at
  ``/incidents`` + ``/incidents/<id>``. ``tools/incident_report.py``
  renders the human summary.

Edge semantics match the breach counter's: one bundle per ENTRY into the
breaching state — a recovery followed by a re-breach dumps again.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Callable, Mapping

from ccfd_tpu.observability.profile import (
    validate_profile,
    write_json_crash_safe,
)

INCIDENT_SCHEMA = "ccfd.incident.v3"

# counters whose totals every snapshot records (and diffs against the
# previous snapshot): the accounting a responder reads first
WATCHED_COUNTERS = (
    "transaction_incoming_total",
    "transaction_outgoing_total",
    "router_shed_total",
    "router_score_errors_total",
    "router_degraded_total",
    "ccfd_shed_total",
    "ccfd_admission_total",
    "ccfd_dispatch_timeout_total",
    "ccfd_h2d_bytes_total",
    "ccfd_xla_compile_events_total",
    "ccfd_slo_breach_total",
    "seldon_api_executor_server_requests_total",
)

# gauge families captured as {labelset: value} state tables
WATCHED_GAUGES = (
    "ccfd_breaker_state",
    "ccfd_inflight_limit",
    "ccfd_inflight_used",
    "ccfd_lifecycle_stage",
    "ccfd_lifecycle_champion_version",
    "ccfd_slo_breaching",
    "ccfd_slo_burn_rate",
    "ccfd_slo_error_budget_remaining",
)


def _labelstr(key) -> str:
    return "|".join(f"{k}={v}" for k, v in key) or "all"


class FlightRecorder:
    """Bounded snapshot ring + incident bundle dumper; see the module
    docstring. Thread-safe: the supervised tick, the SLO engine's breach
    callback and the dispatch watchdog all feed it concurrently."""

    def __init__(
        self,
        registries: Mapping[str, Any],
        registry=None,
        profiler=None,
        telemetry=None,
        sink=None,
        ring: int = 64,
        out_dir: str | None = None,
        max_bundles: int = 16,
        timeout_debounce_s: float = 2.0,
        clock: Callable[[], float] = time.time,
        audit=None,
        capacity=None,
    ):
        self._registries = registries
        self.profiler = profiler
        self.telemetry = telemetry
        self.sink = sink
        # decision-audit plane (observability/audit.py): when wired,
        # every bundle embeds the last N decision-record summaries — the
        # transactions in flight across the breach window
        self.audit = audit
        # capacity observatory (observability/capacity.py): when wired,
        # every bundle embeds the queueing model's breach-time verdict —
        # bottleneck stage, headroom, predicted-vs-observed p99 (v3)
        self.capacity = capacity
        self.decisions_embedded = 16
        self._last_incident_id: str | None = None
        self.out_dir = out_dir or None
        self.max_bundles = max(1, int(max_bundles))
        self._clock = clock
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self.ring: collections.deque = collections.deque(
            maxlen=max(1, int(ring)))
        self._bundles: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self._seq = 0
        self._prev_totals: dict[str, float] = {}
        # dispatch-timeout debounce: a wedged scorer trips EVERY worker's
        # watchdog at the deadline rate — snapshotting each trip would pay
        # a full evidence capture on the already-degraded path AND flush
        # the pre-incident history out of the bounded ring within seconds
        self.timeout_debounce_s = float(timeout_debounce_s)
        self._last_timeout_snap = -float("inf")
        self._c_snapshots = self._c_incidents = self._g_ring = None
        if registry is not None:
            self._c_snapshots = registry.counter(
                "ccfd_incident_snapshots_total",
                "flight-recorder ring snapshots by reason (periodic tick, "
                "dispatch_timeout trip, incident dump)",
            )
            self._c_incidents = registry.counter(
                "ccfd_incidents_total",
                "incident bundles dumped, by trigger type (edge-triggered "
                "with the SLO breach counter: one per entry into the "
                "breaching state)",
            )
            self._g_ring = registry.gauge(
                "ccfd_incident_ring_size",
                "snapshots currently held in the flight-recorder ring",
            )
        if self.out_dir:
            os.makedirs(self.out_dir, exist_ok=True)

    # -- evidence collection ------------------------------------------------
    def _totals(self) -> dict[str, float]:
        out: dict[str, float] = {}
        for reg in self._registries.values():
            for name in WATCHED_COUNTERS:
                m = reg.get(name)
                if m is not None and hasattr(m, "total"):
                    out[name] = out.get(name, 0.0) + float(m.total())
        return out

    def _gauges(self) -> dict[str, dict[str, float]]:
        out: dict[str, dict[str, float]] = {}
        for reg in self._registries.values():
            for name in WATCHED_GAUGES:
                m = reg.get(name)
                if m is None or not hasattr(m, "items"):
                    continue
                table = out.setdefault(name, {})
                for key, val in m.items():
                    table[_labelstr(key)] = val
        return out

    def _stage_summary(self) -> dict[str, Any]:
        """Compact per-stage p99s for ring snapshots (the full digests
        ride only in the bundle's stage_profile)."""
        if self.profiler is None:
            return {}
        out: dict[str, Any] = {}
        try:
            doc = self.profiler.snapshot()
            for stage, entry in doc.get("stages", {}).items():
                comp = {
                    c: entry[c]["p99_ms"]
                    for c in ("queue", "service", "dispatch")
                    if isinstance(entry.get(c), dict)
                    and "p99_ms" in entry[c]
                }
                if comp:
                    comp["rows"] = entry.get("rows", 0)
                    out[stage] = comp
        # ccfd-lint: disable=counted-drops -- bundle section fallback: the section's absence in the shipped bundle IS the record of the failure
        except Exception:  # noqa: BLE001 - evidence, not a crash source
            pass
        return out

    def _traces_summary(self, limit: int = 8) -> list[dict[str, Any]]:
        if self.sink is None:
            return []
        try:
            return self.sink.traces()[:limit]
        # ccfd-lint: disable=counted-drops -- bundle section fallback: an empty traces section in the bundle records the gap
        except Exception:  # noqa: BLE001
            return []

    def _memory_summary(self) -> dict[str, Any]:
        from ccfd_tpu.observability.memory import rss_bytes

        return {"rss_bytes": rss_bytes()}

    def snapshot(self, reason: str = "periodic") -> dict[str, Any]:
        """Collect one system snapshot and append it to the ring."""
        with self._mu:
            # totals are read INSIDE the lock: a periodic tick racing an
            # incident/timeout snapshot on another thread must not diff
            # against the other's baseline (negative deltas in the ring,
            # then double-counted increments on the next tick)
            totals = self._totals()
            deltas = {
                name: round(val - self._prev_totals.get(name, 0.0), 6)
                for name, val in totals.items()
            }
            self._prev_totals = totals
        snap: dict[str, Any] = {
            "ts_unix": self._clock(),
            "reason": reason,
            "counters": totals,
            "counter_deltas": deltas,
            "gauges": self._gauges(),
            "stages_p99_ms": self._stage_summary(),
            "traces": self._traces_summary(),
            "memory": self._memory_summary(),
        }
        if self.telemetry is not None:
            try:
                snap["device"] = self.telemetry.snapshot()
            # ccfd-lint: disable=counted-drops -- bundle section fallback: the empty device section ships in the bundle
            except Exception:  # noqa: BLE001
                snap["device"] = {}
        with self._mu:
            self.ring.append(snap)
            if self._g_ring is not None:
                self._g_ring.set(float(len(self.ring)))
        if self._c_snapshots is not None:
            self._c_snapshots.inc(labels={"reason": reason})
        return snap

    def note_dispatch_timeout(self) -> None:
        """Dispatch-watchdog hook (runtime/overload.py): a killed dispatch
        snapshots the system state into the ring immediately, so watchdog
        kills are post-mortem-able without waiting for an SLO breach.
        Debounced (``timeout_debounce_s``): a timeout STORM takes one
        snapshot per window — the trips themselves stay fully counted in
        ``ccfd_dispatch_timeout_total``, and the snapshot's counters
        record the running total."""
        now = self._clock()
        with self._mu:
            if now - self._last_timeout_snap < self.timeout_debounce_s:
                return
            self._last_timeout_snap = now
        self.snapshot(reason="dispatch_timeout")

    # -- incident bundles ---------------------------------------------------
    def on_breach(self, slo: str, status: Mapping[str, Any]) -> dict:
        """SLOEngine breach-edge callback -> one bundle per breach entry."""
        return self.incident({"type": "slo_breach", "slo": slo},
                             slo_status=dict(status))

    def incident(self, trigger: Mapping[str, Any],
                 slo_status: Mapping[str, Any] | None = None) -> dict:
        live = self.snapshot(reason="incident")
        with self._mu:
            self._seq += 1
            seq = self._seq
            ring = list(self.ring)
        slug = str(trigger.get("slo") or trigger.get("type", "incident"))
        inc_id = f"inc-{seq:04d}-{slug}"
        doc: dict[str, Any] = {
            "schema": INCIDENT_SCHEMA,
            "id": inc_id,
            "generated_unix": self._clock(),
            "trigger": dict(trigger),
            "slo_status": dict(slo_status or {}),
            "snapshot": live,
            "ring": ring,
        }
        if self.profiler is not None:
            try:
                doc["stage_profile"] = self.profiler.snapshot()
            # ccfd-lint: disable=counted-drops -- bundle section fallback: the null stage_profile ships in the bundle
            except Exception:  # noqa: BLE001
                doc["stage_profile"] = None
        if self.audit is not None:
            # which transactions were IN FLIGHT: the newest decision
            # records as they stood at the breach edge (schema v2)
            try:
                doc["decisions"] = self.audit.recent_summaries(
                    self.decisions_embedded)
            # ccfd-lint: disable=counted-drops -- bundle section fallback: the empty decisions section ships in the bundle
            except Exception:  # noqa: BLE001 - evidence, never a crash
                doc["decisions"] = []
        if self.capacity is not None:
            # what the queueing model believed at the breach edge:
            # bottleneck stage + layer, headroom, predicted vs observed
            # p99 (schema v3)
            try:
                doc["capacity"] = self.capacity.breach_summary()
            # ccfd-lint: disable=counted-drops -- bundle section fallback: the null capacity section ships in the bundle
            except Exception:  # noqa: BLE001 - evidence, never a crash
                doc["capacity"] = None
        errs = validate_incident(doc)
        if errs:  # never ship an invalid bundle silently
            doc["validation_errors"] = errs[:10]
        path = None
        if self.out_dir:
            path = os.path.join(self.out_dir, f"{inc_id}.json")
            try:
                write_json_crash_safe(path, doc)
            except OSError:
                path = None
        if path:
            doc["path"] = path
        with self._mu:
            self._bundles[inc_id] = doc
            self._last_incident_id = inc_id
            while len(self._bundles) > self.max_bundles:
                old_id, old = self._bundles.popitem(last=False)
                old_path = old.get("path")
                if old_path:
                    for p in (old_path, old_path + ".sha256"):
                        try:
                            os.remove(p)
                        except OSError:
                            pass
        if self._c_incidents is not None:
            self._c_incidents.inc(
                labels={"trigger": str(trigger.get("type", "unknown"))})
        return doc

    def incidents(self) -> list[dict[str, Any]]:
        """Bundle summaries, newest first — the /incidents body."""
        with self._mu:
            docs = list(self._bundles.values())
        return [
            {
                "id": d["id"],
                "generated_unix": d["generated_unix"],
                "trigger": d["trigger"],
                "ring_depth": len(d.get("ring", [])),
                "path": d.get("path"),
            }
            for d in reversed(docs)
        ]

    def incident_doc(self, inc_id: str) -> dict[str, Any] | None:
        with self._mu:
            return self._bundles.get(inc_id)

    def last_incident_id(self) -> str | None:
        """Newest bundle's id — the decision-audit plane stamps it onto
        routed transactions while the SLO engine reports the breaching
        state still open (operator wiring)."""
        with self._mu:
            return self._last_incident_id

    # -- supervised-service surface ----------------------------------------
    def reset(self) -> None:
        self._stop.clear()

    def stop(self) -> None:
        self._stop.set()

    def run(self, interval_s: float = 5.0) -> None:
        while not self._stop.wait(interval_s):
            self.snapshot()


def _snapshot_errors(where: str, snap: Any) -> list[str]:
    if not isinstance(snap, Mapping):
        return [f"{where}: not a mapping"]
    errs = []
    if not isinstance(snap.get("ts_unix"), (int, float)):
        errs.append(f"{where}.ts_unix: missing")
    if not isinstance(snap.get("reason"), str):
        errs.append(f"{where}.reason: missing")
    for k in ("counters", "counter_deltas", "gauges"):
        if not isinstance(snap.get(k), Mapping):
            errs.append(f"{where}.{k}: missing")
    return errs


def validate_incident(doc: Any) -> list[str]:
    """Schema check for a ``ccfd.incident.v3`` bundle -> list of problems
    ([] = valid). Hand-rolled like ``validate_profile``, and reusing it
    for the embedded StageProfile: the smoke and the exporter contract
    both gate on NAMED failures. v2 added the optional ``decisions``
    embed (decision-record summaries from the breach window); v3 adds
    the optional ``capacity`` embed (the queueing model's breach-time
    verdict: bottleneck stage, headroom, predicted-vs-observed p99)."""
    errs: list[str] = []
    if not isinstance(doc, Mapping):
        return ["document: not a mapping"]
    if doc.get("schema") != INCIDENT_SCHEMA:
        errs.append(f"schema: expected {INCIDENT_SCHEMA!r}, "
                    f"got {doc.get('schema')!r}")
    if not isinstance(doc.get("id"), str) or not doc.get("id"):
        errs.append("id: missing")
    if not isinstance(doc.get("generated_unix"), (int, float)):
        errs.append("generated_unix: missing")
    trigger = doc.get("trigger")
    if not isinstance(trigger, Mapping) or not isinstance(
            trigger.get("type"), str):
        errs.append("trigger: missing mapping with a 'type'")
    ring = doc.get("ring")
    if not isinstance(ring, list):
        errs.append("ring: missing list")
    else:
        for i, snap in enumerate(ring):
            errs.extend(_snapshot_errors(f"ring[{i}]", snap))
    errs.extend(_snapshot_errors("snapshot", doc.get("snapshot")))
    if not isinstance(doc.get("slo_status"), Mapping):
        errs.append("slo_status: missing mapping")
    sp = doc.get("stage_profile")
    if sp is not None:
        errs.extend(f"stage_profile.{e}" for e in validate_profile(sp))
    decisions = doc.get("decisions")
    if decisions is not None:
        if not isinstance(decisions, list):
            errs.append("decisions: must be a list when present")
        else:
            for i, d in enumerate(decisions):
                if not isinstance(d, Mapping) or "seq" not in d:
                    errs.append(f"decisions[{i}]: not a decision-record "
                                "summary (mapping with 'seq')")
                    break
    capacity = doc.get("capacity")
    if capacity is not None:
        if not isinstance(capacity, Mapping):
            errs.append("capacity: must be a mapping when present")
        else:
            for k in ("bottleneck", "e2e", "regressions"):
                if k not in capacity:
                    errs.append(f"capacity.{k}: missing")
    return errs
