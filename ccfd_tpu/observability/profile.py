"""Stage profiles: a machine-readable queueing/service/dispatch decomposition.

PR 2's spans, PR 6's overload gauges and the bench rows are human-readable
evidence; ROADMAP item 3's InferLine-style provisioning planner
(arXiv:1812.01776) needs a machine-readable PROFILE of each pipeline stage
— per stage, how much of a transaction's latency was queueing (waiting for
the stage), service (host work in the stage) and device dispatch (the XLA
round trip), plus how service time scales with batch size (the curve the
planner trades against batching deadlines). This module maintains exactly
that, live:

- :class:`LatencyDigest` — a fixed-geometric-bucket quantile sketch
  (t-digest-shaped accuracy at a fraction of the code): bounded memory,
  mergeable counts, interpolated quantiles. Every component below records
  into digests, never raw samples.
- :class:`StageProfiler` — per-stage accumulators with three components
  (``queue`` / ``service`` / ``dispatch``) and a batch-size-conditioned
  service curve. Fed two ways, both wired by the operator:

  1. **direct observes** on the hot paths that know their own split — the
     router feeds bus queueing delay, decode/route service and the scorer
     dispatch per micro-batch; the serving ``DynamicBatcher`` feeds REST
     batcher wait and dispatch time per coalesced launch;
  2. **span ingestion** — a listener on the PR 2 :class:`SpanSink` maps
     finished spans (every span, not just tail-sampled keeps) onto stages
     by name, so stages with no direct feed (producer, engine REST,
     notify, serving) profile for free wherever tracing is on.

  XLA compile events attribute through a ``jax.monitoring`` duration
  listener (``backend_compile``): a stage whose p99 spikes because a new
  executable compiled mid-traffic shows the compile in the same profile
  (`compile` section + ``ccfd_xla_compile_events_total``), and
  :meth:`StageProfiler.profile_device` wraps ``jax.profiler.trace`` for
  the deep device-level view.

- **StageProfile artifact** — :meth:`StageProfiler.snapshot` renders the
  whole profile as one JSON document (schema :data:`PROFILE_SCHEMA`,
  validated by :func:`validate_profile`), served live at the exporter's
  ``/profile`` endpoint and written crash-safely (tmp+rename) by
  :meth:`StageProfiler.write` / ``tools/slo_report.py``. This document is
  the input contract the future planner consumes.

The profiler is wall-clock-free on the hot path (two ``perf_counter``
reads per batch where it is fed directly) and entirely lock-striped per
stage; a disabled profiler costs one ``is None`` check.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import math
import os
import threading
import time
import weakref
from typing import Any, Iterator, Mapping

PROFILE_SCHEMA = "ccfd.stage_profile.v1"

# the three latency components every stage decomposes into
COMPONENTS = ("queue", "service", "dispatch")

# canonical pipeline stages (ISSUE 9: produce -> bus -> router decode/
# score/route -> engine -> notify, plus the REST serving path). Stages not
# in this tuple are still accepted — the planner contract only promises
# these names when the corresponding path carried traffic.
STAGES = (
    "produce",        # producer batch emit (service)
    "bus",            # topic wait: produce timestamp -> router poll (queue)
    "router.decode",  # record decode into the (B, 30) matrix (service)
    "router.score",   # scorer device round trip (dispatch)
    "router.route",   # rule eval + engine process starts (service)
    "engine",         # KIE REST surface (service)
    "notify",         # notification handling (service)
    "rest",           # serving predict request end to end (service)
    "rest.batcher",   # DynamicBatcher queue sojourn (queue)
    "rest.dispatch",  # serving-side coalesced device dispatch (dispatch)
)

# span name -> (stage, component): the SpanSink ingestion map. The router
# span family (router.batch/decode/score/route) is deliberately ABSENT:
# the router feeds its stages directly (richer — batch sizes, the
# queue/service split — and present even with tracing off), and ingesting
# its spans too would double-count every batch. Stages with no hot-path
# feed profile through their spans.
SPAN_STAGES: Mapping[str, tuple[str, str]] = {
    "producer.batch": ("produce", "service"),
    "producer.produce": ("produce", "service"),
    "engine.rest": ("engine", "service"),
    "notify.handle": ("notify", "service"),
    "serving.predict": ("rest", "service"),
}

# batch-size buckets conditioning the service curve (the scorer's own
# bucket ladder shape)
BATCH_BUCKETS = (1, 8, 64, 256, 1024, 4096, 16384)


class LatencyDigest:
    """Fixed-geometric-bucket latency sketch: 1 µs .. ~137 s at 2^(1/4)
    spacing (~9% worst-case relative quantile error after interpolation),
    bounded memory, cheap adds. NOT thread-safe — callers lock."""

    # 4 buckets per octave over 27 octaves: 1e-6 * 2**(k/4)
    _BASE = 1e-6
    _PER_OCTAVE = 4
    _N = 27 * _PER_OCTAVE + 1

    __slots__ = ("counts", "count", "sum", "min", "max")

    def __init__(self) -> None:
        self.counts = [0] * self._N
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = 0.0

    def _index(self, value: float) -> int:
        if value <= self._BASE:
            return 0
        i = int(math.log2(value / self._BASE) * self._PER_OCTAVE) + 1
        return min(self._N - 1, i)

    @classmethod
    def _upper(cls, i: int) -> float:
        if i <= 0:
            return cls._BASE
        return cls._BASE * 2.0 ** (i / cls._PER_OCTAVE)

    def add(self, value: float, n: int = 1) -> None:
        value = max(0.0, float(value))
        self.counts[self._index(value)] += n
        self.count += n
        self.sum += value * n
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value

    def quantile(self, q: float) -> float:
        """Interpolated quantile in SECONDS; NaN with no samples."""
        if self.count == 0:
            return float("nan")
        rank = q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            prev_cum = cum
            cum += c
            if cum >= rank:
                lo = self._upper(i - 1) if i > 0 else 0.0
                hi = self._upper(i)
                frac = (rank - prev_cum) / c if c else 1.0
                v = lo + (hi - lo) * frac
                # never report outside the observed envelope (the last
                # bucket's upper bound can exceed the true max wildly)
                return min(max(v, self.min), self.max)
        return self.max

    def copy(self) -> "LatencyDigest":
        """Field-complete clone (readers snapshot under the writer's lock;
        the layout knowledge stays HERE, not at every call site)."""
        out = LatencyDigest()
        out.counts = list(self.counts)
        out.count = self.count
        out.sum = self.sum
        out.min = self.min
        out.max = self.max
        return out

    def to_dict(self) -> dict[str, Any]:
        if self.count == 0:
            return {"count": 0, "sum_s": 0.0}
        return {
            "count": self.count,
            "sum_s": round(self.sum, 6),
            "mean_ms": round(1e3 * self.sum / self.count, 4),
            "p50_ms": round(1e3 * self.quantile(0.5), 4),
            "p90_ms": round(1e3 * self.quantile(0.9), 4),
            "p99_ms": round(1e3 * self.quantile(0.99), 4),
            "min_ms": round(1e3 * self.min, 4),
            "max_ms": round(1e3 * self.max, 4),
        }


class _StageAcc:
    __slots__ = ("lock", "digests", "by_batch", "rows")

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.digests = {c: LatencyDigest() for c in COMPONENTS}
        # batch-bucket -> service-or-dispatch digest (the service curve)
        self.by_batch: dict[int, LatencyDigest] = {}
        self.rows = 0


def _batch_bucket(n: int) -> int:
    for b in BATCH_BUCKETS:
        if n <= b:
            return b
    return BATCH_BUCKETS[-1]


# jax.monitoring listeners are process-global with no unregister: one hook,
# registered once, forwarding to the CURRENT profiler via weakref (see
# StageProfiler.arm_compile_listener)
_COMPILE_HOOK_REGISTERED = False
_COMPILE_TARGET: "weakref.ref[StageProfiler] | None" = None

# per-stage compile attribution: backend_compile events fire synchronously
# on the compiling thread, so a contextvar label set by the component that
# triggered the compile (scorer warmup, a seq variant swap, a live
# re-trace) names the stage the compile bills to
_COMPILE_STAGE: contextvars.ContextVar[str] = contextvars.ContextVar(
    "ccfd_compile_stage", default="untagged")


@contextlib.contextmanager
def compile_stage(label: str) -> Iterator[None]:
    """Attribute XLA compiles inside the block to ``label`` (the device
    telemetry plane's executable-inventory companion: WHICH stage paid
    the compile, not just that one happened)."""
    token = _COMPILE_STAGE.set(str(label))
    try:
        yield
    finally:
        _COMPILE_STAGE.reset(token)


def _on_compile_event(event: str, secs: float, **_kw) -> None:
    if not event.endswith("backend_compile_duration"):
        return
    target = _COMPILE_TARGET() if _COMPILE_TARGET is not None else None
    if target is not None:
        target._record_compile(secs)


def record_synthetic_compile(secs: float) -> None:
    """Feed one synthetic backend_compile event to the armed profiler —
    the injection point the ``compile_stall`` device fault
    (runtime/faults.py) uses so a CPU CI drill moves the same
    compile-storm signal a real re-trace storm would. Bills to the
    active :func:`compile_stage` label like any real compile. No-op when
    no profiler armed the listener."""
    _on_compile_event("backend_compile_duration", float(secs))


class StageProfiler:
    """Live per-stage latency decomposition; see the module docstring.

    With a ``registry``, :meth:`refresh_gauges` (called on every
    :meth:`snapshot`, i.e. on every ``/profile`` read and SLO tick)
    exports ``ccfd_stage_latency_ms{stage,component,quantile}`` so the
    SLO Grafana board charts the decomposition without parsing the JSON
    artifact, plus the compile-event counter/clock.
    """

    def __init__(self, registry=None,
                 overload_registry=None) -> None:
        self._stages: dict[str, _StageAcc] = {}
        self._stages_mu = threading.Lock()
        self._overload_registry = overload_registry
        self._compile_mu = threading.Lock()
        self._compile = LatencyDigest()
        # stage label -> digest (see compile_stage): the per-stage compile
        # attribution the Device board and incident bundles read
        self._compile_stages: dict[str, LatencyDigest] = {}
        self._compile_armed = False
        self.registry = registry
        self._g_stage = self._c_compile = self._c_compile_s = None
        self._c_compile_stage_s = None
        if registry is not None:
            self._g_stage = registry.gauge(
                "ccfd_stage_latency_ms",
                "stage-profile latency decomposition by stage, component "
                "(queue/service/dispatch) and quantile",
            )
            self._c_compile = registry.counter(
                "ccfd_xla_compile_events_total",
                "XLA backend_compile events attributed to this process "
                "(jax.monitoring hook; a mid-traffic compile explains a "
                "stage p99 spike)",
            )
            # true counters (ccfd-lint metric-naming): a *_total gauge
            # set() out of order moves the series backwards, which
            # rate()/increase() reads as a counter reset — inc() under
            # the compile lock is monotonic by construction
            self._c_compile_s = registry.counter(
                "ccfd_xla_compile_seconds_total",
                "cumulative wall seconds spent in XLA backend compiles",
            )
            self._c_compile_stage_s = registry.counter(
                "ccfd_compile_stage_seconds_total",
                "cumulative XLA backend-compile seconds attributed to the "
                "stage that triggered them (compile_stage labels; "
                "'untagged' = compiles outside any labeled block)",
            )

    # -- ingestion ---------------------------------------------------------
    def _acc(self, stage: str) -> _StageAcc:
        acc = self._stages.get(stage)
        if acc is None:
            with self._stages_mu:
                acc = self._stages.setdefault(stage, _StageAcc())
        return acc

    def observe(self, stage: str, queue_s: float | None = None,
                service_s: float | None = None,
                dispatch_s: float | None = None,
                batch: int | None = None, rows: int = 1) -> None:
        """Record one sample for ``stage``. Any subset of the three
        components may be present; ``batch`` additionally conditions the
        service/dispatch sample on the batch-size bucket (the service
        curve a provisioning planner fits)."""
        acc = self._acc(stage)
        with acc.lock:
            acc.rows += rows
            if queue_s is not None:
                acc.digests["queue"].add(queue_s)
            if service_s is not None:
                acc.digests["service"].add(service_s)
            if dispatch_s is not None:
                acc.digests["dispatch"].add(dispatch_s)
            if batch is not None and (service_s is not None
                                      or dispatch_s is not None):
                b = _batch_bucket(int(batch))
                d = acc.by_batch.get(b)
                if d is None:
                    d = acc.by_batch[b] = LatencyDigest()
                d.add(dispatch_s if dispatch_s is not None else service_s)

    def on_span(self, span) -> None:
        """SpanSink listener: fold a finished span into its stage (see
        :data:`SPAN_STAGES` for why the router family is excluded)."""
        mapped = SPAN_STAGES.get(span.name)
        if mapped is None:
            return
        stage, component = mapped
        self.observe(stage, **{f"{component}_s": span.duration_s})

    def digest(self, stage: str, component: str) -> LatencyDigest | None:
        """A consistent COPY of the stage/component digest (or None).
        Digests are not thread-safe and hot-path writers hold the stage
        lock — readers (budget ledger, load_shape shares) get a snapshot
        taken under it, never the live object."""
        acc = self._stages.get(stage)
        if acc is None:
            return None
        with acc.lock:
            d = acc.digests.get(component)
            return d.copy() if d is not None else None

    # -- XLA compile attribution ------------------------------------------
    def arm_compile_listener(self) -> bool:
        """Attribute XLA backend compiles via ``jax.monitoring``. The jax
        registration is process-global with no unregister, so exactly ONE
        module-level hook ever registers; it forwards to the most recently
        armed profiler through a weakref (a torn-down platform's profiler
        is collectable and stops receiving events — newest wins, exactly
        like supervisor respawns elsewhere)."""
        global _COMPILE_TARGET
        if not self._compile_armed:
            try:
                import jax.monitoring as monitoring
            # ccfd-lint: disable=counted-drops -- capability probe: no jax.monitoring means compile attribution is off, reported via the False return
            except Exception:  # noqa: BLE001 - profile without jax works
                return False
            global _COMPILE_HOOK_REGISTERED
            if not _COMPILE_HOOK_REGISTERED:
                try:
                    monitoring.register_event_duration_secs_listener(
                        _on_compile_event)
                # ccfd-lint: disable=counted-drops -- capability probe: older jax without the hook, reported via the False return
                except Exception:  # noqa: BLE001 - older jax, no hook
                    return False
                _COMPILE_HOOK_REGISTERED = True
            self._compile_armed = True
        _COMPILE_TARGET = weakref.ref(self)
        return True

    def _record_compile(self, secs: float) -> None:
        stage = _COMPILE_STAGE.get()
        with self._compile_mu:
            self._compile.add(float(secs))
            d = self._compile_stages.get(stage)
            if d is None:
                d = self._compile_stages[stage] = LatencyDigest()
            d.add(float(secs))
            if self._c_compile is not None:
                self._c_compile.inc()
                self._c_compile_s.inc(float(secs))
                self._c_compile_stage_s.inc(float(secs),
                                            labels={"stage": stage})

    def compile_counts(self) -> dict[str, int]:
        """Per-stage compile-event counts (``total`` included) — the cheap
        read the DeviceSupervisor's compile-storm signal and the heal
        drills' warm-re-promotion assertions diff per tick, without
        paying a full :meth:`snapshot`."""
        with self._compile_mu:
            out = {stage: d.count
                   for stage, d in self._compile_stages.items()}
            out["total"] = self._compile.count
        return out

    @contextlib.contextmanager
    def profile_device(self, logdir: str) -> Iterator[None]:
        """Device-level XLA trace (TensorBoard format) around a block —
        the deep-dive companion to the always-on stage profile."""
        import jax

        with jax.profiler.trace(logdir):
            yield

    # -- export ------------------------------------------------------------
    def _overload_section(self) -> dict[str, Any]:
        reg = self._overload_registry
        if reg is None:
            return {}
        out: dict[str, Any] = {}
        try:
            lim = reg.get("ccfd_inflight_limit")
            used = reg.get("ccfd_inflight_used")
            if lim is not None:
                out["inflight"] = {
                    "limit": {("|".join(f"{k}={v}" for k, v in key) or "all"):
                              val for key, val in lim.items()},
                    "used": ({("|".join(f"{k}={v}" for k, v in key) or "all"):
                              val for key, val in used.items()}
                             if used is not None else {}),
                }
            for name in ("ccfd_shed_total", "ccfd_admission_total",
                         "ccfd_dispatch_timeout_total",
                         "ccfd_priority_inversions_total"):
                m = reg.get(name)
                if m is not None and hasattr(m, "total"):
                    out[name] = m.total()
        # ccfd-lint: disable=counted-drops -- read-side export fallback: the overload section is simply absent from /profile, which the reader sees
        except Exception:  # noqa: BLE001 - profile export must never 500
            pass
        return out

    def refresh_gauges(self) -> None:
        if self._g_stage is None:
            return
        with self._stages_mu:
            stages = dict(self._stages)
        for stage, acc in stages.items():
            with acc.lock:
                for comp, d in acc.digests.items():
                    if d.count == 0:
                        continue
                    for q, qname in ((0.5, "p50"), (0.99, "p99")):
                        self._g_stage.set(
                            1e3 * d.quantile(q),
                            labels={"stage": stage, "component": comp,
                                    "quantile": qname})

    def snapshot(self) -> dict[str, Any]:
        """The StageProfile document (:data:`PROFILE_SCHEMA`) — the
        planner input contract; also refreshes the stage gauges."""
        self.refresh_gauges()
        with self._stages_mu:
            stages = dict(self._stages)
        doc_stages: dict[str, Any] = {}
        for stage, acc in stages.items():
            with acc.lock:
                entry: dict[str, Any] = {"rows": acc.rows}
                for comp, d in acc.digests.items():
                    entry[comp] = d.to_dict()
                if acc.by_batch:
                    entry["service_by_batch"] = {
                        str(b): d.to_dict()
                        for b, d in sorted(acc.by_batch.items())
                    }
            doc_stages[stage] = entry
        with self._compile_mu:
            compile_section = self._compile.to_dict()
            compile_by_stage = {s: d.to_dict()
                                for s, d in self._compile_stages.items()}
        return {
            "schema": PROFILE_SCHEMA,
            "generated_unix": time.time(),
            "stages": doc_stages,
            "compile": compile_section,
            "compile_by_stage": compile_by_stage,
            "overload": self._overload_section(),
        }

    def write(self, path: str) -> dict[str, Any]:
        """Crash-safe artifact write (tmp+rename); returns the document."""
        doc = self.snapshot()
        write_json_crash_safe(path, doc)
        return doc


def write_json_crash_safe(path: str, doc: Mapping[str, Any]) -> None:
    """Crash-safe JSON write — tmp + fsync + rename plus a ``.sha256``
    sidecar (runtime/durability.write_json_interchange): a crash
    mid-write leaves the previous artifact intact, never a torn file,
    and the sidecar lets consumers verify the bytes. The one writer
    every profile-family artifact shares (StageProfiler.write,
    tools/slo_report.py, tools/trace_report.py --json, the
    FlightRecorder's incident bundles). Raises OSError on failure, like
    the open() it replaced."""
    from ccfd_tpu.runtime.durability import write_json_interchange

    write_json_interchange(path, doc, artifact="profile_doc",
                           best_effort=False, indent=1, sort_keys=True)


def _digest_errors(where: str, d: Any) -> list[str]:
    errs: list[str] = []
    if not isinstance(d, Mapping):
        return [f"{where}: not a mapping"]
    if not isinstance(d.get("count"), int) or d["count"] < 0:
        errs.append(f"{where}: missing/invalid count")
        return errs
    if d["count"] > 0:
        for k in ("sum_s", "mean_ms", "p50_ms", "p99_ms"):
            v = d.get(k)
            if not isinstance(v, (int, float)) or not math.isfinite(v):
                errs.append(f"{where}: missing/non-finite {k}")
    return errs


def validate_profile(doc: Any) -> list[str]:
    """Schema check for a StageProfile document -> list of problems
    ([] = valid). Hand-rolled (no jsonschema dependency): the planner and
    the CI smoke both gate on it, so failures must NAME the path."""
    errs: list[str] = []
    if not isinstance(doc, Mapping):
        return ["document: not a mapping"]
    if doc.get("schema") != PROFILE_SCHEMA:
        errs.append(f"schema: expected {PROFILE_SCHEMA!r}, "
                    f"got {doc.get('schema')!r}")
    if not isinstance(doc.get("generated_unix"), (int, float)):
        errs.append("generated_unix: missing")
    stages = doc.get("stages")
    if not isinstance(stages, Mapping):
        return errs + ["stages: missing"]
    for name, entry in stages.items():
        if not isinstance(entry, Mapping):
            errs.append(f"stages.{name}: not a mapping")
            continue
        if not isinstance(entry.get("rows"), int):
            errs.append(f"stages.{name}.rows: missing")
        for comp in COMPONENTS:
            if comp in entry:
                errs.extend(_digest_errors(f"stages.{name}.{comp}",
                                           entry[comp]))
        for b, d in (entry.get("service_by_batch") or {}).items():
            if not str(b).isdigit():
                errs.append(f"stages.{name}.service_by_batch: "
                            f"non-integer bucket {b!r}")
            errs.extend(_digest_errors(
                f"stages.{name}.service_by_batch.{b}", d))
    if "compile" in doc:
        errs.extend(_digest_errors("compile", doc["compile"]))
    cbs = doc.get("compile_by_stage")
    if cbs is not None:
        if not isinstance(cbs, Mapping):
            errs.append("compile_by_stage: not a mapping")
        else:
            for stage, d in cbs.items():
                errs.extend(_digest_errors(f"compile_by_stage.{stage}", d))
    return errs
