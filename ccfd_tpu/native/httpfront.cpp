// Native HTTP serving front: request parsing, payload decode, and response
// writing in C++ threads; Python touches only whole scoring batches.
//
// Why: the REST hop's per-request Python cost (~650us: header parse, JSON,
// future/condvar hand-off, response build) is GIL-serialized, capping the
// Seldon-contract endpoint at a few thousand req/s regardless of how fast
// the TPU scores (SURVEY.md §7 "hard parts (a)": p99 <10ms with Python on
// the hot path needs a native decode/batch shim). This front moves the
// whole per-request path into C++:
//
//   epoll IO thread: accept, parse HTTP/1.1 keep-alive, auth-check,
//     decode the canonical Seldon ndarray payload (ccfd_decode_ndarray,
//     decode.cpp) into a float32 row block, enqueue.
//   Python scorer threads: ccfd_front_take() -> ONE batch of concatenated
//     rows across many requests -> scorer.score -> ccfd_front_respond().
//   C++ formats the {"data":{"names":...,"ndarray":[[p0,p1],...]}} body
//     and the IO thread writes it back.
//
// Requests C++ can't finish (non-canonical payloads, GET /prometheus,
// bad JSON) queue as "misc" and a Python thread answers them through the
// same routing logic the pure-Python server uses — identical contract,
// different fast path. The wire format matches serving/server.py exactly.
//
// Concurrency model: ONE IO thread owns every socket (no per-socket
// locking); scorer/misc threads only touch the two queues + response
// queue, all under one mutex; an eventfd wakes the IO thread to flush
// responses. Connection death with in-flight requests is handled by a
// (fd, generation) check at response time.

// epoll/eventfd are Linux-only; on other platforms the front degrades to
// stubs (create returns nullptr -> Python falls back to its own server)
// WITHOUT poisoning the shared .so build for decode/log acceleration.
#ifdef __linux__

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cctype>
#include <cerrno>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>

#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

extern "C" int ccfd_decode_ndarray(const char* buf, size_t len, float* out,
                                   int max_rows, int n_features,
                                   int* width_out);

namespace {

constexpr size_t kMaxHead = 64 * 1024;
constexpr size_t kMaxBody = 256 * 1024 * 1024;
// Native-path row cap per request: anything larger routes to the misc
// (Python) queue so one giant request can never exceed the taker's batch
// buffer and wedge the predict queue head. The Python taker's buffer
// (serving/native_front.py max_batch_rows) must be >= this.
constexpr int kNativeMaxRows = 8192;

struct Conn {
  std::string in;
  std::string out;
  uint64_t gen = 0;
  bool want_close = false;
  bool read_closed = false;  // peer half-closed: EOF is permanently readable
  int pending = 0;  // requests enqueued to Python, response not yet queued
};

struct PredictReq {
  int id;
  int fd;
  uint64_t gen;
  int n_rows;
  int path_tag;  // 0 = .../predictions, 1 = /predict (metrics label)
  std::vector<float> rows;
  double enq_monotonic_ms;
};

struct MiscReq {
  int id;
  int fd;
  uint64_t gen;
  std::string method;
  std::string path;
  std::string body;
};

struct Response {
  int fd;
  uint64_t gen;
  std::string data;
};

// In-front host-tier model: a small dense stack (relu hidden layers,
// sigmoid head) scored directly in the IO thread for requests at or under
// max_rows. This is the zero-handoff hot path: on a small host (the bench
// box has ONE core) the C++->Python->C++ queue round trip per batch costs
// more in context switches and GIL handoffs than the forward itself —
// ~100k MACs for 16 rows of the flagship MLP, a few microseconds at -O3.
// Larger requests still flow to the Python takers (device path).
struct HostModel {
  // dense stack (n_layers > 0) ...
  int n_layers = 0;
  std::vector<int> dims;                 // n_layers+1: in, h1, ..., out(=1)
  std::vector<std::vector<float>> w;     // w[l]: (dims[l+1] x dims[l]) row-major
  std::vector<std::vector<float>> b;     // b[l]: dims[l+1]
  std::vector<float> mu, inv_sigma;      // normalizer (identity if empty)
  // int8-quantized variant (q8 = true): w holds the int8 weight VALUES
  // widened to float (products and their <=256-term partial sums are
  // integers below 2^24, exactly representable — the f32 SIMD dot IS the
  // int32 accumulate, at full vector width), scale[l] the per-output
  // dequant scales; activations requantize per row before every layer
  // (same math as ops/quant.py apply_numpy, bit for bit)
  bool q8 = false;
  std::vector<std::vector<float>> scale;  // scale[l]: dims[l+1]
  std::vector<float> sigma;  // q8 normalizes as (x-mu)/sigma — a DIVISION,
  // because apply_numpy divides and multiply-by-reciprocal differs in the
  // last ulp, which can flip a quantization step at a rounding boundary
  // ... or a boosted tree ensemble (n_trees > 0): complete binary trees
  // of depth tree_depth in heap layout, the same dense embedding the XLA
  // path uses (models/trees.py)
  int n_trees = 0;
  int tree_depth = 0;
  std::vector<int32_t> t_feat;           // (T x 2^D-1) split feature ids
  std::vector<float> t_thr;              // (T x 2^D-1) split thresholds
  std::vector<float> t_leaf;             // (T x 2^D) leaf values
  float t_base = 0.0f;
  int max_rows = 0;
  std::string model_name;
  int gauge_cols[3] = {-1, -1, -1};      // Amount, V17, V10 column indices
};

struct Front {
  int listen_fd = -1;
  int epoll_fd = -1;
  int wake_fd = -1;
  int port = 0;
  int n_features = 30;
  std::string auth;  // "Bearer <token>"; empty = no auth
  std::thread io_thread;
  bool stopping = false;

  std::mutex mu;
  std::condition_variable cv;  // signals scorer/misc threads
  std::deque<PredictReq> predict_q;
  std::deque<MiscReq> misc_q;
  std::deque<Response> resp_q;  // drained by the IO thread
  std::unordered_map<int, std::pair<uint64_t, int>> req_route;  // id -> (gen, fd)
  int next_id = 1;
  uint64_t gen_counter = 1;
  std::unordered_map<int, Conn> conns;

  // stats (read via ccfd_front_stats)
  long n_requests = 0;
  long n_predict = 0;
  long n_misc = 0;
  long n_auth_fail = 0;

  // host-tier model + its metrics (read via ccfd_front_host_stats; Python
  // folds cumulative values into the registry at scrape time). Latency
  // bucket layout mirrors the registry histogram: cumulative le counts.
  HostModel* host = nullptr;
  std::vector<double> lat_ubs;           // upper bounds, last is +inf
  std::vector<long> host_hist[2];        // per endpoint tag, len(lat_ubs)
  double host_sum[2] = {0.0, 0.0};
  long n_host = 0;
  float last_gauges[4] = {0, 0, 0, 0};   // proba_1, Amount, V17, V10
  double last_gauge_ms = 0.0;            // CLOCK_MONOTONIC ms of last update
};

double now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1e3 + ts.tv_nsec / 1e6;
}

void set_nonblock(int fd) {
  // O_NONBLOCK via ioctl-free fcntl
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

const char* reason_of(int status) {
  switch (status) {
    case 200: return "OK";
    case 400: return "Bad Request";
    case 401: return "Unauthorized";
    case 404: return "Not Found";
    case 405: return "Method Not Allowed";
    case 413: return "Payload Too Large";
    default: return "Internal Server Error";
  }
}

// Seldon predict response body: {"data": {...}, "meta": {...}} — the wire
// format serving/server.py and ccfd_front_respond produce, byte-compatible.
std::string format_predict_body(const float* probas, int rows,
                                const char* model) {
  std::string body;
  body.reserve(64 + static_cast<size_t>(rows) * 48);
  body += "{\"data\": {\"names\": [\"proba_0\", \"proba_1\"], \"ndarray\": [";
  char num[64];
  for (int r = 0; r < rows; ++r) {
    double p = static_cast<double>(probas[r]);
    if (r) body += ", ";
    snprintf(num, sizeof(num), "[%.17g, %.17g]", 1.0 - p, p);
    body += num;
  }
  body += "]}, \"meta\": {\"model\": \"";
  body += model;
  body += "\"}}";
  return body;
}

float stable_sigmoid(float z) {
  // overflow-safe in both tails (same shape as utils/metrics_math.py)
  if (z >= 0.0f) return 1.0f / (1.0f + expf(-z));
  float e = expf(z);
  return e / (1.0f + e);
}

// Dense forward: normalize -> relu hidden layers -> sigmoid head.
//
// Layout + explicit SIMD are the whole game here. Lessons baked in (each
// measured on the 30->256->256->1 flagship MLP, 1-vCPU serving host):
// - a per-row scalar loop runs ~2 GFLOP/s (latency-bound accumulator
//   chain): ~60us/row — 10x WORSE than numpy+BLAS;
// - rows therefore process in tiles of kTile with activations TRANSPOSED
//   (feature-major: act[j] is one 16-lane vector over the tile's rows),
//   so every op vectorizes over rows the way BLAS kernels do;
// - gcc-12's autovectorizer scalarizes this loop in context (it only
//   vectorizes it as an isolated function), so the kernel uses explicit
//   GCC vector extensions (v16) — lowered to zmm on AVX512, 2x ymm on
//   AVX2 — instead of hoping;
// - each activation lane load must feed SEVERAL outputs' FMAs (register
//   blocking of 4) or the kernel is load-bound re-streaming the tile.
// Result: ~1.4us/row, ~4x faster than the numpy host tier, ~45x over
// the naive loop.
typedef float v16 __attribute__((vector_size(64)));
constexpr int kTile = 16;

inline v16 splat(float s) { return ((v16){} + 1.0f) * s; }

void dense_layer_tile(const float* __restrict W, const float* __restrict B,
                      const v16* __restrict in, v16* __restrict out,
                      int in_d, int out_d, bool relu) {
  const v16 zero = {};
  int o = 0;
  for (; o + 4 <= out_d; o += 4) {
    const float* __restrict w0 = W + static_cast<size_t>(o) * in_d;
    const float* __restrict w1 = w0 + in_d;
    const float* __restrict w2 = w1 + in_d;
    const float* __restrict w3 = w2 + in_d;
    v16 a0 = splat(B[o]), a1 = splat(B[o + 1]), a2 = splat(B[o + 2]),
        a3 = splat(B[o + 3]);
    for (int j = 0; j < in_d; ++j) {
      const v16 lane = in[j];
      a0 += w0[j] * lane;
      a1 += w1[j] * lane;
      a2 += w2[j] * lane;
      a3 += w3[j] * lane;
    }
    if (relu) {
      a0 = a0 > zero ? a0 : zero;
      a1 = a1 > zero ? a1 : zero;
      a2 = a2 > zero ? a2 : zero;
      a3 = a3 > zero ? a3 : zero;
    }
    out[o] = a0;
    out[o + 1] = a1;
    out[o + 2] = a2;
    out[o + 3] = a3;
  }
  for (; o < out_d; ++o) {
    const float* __restrict wr = W + static_cast<size_t>(o) * in_d;
    v16 acc = splat(B[o]);
    for (int j = 0; j < in_d; ++j) acc += wr[j] * in[j];
    if (relu) acc = acc > zero ? acc : zero;
    out[o] = acc;
  }
}

// Per-row symmetric int8 requantization over a transposed tile: amax
// across the in_d lanes, s = max(amax/127, eps), q = clip(rint(h/s)).
// rintf under the default FE_TONEAREST mode rounds half-to-even exactly
// like np.rint, so the C++ tier reproduces ops/quant.py bit for bit.
v16 rowquant_tile(v16* __restrict cur, int in_d) {
  v16 amax = {};
  for (int j = 0; j < in_d; ++j) {
    const v16 a = cur[j] < 0.0f ? -cur[j] : cur[j];
    amax = amax > a ? amax : a;
  }
  v16 s = amax / 127.0f;
  const v16 eps = splat(1e-8f);
  s = s > eps ? s : eps;
  for (int j = 0; j < in_d; ++j) {
    const v16 scaled = cur[j] / s;
    float* lane = reinterpret_cast<float*>(cur + j);
    const float* sl = reinterpret_cast<const float*>(&scaled);
    for (int t = 0; t < kTile; ++t) {
      float q = rintf(sl[t]);
      q = q < -127.0f ? -127.0f : (q > 127.0f ? 127.0f : q);
      lane[t] = q;
    }
  }
  return s;
}

// One quantized dense layer on a tile: integer-exact f32 dot of the
// (already row-quantized) activations against the int8-valued weights,
// accumulated from ZERO, then dequant (acc * s_row) * scale_o + b_o
// in exactly apply_numpy's multiplication order.
void q8_dense_layer_tile(const float* __restrict W, const float* __restrict B,
                         const float* __restrict S, const v16 s_row,
                         const v16* __restrict in, v16* __restrict out,
                         int in_d, int out_d, bool relu) {
  const v16 zero = {};
  for (int o = 0; o < out_d; ++o) {
    const float* __restrict wr = W + static_cast<size_t>(o) * in_d;
    v16 acc = {};
    for (int j = 0; j < in_d; ++j) acc += wr[j] * in[j];
    v16 r = (acc * s_row) * S[o] + splat(B[o]);
    if (relu) r = r > zero ? r : zero;
    out[o] = r;
  }
}

void host_q8_score(const HostModel* m, const float* rows, int n_rows,
                   int n_features, float* proba_out) {
  int max_d = 0;
  for (int d : m->dims) max_d = d > max_d ? d : max_d;
  std::vector<v16> buf0(max_d), buf1(max_d);
  for (int start = 0; start < n_rows; start += kTile) {
    const int tr = n_rows - start < kTile ? n_rows - start : kTile;
    v16* cur = buf0.data();
    for (int j = 0; j < m->dims[0]; ++j) {
      float* lane = reinterpret_cast<float*>(cur + j);
      const float muj = m->mu.empty() ? 0.0f : m->mu[j];
      const float sgj = m->sigma.empty() ? 1.0f : m->sigma[j];
      for (int t = 0; t < tr; ++t)
        lane[t] =
            (rows[static_cast<size_t>(start + t) * n_features + j] - muj) /
            sgj;
      for (int t = tr; t < kTile; ++t) lane[t] = 0.0f;
    }
    v16* nxt = buf1.data();
    for (int l = 0; l < m->n_layers; ++l) {
      const v16 s_row = rowquant_tile(cur, m->dims[l]);
      q8_dense_layer_tile(m->w[l].data(), m->b[l].data(),
                          m->scale[l].data(), s_row, cur, nxt, m->dims[l],
                          m->dims[l + 1], l != m->n_layers - 1);
      v16* tmp = cur;
      cur = nxt;
      nxt = tmp;
    }
    const float* z = reinterpret_cast<const float*>(cur);
    for (int t = 0; t < tr; ++t)
      proba_out[start + t] = stable_sigmoid(z[t]);
  }
}

// Boosted-ensemble eval: per row, every tree descends its D levels in a
// tight scalar loop over tiny resident arrays (a 100-tree depth-4
// ensemble is ~400 compare+index steps ≈ 1-2us/row — the gathers don't
// vectorize with portable vector extensions, and don't need to).
void host_trees_score(const HostModel* m, const float* rows, int n_rows,
                      int n_features, float* proba_out) {
  const int n_int = (1 << m->tree_depth) - 1;
  const int n_leaf = 1 << m->tree_depth;
  for (int r = 0; r < n_rows; ++r) {
    const float* x = rows + static_cast<size_t>(r) * n_features;
    float acc = m->t_base;
    for (int t = 0; t < m->n_trees; ++t) {
      const int32_t* feat = m->t_feat.data() + static_cast<size_t>(t) * n_int;
      const float* thr = m->t_thr.data() + static_cast<size_t>(t) * n_int;
      int idx = 0;
      for (int level = 0; level < m->tree_depth; ++level) {
        const int32_t f = feat[idx];
        const float xv = (f >= 0 && f < n_features) ? x[f] : 0.0f;
        idx = 2 * idx + 1 + (xv > thr[idx] ? 1 : 0);
      }
      acc += m->t_leaf[static_cast<size_t>(t) * n_leaf + (idx - n_int)];
    }
    proba_out[r] = stable_sigmoid(acc);
  }
}

void host_model_score(const HostModel* m, const float* rows, int n_rows,
                      int n_features, float* proba_out) {
  if (m->n_trees > 0) {
    host_trees_score(m, rows, n_rows, n_features, proba_out);
    return;
  }
  if (m->q8) {
    host_q8_score(m, rows, n_rows, n_features, proba_out);
    return;
  }
  int max_d = 0;
  for (int d : m->dims) max_d = d > max_d ? d : max_d;
  std::vector<v16> buf0(max_d), buf1(max_d);  // v16 allocations are aligned
  for (int start = 0; start < n_rows; start += kTile) {
    const int tr = n_rows - start < kTile ? n_rows - start : kTile;
    v16* cur = buf0.data();
    // load transposed (+normalize); pad lanes beyond tr with zeros
    for (int j = 0; j < m->dims[0]; ++j) {
      float* lane = reinterpret_cast<float*>(cur + j);
      const float muj = m->mu.empty() ? 0.0f : m->mu[j];
      const float isj = m->mu.empty() ? 1.0f : m->inv_sigma[j];
      for (int t = 0; t < tr; ++t)
        lane[t] =
            (rows[static_cast<size_t>(start + t) * n_features + j] - muj) *
            isj;
      for (int t = tr; t < kTile; ++t) lane[t] = 0.0f;
    }
    v16* nxt = buf1.data();
    for (int l = 0; l < m->n_layers; ++l) {
      dense_layer_tile(m->w[l].data(), m->b[l].data(), cur, nxt, m->dims[l],
                       m->dims[l + 1], l != m->n_layers - 1);
      v16* tmp = cur;
      cur = nxt;
      nxt = tmp;
    }
    const float* z = reinterpret_cast<const float*>(cur);
    for (int t = 0; t < tr; ++t)
      proba_out[start + t] = stable_sigmoid(z[t]);
  }
}

std::string make_response(int status, const char* ctype, const char* body,
                          size_t body_len) {
  char head[256];
  int n = snprintf(head, sizeof(head),
                   "HTTP/1.1 %d %s\r\nContent-Type: %s\r\n"
                   "Content-Length: %zu\r\n\r\n",
                   status, reason_of(status), ctype, body_len);
  std::string out;
  out.reserve(n + body_len);
  out.append(head, n);
  out.append(body, body_len);
  return out;
}

void queue_write(Front* f, int fd, std::string data);  // fwd

// Locking discipline: every function below (handle_one_request,
// queue_write, flush_conn, close_conn) REQUIRES f->mu held by the caller
// — std::mutex is non-recursive, so nothing here may lock it again.

// Parse one complete request out of c->in; returns false if incomplete.
bool handle_one_request(Front* f, int fd, Conn* c) {
  size_t head_end = c->in.find("\r\n\r\n");
  if (head_end == std::string::npos) {
    if (c->in.size() > kMaxHead) {
      queue_write(f, fd, make_response(400, "text/plain", "head too large", 14));
      c->want_close = true;
    }
    return false;
  }
  // request line
  size_t line_end = c->in.find("\r\n");
  std::string line = c->in.substr(0, line_end);
  size_t sp1 = line.find(' ');
  size_t sp2 = line.find(' ', sp1 + 1);
  if (sp1 == std::string::npos) {
    queue_write(f, fd, make_response(400, "text/plain", "bad request line", 16));
    c->want_close = true;
    return false;
  }
  std::string method = line.substr(0, sp1);
  std::string path = sp2 == std::string::npos ? line.substr(sp1 + 1)
                                              : line.substr(sp1 + 1, sp2 - sp1 - 1);
  // headers we care about: content-length, authorization, connection
  size_t content_length = 0;
  std::string auth_header;
  bool close_conn = false;
  size_t pos = line_end + 2;
  while (pos < head_end) {
    size_t eol = c->in.find("\r\n", pos);
    if (eol == std::string::npos || eol > head_end) eol = head_end;
    size_t colon = c->in.find(':', pos);
    if (colon != std::string::npos && colon < eol) {
      std::string key = c->in.substr(pos, colon - pos);
      for (auto& ch : key) ch = tolower(ch);
      size_t vstart = colon + 1;
      while (vstart < eol && (c->in[vstart] == ' ' || c->in[vstart] == '\t'))
        ++vstart;
      std::string val = c->in.substr(vstart, eol - vstart);
      while (!val.empty() && (val.back() == ' ' || val.back() == '\t'))
        val.pop_back();  // trailing OWS is legal in a field line (RFC 9110)
      if (key == "content-length") {
        // a non-numeric length silently read as 0 would leave the body
        // bytes in the buffer to be parsed as the NEXT request line —
        // reject like the Python transport does
        char* endp = nullptr;
        content_length = strtoul(val.c_str(), &endp, 10);
        if (val.empty() || endp == val.c_str() || *endp != '\0') {
          queue_write(f, fd,
                      make_response(400, "text/plain", "bad content-length", 18));
          c->want_close = true;
          return false;
        }
      } else if (key == "authorization") {
        auth_header = val;
      } else if (key == "connection") {
        for (auto& ch : val) ch = tolower(ch);
        close_conn = (val == "close");
      }
    }
    pos = eol + 2;
  }
  if (content_length > kMaxBody) {
    queue_write(f, fd, make_response(413, "text/plain", "body too large", 14));
    c->want_close = true;
    return false;
  }
  size_t total = head_end + 4 + content_length;
  if (c->in.size() < total) return false;  // body incomplete
  std::string body = c->in.substr(head_end + 4, content_length);
  c->in.erase(0, total);
  if (close_conn) c->want_close = true;
  ++f->n_requests;

  // auth gate (Seldon bearer token, reference README.md:372-384)
  if (!f->auth.empty() && method == "POST" && auth_header != f->auth) {
    ++f->n_auth_fail;
    const char* msg = "{\"error\": \"unauthorized\"}";
    queue_write(f, fd, make_response(401, "application/json", msg, strlen(msg)));
    return true;
  }

  bool is_predict_path = false;
  int path_tag = 0;
  {
    std::string p = path;
    while (!p.empty() && p.back() == '/') p.pop_back();
    is_predict_path =
        (p.size() >= 12 && p.compare(p.size() - 12, 12, "/predictions") == 0) ||
        p == "/predict";
    if (p == "/predict") path_tag = 1;
  }
  if (method == "POST" && is_predict_path) {
    // canonical payload -> native decode -> host-tier score in THIS thread
    // (small request + host model set) or the predict queue for Python/
    // device scoring; anything odd (and anything over the native row cap)
    // falls through to Python via the misc queue (exact-contract replies)
    double t0 = now_ms();
    std::vector<float> rows;
    int est = 0;
    for (char ch : body)
      if (ch == '[') ++est;
    if (est > 0 && est <= kNativeMaxRows + 1) {
      rows.resize(static_cast<size_t>(est) * f->n_features);
      int width = 0;
      int n = ccfd_decode_ndarray(body.data(), body.size(), rows.data(), est,
                                  f->n_features, &width);
      if (n >= 0 && n <= kNativeMaxRows) {
        if (f->host != nullptr && n <= f->host->max_rows) {
          // zero-handoff path: parse -> forward -> format, one thread
          std::vector<float> proba(n > 0 ? n : 1);
          host_model_score(f->host, rows.data(), n, f->n_features,
                           proba.data());
          std::string body_out = format_predict_body(
              proba.data(), n, f->host->model_name.c_str());
          queue_write(f, fd, make_response(200, "application/json",
                                           body_out.data(), body_out.size()));
          ++f->n_host;
          double lat_s = (now_ms() - t0) / 1e3;
          int tag = path_tag ? 1 : 0;
          if (!f->host_hist[tag].empty()) {
            f->host_sum[tag] += lat_s;
            for (size_t i = 0; i < f->lat_ubs.size(); ++i)
              if (lat_s <= f->lat_ubs[i]) ++f->host_hist[tag][i];
          }
          if (n > 0) {
            const float* lastrow =
                rows.data() + static_cast<size_t>(n - 1) * f->n_features;
            f->last_gauges[0] = proba[n - 1];
            for (int g = 0; g < 3; ++g) {
              int col = f->host->gauge_cols[g];
              if (col >= 0 && col < f->n_features)
                f->last_gauges[g + 1] = lastrow[col];
            }
            f->last_gauge_ms = now_ms();
          }
          return true;
        }
        rows.resize(static_cast<size_t>(n) * f->n_features);
        int id = f->next_id++;
        f->req_route[id] = {c->gen, fd};
        f->predict_q.push_back(
            {id, fd, c->gen, n, path_tag, std::move(rows), t0});
        ++f->n_predict;
        ++c->pending;  // a Connection:close conn must outlive its answers
        f->cv.notify_all();
        return true;
      }
    }
  }
  // misc: Python answers through the shared routing logic
  int id = f->next_id++;
  f->req_route[id] = {c->gen, fd};
  f->misc_q.push_back({id, fd, c->gen, method, path, std::move(body)});
  ++f->n_misc;
  ++c->pending;
  f->cv.notify_all();
  return true;
}

void queue_write(Front* f, int fd, std::string data) {
  auto it = f->conns.find(fd);
  if (it == f->conns.end()) return;
  it->second.out += data;
}

void flush_conn(Front* f, int fd, Conn* c) {
  while (!c->out.empty()) {
    ssize_t n = send(fd, c->out.data(), c->out.size(), MSG_NOSIGNAL);
    if (n > 0) {
      c->out.erase(0, n);
    } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      // wait for EPOLLOUT; a half-closed conn must not re-arm EPOLLIN
      // here either (its EOF level-triggers forever -> busy spin)
      struct epoll_event ev;
      ev.events = EPOLLOUT | (c->read_closed ? 0 : EPOLLIN);
      ev.data.fd = fd;
      epoll_ctl(f->epoll_fd, EPOLL_CTL_MOD, fd, &ev);
      return;
    } else {
      c->want_close = true;
      return;
    }
  }
  struct epoll_event ev;
  // a half-closed conn must NOT re-arm EPOLLIN: its EOF is permanently
  // readable and would spin the loop until teardown
  ev.events = c->read_closed ? 0 : EPOLLIN;
  ev.data.fd = fd;
  epoll_ctl(f->epoll_fd, EPOLL_CTL_MOD, fd, &ev);
}

void close_conn(Front* f, int fd) {
  f->conns.erase(fd);
  epoll_ctl(f->epoll_fd, EPOLL_CTL_DEL, fd, nullptr);
  close(fd);
}

void io_loop(Front* f) {
  struct epoll_event evs[128];
  while (true) {
    int n = epoll_wait(f->epoll_fd, evs, 128, 200);
    {
      std::lock_guard<std::mutex> lk(f->mu);
      if (f->stopping) return;
      // drain responses queued by scorer/misc threads
      while (!f->resp_q.empty()) {
        Response r = std::move(f->resp_q.front());
        f->resp_q.pop_front();
        auto it = f->conns.find(r.fd);
        if (it == f->conns.end() || it->second.gen != r.gen) continue;
        it->second.out += r.data;
        if (it->second.pending > 0) --it->second.pending;
        // the connection is serialized (one Python-bound request in
        // flight keeps HTTP/1.1 responses in request order): now that
        // its answer is queued, parse any requests buffered behind it
        while (it->second.pending == 0 &&
               handle_one_request(f, r.fd, &it->second)) {
        }
      }
    }
    for (int i = 0; i < n; ++i) {
      int fd = evs[i].data.fd;
      if (fd == f->wake_fd) {
        uint64_t junk;
        while (read(f->wake_fd, &junk, 8) == 8) {
        }
        continue;
      }
      if (fd == f->listen_fd) {
        while (true) {
          int cfd = accept(f->listen_fd, nullptr, nullptr);
          if (cfd < 0) break;
          set_nonblock(cfd);
          int one = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          struct epoll_event ev;
          ev.events = EPOLLIN;
          ev.data.fd = cfd;
          epoll_ctl(f->epoll_fd, EPOLL_CTL_ADD, cfd, &ev);
          std::lock_guard<std::mutex> lk(f->mu);
          Conn c;
          c.gen = f->gen_counter++;
          f->conns.emplace(cfd, std::move(c));
        }
        continue;
      }
      auto it = f->conns.find(fd);
      if (it == f->conns.end()) continue;
      Conn* c = &it->second;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        std::lock_guard<std::mutex> lk(f->mu);
        close_conn(f, fd);
        continue;
      }
      if (evs[i].events & EPOLLIN) {
        char buf[1 << 16];
        bool peer_closed = false;
        while (true) {
          ssize_t r = recv(fd, buf, sizeof(buf), 0);
          if (r > 0) {
            c->in.append(buf, r);
            if (c->in.size() > kMaxBody + kMaxHead) {
              c->want_close = true;
              break;
            }
          } else if (r == 0) {
            peer_closed = true;
            break;
          } else {
            break;  // EAGAIN or error
          }
        }
        {
          std::lock_guard<std::mutex> lk(f->mu);
          // serialize per connection: HTTP/1.1 requires responses in
          // request order, and Python-bound requests complete out of
          // order across the scorer/misc queues — so at most ONE is in
          // flight per connection; buffered pipelined requests parse
          // when its response drains (see resp_q loop)
          while (c->pending == 0 && handle_one_request(f, fd, c)) {
          }
        }
        if (peer_closed) {
          std::lock_guard<std::mutex> lk(f->mu);
          auto itc = f->conns.find(fd);
          if (itc == f->conns.end()) continue;
          // a half-closing client (shutdown(SHUT_WR) after the request)
          // still expects its response: defer teardown to the pending/
          // flush machinery; stop watching EPOLLIN so the permanently
          // readable EOF doesn't spin the loop
          itc->second.want_close = true;
          itc->second.read_closed = true;
          if (itc->second.pending == 0 && itc->second.out.empty()) {
            close_conn(f, fd);
          } else {
            // stop monitoring entirely while the response is produced:
            // EPOLLIN would fire forever on the EOF, and EPOLLOUT fires
            // immediately on an empty out buffer — either way a busy
            // spin. The resp-drain flush sweep delivers the answer.
            struct epoll_event ev;
            ev.events = 0;
            ev.data.fd = fd;
            epoll_ctl(f->epoll_fd, EPOLL_CTL_MOD, fd, &ev);
          }
          continue;
        }
      }
      {
        std::lock_guard<std::mutex> lk(f->mu);
        auto it2 = f->conns.find(fd);
        if (it2 == f->conns.end()) continue;
        flush_conn(f, fd, &it2->second);
        if (it2->second.want_close && it2->second.out.empty() &&
            it2->second.pending == 0)
          close_conn(f, fd);
      }
    }
    // flush conns that got responses but no epoll event this round, and
    // retire Connection:close conns whose last pending answer just left
    std::lock_guard<std::mutex> lk(f->mu);
    std::vector<int> done;
    for (auto& kv : f->conns) {
      if (!kv.second.out.empty()) flush_conn(f, kv.first, &kv.second);
      if (kv.second.want_close && kv.second.out.empty() &&
          kv.second.pending == 0)
        done.push_back(kv.first);
    }
    for (int fd : done) close_conn(f, fd);
  }
}

}  // namespace

extern "C" {

void* ccfd_front_create(const char* host, int port, int n_features,
                        const char* auth_token, int* port_out) {
  Front* f = new Front();
  f->n_features = n_features;
  if (auth_token != nullptr && auth_token[0] != '\0')
    f->auth = std::string("Bearer ") + auth_token;
  f->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (f->listen_fd < 0) {
    delete f;
    return nullptr;
  }
  int one = 1;
  setsockopt(f->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  if (host != nullptr && host[0] != '\0' &&
      strcmp(host, "0.0.0.0") != 0) {
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      close(f->listen_fd);
      delete f;
      return nullptr;  // unparseable bind host: caller falls back
    }
  }
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(f->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      listen(f->listen_fd, 256) < 0) {
    close(f->listen_fd);
    delete f;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(f->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  f->port = ntohs(addr.sin_port);
  if (port_out != nullptr) *port_out = f->port;
  set_nonblock(f->listen_fd);
  f->epoll_fd = epoll_create1(0);
  f->wake_fd = eventfd(0, EFD_NONBLOCK);
  struct epoll_event ev;
  ev.events = EPOLLIN;
  ev.data.fd = f->listen_fd;
  epoll_ctl(f->epoll_fd, EPOLL_CTL_ADD, f->listen_fd, &ev);
  ev.data.fd = f->wake_fd;
  epoll_ctl(f->epoll_fd, EPOLL_CTL_ADD, f->wake_fd, &ev);
  f->io_thread = std::thread(io_loop, f);
  return f;
}

// Dequeue up to max_reqs predict requests / max_rows total rows as ONE
// concatenated row block. meta_out: [id, n_rows, path_tag] per request;
// enq_ms_out: per-request enqueue timestamps (CLOCK_MONOTONIC ms).
// Returns the number of requests (0 on timeout, -1 when stopping).
int ccfd_front_take(void* h, float* rows_out, int max_rows, int* meta_out,
                    double* enq_ms_out, int max_reqs, int timeout_ms) {
  Front* f = static_cast<Front*>(h);
  std::unique_lock<std::mutex> lk(f->mu);
  if (f->predict_q.empty()) {
    f->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                   [f] { return f->stopping || !f->predict_q.empty(); });
  }
  if (f->stopping) return -1;
  int n_reqs = 0;
  int rows_used = 0;
  while (!f->predict_q.empty() && n_reqs < max_reqs) {
    PredictReq& r = f->predict_q.front();
    if (rows_used + r.n_rows > max_rows) {
      if (n_reqs == 0) {
        // defensive: a request bigger than the taker's whole buffer
        // (impossible while kNativeMaxRows <= the taker's max_rows, but
        // a misconfigured caller must not wedge the queue head) — fail
        // it rather than starve everything behind it
        const char* msg = "{\"error\": \"request exceeds native batch\"}";
        Response resp;
        resp.data = make_response(500, "application/json", msg, strlen(msg));
        auto it = f->req_route.find(r.id);
        if (it != f->req_route.end()) {
          resp.gen = it->second.first;
          resp.fd = it->second.second;
          f->req_route.erase(it);
          f->resp_q.push_back(std::move(resp));
        }
        f->predict_q.pop_front();
        continue;
      }
      break;
    }
    memcpy(rows_out + static_cast<size_t>(rows_used) * f->n_features,
           r.rows.data(), r.rows.size() * sizeof(float));
    meta_out[3 * n_reqs] = r.id;
    meta_out[3 * n_reqs + 1] = r.n_rows;
    meta_out[3 * n_reqs + 2] = r.path_tag;
    enq_ms_out[n_reqs] = r.enq_monotonic_ms;
    rows_used += r.n_rows;
    ++n_reqs;
    f->predict_q.pop_front();
  }
  return n_reqs;
}

// Respond to previously taken predict requests: probas holds one float per
// row in take() order; C++ formats the Seldon response body per request.
void ccfd_front_respond(void* h, const int* req_ids, const int* row_counts,
                        int n_reqs, const float* probas, const char* model) {
  Front* f = static_cast<Front*>(h);
  int off = 0;
  std::vector<Response> ready;
  ready.reserve(n_reqs);
  for (int i = 0; i < n_reqs; ++i) {
    int rows = row_counts[i];
    std::string body = format_predict_body(probas + off, rows, model);
    off += rows;
    Response resp;
    resp.data = make_response(200, "application/json", body.data(), body.size());
    ready.push_back(std::move(resp));
  }
  {
    std::lock_guard<std::mutex> lk(f->mu);
    for (int i = 0; i < n_reqs; ++i) {
      auto it = f->req_route.find(req_ids[i]);
      if (it == f->req_route.end()) continue;
      ready[i].gen = it->second.first;
      ready[i].fd = it->second.second;
      f->req_route.erase(it);
      f->resp_q.push_back(std::move(ready[i]));
    }
  }
  uint64_t one = 1;
  ssize_t ignored = write(f->wake_fd, &one, 8);
  (void)ignored;
}

// Nonblocking take of one misc request (GET /prometheus, non-canonical
// POST bodies, ...). Returns req id (>0), 0 if none, -1 when stopping.
// method/path copy into fixed buffers; body via a malloc'd pointer the
// caller frees with ccfd_front_free.
int ccfd_front_take_misc(void* h, char* method_out, int method_cap,
                         char* path_out, int path_cap, char** body_out,
                         int* body_len_out, int timeout_ms) {
  Front* f = static_cast<Front*>(h);
  std::unique_lock<std::mutex> lk(f->mu);
  if (f->misc_q.empty()) {
    f->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                   [f] { return f->stopping || !f->misc_q.empty(); });
  }
  if (f->stopping) return -1;
  if (f->misc_q.empty()) return 0;
  MiscReq r = std::move(f->misc_q.front());
  f->misc_q.pop_front();
  snprintf(method_out, method_cap, "%s", r.method.c_str());
  snprintf(path_out, path_cap, "%s", r.path.c_str());
  char* body = static_cast<char*>(malloc(r.body.size() + 1));
  memcpy(body, r.body.data(), r.body.size());
  body[r.body.size()] = '\0';
  *body_out = body;
  *body_len_out = static_cast<int>(r.body.size());
  return r.id;
}

void ccfd_front_free(char* p) { free(p); }

void ccfd_front_respond_misc(void* h, int req_id, int status,
                             const char* ctype, const char* body,
                             int body_len) {
  Front* f = static_cast<Front*>(h);
  Response resp;
  resp.data = make_response(status, ctype, body, body_len);
  {
    std::lock_guard<std::mutex> lk(f->mu);
    auto it = f->req_route.find(req_id);
    if (it == f->req_route.end()) return;
    resp.gen = it->second.first;
    resp.fd = it->second.second;
    f->req_route.erase(it);
    f->resp_q.push_back(std::move(resp));
  }
  uint64_t one = 1;
  ssize_t ignored = write(f->wake_fd, &one, 8);
  (void)ignored;
}

void ccfd_front_stats(void* h, long* out4) {
  Front* f = static_cast<Front*>(h);
  std::lock_guard<std::mutex> lk(f->mu);
  out4[0] = f->n_requests;
  out4[1] = f->n_predict;
  out4[2] = f->n_misc;
  out4[3] = f->n_auth_fail;
}

namespace {
// Shared install protocol for every host-model family: fill the common
// fields and swap the pointer under the front's mutex. One copy of the
// swap discipline — the per-family setters only build their payload.
void install_host_model(Front* f, HostModel* m, int max_rows,
                        const char* model_name, const int* gauge_cols) {
  if (m != nullptr) {
    m->max_rows = max_rows;
    m->model_name = model_name != nullptr ? model_name : "model";
    if (gauge_cols != nullptr)
      for (int g = 0; g < 3; ++g) m->gauge_cols[g] = gauge_cols[g];
  }
  HostModel* old;
  {
    std::lock_guard<std::mutex> lk(f->mu);
    old = f->host;
    f->host = m;
  }
  delete old;
}
}  // namespace

// Install/replace the in-front host-tier model. weights holds the layers
// concatenated, each (dims[l+1] x dims[l]) ROW-MAJOR — i.e. transposed
// from the Python (in x out) layout so every output neuron's weights are
// contiguous. biases likewise concatenated. mean/inv_std are n_features
// normalizer vectors (both null = identity). gauge_cols: column indices
// for the Amount/V17/V10 gauges (-1 = absent). n_layers <= 0 or
// max_rows <= 0 clears the model (requests flow to the Python takers).
void ccfd_front_set_host_model(void* h, int n_layers, const int* dims,
                               const float* weights, const float* biases,
                               const float* mean, const float* inv_std,
                               int max_rows, const char* model_name,
                               const int* gauge_cols) {
  Front* f = static_cast<Front*>(h);
  HostModel* m = nullptr;
  if (n_layers > 0 && max_rows > 0) {
    m = new HostModel();
    m->n_layers = n_layers;
    m->dims.assign(dims, dims + n_layers + 1);
    size_t w_off = 0;
    size_t b_off = 0;
    for (int l = 0; l < n_layers; ++l) {
      size_t w_n = static_cast<size_t>(m->dims[l]) * m->dims[l + 1];
      m->w.emplace_back(weights + w_off, weights + w_off + w_n);
      w_off += w_n;
      m->b.emplace_back(biases + b_off, biases + b_off + m->dims[l + 1]);
      b_off += m->dims[l + 1];
    }
    if (mean != nullptr && inv_std != nullptr) {
      m->mu.assign(mean, mean + m->dims[0]);
      m->inv_sigma.assign(inv_std, inv_std + m->dims[0]);
    }
  }
  install_host_model(f, m, max_rows, model_name, gauge_cols);
}

// Install/replace the int8-quantized in-front model (the q8 analog of
// ccfd_front_set_host_model): weights holds the per-layer int8 weight
// VALUES widened to float, (dims[l+1] x dims[l]) row-major concatenated;
// scales the per-output dequant scales concatenated; mean/sigma the RAW
// normalizer (the q8 path divides by sigma — see HostModel::sigma).
// Scoring semantics are ops/quant.py apply_numpy, bit for bit.
void ccfd_front_set_host_q8_model(void* h, int n_layers, const int* dims,
                                  const float* weights, const float* scales,
                                  const float* biases, const float* mean,
                                  const float* sigma, int max_rows,
                                  const char* model_name,
                                  const int* gauge_cols) {
  Front* f = static_cast<Front*>(h);
  // integer-exactness bound: every partial sum must stay an integer below
  // 2^24; 127*127*N < 2^24 requires layer widths N <= 1040. A wider model
  // would silently lose the bit-parity contract — refuse the install
  // (requests flow to the Python takers, whose int32 math has no bound).
  bool exact = true;
  for (int l = 0; l < n_layers; ++l)
    if (dims[l] > 1040) exact = false;
  HostModel* m = nullptr;
  if (n_layers > 0 && max_rows > 0 && exact) {
    m = new HostModel();
    m->q8 = true;
    m->n_layers = n_layers;
    m->dims.assign(dims, dims + n_layers + 1);
    size_t w_off = 0;
    size_t b_off = 0;
    for (int l = 0; l < n_layers; ++l) {
      size_t w_n = static_cast<size_t>(m->dims[l]) * m->dims[l + 1];
      m->w.emplace_back(weights + w_off, weights + w_off + w_n);
      w_off += w_n;
      m->b.emplace_back(biases + b_off, biases + b_off + m->dims[l + 1]);
      m->scale.emplace_back(scales + b_off, scales + b_off + m->dims[l + 1]);
      b_off += m->dims[l + 1];
    }
    if (mean != nullptr && sigma != nullptr) {
      m->mu.assign(mean, mean + m->dims[0]);
      m->sigma.assign(sigma, sigma + m->dims[0]);
    }
  }
  install_host_model(f, m, max_rows, model_name, gauge_cols);
}

// Install/replace an in-front boosted-tree ensemble (the tree analog of
// ccfd_front_set_host_model): feat/thr are (n_trees x 2^depth-1), leaf is
// (n_trees x 2^depth), heap layout, identical semantics to the XLA
// evaluator in models/trees.py. n_trees <= 0 or max_rows <= 0 clears.
void ccfd_front_set_host_trees(void* h, int n_trees, int depth,
                               const int32_t* feat, const float* thr,
                               const float* leaf, float base, int max_rows,
                               const char* model_name,
                               const int* gauge_cols) {
  Front* f = static_cast<Front*>(h);
  HostModel* m = nullptr;
  if (n_trees > 0 && depth > 0 && max_rows > 0) {
    m = new HostModel();
    m->n_trees = n_trees;
    m->tree_depth = depth;
    const size_t n_int = (static_cast<size_t>(1) << depth) - 1;
    const size_t n_leaf = static_cast<size_t>(1) << depth;
    m->t_feat.assign(feat, feat + n_trees * n_int);
    m->t_thr.assign(thr, thr + n_trees * n_int);
    m->t_leaf.assign(leaf, leaf + n_trees * n_leaf);
    m->t_base = base;
  }
  install_host_model(f, m, max_rows, model_name, gauge_cols);
}

// Latency-histogram bucket layout for host-scored requests; must match the
// Python registry's histogram so cumulative counts fold 1:1 at scrape.
void ccfd_front_set_latency_buckets(void* h, const double* ubs, int n) {
  Front* f = static_cast<Front*>(h);
  std::lock_guard<std::mutex> lk(f->mu);
  f->lat_ubs.assign(ubs, ubs + n);
  for (int tag = 0; tag < 2; ++tag) {
    f->host_hist[tag].assign(static_cast<size_t>(n), 0);
    f->host_sum[tag] = 0.0;
  }
}

// Cumulative host-scored metrics: out_counts = 2 x n_buckets le-counts
// (tag 0 then tag 1), out_sums = 2 latency sums, gauges = last
// proba_1/Amount/V17/V10. Returns n_host; *last_gauge_ms_out is the
// CLOCK_MONOTONIC ms of the newest host-scored gauge update so the
// scraper can order it against Python-path gauge writes (same clock as
// Python's time.monotonic) instead of overwriting newer values.
long ccfd_front_host_stats(void* h, long* out_counts, double* out_sums,
                           float* gauges, double* last_gauge_ms_out) {
  Front* f = static_cast<Front*>(h);
  std::lock_guard<std::mutex> lk(f->mu);
  size_t nb = f->lat_ubs.size();
  for (int tag = 0; tag < 2; ++tag) {
    for (size_t i = 0; i < nb; ++i)
      out_counts[tag * nb + i] = f->host_hist[tag].empty()
                                     ? 0
                                     : f->host_hist[tag][i];
    out_sums[tag] = f->host_sum[tag];
  }
  for (int g = 0; g < 4; ++g) gauges[g] = f->last_gauges[g];
  if (last_gauge_ms_out != nullptr) *last_gauge_ms_out = f->last_gauge_ms;
  return f->n_host;
}

// Stop serving: wakes takers (they return -1) and joins the IO thread,
// but does NOT free the Front — Python threads may still be inside
// take()/take_misc() on this pointer. The caller joins its worker
// threads and then calls ccfd_front_destroy.
void ccfd_front_stop(void* h) {
  Front* f = static_cast<Front*>(h);
  {
    std::lock_guard<std::mutex> lk(f->mu);
    f->stopping = true;
    f->cv.notify_all();
  }
  uint64_t one = 1;
  ssize_t ignored = write(f->wake_fd, &one, 8);
  (void)ignored;
  if (f->io_thread.joinable()) f->io_thread.join();
  {
    std::lock_guard<std::mutex> lk(f->mu);
    for (auto& kv : f->conns) close(kv.first);
    f->conns.clear();
  }
  close(f->listen_fd);
  // epoll_fd/wake_fd stay OPEN until destroy: a worker wedged inside a
  // device dispatch may still call respond() after stop(), and writing
  // the wake token to a closed (possibly REUSED) fd would inject bytes
  // into an unrelated stream. An unread eventfd write is harmless.
}

void ccfd_front_destroy(void* h) {
  Front* f = static_cast<Front*>(h);
  close(f->epoll_fd);
  close(f->wake_fd);
  delete f->host;
  delete f;
}

}  // extern "C"

#else  // !__linux__: stubs — native front unavailable, Python transport used

#include <cstddef>
#include <cstdint>

extern "C" {

void* ccfd_front_create(const char*, int, int, const char*, int*) {
  return nullptr;
}
int ccfd_front_take(void*, float*, int, int*, double*, int, int) { return -1; }
void ccfd_front_respond(void*, const int*, const int*, int, const float*,
                        const char*) {}
int ccfd_front_take_misc(void*, char*, int, char*, int, char**, int*, int) {
  return -1;
}
void ccfd_front_free(char*) {}
void ccfd_front_respond_misc(void*, int, int, const char*, const char*, int) {}
void ccfd_front_stats(void*, long* out4) {
  out4[0] = out4[1] = out4[2] = out4[3] = 0;
}
void ccfd_front_set_host_model(void*, int, const int*, const float*,
                               const float*, const float*, const float*, int,
                               const char*, const int*) {}
void ccfd_front_set_host_q8_model(void*, int, const int*, const float*,
                                  const float*, const float*, const float*,
                                  const float*, int, const char*,
                                  const int*) {}
void ccfd_front_set_host_trees(void*, int, int, const int32_t*, const float*,
                               const float*, float, int, const char*,
                               const int*) {}
void ccfd_front_set_latency_buckets(void*, const double*, int) {}
long ccfd_front_host_stats(void*, long*, double*, float*, double*) {
  return 0;
}
void ccfd_front_stop(void*) {}
void ccfd_front_destroy(void*) {}

}  // extern "C"

#endif  // __linux__
