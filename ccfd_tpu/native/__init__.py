"""Native (C++) hot-path bindings with a transparent numpy fallback.

Builds ``decode.cpp`` with g++ on first import (cached next to the source),
loads it via ctypes, and exposes:

- ``decode_csv(data: bytes, n_features) -> (np.ndarray (B, F) f32, bad_rows)``
- ``pad_batch(x, bucket_rows) -> np.ndarray (bucket, F) f32``

If no toolchain is available the numpy implementations (identical
semantics, asserted by tests/test_native.py) are used — the framework never
hard-requires a compiler at runtime.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRCS = [
    os.path.join(_HERE, "decode.cpp"),
    os.path.join(_HERE, "log.cpp"),
    os.path.join(_HERE, "httpfront.cpp"),
]
_SO = os.path.join(_HERE, "_ccfd_native.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _build() -> str | None:
    present = [s for s in _SRCS if os.path.exists(s)]
    if os.path.exists(_SO) and (
        len(present) < len(_SRCS)  # sources (partially) stripped: a
        # rebuild is impossible, so trust the shipped .so
        or os.path.getmtime(_SO) >= max(os.path.getmtime(s) for s in present)
    ):
        return _SO
    if len(present) < len(_SRCS):
        return None  # no .so and no complete sources: numpy fallback
    # CCFD_NATIVE_MARCH overrides the target microarchitecture: container
    # images built on one CPU and deployed to another must NOT bake the
    # builder's -march=native (a zmm-tuned .so can SIGILL on the deploy
    # node) — e.g. x86-64-v3 is the portable-with-AVX2 choice
    march = os.environ.get("CCFD_NATIVE_MARCH", "native")
    try:
        subprocess.run(
            ["g++", "-O3", f"-march={march}", "-shared", "-fPIC", "-pthread",
             *_SRCS, "-o", _SO],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return _SO
    except (OSError, subprocess.SubprocessError):
        return None


def _load():
    global _lib, _build_failed
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        path = _build()
        if path is None:
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            # a shipped .so that won't load here (glibc/arch mismatch on a
            # different deploy node): rebuild from sources when possible,
            # else degrade to the numpy paths — never hard-fail the caller
            try:
                os.remove(path)
            except OSError:
                pass
            path = _build()
            if path is None:
                _build_failed = True
                return None
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                _build_failed = True
                return None
        lib.ccfd_decode_csv.restype = ctypes.c_int
        lib.ccfd_decode_csv.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.ccfd_decode_ndarray.restype = ctypes.c_int
        lib.ccfd_decode_ndarray.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.ccfd_pad_batch.restype = None
        lib.ccfd_pad_batch.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int,
        ]
        lib.ccfd_front_create.restype = ctypes.c_void_p
        lib.ccfd_front_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.ccfd_front_take.restype = ctypes.c_int
        lib.ccfd_front_take.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_double),
            ctypes.c_int, ctypes.c_int,
        ]
        lib.ccfd_front_respond.restype = None
        lib.ccfd_front_respond.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.c_char_p,
        ]
        lib.ccfd_front_take_misc.restype = ctypes.c_int
        lib.ccfd_front_take_misc.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_int, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ]
        lib.ccfd_front_free.restype = None
        lib.ccfd_front_free.argtypes = [ctypes.c_void_p]
        lib.ccfd_front_respond_misc.restype = None
        lib.ccfd_front_respond_misc.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_int,
        ]
        lib.ccfd_front_stats.restype = None
        lib.ccfd_front_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_long)
        ]
        lib.ccfd_front_set_host_model.restype = None
        lib.ccfd_front_set_host_model.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
        ]
        # newer symbol: a shipped pre-q8 .so loaded via the trust path
        # (sources stripped) must degrade to "q8 pusher unavailable",
        # not hard-fail every native entry point
        if hasattr(lib, "ccfd_front_set_host_q8_model"):
            lib.ccfd_front_set_host_q8_model.restype = None
            lib.ccfd_front_set_host_q8_model.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
            ]
        lib.ccfd_front_set_host_trees.restype = None
        lib.ccfd_front_set_host_trees.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_float, ctypes.c_int,
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
        ]
        lib.ccfd_front_set_latency_buckets.restype = None
        lib.ccfd_front_set_latency_buckets.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_double), ctypes.c_int,
        ]
        lib.ccfd_front_host_stats.restype = ctypes.c_long
        lib.ccfd_front_host_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.ccfd_front_stop.restype = None
        lib.ccfd_front_stop.argtypes = [ctypes.c_void_p]
        lib.ccfd_front_destroy.restype = None
        lib.ccfd_front_destroy.argtypes = [ctypes.c_void_p]
        lib.ccfd_log_frame.restype = ctypes.c_size_t
        lib.ccfd_log_frame.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.ccfd_log_scan.restype = ctypes.c_int
        lib.ccfd_log_scan.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# numpy reference implementations (identical semantics)


def _decode_csv_numpy(data: bytes, n_features: int) -> tuple[np.ndarray, int]:
    lines = data.decode("utf-8", errors="replace").splitlines()
    out = np.zeros((len(lines), n_features), np.float32)
    bad = 0
    for i, line in enumerate(lines):
        parts = line.split(",")
        if len(parts) != n_features:
            bad += 1
            continue
        try:
            out[i] = [float(p) for p in parts]
        except ValueError:
            out[i] = 0.0
            bad += 1
    return out, bad


def decode_csv(data: bytes, n_features: int = 30) -> tuple[np.ndarray, int]:
    """Newline-separated CSV float rows -> ((B, F) float32, #bad rows)."""
    if not data:
        return np.zeros((0, n_features), np.float32), 0
    lib = _load()
    if lib is None:
        return _decode_csv_numpy(data, n_features)
    max_rows = data.count(b"\n") + (0 if data.endswith(b"\n") else 1)
    out = np.zeros((max_rows, n_features), np.float32)
    bad = ctypes.c_int(0)
    rows = lib.ccfd_decode_csv(
        data,
        len(data),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        max_rows,
        n_features,
        ctypes.byref(bad),
    )
    return out[:rows], int(bad.value)


def decode_ndarray_json(
    body: bytes, n_features: int = 30, max_rows: int = 1 << 16
) -> np.ndarray | None:
    """Parse a canonical Seldon predict payload's ``data.ndarray`` matrix
    (reference request shape README.md:454-459) natively into (B, F)
    float32. Returns None when the payload needs the Python JSON path — a
    ``names`` key (column remapping), non-numeric cells, rows wider than
    the schema, oversize batches, malformed JSON, or no native toolchain.
    Short rows zero-pad, matching the Python decoder's semantics."""
    lib = _load()
    if lib is None or not body:
        return None
    # '[' count bounds the row count tightly (outer bracket + one per row),
    # so the scratch buffer is sized to the request, not the global cap
    max_rows = min(max_rows, body.count(b"["))
    if max_rows <= 0:
        return None
    out = np.empty((max_rows, n_features), np.float32)
    width = ctypes.c_int(0)
    rows = lib.ccfd_decode_ndarray(
        body,
        len(body),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        max_rows,
        n_features,
        ctypes.byref(width),
    )
    if rows < 0:
        return None
    return out[:rows]


def frame_records(payloads: list[bytes]) -> bytes:
    """Frame payloads as ``[u32 len][u32 crc32][payload]...`` (one buffer)."""
    if not payloads:
        return b""
    lib = _load()
    if lib is None:
        import binascii
        import struct

        parts = []
        for p in payloads:
            parts.append(struct.pack("<II", len(p), binascii.crc32(p)))
            parts.append(p)
        return b"".join(parts)
    concat = b"".join(payloads)
    lens = (ctypes.c_uint32 * len(payloads))(*[len(p) for p in payloads])
    out = ctypes.create_string_buffer(len(concat) + 8 * len(payloads))
    n = lib.ccfd_log_frame(
        concat, lens, len(payloads), ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8))
    )
    return out.raw[:n]


def scan_records(buf: bytes) -> tuple[list[bytes], int, bool]:
    """Replay a segment buffer -> (payloads, valid_prefix_len, corrupt).

    Stops at the first torn or corrupt frame; ``valid_prefix_len`` is where
    a recovering writer should truncate. ``corrupt`` distinguishes a bad
    CRC / insane length from a clean partial tail.
    """
    lib = _load()
    if lib is None:
        return _scan_records_py(buf)
    out: list[bytes] = []
    pos = 0
    corrupt = False
    chunk = 4096
    offs = (ctypes.c_uint64 * chunk)()
    lens = (ctypes.c_uint32 * chunk)()
    consumed = ctypes.c_size_t(0)
    # one buffer copy up front, then chunked scans by pointer offset —
    # re-slicing bytes per chunk would make large-segment replay O(n^2)
    base = ctypes.create_string_buffer(buf, len(buf))
    addr = ctypes.addressof(base)
    while pos < len(buf):
        n = lib.ccfd_log_scan(
            ctypes.c_char_p(addr + pos), len(buf) - pos, offs, lens, chunk,
            ctypes.byref(consumed),
        )
        got = n if n >= 0 else -n - 1  # corruption encodes -(valid+1)
        for i in range(got):
            off = pos + offs[i]
            out.append(buf[off : off + lens[i]])
        pos += consumed.value
        if n < 0:
            corrupt = True
            break
        if n < chunk:  # clean end (EOF or partial tail)
            break
    return out, pos, corrupt


def _scan_records_py(buf: bytes) -> tuple[list[bytes], int, bool]:
    import binascii
    import struct

    out: list[bytes] = []
    pos = 0
    while pos + 8 <= len(buf):
        plen, want = struct.unpack_from("<II", buf, pos)
        if plen > 1 << 30:
            return out, pos, True
        if pos + 8 + plen > len(buf):
            break
        payload = buf[pos + 8 : pos + 8 + plen]
        if binascii.crc32(payload) != want:
            return out, pos, True
        out.append(payload)
        pos += 8 + plen
    return out, pos, False


def pad_batch(x: np.ndarray, bucket_rows: int) -> np.ndarray:
    """(n, F) -> (bucket_rows, F) zero-padded float32 (truncates if larger)."""
    x = np.ascontiguousarray(x, np.float32)
    lib = _load()
    if lib is None:
        out = np.zeros((bucket_rows, x.shape[1]), np.float32)
        out[: min(len(x), bucket_rows)] = x[:bucket_rows]
        return out
    out = np.empty((bucket_rows, x.shape[1]), np.float32)
    lib.ccfd_pad_batch(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        x.shape[0],
        x.shape[1],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        bucket_rows,
    )
    return out
