// Native hot-path: CSV transaction decode + batch assembly.
//
// The reference's per-message hop runs feature extraction inside a JVM Camel
// route (reference deploy/router.yaml, README.md:549); our router instead
// assembles one (B, 30) float32 matrix per micro-batch and the Python
// dict-walk is the slowest host-side stage at high throughput. This decoder
// parses newline-separated CSV transaction rows straight into the caller's
// float32 buffer — one pass, no allocations, no Python per-field overhead.
//
// Exposed via ctypes (see ccfd_tpu/native/__init__.py); the fallback numpy
// path implements identical semantics, asserted by tests.

#include <cstddef>
#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// Parse up to max_rows CSV rows of exactly n_features floats each from
// buf[0..len) into out (row-major, max_rows * n_features floats).
// Rows with parse errors or the wrong field count are zero-filled and
// counted in *bad_rows. Returns the number of rows consumed.
int ccfd_decode_csv(const char* buf, size_t len, float* out, int max_rows,
                    int n_features, int* bad_rows) {
  int rows = 0;
  int bad = 0;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end && rows < max_rows) {
    const char* line_end = static_cast<const char*>(memchr(p, '\n', end - p));
    if (line_end == nullptr) line_end = end;
    float* row_out = out + static_cast<size_t>(rows) * n_features;
    int field = 0;
    bool ok = true;
    const char* q = p;
    while (q < line_end && field < n_features) {
      char* next = nullptr;
      float v = strtof(q, &next);
      if (next == q) {  // no parse progress
        ok = false;
        break;
      }
      row_out[field++] = v;
      q = next;
      if (q < line_end) {
        if (*q == ',') {
          ++q;
        } else if (*q != '\n' && *q != '\r') {
          ok = false;
          break;
        }
      }
    }
    // trailing \r (CRLF) is fine; any other leftover content means the row
    // had extra fields — reject it like the numpy fallback does
    while (q < line_end && *q == '\r') ++q;
    if (!ok || field != n_features || q != line_end) {
      memset(row_out, 0, sizeof(float) * n_features);
      ++bad;
    }
    ++rows;
    p = (line_end < end) ? line_end + 1 : end;
  }
  if (bad_rows != nullptr) *bad_rows = bad;
  return rows;
}

// Batch assembly: scatter variable-count rows into a zero-padded bucket.
// src is n_rows * n_features floats; dst is bucket_rows * n_features and is
// fully zeroed first (padding rows score as zeros).
void ccfd_pad_batch(const float* src, int n_rows, int n_features, float* dst,
                    int bucket_rows) {
  const size_t row_bytes = sizeof(float) * static_cast<size_t>(n_features);
  memset(dst, 0, row_bytes * static_cast<size_t>(bucket_rows));
  const int copy = n_rows < bucket_rows ? n_rows : bucket_rows;
  memcpy(dst, src, row_bytes * static_cast<size_t>(copy));
}

}  // extern "C"
