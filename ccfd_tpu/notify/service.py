"""Customer-notification simulator (the reference's notification service).

Subscribes to ``ccd-customer-outgoing``, "sends" the customer an inquiry
(simulated SMS/email), randomly decides whether the customer replies and
whether they approve, and publishes replies to ``ccd-customer-response``
(reference deploy/notification-service.yaml:50-52, README.md:410-422,
554-569, docs/images/events-2.final.png). No-reply simulates the silent
customer, which is what arms the engine's DMN timer path.

Deterministic under a seed so integration tests can script exact outcomes.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any

import numpy as np

from ccfd_tpu.bus.broker import Broker
from ccfd_tpu.config import Config
from ccfd_tpu.metrics.prom import Registry


class NotificationService:
    def __init__(
        self,
        cfg: Config,
        broker: Broker,
        registry: Registry | None = None,
        reply_prob: float = 0.8,
        approve_prob: float = 0.7,
        seed: int = 0,
        tracer=None,
    ):
        self.cfg = cfg
        self.broker = broker
        # observability/trace.py: each handled notification resumes the
        # trace context carried on the record and stamps the customer
        # response it produces, so the reply leg stays on the same trace
        self.tracer = tracer
        self.registry = registry or Registry()
        self.reply_prob = reply_prob
        self.approve_prob = approve_prob
        self._rng = np.random.default_rng(seed)
        self._consumer = broker.consumer(
            "notification-service", (cfg.customer_notification_topic,)
        )
        r = self.registry
        self._c_sent = r.counter("notifications_sent_total", "inquiries sent")
        self._c_replied = r.counter("notifications_replied_total", "replies by result")
        self._c_silent = r.counter("notifications_no_reply_total", "silent customers")
        self._stop = threading.Event()

    def step(self, max_records: int = 256, poll_timeout_s: float = 0.0) -> int:
        records = self._consumer.poll(max_records, poll_timeout_s)
        for rec in records:
            msg: dict[str, Any] = rec.value or {}
            self._c_sent.inc()
            if self._rng.random() >= self.reply_prob:
                self._c_silent.inc()
                continue  # customer never answers -> engine timer will fire
            approved = bool(self._rng.random() < self.approve_prob)
            self._c_replied.inc(
                labels={"response": "approved" if approved else "non_approved"}
            )
            span_cm = contextlib.nullcontext()
            if self.tracer is not None:
                from ccfd_tpu.observability import trace as _trace

                span_cm = self.tracer.span(
                    "notify.handle",
                    parent=_trace.extract_context(
                        getattr(rec, "headers", None)))
            with span_cm:
                resp_headers = (_trace.inject_headers()
                                if self.tracer is not None else None)
                # headers kwarg only when stamping: broker test doubles
                # that predate record headers keep working untraced
                kw = {"headers": resp_headers} if resp_headers else {}
                self.broker.produce(
                    self.cfg.customer_response_topic,
                    {
                        "process_id": msg.get("process_id"),
                        "customer_id": msg.get("customer_id"),
                        "approved": approved,
                    },
                    key=msg.get("process_id"),
                    **kw,
                )
        return len(records)

    def reset(self) -> None:
        """Re-arm after stop(); called by the supervisor before respawn
        (clearing inside run() would race a concurrent stop())."""
        self._stop.clear()

    def run(self, poll_timeout_s: float = 0.05) -> None:
        while not self._stop.is_set():
            self.step(poll_timeout_s=poll_timeout_s)

    def start(self, poll_timeout_s: float = 0.05) -> threading.Thread:
        t = threading.Thread(
            target=self.run, args=(poll_timeout_s,), daemon=True, name="ccfd-notify"
        )
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()

    def close(self) -> None:
        self.stop()
        self._consumer.close()
