"""Durable segment logs for the bus: Kafka's recovery story, one dir per cluster.

The reference's pipeline survives restarts because Kafka persists every
topic as on-disk log segments and consumers resume from committed group
offsets (reference deploy/frauddetection_cr.yaml:73-77; SURVEY.md §5
"Checkpoint / resume": "Kafka consumer offsets ... are the de-facto resume
mechanisms"). This module gives the in-process broker the same property:

- one append-only segment file per (topic, partition):  ``t<i>_p<k>.log``
- a topic catalog (``meta.log``) mapping topic names to file ids and
  partition counts, so filenames never depend on topic-name sanitization
- a committed-offsets log (``offsets.log``), appended on every group
  commit, last-write-wins on replay; the file is COMPACTED on reopen
  (rewritten to one entry per (group, topic, partition), tmp + rename)
  once the append tail dominates, so long-running durable buses don't pay
  unbounded reopen time for commit history

Retention (round 5; closes the round-4 "unbounded bus" ceiling): each
(topic, partition) is a CHAIN of segment files ``t<i>_p<k>.<base>.log``
where ``<base>`` is the offset of the segment's first record — exactly
Kafka's on-disk layout (``00000000000000000000.log``). The active segment
rolls once it passes ``segment_bytes``; ``trim_partition`` deletes whole
segments strictly below a given offset (the broker calls it with its
delete-before-committed-offset retention floor, bus/broker.py). A legacy
un-suffixed ``t<i>_p<k>.log`` replays as the base-0 segment, so pre-
rotation log dirs keep working. Offsets are permanent: a record's offset
never changes when older segments are deleted, and replay returns the
chain's base so the in-memory partition rebases correctly.

Framing is ``[u32 len][u32 crc32][payload]`` with the byte-crunching
(frame building, replay scan, torn-tail detection) in C++
(ccfd_tpu/native/log.cpp) and a bit-identical Python fallback. On reopen,
a file whose tail is torn (crashed mid-write) or corrupt is truncated to
its valid prefix — exactly Kafka's log-recovery behavior.

Durability model matches Kafka's default: every append is an ``os.write``
straight to the OS page cache (survives process crash); ``fsync=True``
additionally syncs per append for host-crash durability at a latency cost.

Record payloads carry a JSON header (key, timestamp) plus a type-tagged
value (raw bytes / utf-8 / JSON), so CSV wire lines and dict transactions
round-trip byte-exactly.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Any

from ccfd_tpu.native import frame_records, scan_records

_TAG_BYTES = 0
_TAG_STR = 1
_TAG_JSON = 2


def encode_entry(key: Any, timestamp: float, value: Any) -> bytes:
    """(key, ts, value) -> payload bytes. Bytes/str values stay byte-exact.

    Bytes keys (which partition routing supports) ride as hex under "kb";
    everything else must be JSON-able, failing here before the in-memory
    append so memory and disk never diverge.
    """
    if isinstance(key, bytes):
        header = json.dumps({"kb": key.hex(), "ts": timestamp}).encode()
    else:
        header = json.dumps({"k": key, "ts": timestamp}).encode()
    if isinstance(value, bytes):
        tag, body = _TAG_BYTES, value
    elif isinstance(value, str):
        tag, body = _TAG_STR, value.encode()
    else:
        tag, body = _TAG_JSON, json.dumps(value).encode()
    return struct.pack("<BI", tag, len(header)) + header + body


def decode_entry(payload: bytes) -> tuple[Any, float, Any]:
    tag, hlen = struct.unpack_from("<BI", payload, 0)
    header = json.loads(payload[5 : 5 + hlen])
    body = payload[5 + hlen :]
    if tag == _TAG_BYTES:
        value: Any = body
    elif tag == _TAG_STR:
        value = body.decode()
    elif tag == _TAG_JSON:
        value = json.loads(body)
    else:
        raise ValueError(f"unknown value tag {tag}")
    key = bytes.fromhex(header["kb"]) if "kb" in header else header.get("k")
    return key, float(header.get("ts", 0.0)), value


# bound the byte-wise resync scan after a mid-file corrupt frame: the
# scan is corruption-path-only, but a 64 MiB segment must not stall
# reopen for minutes hunting a resync point through garbage
_RESYNC_SCAN_BYTES = 8 * 1024 * 1024


def _count_records_past_corruption(buf: bytes, valid: int) -> int:
    """How many VALID records sit beyond a corrupt frame at ``valid``.

    Truncating at the first corrupt frame is the only offset-safe
    recovery (later records' offsets would silently shift), but doing it
    SILENTLY hides that mid-file corruption — unlike a torn tail — drops
    real, durable records. Resync by scanning forward for the next
    parseable frame chain and count what the truncation discards, so the
    loss is loud (``ccfd_storage_log_truncated_records_total``) instead
    of invisible."""
    import binascii
    import struct

    end = len(buf)
    limit = min(end - 8, valid + 1 + _RESYNC_SCAN_BYTES)
    pos = valid + 1
    while pos <= limit:
        ln, crc = struct.unpack_from("<II", buf, pos)
        if 0 < ln <= end - pos - 8 and (
                binascii.crc32(buf[pos + 8: pos + 8 + ln]) & 0xFFFFFFFF
                == crc):
            recs, _consumed, _corrupt = scan_records(buf[pos:])
            return len(recs)
        pos += 1
    return 0


class SegmentFile:
    """One append-only framed file. Replay truncates a torn/corrupt tail;
    mid-file corruption (bitrot, not a crash) truncates too — offsets
    must stay stable — but counts and loudly logs the valid records the
    truncation drops (ISSUE 13 satellite)."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = fsync
        self._fd: int | None = None

    def replay(self) -> list[bytes]:
        if not os.path.exists(self.path):
            return []
        with open(self.path, "rb") as f:
            buf = f.read()
        payloads, valid, corrupt = scan_records(buf)
        if valid < len(buf):  # crashed tail: recover the valid prefix
            if corrupt:
                dropped = _count_records_past_corruption(buf, valid)
                if dropped:
                    import logging

                    from ccfd_tpu.runtime.durability import note

                    note("log_truncated_records", dropped)
                    logging.getLogger(__name__).error(
                        "segment %s: corrupt frame at byte %d drops %d "
                        "VALID later record(s) — truncating to the valid "
                        "prefix (offsets must stay stable); re-drive from "
                        "an earlier cut recovers them", self.path, valid,
                        dropped)
            with open(self.path, "r+b") as f:
                f.truncate(valid)
        return payloads

    def _ensure_open(self) -> int:
        if self._fd is None:
            self._fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        return self._fd

    def append(self, *payloads: bytes) -> None:
        fd = self._ensure_open()
        os.write(fd, frame_records(list(payloads)))
        if self.fsync:
            os.fsync(fd)

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None


class _SegmentSeries:
    """The on-disk segment chain for one (topic, partition).

    Kafka's layout: each file is named by the offset of its first record,
    the last file is the active (append) segment, rolling at
    ``segment_bytes``, and retention deletes whole files from the front.
    Offsets are permanent — deleting old segments never renumbers
    anything; replay hands back the chain's first base so the in-memory
    partition rebases instead of assuming 0.
    """

    def __init__(self, directory: str, tid: int, part: int,
                 fsync: bool, segment_bytes: int):
        self.dir = directory
        self.prefix = f"t{tid}_p{part}"
        self.fsync = fsync
        self.segment_bytes = segment_bytes
        self.chain: list[tuple[int, str]] = []  # (base, path), ascending
        self._active: SegmentFile | None = None
        self._active_base = 0
        self._active_count = 0
        self._active_bytes = 0

    def _path(self, base: int) -> str:
        # zero-padded to 20 digits like Kafka: lexical order == offset order
        return os.path.join(self.dir, f"{self.prefix}.{base:020d}.log")

    def _discover(self) -> None:
        chain: list[tuple[int, str]] = []
        legacy = os.path.join(self.dir, self.prefix + ".log")
        if os.path.exists(legacy):  # pre-rotation dirs: the base-0 segment
            chain.append((0, legacy))
        pre = self.prefix + "."
        for name in os.listdir(self.dir):
            if name.startswith(pre) and name.endswith(".log"):
                mid = name[len(pre):-4]
                if mid.isdigit():
                    chain.append((int(mid), os.path.join(self.dir, name)))
        chain.sort()
        self.chain = chain

    def replay(self) -> tuple[int, list[bytes]]:
        """-> (base offset of the first retained record, payloads).

        Torn tails truncate to the valid prefix (Kafka log recovery). A
        truncation that is NOT in the last segment leaves every later
        segment's base pointing past a hole, so the chain keeps its
        longest offset-consistent prefix and the orphaned files are
        deleted — at-least-once replay from an earlier cut beats replaying
        records at silently wrong offsets."""
        self._discover()
        if not self.chain:
            self._active = None
            self._active_base = self._active_count = self._active_bytes = 0
            return 0, []
        base0 = self.chain[0][0]
        payloads: list[bytes] = []
        expected = base0
        kept = 0
        for i, (base, path) in enumerate(self.chain):
            if base != expected:
                for _, orphan in self.chain[i:]:
                    try:
                        os.unlink(orphan)
                    except OSError:
                        pass
                break
            seg = SegmentFile(path, self.fsync)
            recs = seg.replay()
            seg.close()
            payloads.extend(recs)
            expected = base + len(recs)
            kept = i + 1
        self.chain = self.chain[:kept]
        last_base, last_path = self.chain[-1]
        self._active = SegmentFile(last_path, self.fsync)
        self._active_base = last_base
        self._active_count = expected - last_base
        try:
            self._active_bytes = os.path.getsize(last_path)
        except OSError:
            self._active_bytes = 0
        return base0, payloads

    def append(self, *payloads: bytes) -> None:
        if self._active is None:
            self._active = SegmentFile(self._path(self._active_base),
                                       self.fsync)
            self.chain.append((self._active_base, self._active.path))
        self._active.append(*payloads)
        self._active_count += len(payloads)
        # 8 framing bytes ([u32 len][u32 crc]) per record
        self._active_bytes += sum(len(p) + 8 for p in payloads)
        if self._active_bytes >= self.segment_bytes:
            self._roll()

    def _roll(self) -> None:
        self._active.close()
        self._active_base += self._active_count
        self._active_count = 0
        self._active_bytes = 0
        self._active = SegmentFile(self._path(self._active_base), self.fsync)
        self._active._ensure_open()  # the empty active must exist on disk:
        self.chain.append((self._active_base, self._active.path))
        # a crash right after the roll otherwise replays a chain whose
        # last base has no file, and new appends would recreate it anyway

    def trim_to(self, offset: int) -> int:
        """Delete whole segments whose every record sits below ``offset``.
        The active segment is never deleted; returns segments removed."""
        n = 0
        while len(self.chain) >= 2 and self.chain[1][0] <= offset:
            _, path = self.chain.pop(0)
            try:
                os.unlink(path)
            except OSError:
                pass
            n += 1
        return n

    @property
    def start_offset(self) -> int:
        return self.chain[0][0] if self.chain else self._active_base

    def close(self) -> None:
        if self._active is not None:
            self._active.close()
            self._active = None


# 64 MiB: big enough that rotation costs nothing at demo rates, small
# enough that retention reclaims space promptly on long soaks
DEFAULT_SEGMENT_BYTES = 64 * 1024 * 1024


class BusLog:
    """Directory of segment files backing one Broker instance."""

    META = "meta.log"
    OFFSETS = "offsets.log"

    def __init__(self, directory: str, fsync: bool = False,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES):
        self.dir = directory
        self.fsync = fsync
        self.segment_bytes = segment_bytes
        os.makedirs(directory, exist_ok=True)
        # a crash mid-compaction (or mid-write anywhere in this dir)
        # leaves orphan *.tmp debris — e.g. offsets.log's compaction tmp;
        # swept at open, counted in ccfd_storage_tmp_swept_total
        from ccfd_tpu.runtime.durability import sweep_tmp

        sweep_tmp(directory)
        self._meta = SegmentFile(os.path.join(directory, self.META), fsync)
        self._offsets = SegmentFile(os.path.join(directory, self.OFFSETS), fsync)
        self._topic_ids: dict[str, int] = {}
        self._partitions: dict[str, int] = {}
        self._series: dict[tuple[str, int], _SegmentSeries] = {}

    # -- replay -------------------------------------------------------------

    def replay_topics(self) -> dict[str, int]:
        """meta.log -> {topic: n_partitions}; also primes the file-id map."""
        for payload in self._meta.replay():
            m = json.loads(payload)
            self._topic_ids[m["topic"]] = int(m["id"])
            self._partitions[m["topic"]] = int(m["partitions"])
        return dict(self._partitions)

    def replay_partition(
        self, topic: str, part: int
    ) -> tuple[int, list[tuple[Any, float, Any]]]:
        """-> (base offset of the first retained record, decoded records)."""
        base, payloads = self._segment(topic, part).replay()
        return base, [decode_entry(p) for p in payloads]

    def replay_offsets(self) -> dict[str, dict[tuple[str, int], int]]:
        groups: dict[str, dict[tuple[str, int], int]] = {}
        n_raw = 0
        for payload in self._offsets.replay():
            n_raw += 1
            o = json.loads(payload)
            g = groups.setdefault(o["g"], {})
            tp = (o["t"], int(o["p"]))
            # Last-wins, not max: every append happens under the broker
            # lock, so file order IS logical order — and an administrative
            # rewind (Broker.reset_offsets, the crash-recovery replay cut)
            # must survive a broker crash rather than be undone by an
            # earlier, higher commit on replay.
            g[tp] = int(o["o"])
        n_unique = sum(len(g) for g in groups.values())
        # offsets.log grows one entry per commit forever; once history
        # dominates (>4x the live key count), rewrite it compacted. Atomic
        # (tmp + rename) and done before any append opens the file, so a
        # crash mid-compaction leaves either the old or the new file intact.
        if n_raw > max(64, 4 * n_unique):
            tmp = self._offsets.path + ".tmp"
            # fsync=True regardless of the bus's per-append policy: this
            # is a REWRITE, not an append — a rename that survives a host
            # crash whose data did not would lose every committed offset
            # (appends merely lose their tail; ISSUE 13 satellite)
            compacted = SegmentFile(tmp, fsync=True)
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass
            payloads = [
                json.dumps({"g": g_name, "t": t, "p": p, "o": off}).encode()
                for g_name, tps in groups.items()
                for (t, p), off in tps.items()
            ]
            if payloads:  # one write (and one fsync) for the whole rewrite
                compacted.append(*payloads)
            compacted.close()
            os.replace(tmp, self._offsets.path)
        return groups

    # -- append -------------------------------------------------------------

    def add_topic(self, topic: str, n_partitions: int) -> None:
        if topic in self._topic_ids:
            return
        tid = len(self._topic_ids)
        self._topic_ids[topic] = tid
        self._partitions[topic] = n_partitions
        self._meta.append(
            json.dumps({"topic": topic, "id": tid, "partitions": n_partitions}).encode()
        )

    def append_record(
        self, topic: str, part: int, key: Any, timestamp: float, value: Any
    ) -> None:
        self._segment(topic, part).append(encode_entry(key, timestamp, value))

    def append_payload(self, topic: str, part: int, payload: bytes) -> None:
        """Append an already-encoded entry (producers pre-encode so encode
        errors surface before any in-memory state mutates)."""
        self._segment(topic, part).append(payload)

    def commit_offset(self, group: str, topic: str, part: int, offset: int) -> None:
        self._offsets.append(
            json.dumps({"g": group, "t": topic, "p": part, "o": offset}).encode()
        )

    def trim_partition(self, topic: str, part: int, offset: int) -> int:
        """Delete whole on-disk segments strictly below ``offset`` (the
        broker's retention floor).  Returns segments removed."""
        return self._segment(topic, part).trim_to(offset)

    def start_offset(self, topic: str, part: int) -> int:
        return self._segment(topic, part).start_offset

    def _segment(self, topic: str, part: int) -> _SegmentSeries:
        series = self._series.get((topic, part))
        if series is None:
            tid = self._topic_ids[topic]
            series = _SegmentSeries(self.dir, tid, part, self.fsync,
                                    self.segment_bytes)
            self._series[(topic, part)] = series
        return series

    def close(self) -> None:
        self._meta.close()
        self._offsets.close()
        for series in self._series.values():
            series.close()
