"""In-process message bus with Kafka-shaped semantics.

The reference's transport is a Strimzi Kafka cluster with 3 brokers and the
topics ``odh-demo``, ``ccd-customer-outgoing``, ``ccd-customer-response``
(reference deploy/frauddetection_cr.yaml:73-77, deploy/router.yaml:55-62).
This module provides the same *semantics* — partitioned topics, keyed
partitioning, consumer groups with per-group committed offsets, blocking
polls — as a zero-dependency in-process broker, so every component of the
framework is written against a Kafka-shaped API and can swap in a real
``kafka-python`` client via the same interface when a cluster exists
(see ``KafkaAdapter`` stub at the bottom).

Semantics kept faithful to Kafka:
- total order *within* a partition, none across partitions;
- hash(key) % n_partitions routing, round-robin for keyless records;
- consumer groups: each partition is owned by exactly one live member;
  offsets are committed per (group, topic, partition) and survive consumer
  close/reopen (resume-from-offset is the reference's de-facto recovery
  mechanism, SURVEY.md §5 "Checkpoint / resume").
"""

from __future__ import annotations

import binascii
import itertools
import threading
import time
from typing import Any, Iterable, Mapping, NamedTuple


class Record(NamedTuple):
    # NamedTuple, not a frozen dataclass: construction shows up on the
    # produce hot path (one Record per transaction at wire rate), and a
    # frozen dataclass pays object.__setattr__ per field.
    #
    # GC note (measured, 20-min endurance soak): partitions retain every
    # record (the documented retention=-1 model), and CPython only
    # UNTRACKS exact tuples — NamedTuple instances are tuple subclasses
    # and stay GC-tracked forever, so gen-2 collections scan the whole
    # retained history (4.3 s of pure scan at 10M records — the soak's
    # 11.6 s progress stall). Partitions therefore store PLAIN tuples in
    # Record field order; consumer-facing APIs rebuild Record views at
    # poll time (Record._make, ~100 ns on records consumed once).
    # Bytes/str-valued records then leave gen-2 scans entirely;
    # dict-valued ones (audit events) remain tracked — that residual is
    # the retention limitation's, not the container's (a trace-stamped
    # batch's shared headers dict adds ONE tracked object per batch,
    # not per record — every record in the batch aliases it).
    #
    # ``headers`` carries Kafka-style record headers — today the W3C
    # ``traceparent`` stamped per produced batch (observability/trace.py)
    # so consumers resume the producer's trace. In-memory only: the
    # durable log does not persist headers (a replayed record's trace
    # ended with the process that emitted it), and None stays the common
    # case on untraced paths.
    topic: str
    partition: int
    offset: int
    key: Any
    value: Any
    timestamp: float
    headers: Any = None


# Group name under which runtime/recovery.py pins its last durable cut:
# retention treats the pin as any other committed position, so records at
# or above the last checkpoint cut can never be deleted and a crash
# restore can always replay from the cut. This is what makes the broker's
# delete-before-committed-offset retention safe BY CONSTRUCTION alongside
# the framework's rewind-based recovery (Kafka's pure size/time retention
# would happily delete a cut's records out from under it).
RETENTION_PIN_GROUP = "__ccfd_cut_pin__"


class _Partition:
    """One partition's in-memory tail: a record list plus the offset of
    its first element.

    ``offset == base + index`` (was ``offset == list index`` before round
    5's retention work): retention trims the front of ``records`` and
    advances ``base``, so offsets stay permanent — exactly Kafka's
    log-start-offset — while memory stays capped. Records remain plain
    6-tuples in Record field order (exact tuples untrack from gen-2 GC,
    see Record's GC note). A list with batched front-deletes beats a
    deque here: the fetch path slices hot (O(k) on a list, O(n) on a
    deque), while trims are amortized over thousands of appends."""

    __slots__ = ("base", "records")

    def __init__(self, base: int = 0):
        self.base = base
        self.records: list[tuple] = []

    @property
    def end(self) -> int:
        return self.base + len(self.records)

    def slice(self, start: int, max_n: int) -> tuple[int, list[tuple]]:
        """-> (effective start offset, records). A ``start`` below
        ``base`` reads from the earliest retained record — Kafka's
        auto.offset.reset=earliest on an out-of-range fetch."""
        eff = max(start, self.base)
        i = eff - self.base
        return eff, self.records[i:i + max_n]

    def trim_to(self, offset: int) -> int:
        """Drop records below ``offset``; returns how many were dropped."""
        n = min(max(offset - self.base, 0), len(self.records))
        if n:
            del self.records[:n]
            self.base += n
        return n


class StaleEpochError(RuntimeError):
    """A manual commit was fenced: it carried a group epoch older than the
    group's current rebalance epoch, or named a partition the committer no
    longer owns. The Kafka analog is a ``CommitFailedError`` after a
    generation change — a member whose partitions were re-assigned (death,
    join, fence) must NOT be able to move the group's committed offsets,
    or the new owner's position silently jumps past records it never saw
    (a drop) or behind records it already routed (a double-route)."""

    def __init__(self, group_id: str, epoch: int, current_epoch: int,
                 detail: str = ""):
        msg = (f"stale epoch {epoch} for group {group_id!r} "
               f"(current {current_epoch})")
        if detail:
            msg += f": {detail}"
        super().__init__(msg)
        self.group_id = group_id
        self.epoch = epoch
        self.current_epoch = current_epoch


class _Topic:
    def __init__(self, name: str, n_partitions: int,
                 bases: list[int] | None = None):
        self.name = name
        self.partitions: list[_Partition] = [
            _Partition(bases[i] if bases else 0) for i in range(n_partitions)
        ]
        self._rr = itertools.count()

    @property
    def n_partitions(self) -> int:
        return len(self.partitions)

    def route(self, key: Any) -> int:
        if key is None:
            return next(self._rr) % self.n_partitions
        # stable across processes (Python's str hash is per-process salted;
        # a durable log replayed into a new process must keep key->partition
        # ordering, like Kafka's murmur2-on-key-bytes)
        data = key if isinstance(key, bytes) else str(key).encode()
        return binascii.crc32(data) % self.n_partitions


class Broker:
    """Thread-safe in-process broker. One instance == one cluster.

    With ``log_dir`` set, every record and committed offset also lands in
    an on-disk segment log (ccfd_tpu/bus/log.py): reopening a Broker on the
    same directory replays topics, records, and group offsets, so consumers
    resume exactly where the crashed process left off — the reference's
    Kafka recovery semantics (SURVEY.md §5).
    """

    def __init__(
        self,
        default_partitions: int = 3,
        log_dir: str | None = None,
        fsync: bool = False,
        retention_records: int | None = None,
        segment_bytes: int | None = None,
        retention_overrides: dict[str, int | None] | None = None,
    ):
        """``retention_records``: cap each partition's retained history.

        Kafka-shaped retention with one deliberate strengthening: a
        record is only eligible for deletion once it is BOTH older than
        the newest ``retention_records`` AND below every consumer
        group's committed offset for that partition (Kafka's time/size
        retention deletes regardless of consumers; this framework's
        crash recovery replays from committed cuts — runtime/recovery.py
        pins its last durable cut as a committed position, see
        ``RETENTION_PIN_GROUP`` — so delete-before-committed-offset is
        the only retention that cannot break recovery by construction).
        ``None`` (default) keeps the historical retain-everything
        behavior. ``segment_bytes`` sizes the on-disk rolling segments
        (bus/log.py); retention deletes whole rolled segments.

        ``retention_overrides`` is the per-topic config analog of Kafka's
        ``retention.bytes`` topic override: ``{topic: cap}`` with ``None``
        meaning retain-everything for that topic (an audit ledger and a
        high-volume data topic rarely want the same window). Also
        settable live via :meth:`set_topic_retention` (the
        ``kafka-configs --alter --topic`` analog)."""
        self._default_partitions = default_partitions
        self._topics: dict[str, _Topic] = {}
        self._groups: dict[str, dict[tuple[str, int], int]] = {}  # group -> {(t,p): offset}
        self._members: dict[str, list["Consumer"]] = {}
        # group -> rebalance epoch (Kafka's group generation): bumped on
        # EVERY membership change, including down to zero members, so a
        # commit from a member that was fenced out can never match
        self._group_epochs: dict[str, int] = {}
        self.fenced_commits = 0  # lifetime count of refused stale commits
        self._lock = threading.Lock()
        self._data_ready = threading.Condition(self._lock)
        self.retention_records = retention_records or None
        # normalize at intake: 0 and None both mean retain-everything
        # (matching the CCFD_BUS_RETENTION_* env forms), so no caller can
        # accidentally configure a cap-zero topic that trims to the
        # committed floor
        self._retention_overrides = {
            t: (cap or None) for t, cap in (retention_overrides or {}).items()
        }
        self.records_trimmed = 0   # lifetime count, for soaks/exporters
        self.oor_resets = 0        # fetches clamped to log-start (Kafka's
        #                            auto.offset.reset=earliest analog)
        self._since_retention: dict[str, int] = {}  # topic -> appends
        self._log_dir = log_dir
        self._fsync = fsync
        self._segment_bytes = segment_bytes
        self.crash_restarts = 0
        self._log = None
        if log_dir is not None:
            self._open_and_replay_log()

    def _open_and_replay_log(self) -> None:
        """Open the segment log and replay it into (empty) in-memory state.
        Runs at construction and again inside ``crash_restart``."""
        from ccfd_tpu.bus.log import BusLog, DEFAULT_SEGMENT_BYTES

        self._log = BusLog(
            self._log_dir, fsync=self._fsync,
            segment_bytes=self._segment_bytes or DEFAULT_SEGMENT_BYTES,
        )
        for name, n_parts in self._log.replay_topics().items():
            bases = []
            replays = []
            for p in range(n_parts):
                base, recs = self._log.replay_partition(name, p)
                bases.append(base)
                replays.append(recs)
            t = _Topic(name, n_parts, bases=bases)
            self._topics[name] = t
            for p, recs in enumerate(replays):
                part = t.partitions[p]
                for key, ts, value in recs:
                    part.records.append(
                        (name, p, part.end, key, value, ts, None))
        # Clamp replayed offsets to the replayed log: a torn-tail
        # truncation may have dropped records whose consumption was
        # already committed; an out-of-range offset would silently skip
        # every record produced at those slots after restart (Kafka
        # resets out-of-range offsets the same way). The low clamp is
        # the partition's log-start: retention may have deleted the
        # committed position's records.
        for g, tps in self._log.replay_offsets().items():
            mine = self._groups.setdefault(g, {})
            for (tname, p), off in tps.items():
                t = self._topics.get(tname)
                if t is None or p >= t.n_partitions:
                    continue  # topic/partition lost with the meta log
                part = t.partitions[p]
                mine[(tname, p)] = max(part.base, min(off, part.end))

    def crash_restart(self) -> dict:
        """Crash the durable broker and restart it from its own disk, IN
        PLACE, with consumers attached mid-stream.

        The analog of a Kafka broker pod dying and its replacement
        mounting the same PV (reference deploy/frauddetection_cr.yaml:
        73-77 — Strimzi persistent-claim storage): every byte of
        in-memory state is dropped exactly as a process death would drop
        it, then the on-disk segment log replays back into THIS object,
        so attached components — who hold the broker reference the way
        Kafka clients hold a bootstrap address — resume against the
        restarted broker without being rebuilt. Durability analysis of
        why close-then-replay equals a crash from the disk's standpoint:
        every append was already an ``os.write`` (page cache) at produce
        time, and close adds no flush beyond that; the only write that
        happens on OPEN (offsets.log compaction) is atomic tmp+rename.

        Consumers survive because group offsets are replayed from the
        durable offsets log — a registered member keeps its assignment
        (a reconnecting client) and its next poll resumes from the
        committed position. Raises on a memory-only broker: with no log
        there is nothing to restart FROM (a real all-RAM bus crash is
        total data loss, which the chaos soak would report as exactly
        that)."""
        with self._lock:
            if self._log is None:
                raise RuntimeError("memory-only broker cannot crash_restart")
            self._log.close()
            self._topics.clear()
            self._groups.clear()
            self._since_retention.clear()
            self._open_and_replay_log()
            # surviving members are clients reconnecting to the restarted
            # broker: re-register their topics and rebalance each group.
            # Manual fetch positions are dropped wholesale — a torn-tail
            # truncation may have shortened the log below a position, and
            # a stale position above the replayed end would silently skip
            # records produced at those slots after restart; resuming from
            # the (replay-clamped) committed offset is the safe cut.
            for g, members in self._members.items():
                for m in members:
                    m._positions.clear()
                    for tname in m.topics:
                        self._topic(tname)
                self._rebalance(g)
            self.crash_restarts += 1
            self._data_ready.notify_all()
            return {
                "topics": {n: [p.end for p in t.partitions]
                           for n, t in self._topics.items()},
                "groups": {g: dict(tps) for g, tps in self._groups.items()},
            }

    # -- admin ------------------------------------------------------------
    def create_topic(self, name: str, n_partitions: int | None = None) -> None:
        with self._lock:
            if name not in self._topics:
                n = n_partitions or self._default_partitions
                self._topics[name] = _Topic(name, n)
                if self._log is not None:
                    self._log.add_topic(name, n)

    def _topic(self, name: str) -> _Topic:
        t = self._topics.get(name)
        if t is None:
            self._topics[name] = t = _Topic(name, self._default_partitions)
            if self._log is not None:
                self._log.add_topic(name, t.n_partitions)
        return t

    def close(self) -> None:
        """Flush and close segment files (no-op for a memory-only broker)."""
        with self._lock:
            if self._log is not None:
                self._log.close()

    def end_offsets(self, topic: str) -> list[int]:
        with self._lock:
            return [p.end for p in self._topic(topic).partitions]

    def beginning_offsets(self, topic: str) -> list[int]:
        """Per-partition log-start offset (Kafka ``beginning_offsets``):
        0 until retention trims, then the earliest retained offset."""
        with self._lock:
            return [p.base for p in self._topic(topic).partitions]

    def health_snapshot(self) -> dict:
        """One consistent view for health/lag exporters: per-topic partition
        end offsets plus per-group committed offsets, with groups that
        registered but never committed (e.g. a consumer wedged since
        startup) seeded at the partition LOG-START over their assigned
        partitions — their lag reads as every deliverable record (the way
        Kafka reports lag against the log-start), not as a full log whose
        trimmed head could never be delivered. Retention's own floor keeps
        the stronger seed (0): an attached-but-never-committed member
        still protects its whole backlog from deletion."""
        with self._lock:
            topics = {
                name: [p.end for p in t.partitions]
                for name, t in self._topics.items()
            }
            # same locked view as the ends: a separate beginning_offsets
            # call could land after a produce+trim and publish a negative
            # retained-records gauge
            begins = {
                name: [p.base for p in t.partitions]
                for name, t in self._topics.items()
            }
            groups: dict[str, dict[tuple[str, int], int]] = {
                g: dict(tps) for g, tps in self._groups.items()
            }
            for g, members in self._members.items():
                tps = groups.setdefault(g, {})
                for m in members:
                    for tp in m._assignment:
                        tps.setdefault(
                            tp,
                            self._topics[tp[0]].partitions[tp[1]].base,
                        )
        return {"topics": topics, "begins": begins, "groups": groups}

    # -- produce ----------------------------------------------------------
    def produce(self, topic: str, value: Any, key: Any = None,
                partition: int | None = None,
                headers: Mapping[str, str] | None = None) -> Record:
        """Append one record. ``partition`` overrides key routing (the
        Kafka producer's explicit-partition mode) — control records that
        must reach EVERY partition, like the recovery coordinator's
        ``engine_restored`` marker, produce once per partition with it.
        ``headers`` are Kafka-style record headers (trace context rides
        here); in-memory only, not persisted to the durable log."""
        with self._lock:
            t = self._topic(topic)
            if partition is None:
                part = t.route(key)
            else:
                if not 0 <= partition < t.n_partitions:
                    raise ValueError(
                        f"partition {partition} out of range for {topic!r} "
                        f"({t.n_partitions} partitions)"
                    )
                part = partition
            now = time.time()
            pobj = t.partitions[part]
            item = (topic, part, pobj.end, key, value, now, headers)
            if self._log is not None:
                # encode BEFORE any mutation: an unencodable record must
                # fail cleanly, not leave memory and disk diverged — and
                # the LOG write precedes the in-memory append (same
                # failure contract as produce_batch): memory must never
                # hold a record the log would lose across a restart
                from ccfd_tpu.bus.log import encode_entry

                payload = encode_entry(key, now, value)
                self._log.append_payload(topic, part, payload)
            pobj.records.append(item)  # exact tuple: GC-untrackable
            self._maybe_retention(topic, t, 1)
            self._data_ready.notify_all()
            return Record._make(item)

    def produce_batch(
        self, topic: str, values: Iterable[Any],
        keys: Iterable[Any] | None = None,
        headers: Mapping[str, str] | None = None,
    ) -> int:
        """Append many records under ONE lock acquisition (the producer's
        hot path; same surface as RemoteBroker.produce_batch). One
        ``headers`` mapping stamps the WHOLE batch (the producer's trace
        context per transaction batch) — every record aliases it, so the
        cost is one dict per batch, not per record.

        Failure contract: encode errors fail the WHOLE batch before any
        state mutates (payloads are built up front). An I/O error from the
        durable log mid-batch commits the prefix 0..k-1 — to both disk and
        memory, consistently — and raises; that is the same
        prefix-committed outcome as k individual ``produce`` calls. The log
        write precedes the in-memory append per record, so memory never
        holds a record the log would lose across a restart."""
        values = list(values)
        key_list = list(keys) if keys is not None else [None] * len(values)
        if len(key_list) != len(values):
            raise ValueError("keys and values must have equal length")
        if not values:
            return 0
        with self._lock:
            t = self._topic(topic)
            now = time.time()
            payloads = None
            if self._log is not None:
                from ccfd_tpu.bus.log import encode_entry

                payloads = [
                    encode_entry(k, now, v) for k, v in zip(key_list, values)
                ]
            appended = 0
            try:
                for i, (v, k) in enumerate(zip(values, key_list)):
                    part = t.route(k)
                    if payloads is not None:
                        self._log.append_payload(topic, part, payloads[i])
                    pobj = t.partitions[part]
                    pobj.records.append(
                        (topic, part, pobj.end, k, v, now, headers))
                    appended += 1
            finally:
                if appended:
                    self._maybe_retention(topic, t, appended)
                    self._data_ready.notify_all()
            return len(values)

    # -- consume ----------------------------------------------------------
    def consumer(self, group_id: str, topics: Iterable[str],
                 auto_commit: bool = True) -> "Consumer":
        """``auto_commit=False`` gives manual-commit (at-least-once)
        semantics: poll advances a private per-consumer position, and
        nothing moves the group's committed offset until
        :meth:`Consumer.commit` — which is epoch-fenced against
        rebalances (see :class:`StaleEpochError`)."""
        with self._lock:
            for t in topics:
                self._topic(t)
            c = Consumer(self, group_id, tuple(topics),
                         auto_commit=auto_commit)
            self._members.setdefault(group_id, []).append(c)
            self._rebalance(group_id)
            return c

    def group_epoch(self, group_id: str) -> int:
        """Current rebalance epoch for a group (0 = never had a member)."""
        with self._lock:
            return self._group_epochs.get(group_id, 0)

    def _close(self, consumer: "Consumer") -> None:
        with self._lock:
            members = self._members.get(consumer.group_id, [])
            if consumer in members:
                members.remove(consumer)
                self._rebalance(consumer.group_id)

    def _rebalance(self, group_id: str) -> None:
        """Round-robin partition assignment over live group members.

        Bumps the group epoch FIRST — even when the group just lost its
        last member — so any in-flight manual commit stamped with the
        pre-rebalance epoch is fenced (StaleEpochError), Kafka's group
        generation. Manual consumers' private positions are cleared
        WHOLESALE: a batch polled under the old epoch can never commit
        (the fence), so its records must redeliver from the committed
        offset to whichever member now owns the partition — including
        the same member. Pruning to the kept assignment instead would
        silently DROP fenced in-flight records on retained partitions
        (position advanced past them, commit refused, never re-read)."""
        self._group_epochs[group_id] = (
            self._group_epochs.get(group_id, 0) + 1)
        epoch = self._group_epochs[group_id]
        members = self._members.get(group_id, [])
        if not members:
            return
        all_parts: list[tuple[str, int]] = []
        topics = sorted({t for m in members for t in m.topics})
        for tname in topics:
            t = self._topic(tname)
            all_parts.extend((tname, p) for p in range(t.n_partitions))
        for m in members:
            m._assignment = []
            m.epoch = epoch
        for i, tp in enumerate(all_parts):
            owner = members[i % len(members)]
            if tp[0] in owner.topics:
                owner._assignment.append(tp)
            else:  # partition of a topic this member didn't subscribe to
                for m in members:
                    if tp[0] in m.topics:
                        m._assignment.append(tp)
                        break
        for m in members:
            if not m._auto_commit:
                m._positions.clear()

    def committed_offsets(self, group_id: str, topic: str) -> list[int]:
        """Committed offset per partition for a consumer group — the
        ``kafka-consumer-groups --describe`` analog. The checkpoint
        coordinator (runtime/recovery.py) records these as the
        consistent-cut position alongside an engine snapshot."""
        with self._lock:
            t = self._topic(topic)
            return [
                self._committed(group_id, (topic, p))
                for p in range(t.n_partitions)
            ]

    def reset_offsets(self, group_id: str, topic: str,
                      offsets: list[int]) -> None:
        """Rewind (or advance) a group's committed offsets — Kafka's
        ``kafka-consumer-groups --reset-offsets --to-offset`` analog.

        Live consumers pick the change up on their next poll (every fetch
        reads the group offset; consumers hold no position of their own).
        Out-of-range values clamp to the partition log, like Kafka's
        auto.offset.reset. With a durable log the reset is recorded, so a
        broker crash-replay resumes from the reset position, not the old
        high-water mark (bus/log.py replays offsets last-wins)."""
        with self._lock:
            t = self._topic(topic)
            if len(offsets) != t.n_partitions:
                raise ValueError(
                    f"{topic!r} has {t.n_partitions} partitions, "
                    f"got {len(offsets)} offsets"
                )
            g = self._groups.setdefault(group_id, {})
            for p, off in enumerate(offsets):
                pobj = t.partitions[p]
                # clamp low to log-start: retention may have deleted the
                # requested position (Kafka resets to earliest the same
                # way). Counted: a rewind that aimed below the retained
                # log (e.g. a GENESIS restore with retention on — the
                # coordinator's pin only protects replay from the last
                # durable cut, not from offset 0) replays less than the
                # caller asked for, and operators should see that.
                if int(off) < pobj.base:
                    self.oor_resets += 1
                off = max(pobj.base, min(int(off), pobj.end))
                g[(topic, p)] = off
                if self._log is not None:
                    self._log.commit_offset(group_id, topic, p, off)
            # manual-mode consumers must see the rewind: drop their
            # private positions for this topic so the next fetch re-reads
            # from the (reset) committed offset
            for m in self._members.get(group_id, []):
                if not m._auto_commit:
                    for p in range(t.n_partitions):
                        m._positions.pop((topic, p), None)
            # rewound consumers may have records to re-read right now
            self._data_ready.notify_all()

    # -- retention --------------------------------------------------------
    def _topic_cap(self, topic: str) -> int | None:
        """Effective retained-record cap for a topic (override > default)."""
        if topic in self._retention_overrides:
            return self._retention_overrides[topic]
        return self.retention_records

    def set_topic_retention(self, topic: str, records: int | None) -> None:
        """Per-topic retention override, live (``kafka-configs --alter``
        analog): ``records`` caps the topic's partitions; ``None`` or
        ``0`` makes the topic retain-everything regardless of the broker
        default (the same sentinel the env forms use)."""
        records = records or None
        with self._lock:
            self._retention_overrides[topic] = records
            t = self._topics.get(topic)
            if t is not None and records is not None:
                self._enforce_retention_locked(topic, t)

    def _maybe_retention(self, topic: str, t: _Topic, appended: int) -> None:
        """Amortized retention check, called under the lock after appends:
        runs the real enforcement once per ~1/8th of the retention window
        of fresh records, so the trim's O(dropped) list-delete spreads over
        thousands of produce calls."""
        cap = self._topic_cap(topic)
        if cap is None:
            return
        n = self._since_retention.get(topic, 0) + appended
        if n < max(1024, cap // 8):
            self._since_retention[topic] = n
            return
        self._since_retention[topic] = 0
        self._enforce_retention_locked(topic, t)

    def enforce_retention(self, topic: str | None = None) -> int:
        """Run retention now (tests, shutdown); returns records trimmed."""
        with self._lock:
            before = self.records_trimmed
            names = [topic] if topic is not None else list(self._topics)
            for name in names:
                t = self._topics.get(name)
                if t is not None and self._topic_cap(name) is not None:
                    self._enforce_retention_locked(name, t)
            return self.records_trimmed - before

    def _enforce_retention_locked(self, tname: str, t: _Topic) -> None:
        cap = self._topic_cap(tname)
        if cap is None:
            return
        for p, pobj in enumerate(t.partitions):
            floor = pobj.end - cap
            if floor <= pobj.base:
                continue
            # delete-before-committed-offset: the trim stops at the
            # lowest committed position any group holds for this
            # partition. Members that attached but never committed hold
            # position 0 implicitly — their whole backlog is protected,
            # exactly Kafka's lag accounting (health_snapshot seeds the
            # same way). No group at all -> pure size retention.
            tp = (tname, p)
            mins = [tps[tp] for tps in self._groups.values() if tp in tps]
            for g, members in self._members.items():
                if tp not in self._groups.get(g, {}) and any(
                    tp in m._assignment for m in members
                ):
                    mins.append(0)
            committed_min = min(mins) if mins else pobj.end
            trim_to = min(committed_min, floor)
            dropped = pobj.trim_to(trim_to)
            if dropped:
                self.records_trimmed += dropped
                if self._log is not None:
                    self._log.trim_partition(tname, p, pobj.base)

    def _committed(self, group_id: str, tp: tuple[str, int]) -> int:
        return self._groups.setdefault(group_id, {}).get(tp, 0)

    def _commit(self, group_id: str, tp: tuple[str, int], offset: int) -> None:
        g = self._groups.setdefault(group_id, {})
        if offset > g.get(tp, 0):
            g[tp] = offset
            if self._log is not None:
                self._log.commit_offset(group_id, tp[0], tp[1], offset)

    def _consumer_commit(
        self, consumer: "Consumer",
        offsets: Mapping[tuple[str, int], int] | None = None,
        epoch: int | None = None,
    ) -> dict[tuple[str, int], int]:
        """Epoch-fenced manual commit (Consumer.commit body, under lock).

        ``epoch=None`` fences against the epoch stamped at the consumer's
        last poll — the epoch the records being committed were DELIVERED
        under. A rebalance between poll and commit (member death, join,
        supervisor fence) refuses the commit: the records redeliver to
        the partitions' new owners instead of being marked consumed by a
        member that no longer owns them."""
        with self._lock:
            cur = self._group_epochs.get(consumer.group_id, 0)
            eff = consumer._poll_epoch if epoch is None else int(epoch)
            members = self._members.get(consumer.group_id, [])
            if consumer._closed or consumer not in members:
                self.fenced_commits += 1
                raise StaleEpochError(consumer.group_id, eff, cur,
                                      "consumer fenced out of the group")
            if eff != cur:
                self.fenced_commits += 1
                raise StaleEpochError(consumer.group_id, eff, cur)
            if offsets is None:
                to_commit = dict(consumer._positions)
            else:
                assigned = set(consumer._assignment)
                to_commit = {}
                for tp, off in offsets.items():
                    tp = (tp[0], int(tp[1]))
                    if tp not in assigned:
                        self.fenced_commits += 1
                        raise StaleEpochError(
                            consumer.group_id, eff, cur,
                            f"partition {tp} not assigned to committer")
                    to_commit[tp] = int(off)
            for tp, off in to_commit.items():
                self._commit(consumer.group_id, tp, off)
            return to_commit

    def _fetch(
        self, consumer: "Consumer", max_records: int
    ) -> list[Record]:
        out: list[Record] = []
        consumer._poll_epoch = self._group_epochs.get(consumer.group_id, 0)
        # Rotate the scan start across polls (Kafka clients do the same):
        # a loaded partition early in a fixed order would otherwise starve
        # later ones for as long as it keeps filling max_records — found
        # live in the round-5 soak, where partition 2's backlog (and the
        # retention pin reflecting it) grew for the whole run while 0/1
        # stayed current.
        n = len(consumer._assignment)
        first = consumer._fetch_start % n if n else 0
        for k in range(n):
            tname, p = consumer._assignment[(first + k) % n]
            if len(out) >= max_records:
                break
            t = self._topic(tname)
            tp = (tname, p)
            if consumer._auto_commit:
                start = self._committed(consumer.group_id, tp)
            else:
                # manual mode: a private fetch position rides ahead of
                # the group's committed offset; nothing below moves the
                # committed offset until Consumer.commit
                start = consumer._positions.get(
                    tp, self._committed(consumer.group_id, tp))
            eff, take = t.partitions[p].slice(start, max_records - len(out))
            if eff > start:
                # committed position fell below the log-start (possible
                # only for positions retention proved consumed or that a
                # rewind aimed below the retained log): reset-to-earliest.
                # Commit the clamped position even when the take is empty
                # (idle topic: base == end) — otherwise every subsequent
                # poll re-detects the same clamp and oor_resets inflates
                # forever on a topic that had exactly one reset.
                self.oor_resets += 1
                if not take:
                    if consumer._auto_commit:
                        self._commit(consumer.group_id, tp, eff)
                    else:
                        consumer._positions[tp] = eff
            if take:
                # stored as exact tuples (GC untracking, see Record);
                # consumers get the Record view
                out.extend(map(Record._make, take))
                if consumer._auto_commit:
                    self._commit(consumer.group_id, tp, eff + len(take))
                else:
                    consumer._positions[tp] = eff + len(take)
        consumer._fetch_start = first + 1
        return out


class Consumer:
    """Poll-based consumer. With ``auto_commit=True`` (default) offsets
    commit on poll (at-most-once hand-off inside one process; the
    in-process broker never loses the log, so replay is available by
    resetting the group offset). With ``auto_commit=False`` poll advances
    a private position and :meth:`commit` moves the group offset under an
    epoch fence — the at-least-once mode the fleet's commit-after-route
    discipline runs on."""

    def __init__(self, broker: Broker, group_id: str, topics: tuple[str, ...],
                 auto_commit: bool = True):
        self._broker = broker
        self.group_id = group_id
        self.topics = topics
        self._assignment: list[tuple[str, int]] = []
        self._fetch_start = 0  # rotating fetch fairness cursor (_fetch)
        self._closed = False
        self._auto_commit = auto_commit
        self._positions: dict[tuple[str, int], int] = {}
        self.epoch = 0       # group epoch stamped at the last rebalance
        self._poll_epoch = 0  # group epoch stamped at the last poll

    def assignment(self) -> list[tuple[str, int]]:
        """Currently owned (topic, partition) pairs (Kafka assignment())."""
        with self._broker._lock:
            return list(self._assignment)

    def commit(
        self,
        offsets: Mapping[tuple[str, int], int] | None = None,
        epoch: int | None = None,
    ) -> dict[tuple[str, int], int]:
        """Manual commit (``auto_commit=False`` mode). ``offsets=None``
        commits the broker-held fetch positions; an explicit mapping
        ``{(topic, partition): next_offset}`` commits exactly those.
        Fenced by ``epoch`` (default: the epoch of this consumer's last
        poll) — raises :class:`StaleEpochError` if the group rebalanced
        since, or if an explicit partition is not currently assigned to
        this consumer. Returns what was committed."""
        return self._broker._consumer_commit(self, offsets, epoch)

    def poll(self, max_records: int = 500, timeout_s: float = 0.0) -> list[Record]:
        deadline = time.monotonic() + timeout_s
        while True:
            with self._broker._lock:
                if self._closed:
                    return []
                recs = self._broker._fetch(self, max_records)
                if recs:
                    return recs
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._broker._data_ready.wait(timeout=min(remaining, 0.05))

    def close(self) -> None:
        self._closed = True
        self._broker._close(self)

    def __enter__(self) -> "Consumer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def __getattr__(name: str):
    # KafkaAdapter lives in its own module (it pulls in the json/base64
    # wire codec); re-exported here because this is where callers expect
    # the real-cluster seam to be.
    if name == "KafkaAdapter":
        from ccfd_tpu.bus.kafka_adapter import KafkaAdapter

        return KafkaAdapter
    raise AttributeError(name)
