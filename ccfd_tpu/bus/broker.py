"""In-process message bus with Kafka-shaped semantics.

The reference's transport is a Strimzi Kafka cluster with 3 brokers and the
topics ``odh-demo``, ``ccd-customer-outgoing``, ``ccd-customer-response``
(reference deploy/frauddetection_cr.yaml:73-77, deploy/router.yaml:55-62).
This module provides the same *semantics* — partitioned topics, keyed
partitioning, consumer groups with per-group committed offsets, blocking
polls — as a zero-dependency in-process broker, so every component of the
framework is written against a Kafka-shaped API and can swap in a real
``kafka-python`` client via the same interface when a cluster exists
(see ``KafkaAdapter`` stub at the bottom).

Semantics kept faithful to Kafka:
- total order *within* a partition, none across partitions;
- hash(key) % n_partitions routing, round-robin for keyless records;
- consumer groups: each partition is owned by exactly one live member;
  offsets are committed per (group, topic, partition) and survive consumer
  close/reopen (resume-from-offset is the reference's de-facto recovery
  mechanism, SURVEY.md §5 "Checkpoint / resume").
"""

from __future__ import annotations

import binascii
import itertools
import threading
import time
from typing import Any, Iterable, NamedTuple


class Record(NamedTuple):
    # NamedTuple, not a frozen dataclass: construction shows up on the
    # produce hot path (one Record per transaction at wire rate), and a
    # frozen dataclass pays object.__setattr__ per field.
    #
    # GC note (measured, 20-min endurance soak): partitions retain every
    # record (the documented retention=-1 model), and CPython only
    # UNTRACKS exact tuples — NamedTuple instances are tuple subclasses
    # and stay GC-tracked forever, so gen-2 collections scan the whole
    # retained history (4.3 s of pure scan at 10M records — the soak's
    # 11.6 s progress stall). Partitions therefore store PLAIN tuples in
    # Record field order; consumer-facing APIs rebuild Record views at
    # poll time (Record._make, ~100 ns on records consumed once).
    # Bytes/str-valued records then leave gen-2 scans entirely;
    # dict-valued ones (audit events) remain tracked — that residual is
    # the retention limitation's, not the container's.
    topic: str
    partition: int
    offset: int
    key: Any
    value: Any
    timestamp: float


class _Topic:
    def __init__(self, name: str, n_partitions: int):
        self.name = name
        # plain 6-tuples in Record field order, NOT Record instances —
        # exact tuples untrack from gen-2 GC (see Record's GC note);
        # consumer-facing APIs rebuild Record views at poll time
        self.partitions: list[list[tuple]] = [[] for _ in range(n_partitions)]
        self._rr = itertools.count()

    @property
    def n_partitions(self) -> int:
        return len(self.partitions)

    def route(self, key: Any) -> int:
        if key is None:
            return next(self._rr) % self.n_partitions
        # stable across processes (Python's str hash is per-process salted;
        # a durable log replayed into a new process must keep key->partition
        # ordering, like Kafka's murmur2-on-key-bytes)
        data = key if isinstance(key, bytes) else str(key).encode()
        return binascii.crc32(data) % self.n_partitions


class Broker:
    """Thread-safe in-process broker. One instance == one cluster.

    With ``log_dir`` set, every record and committed offset also lands in
    an on-disk segment log (ccfd_tpu/bus/log.py): reopening a Broker on the
    same directory replays topics, records, and group offsets, so consumers
    resume exactly where the crashed process left off — the reference's
    Kafka recovery semantics (SURVEY.md §5).
    """

    def __init__(
        self,
        default_partitions: int = 3,
        log_dir: str | None = None,
        fsync: bool = False,
    ):
        self._default_partitions = default_partitions
        self._topics: dict[str, _Topic] = {}
        self._groups: dict[str, dict[tuple[str, int], int]] = {}  # group -> {(t,p): offset}
        self._members: dict[str, list["Consumer"]] = {}
        self._lock = threading.Lock()
        self._data_ready = threading.Condition(self._lock)
        self._log = None
        if log_dir is not None:
            from ccfd_tpu.bus.log import BusLog

            self._log = BusLog(log_dir, fsync=fsync)
            for name, n_parts in self._log.replay_topics().items():
                t = _Topic(name, n_parts)
                self._topics[name] = t
                for p in range(n_parts):
                    for key, ts, value in self._log.replay_partition(name, p):
                        t.partitions[p].append(
                            (name, p, len(t.partitions[p]), key, value, ts)
                        )
            # Clamp replayed offsets to the replayed log: a torn-tail
            # truncation may have dropped records whose consumption was
            # already committed; an out-of-range offset would silently skip
            # every record produced at those slots after restart (Kafka
            # resets out-of-range offsets the same way).
            for g, tps in self._log.replay_offsets().items():
                mine = self._groups.setdefault(g, {})
                for (tname, p), off in tps.items():
                    t = self._topics.get(tname)
                    if t is None or p >= t.n_partitions:
                        continue  # topic/partition lost with the meta log
                    mine[(tname, p)] = min(off, len(t.partitions[p]))

    # -- admin ------------------------------------------------------------
    def create_topic(self, name: str, n_partitions: int | None = None) -> None:
        with self._lock:
            if name not in self._topics:
                n = n_partitions or self._default_partitions
                self._topics[name] = _Topic(name, n)
                if self._log is not None:
                    self._log.add_topic(name, n)

    def _topic(self, name: str) -> _Topic:
        t = self._topics.get(name)
        if t is None:
            self._topics[name] = t = _Topic(name, self._default_partitions)
            if self._log is not None:
                self._log.add_topic(name, t.n_partitions)
        return t

    def close(self) -> None:
        """Flush and close segment files (no-op for a memory-only broker)."""
        with self._lock:
            if self._log is not None:
                self._log.close()

    def end_offsets(self, topic: str) -> list[int]:
        with self._lock:
            return [len(p) for p in self._topic(topic).partitions]

    def health_snapshot(self) -> dict:
        """One consistent view for health/lag exporters: per-topic partition
        end offsets plus per-group committed offsets, with groups that
        registered but never committed (e.g. a consumer wedged since
        startup) seeded at offset 0 over their assigned partitions — their
        lag reads as the full log, the way Kafka reports it."""
        with self._lock:
            topics = {
                name: [len(p) for p in t.partitions]
                for name, t in self._topics.items()
            }
            groups: dict[str, dict[tuple[str, int], int]] = {
                g: dict(tps) for g, tps in self._groups.items()
            }
            for g, members in self._members.items():
                tps = groups.setdefault(g, {})
                for m in members:
                    for tp in m._assignment:
                        tps.setdefault(tp, 0)
        return {"topics": topics, "groups": groups}

    # -- produce ----------------------------------------------------------
    def produce(self, topic: str, value: Any, key: Any = None,
                partition: int | None = None) -> Record:
        """Append one record. ``partition`` overrides key routing (the
        Kafka producer's explicit-partition mode) — control records that
        must reach EVERY partition, like the recovery coordinator's
        ``engine_restored`` marker, produce once per partition with it."""
        with self._lock:
            t = self._topic(topic)
            if partition is None:
                part = t.route(key)
            else:
                if not 0 <= partition < t.n_partitions:
                    raise ValueError(
                        f"partition {partition} out of range for {topic!r} "
                        f"({t.n_partitions} partitions)"
                    )
                part = partition
            now = time.time()
            item = (topic, part, len(t.partitions[part]), key, value, now)
            if self._log is not None:
                # encode BEFORE the in-memory append: an unencodable record
                # must fail cleanly, not leave memory and disk diverged
                from ccfd_tpu.bus.log import encode_entry

                payload = encode_entry(key, now, value)
            t.partitions[part].append(item)  # exact tuple: GC-untrackable
            if self._log is not None:
                self._log.append_payload(topic, part, payload)
            self._data_ready.notify_all()
            return Record._make(item)

    def produce_batch(
        self, topic: str, values: Iterable[Any], keys: Iterable[Any] | None = None
    ) -> int:
        """Append many records under ONE lock acquisition (the producer's
        hot path; same surface as RemoteBroker.produce_batch).

        Failure contract: encode errors fail the WHOLE batch before any
        state mutates (payloads are built up front). An I/O error from the
        durable log mid-batch commits the prefix 0..k-1 — to both disk and
        memory, consistently — and raises; that is the same
        prefix-committed outcome as k individual ``produce`` calls. The log
        write precedes the in-memory append per record, so memory never
        holds a record the log would lose across a restart."""
        values = list(values)
        key_list = list(keys) if keys is not None else [None] * len(values)
        if len(key_list) != len(values):
            raise ValueError("keys and values must have equal length")
        if not values:
            return 0
        with self._lock:
            t = self._topic(topic)
            now = time.time()
            payloads = None
            if self._log is not None:
                from ccfd_tpu.bus.log import encode_entry

                payloads = [
                    encode_entry(k, now, v) for k, v in zip(key_list, values)
                ]
            appended = 0
            try:
                for i, (v, k) in enumerate(zip(values, key_list)):
                    part = t.route(k)
                    if payloads is not None:
                        self._log.append_payload(topic, part, payloads[i])
                    t.partitions[part].append(
                        (topic, part, len(t.partitions[part]), k, v, now)
                    )
                    appended += 1
            finally:
                if appended:
                    self._data_ready.notify_all()
            return len(values)

    # -- consume ----------------------------------------------------------
    def consumer(self, group_id: str, topics: Iterable[str]) -> "Consumer":
        with self._lock:
            for t in topics:
                self._topic(t)
            c = Consumer(self, group_id, tuple(topics))
            self._members.setdefault(group_id, []).append(c)
            self._rebalance(group_id)
            return c

    def _close(self, consumer: "Consumer") -> None:
        with self._lock:
            members = self._members.get(consumer.group_id, [])
            if consumer in members:
                members.remove(consumer)
                self._rebalance(consumer.group_id)

    def _rebalance(self, group_id: str) -> None:
        """Round-robin partition assignment over live group members."""
        members = self._members.get(group_id, [])
        if not members:
            return
        all_parts: list[tuple[str, int]] = []
        topics = sorted({t for m in members for t in m.topics})
        for tname in topics:
            t = self._topic(tname)
            all_parts.extend((tname, p) for p in range(t.n_partitions))
        for m in members:
            m._assignment = []
        for i, tp in enumerate(all_parts):
            owner = members[i % len(members)]
            if tp[0] in owner.topics:
                owner._assignment.append(tp)
            else:  # partition of a topic this member didn't subscribe to
                for m in members:
                    if tp[0] in m.topics:
                        m._assignment.append(tp)
                        break

    def committed_offsets(self, group_id: str, topic: str) -> list[int]:
        """Committed offset per partition for a consumer group — the
        ``kafka-consumer-groups --describe`` analog. The checkpoint
        coordinator (runtime/recovery.py) records these as the
        consistent-cut position alongside an engine snapshot."""
        with self._lock:
            t = self._topic(topic)
            return [
                self._committed(group_id, (topic, p))
                for p in range(t.n_partitions)
            ]

    def reset_offsets(self, group_id: str, topic: str,
                      offsets: list[int]) -> None:
        """Rewind (or advance) a group's committed offsets — Kafka's
        ``kafka-consumer-groups --reset-offsets --to-offset`` analog.

        Live consumers pick the change up on their next poll (every fetch
        reads the group offset; consumers hold no position of their own).
        Out-of-range values clamp to the partition log, like Kafka's
        auto.offset.reset. With a durable log the reset is recorded, so a
        broker crash-replay resumes from the reset position, not the old
        high-water mark (bus/log.py replays offsets last-wins)."""
        with self._lock:
            t = self._topic(topic)
            if len(offsets) != t.n_partitions:
                raise ValueError(
                    f"{topic!r} has {t.n_partitions} partitions, "
                    f"got {len(offsets)} offsets"
                )
            g = self._groups.setdefault(group_id, {})
            for p, off in enumerate(offsets):
                off = max(0, min(int(off), len(t.partitions[p])))
                g[(topic, p)] = off
                if self._log is not None:
                    self._log.commit_offset(group_id, topic, p, off)
            # rewound consumers may have records to re-read right now
            self._data_ready.notify_all()

    def _committed(self, group_id: str, tp: tuple[str, int]) -> int:
        return self._groups.setdefault(group_id, {}).get(tp, 0)

    def _commit(self, group_id: str, tp: tuple[str, int], offset: int) -> None:
        g = self._groups.setdefault(group_id, {})
        if offset > g.get(tp, 0):
            g[tp] = offset
            if self._log is not None:
                self._log.commit_offset(group_id, tp[0], tp[1], offset)

    def _fetch(
        self, consumer: "Consumer", max_records: int
    ) -> list[Record]:
        out: list[Record] = []
        for tname, p in consumer._assignment:
            if len(out) >= max_records:
                break
            t = self._topic(tname)
            start = self._committed(consumer.group_id, (tname, p))
            log = t.partitions[p]
            take = log[start : start + (max_records - len(out))]
            if take:
                # stored as exact tuples (GC untracking, see Record);
                # consumers get the Record view
                out.extend(map(Record._make, take))
                self._commit(consumer.group_id, (tname, p), start + len(take))
        return out


class Consumer:
    """Poll-based consumer. Offsets auto-commit on poll (at-most-once hand-off
    inside one process; the in-process broker never loses the log, so replay
    is available by resetting the group offset)."""

    def __init__(self, broker: Broker, group_id: str, topics: tuple[str, ...]):
        self._broker = broker
        self.group_id = group_id
        self.topics = topics
        self._assignment: list[tuple[str, int]] = []
        self._closed = False

    def poll(self, max_records: int = 500, timeout_s: float = 0.0) -> list[Record]:
        deadline = time.monotonic() + timeout_s
        while True:
            with self._broker._lock:
                if self._closed:
                    return []
                recs = self._broker._fetch(self, max_records)
                if recs:
                    return recs
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._broker._data_ready.wait(timeout=min(remaining, 0.05))

    def close(self) -> None:
        self._closed = True
        self._broker._close(self)

    def __enter__(self) -> "Consumer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def __getattr__(name: str):
    # KafkaAdapter lives in its own module (it pulls in the json/base64
    # wire codec); re-exported here because this is where callers expect
    # the real-cluster seam to be.
    if name == "KafkaAdapter":
        from ccfd_tpu.bus.kafka_adapter import KafkaAdapter

        return KafkaAdapter
    raise AttributeError(name)
