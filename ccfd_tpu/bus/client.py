"""Remote broker client: the Broker/Consumer surface over HTTP.

Components take a broker object and never care whether it is the
in-process ``Broker`` or this client pointed at a ``BrokerServer``
(``BROKER_URL=http://host:port`` — the reference's services get their
Kafka bootstrap the same way, reference deploy/router.yaml:55-56,
notification-service.yaml:50-52). Poll long-polls server-side, so idle
remote consumers don't spin.

Delivery semantics across transport failures:

- ``produce``/``produce_batch`` never blind-retry after the request may
  have reached the server (a re-send would duplicate records and start
  duplicate fraud cases downstream); only a refused connection retries.
- ``poll`` carries a client-side sequence number. The server caches the
  last delivered batch per (consumer, seq); a retry after a lost response
  re-sends the SAME seq and gets the SAME batch back instead of the next
  one — at-least-once delivery instead of silent loss, without giving up
  the broker's auto-commit fetch path.

``broker_from_url`` is the one seam: ``inproc://`` (or empty) builds a
local Broker, ``http://`` builds this client.
"""

from __future__ import annotations

from typing import Any, Iterable

from ccfd_tpu.bus.broker import StaleEpochError
from ccfd_tpu.bus.server import decode_value, encode_value
from ccfd_tpu.utils.httpclient import PooledHTTPClient


class RemoteBusError(ConnectionError):
    pass


class RemoteBroker:
    def __init__(
        self,
        base_url: str,
        pool_size: int = 4,
        timeout_s: float = 40.0,  # > max server-side long-poll (30s)
        retries: int = 2,
        breaker=None,
        faults=None,
        tracer=None,
    ):
        # breaker/faults ride the shared transport (utils/httpclient.py);
        # note poll redelivery still holds under injected faults — the seq
        # only advances on a successful, uncorrupted response. The tracer
        # (observability/trace.py) makes every bus RPC a client span and
        # injects traceparent, so a produced batch's context reaches the
        # BrokerServer and rides its records.
        self._http = PooledHTTPClient(
            base_url, default_port=9092, pool_size=pool_size,
            timeout_s=timeout_s, retries=retries,
            scheme_error="RemoteBroker needs an http:// URL",
            breaker=breaker, faults=faults,
            tracer=tracer, trace_edge="bus",
        )

    def _request(
        self, method: str, path: str, body: Any = None, idempotent: bool = True
    ) -> tuple[int, Any]:
        try:
            return self._http.request(method, path, body, idempotent=idempotent)
        except ConnectionError as e:
            raise RemoteBusError(str(e)) from e

    # -- Broker surface ----------------------------------------------------
    def produce(self, topic: str, value: Any, key: Any = None,
                partition: int | None = None,
                headers: dict | None = None) -> dict[str, Any]:
        """``partition`` overrides key routing — same surface as
        ``Broker.produce`` / ``KafkaAdapter.produce`` (control records
        like the recovery coordinator's per-partition markers need it on
        every transport). ``headers`` stamps the record server-side
        (trace context; the HTTP traceparent header also does this
        implicitly when the transport is traced)."""
        rec: dict[str, Any] = {
            "value": encode_value(value), "key": encode_value(key),
        }
        if partition is not None:
            rec["partition"] = int(partition)
        body_out: dict[str, Any] = {"records": [rec]}
        if headers:
            body_out["headers"] = dict(headers)
        code, body = self._request(
            "POST", f"/topics/{topic}/produce", body_out,
            idempotent=False,
        )
        if code != 200:
            raise RemoteBusError(f"produce to {topic!r} failed: {code} {body}")
        return body["metas"][0]

    def produce_batch(
        self, topic: str, values: Iterable[Any],
        keys: Iterable[Any] | None = None,
        headers: dict | None = None,
    ) -> int:
        """One HTTP round-trip for many records (the producer's hot path);
        one ``headers`` mapping stamps the whole batch server-side."""
        if keys is None:
            records = [{"value": encode_value(v), "key": None} for v in values]
        else:
            records = [
                {"value": encode_value(v), "key": encode_value(k)}
                for v, k in zip(values, keys)
            ]
        if not records:
            return 0
        body_out: dict[str, Any] = {"records": records}
        if headers:
            body_out["headers"] = dict(headers)
        code, body = self._request(
            "POST", f"/topics/{topic}/produce", body_out,
            idempotent=False,
        )
        if code != 200:
            raise RemoteBusError(f"produce to {topic!r} failed: {code} {body}")
        return len(body["metas"])

    def end_offsets(self, topic: str) -> list[int]:
        code, body = self._request("GET", f"/topics/{topic}/offsets")
        if code != 200:
            raise RemoteBusError(f"offsets for {topic!r} failed: {code}")
        return body

    def beginning_offsets(self, topic: str) -> list[int]:
        """Per-partition log-start (0 until server-side retention trims)."""
        code, body = self._request("GET", f"/topics/{topic}/offsets/begin")
        if code != 200:
            raise RemoteBusError(f"begin offsets for {topic!r} failed: {code}")
        return body

    # -- offset admin (parity with Broker / KafkaAdapter) ------------------
    def committed_offsets(self, group_id: str, topic: str) -> list[int]:
        code, body = self._request(
            "GET", f"/groups/{group_id}/topics/{topic}/offsets")
        if code != 200:
            raise RemoteBusError(
                f"committed offsets for {group_id!r}/{topic!r} failed: {code}")
        return body

    def reset_offsets(self, group_id: str, topic: str,
                      offsets: list[int]) -> None:
        """Rewind (or advance) a group's committed offsets on the server —
        the missing piece for checkpoint-rewind crash recovery (and the
        coordinator's retention pin) over the remote transport. Idempotent:
        re-sending the same reset converges to the same committed state,
        so transport retries are safe."""
        code, body = self._request(
            "POST", f"/groups/{group_id}/topics/{topic}/offsets",
            {"offsets": [int(o) for o in offsets]},
        )
        if code != 200:
            raise RemoteBusError(
                f"reset offsets for {group_id!r}/{topic!r} failed: "
                f"{code} {body}")

    def group_epoch(self, group_id: str) -> int:
        """Current rebalance epoch for a group (0 = never had a member)."""
        code, body = self._request("GET", f"/groups/{group_id}/epoch")
        if code != 200:
            raise RemoteBusError(f"group epoch for {group_id!r} failed: {code}")
        return int(body["epoch"])

    def fence_group(self, group_id: str, idle_s: float = 0.0) -> dict:
        """Explicitly fence a group's idle consumers server-side (the fleet
        supervisor's member-death actuator); returns {closed, epoch}."""
        code, body = self._request(
            "POST", f"/groups/{group_id}/fence", {"idle_s": float(idle_s)})
        if code != 200:
            raise RemoteBusError(f"fence for {group_id!r} failed: {code} {body}")
        return body

    def consumer(self, group_id: str, topics: Iterable[str],
                 auto_commit: bool = True) -> "RemoteConsumer":
        code, body = self._request(
            "POST", "/consumers",
            {"group": group_id, "topics": list(topics),
             "auto_commit": bool(auto_commit)},
        )
        if code != 201:
            raise RemoteBusError(f"consumer create failed: {code} {body}")
        return RemoteConsumer(self, int(body["consumer_id"]), group_id,
                              tuple(topics), auto_commit=auto_commit,
                              epoch=int(body.get("epoch", 0)))

    def close(self) -> None:
        self._http.close()


class _RemoteRecord:
    """Record view over the wire: same attribute surface as bus.broker.Record."""

    __slots__ = ("topic", "partition", "offset", "key", "value", "timestamp",
                 "headers")

    def __init__(self, d: dict[str, Any]):
        self.topic = d["topic"]
        self.partition = d["partition"]
        self.offset = d["offset"]
        self.key = decode_value(d["key"])
        self.value = decode_value(d["value"])
        self.timestamp = d["timestamp"]
        self.headers = d.get("headers")  # absent on the wire when None


class RemoteConsumer:
    def __init__(
        self, broker: RemoteBroker, cid: int, group_id: str,
        topics: tuple[str, ...], auto_commit: bool = True, epoch: int = 0,
    ):
        self._broker = broker
        self._cid = cid
        self.group_id = group_id
        self.topics = topics
        self._seq = 0
        self._closed = False
        self._auto_commit = auto_commit
        # group epoch this consumer last synced with the server; in manual
        # mode updated to the DELIVERY epoch of each poll — the fence every
        # subsequent commit() carries
        self.epoch = epoch
        self.assignment: list[tuple[str, int]] = []

    def _poll_once(
        self, seq: int, max_records: int, timeout_s: float
    ) -> tuple[int, Any]:
        # idempotent BECAUSE of the seq: a retry re-requests the same batch
        payload: dict[str, Any] = {
            "max_records": max_records, "timeout_s": timeout_s, "seq": seq,
        }
        if not self._auto_commit:
            # manual mode declares its epoch: a rebalance under this
            # consumer surfaces as 409 BEFORE records are consumed under
            # an assignment it no longer holds
            payload["epoch"] = self.epoch
        return self._broker._request(
            "POST", f"/consumers/{self._cid}/poll", payload,
        )

    def poll(self, max_records: int = 500, timeout_s: float = 0.0) -> list[_RemoteRecord]:
        if self._closed:
            return []
        # advance seq only AFTER a successful response: if transport retries
        # are exhausted and RemoteBusError propagates, the next poll() call
        # re-sends the SAME seq, so a batch the broker consumed and
        # auto-committed under the failed seq is redelivered from the
        # server-side cache instead of silently lost (at-least-once across
        # application-level retries, not just in-request transport retries)
        seq = self._seq + 1
        code, body = self._poll_once(seq, max_records, timeout_s)
        if code == 404:  # reaped by session timeout: re-register and retry once
            fresh = self._broker.consumer(self.group_id, self.topics,
                                          auto_commit=self._auto_commit)
            self._cid = fresh._cid
            self.epoch = fresh.epoch
            code, body = self._poll_once(seq, max_records, timeout_s)
        if code == 409:
            # the group rebalanced under us (member died/joined/was
            # fenced): transparent resync — adopt the new epoch and
            # assignment, retry once. Anything uncommitted from the old
            # epoch redelivers to the partitions' current owners.
            self.epoch = int(body.get("epoch", self.epoch))
            asn = body.get("assignment")
            if asn is not None:
                self.assignment = [tuple(tp) for tp in asn]
            code, body = self._poll_once(seq, max_records, timeout_s)
        if code != 200:
            raise RemoteBusError(f"poll failed: {code} {body}")
        # decode BEFORE advancing seq: a decode error (version-skewed server)
        # must leave the seq un-advanced so the retry still hits the cache —
        # and surface as RemoteBusError so callers' bus error handling engages
        try:
            records = [_RemoteRecord(r) for r in body["records"]]
        except (KeyError, ValueError, TypeError) as e:
            raise RemoteBusError(f"undecodable poll batch: {e}") from e
        self._seq = seq
        self.epoch = int(body.get("epoch", self.epoch))
        asn = body.get("assignment")
        if asn is not None:
            self.assignment = [tuple(tp) for tp in asn]
        return records

    def commit(
        self,
        offsets: dict[tuple[str, int], int] | None = None,
        epoch: int | None = None,
    ) -> dict[tuple[str, int], int]:
        """Manual commit (``auto_commit=False`` mode), epoch-fenced.

        ``offsets=None`` commits the server-held fetch positions;
        an explicit ``{(topic, partition): next_offset}`` mapping commits
        exactly those. The commit carries ``epoch`` (default: the epoch
        of the last poll — the epoch its records were delivered under);
        a group rebalance since then refuses the commit with
        :class:`StaleEpochError`. A 404 — this consumer already reaped or
        fenced at the broker — is ALSO StaleEpochError, never a
        re-register: a fenced member's in-flight commit must die with its
        registration, or the fence is a fiction."""
        body: dict[str, Any] = {
            "epoch": self.epoch if epoch is None else int(epoch)}
        if offsets is not None:
            wire: dict[str, dict[str, int]] = {}
            for (t, p), off in offsets.items():
                wire.setdefault(t, {})[str(int(p))] = int(off)
            body["offsets"] = wire
        code, resp = self._broker._request(
            "POST", f"/consumers/{self._cid}/commit", body)
        if code == 404:
            raise StaleEpochError(
                self.group_id, int(body["epoch"]), -1,
                "consumer fenced (reaped) at broker")
        if code == 409:
            raise StaleEpochError(
                self.group_id, int(body["epoch"]),
                int(resp.get("epoch", -1)) if isinstance(resp, dict) else -1)
        if code != 200:
            raise RemoteBusError(f"commit failed: {code} {resp}")
        self.epoch = int(resp.get("epoch", self.epoch))
        return {(t, int(p)): int(off)
                for t, p, off in resp.get("committed", [])}

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._broker._request("POST", f"/consumers/{self._cid}/close", {})
            except RemoteBusError:  # pragma: no cover - server already gone
                pass

    def __enter__(self) -> "RemoteConsumer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def broker_from_url(broker_url: str, **local_kwargs):
    """The one seam components use: BROKER_URL decides local vs remote.

    ``http://host:port`` → networked bus server client;
    ``kafka://bootstrap`` → real-cluster kafka-python adapter
    (reference ProducerDeployment.yaml:96-97 passes the bootstrap the
    same way); anything else → caller builds the in-process Broker.
    """
    if broker_url.startswith("http://"):
        return RemoteBroker(broker_url)
    if broker_url.startswith("kafka://"):
        from ccfd_tpu.bus.kafka_adapter import KafkaAdapter

        # registry= flows through so the adapter's health counters
        # (kafka_adapter_records_produced_total / _send_errors_total, the
        # KafkaCluster board's adapter panels) exist in real deployments,
        # not just tests
        return KafkaAdapter(broker_url[len("kafka://"):], **local_kwargs)
    return None  # caller builds the in-process Broker (with its own options)
