"""Networked broker: the bus as its own service, like the reference's Kafka.

The reference's message plane is a Strimzi Kafka cluster reached over the
network at ``odh-message-bus-kafka-brokers:9092`` (reference
deploy/router.yaml:55-56); every other service — producer, router, KIE
server, notification service — is a separate pod speaking to it. The
in-process ``Broker`` (ccfd_tpu/bus/broker.py) carries the semantics; this
server puts them behind HTTP so the same per-service topology deploys here:
one ``python -m ccfd_tpu bus serve`` process (optionally durable via
``--dir``), and N components connecting with ``BROKER_URL=http://host:port``
through ``RemoteBroker`` (ccfd_tpu/bus/client.py).

Contract (JSON bodies; bytes values ride base64 under ``{"__b64__": ...}``):

    POST /topics/{topic}/produce     {records: [{value, key?}...]} -> metas
    GET  /topics/{topic}/offsets                                   -> [int]
    POST /consumers                  {group, topics[], auto_commit?}
                                            -> {consumer_id, epoch}
    POST /consumers/{id}/poll        {max_records, timeout_s, epoch?}
                                            -> {records, epoch} | 409 stale
    POST /consumers/{id}/commit      {offsets?, epoch?}
                                            -> {committed, epoch} | 409 fenced
    POST /consumers/{id}/close                                      -> {}
    GET  /groups/{group}/epoch                                  -> {epoch}
    POST /groups/{group}/fence       {idle_s}         -> {closed, epoch}
    GET  /metrics | /health/status

Manual-commit consumers (``auto_commit: false``) get at-least-once
semantics under an epoch fence: every rebalance (member join, death/reap,
explicit fence) bumps the group epoch, and a commit stamped with an older
epoch — e.g. from a killed member's in-flight batch — is refused with 409,
never silently applied (bus/broker.py StaleEpochError).

Long-polling maps straight onto ``Consumer.poll(timeout_s=...)`` — the
handler thread parks on the broker's condition variable, so an idle
consumer costs a blocked thread, not a busy loop (the threaded server gives
each request its own thread). Consumers that stop polling for
``consumer_ttl_s`` are reaped so their partitions rebalance to live group
members — Kafka's session-timeout behavior.
"""

from __future__ import annotations

import base64
import contextlib
import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler
from typing import Any

from ccfd_tpu.utils.httpserver import FrameworkHTTPServer

from ccfd_tpu.bus.broker import Broker, Consumer, Record, StaleEpochError
from ccfd_tpu.metrics.prom import Registry

_PRODUCE = re.compile(r"^/topics/([\w.-]+)/produce$")
_OFFSETS = re.compile(r"^/topics/([\w.-]+)/offsets$")
_BEGIN = re.compile(r"^/topics/([\w.-]+)/offsets/begin$")
_GROUP_OFFSETS = re.compile(r"^/groups/([\w.-]+)/topics/([\w.-]+)/offsets$")
_GROUP_EPOCH = re.compile(r"^/groups/([\w.-]+)/epoch$")
_GROUP_FENCE = re.compile(r"^/groups/([\w.-]+)/fence$")
_POLL = re.compile(r"^/consumers/(\d+)/poll$")
_COMMIT = re.compile(r"^/consumers/(\d+)/commit$")
_CLOSE = re.compile(r"^/consumers/(\d+)/close$")


def encode_value(v: Any) -> Any:
    """JSON-safe wire form; bytes ride base64 (CSV lines stay byte-exact)."""
    if isinstance(v, bytes):
        return {"__b64__": base64.b64encode(v).decode()}
    return v


def decode_value(v: Any) -> Any:
    if isinstance(v, dict) and set(v) == {"__b64__"}:
        return base64.b64decode(v["__b64__"])
    return v


def record_view(r: Record) -> dict[str, Any]:
    view = {
        "topic": r.topic,
        "partition": r.partition,
        "offset": r.offset,
        "key": encode_value(r.key),
        "value": encode_value(r.value),
        "timestamp": r.timestamp,
    }
    if r.headers:  # trace context etc.; absent stays off the wire
        view["headers"] = dict(r.headers)
    return view


class BrokerServer:
    def __init__(
        self,
        broker: Broker | None = None,
        registry: Registry | None = None,
        consumer_ttl_s: float = 60.0,
        tracer=None,
    ):
        self.broker = broker or Broker()
        self.registry = registry or Registry()
        self.consumer_ttl_s = consumer_ttl_s
        # observability.trace.Tracer: produce requests join the caller's
        # trace (traceparent header) with a server-side span
        self.tracer = tracer
        self._consumers: dict[int, Consumer] = {}
        self._last_poll: dict[int, float] = {}
        # last delivered batch per consumer, keyed by the client's poll seq:
        # a retry after a lost response re-sends the same seq and gets the
        # same records back (at-least-once) instead of the next batch
        self._delivered: dict[int, tuple[int, list[dict[str, Any]]]] = {}
        self._cid = 0
        self._lock = threading.Lock()
        self._httpd: FrameworkHTTPServer | None = None
        r = self.registry
        self._c_produced = r.counter("bus_records_produced_total", "records in")
        self._c_delivered = r.counter("bus_records_delivered_total", "records out")
        self._g_consumers = r.gauge("bus_consumers", "live remote consumers")
        # broker-health surface, the analog of the reference Kafka board's
        # messages-in-per-topic and partition-health stats
        # (reference deploy/grafana/Kafka.json broker/partition panels)
        self._c_topic_in = r.counter(
            "bus_topic_records_in_total", "records in by topic"
        )
        self._g_end_offset = r.gauge(
            "bus_topic_end_offset", "log end offset by topic/partition"
        )
        self._g_backlog = r.gauge(
            "bus_topic_backlog", "unconsumed records by group/topic"
        )
        # retention surface (reference Kafka board's log-size panels):
        # log-start offset per partition (rises as retention trims), total
        # records deleted by retention, and out-of-range resets (a fetch
        # or rewind that aimed below the retained log)
        self._g_start_offset = r.gauge(
            "bus_topic_log_start_offset", "log start offset by topic/partition"
        )
        self._g_retained = r.gauge(
            "bus_topic_retained_records", "retained records by topic/partition"
        )
        # true counters (ccfd-lint metric-naming: a *_total gauge reads as
        # a broken counter to rate()/increase()): published as DELTAS of
        # the broker's monotonic tallies at scrape time, so a broker
        # crash_restart mid-soak reads as a flat spot, not a reset
        self._c_trimmed = r.counter(
            "bus_records_trimmed_total", "records deleted by retention"
        )
        self._c_oor = r.counter(
            "bus_offset_out_of_range_resets_total",
            "fetches/rewinds clamped to the log start",
        )
        self._last_trimmed = 0
        self._last_oor = 0

    def refresh_health_gauges(self) -> None:
        """Publish per-topic end offsets and per-group backlog (lag) the way
        a Kafka exporter does — at scrape time, not on the produce path.
        The snapshot itself is the broker's job (it owns the lock and the
        data structures); this layer only turns it into gauges."""
        snap = self.broker.health_snapshot()
        topics = snap["topics"]
        groups = snap["groups"]
        all_begins = snap.get("begins", {})
        for name, ends in topics.items():
            begins = all_begins.get(name)
            for p, end in enumerate(ends):
                labels = {"topic": name, "partition": str(p)}
                self._g_end_offset.set(end, labels=labels)
                if begins is not None:
                    self._g_start_offset.set(begins[p], labels=labels)
                    self._g_retained.set(end - begins[p], labels=labels)
        # delta fold under the server lock: two concurrent scrapes racing
        # the read-inc-update sequence would double-count a delta
        with self._lock:
            if hasattr(self.broker, "records_trimmed"):
                cur = int(self.broker.records_trimmed)
                self._c_trimmed.inc(max(0, cur - self._last_trimmed))
                self._last_trimmed = cur
            if hasattr(self.broker, "oor_resets"):
                cur = int(self.broker.oor_resets)
                self._c_oor.inc(max(0, cur - self._last_oor))
                self._last_oor = cur
        for g, tps in groups.items():
            lag_by_topic: dict[str, int] = {}
            for (tname, p), committed in tps.items():
                ends = topics.get(tname)
                if ends is not None and p < len(ends):
                    lag_by_topic[tname] = lag_by_topic.get(tname, 0) + max(
                        0, ends[p] - committed
                    )
            for tname, lag in lag_by_topic.items():
                self._g_backlog.set(lag, labels={"group": g, "topic": tname})

    # -- consumer registry -------------------------------------------------
    def _register(self, group: str, topics: list[str],
                  auto_commit: bool = True) -> int:
        with self._lock:
            self._reap_locked()
            self._cid += 1
            cid = self._cid
            self._consumers[cid] = self.broker.consumer(
                group, tuple(topics), auto_commit=auto_commit)
            self._last_poll[cid] = time.monotonic()
            self._g_consumers.set(len(self._consumers))
            return cid

    def fence_group(self, group: str, idle_s: float = 0.0) -> int:
        """Explicitly fence a group's idle consumers (the supervisor's
        member-death actuator): every consumer of ``group`` that has not
        polled within ``idle_s`` is closed NOW — its partitions rebalance
        to survivors and the group epoch bumps, so any commit the dead
        member still had in flight is refused (StaleEpochError). Faster
        than waiting out ``consumer_ttl_s``; returns consumers closed."""
        now = time.monotonic()
        closed: list[Consumer] = []
        with self._lock:
            dead = [
                cid for cid, c in self._consumers.items()
                if c.group_id == group
                and now - self._last_poll.get(cid, 0.0) >= idle_s
            ]
            for cid in dead:
                c = self._consumers.pop(cid, None)
                self._last_poll.pop(cid, None)
                self._delivered.pop(cid, None)
                if c is not None:
                    closed.append(c)
            self._g_consumers.set(len(self._consumers))
        for c in closed:
            c.close()
        return len(closed)

    def _consumer(self, cid: int) -> Consumer | None:
        with self._lock:
            # reap here too: registration alone would let a dead group
            # member pin its partitions forever while survivors keep polling
            self._reap_locked(keep=cid)
            self._last_poll[cid] = time.monotonic()
            return self._consumers.get(cid)

    def _close_consumer(self, cid: int) -> bool:
        with self._lock:
            c = self._consumers.pop(cid, None)
            self._last_poll.pop(cid, None)
            self._delivered.pop(cid, None)
            self._g_consumers.set(len(self._consumers))
        if c is None:
            return False
        c.close()
        return True

    def _reap_locked(self, keep: int | None = None) -> None:
        """Close consumers that stopped polling (Kafka session timeout):
        their partitions rebalance to surviving group members."""
        now = time.monotonic()
        dead = [
            cid
            for cid, t in self._last_poll.items()
            if cid != keep and now - t > self.consumer_ttl_s
        ]
        for cid in dead:
            c = self._consumers.pop(cid, None)
            self._last_poll.pop(cid, None)
            self._delivered.pop(cid, None)
            if c is not None:
                c.close()
        if dead:
            self._g_consumers.set(len(self._consumers))

    # -- HTTP ----------------------------------------------------------------
    def _handler_class(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # quiet
                pass

            def _send_json(self, code: int, obj: Any) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.rstrip("/")
                if path in ("/metrics", "/prometheus"):
                    server.refresh_health_gauges()
                    body = server.registry.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path in ("/health/status", "/health", "/healthz"):
                    self._send_json(200, {"status": "ok"})
                    return
                m = _BEGIN.match(path)
                if m:
                    self._send_json(
                        200, server.broker.beginning_offsets(m.group(1)))
                    return
                m = _OFFSETS.match(path)
                if m:
                    self._send_json(200, server.broker.end_offsets(m.group(1)))
                    return
                m = _GROUP_OFFSETS.match(path)
                if m:
                    self._send_json(200, server.broker.committed_offsets(
                        m.group(1), m.group(2)))
                    return
                m = _GROUP_EPOCH.match(path)
                if m:
                    self._send_json(
                        200, {"epoch": server.broker.group_epoch(m.group(1))})
                    return
                self._send_json(404, {"error": "not found"})

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                except ValueError:
                    length = 0
                raw = self.rfile.read(length) if length else b"{}"
                try:
                    payload = json.loads(raw or b"{}")
                except (ValueError, json.JSONDecodeError):
                    self._send_json(400, {"error": "malformed JSON body"})
                    return
                if not isinstance(payload, dict):
                    self._send_json(400, {"error": "JSON body must be an object"})
                    return
                path = self.path.rstrip("/")
                m = _PRODUCE.match(path)
                if m:
                    records = payload.get("records")
                    if not isinstance(records, list):
                        self._send_json(400, {"error": "need records: [...]"})
                        return
                    # batch-level trace context: the producing client's
                    # traceparent (HTTP header) stamps every record of the
                    # batch, so remote consumers resume the SAME trace the
                    # in-process transport would carry. An explicit
                    # "headers" body field wins (a relay forwarding records
                    # that already carry their own context).
                    rec_headers = payload.get("headers")
                    if rec_headers is not None and not isinstance(rec_headers, dict):
                        rec_headers = None  # malformed: drop, don't 500
                    span_cm = None
                    if server.tracer is not None:
                        from ccfd_tpu.observability import trace as _trace

                        parent = _trace.extract_context(self.headers)
                        span_cm = server.tracer.span(
                            "bus.produce", parent=parent,
                            attrs={"topic": m.group(1),
                                   "records": len(records)},
                        )
                        if rec_headers is None and parent is not None:
                            rec_headers = {
                                _trace.TRACEPARENT:
                                    _trace.format_traceparent(parent),
                            }
                    elif rec_headers is None:
                        tp = self.headers.get("traceparent")
                        if tp:
                            rec_headers = {"traceparent": tp}
                    # explicit-partition mode (control records, e.g.
                    # recovery's engine_restored markers). Validate the
                    # WHOLE batch before producing anything: a mid-batch
                    # reject would otherwise leave a silent prefix in the
                    # log that the counters never saw. (bool is an int
                    # subclass in Python — JSON true must not route to
                    # partition 1.)
                    for r in records:
                        part = r.get("partition")
                        if part is not None and (
                            isinstance(part, bool)
                            or not isinstance(part, int)
                        ):
                            self._send_json(
                                400, {"error": "partition must be an int"}
                            )
                            return
                    metas = []
                    try:
                        with (span_cm if span_cm is not None
                              else contextlib.nullcontext()):
                            for r in records:
                                rec = server.broker.produce(
                                    m.group(1),
                                    decode_value(r.get("value")),
                                    key=decode_value(r.get("key")),
                                    partition=r.get("partition"),
                                    headers=rec_headers,
                                )
                                metas.append({"partition": rec.partition,
                                              "offset": rec.offset})
                    except ValueError as e:
                        # out-of-range partition: records 0..k-1 ARE in
                        # the log — count them so metrics agree with
                        # end_offsets, and tell the client how far it got
                        if metas:
                            server._c_produced.inc(len(metas))
                            server._c_topic_in.inc(
                                len(metas), labels={"topic": m.group(1)}
                            )
                        self._send_json(
                            400, {"error": str(e), "produced": len(metas)}
                        )
                        return
                    server._c_produced.inc(len(metas))
                    server._c_topic_in.inc(len(metas), labels={"topic": m.group(1)})
                    self._send_json(200, {"metas": metas})
                    return
                if path == "/consumers":
                    group = payload.get("group")
                    topics = payload.get("topics")
                    if not group or not isinstance(topics, list) or not topics:
                        self._send_json(400, {"error": "need group and topics[]"})
                        return
                    auto_commit = bool(payload.get("auto_commit", True))
                    cid = server._register(str(group), [str(t) for t in topics],
                                           auto_commit=auto_commit)
                    self._send_json(201, {
                        "consumer_id": cid,
                        "epoch": server.broker.group_epoch(str(group)),
                    })
                    return
                m = _POLL.match(path)
                if m:
                    cid = int(m.group(1))
                    c = server._consumer(cid)
                    if c is None:
                        self._send_json(404, {"error": "no such consumer"})
                        return
                    # optional client-epoch fence: a manual-commit client
                    # sends the epoch it last synced; a mismatch means the
                    # group rebalanced under it — 409 with the new epoch +
                    # assignment lets it resync BEFORE consuming records
                    # it would later be fenced from committing
                    want_epoch = payload.get("epoch")
                    if want_epoch is not None:
                        cur = server.broker.group_epoch(c.group_id)
                        if int(want_epoch) != cur:
                            self._send_json(409, {
                                "error": "stale epoch",
                                "epoch": cur,
                                "assignment": [list(tp)
                                               for tp in c.assignment()],
                            })
                            return
                    seq = payload.get("seq")
                    if seq is not None:
                        with server._lock:
                            cached = server._delivered.get(cid)
                        if cached is not None and cached[0] == seq:
                            # response to this seq was lost in transit:
                            # redeliver, don't advance past the batch
                            self._send_json(
                                200, {"records": cached[1],
                                      "epoch": cached[2]})
                            return
                    timeout = min(float(payload.get("timeout_s", 0.0)), 30.0)
                    recs = c.poll(
                        max_records=int(payload.get("max_records", 500)),
                        timeout_s=timeout,
                    )
                    views = [record_view(r) for r in recs]
                    # the epoch these records were DELIVERED under — the
                    # commit fence for this batch
                    poll_epoch = c._poll_epoch
                    if seq is not None and recs:
                        with server._lock:
                            server._delivered[cid] = (seq, views, poll_epoch)
                    server._c_delivered.inc(len(recs))
                    self._send_json(200, {"records": views,
                                          "epoch": poll_epoch,
                                          "assignment": [list(tp) for tp in
                                                         c.assignment()]})
                    return
                m = _COMMIT.match(path)
                if m:
                    cid = int(m.group(1))
                    c = server._consumer(cid)
                    if c is None:
                        # a reaped/fenced consumer CANNOT commit — the 404
                        # is the fence for a killed member whose commit
                        # raced its own reaping (the client maps this to
                        # StaleEpochError, never to re-register)
                        self._send_json(404, {"error": "no such consumer"})
                        return
                    offsets = payload.get("offsets")
                    conv = None
                    if offsets is not None:
                        if not isinstance(offsets, dict):
                            self._send_json(
                                400, {"error": "offsets must be an object"})
                            return
                        try:
                            conv = {
                                (str(t), int(p)): int(off)
                                for t, parts in offsets.items()
                                for p, off in parts.items()
                            }
                        except (TypeError, ValueError, AttributeError):
                            self._send_json(
                                400,
                                {"error": "offsets must be "
                                          "{topic: {partition: offset}}"})
                            return
                    try:
                        done = c.commit(conv, epoch=payload.get("epoch"))
                    except StaleEpochError as e:
                        self._send_json(409, {
                            "error": "stale epoch",
                            "epoch": e.current_epoch,
                            "detail": str(e),
                        })
                        return
                    self._send_json(200, {
                        "committed": [[t, p, off]
                                      for (t, p), off in done.items()],
                        "epoch": server.broker.group_epoch(c.group_id),
                    })
                    return
                m = _GROUP_FENCE.match(path)
                if m:
                    idle_s = float(payload.get("idle_s", 0.0))
                    n = server.fence_group(m.group(1), idle_s=idle_s)
                    self._send_json(200, {
                        "closed": n,
                        "epoch": server.broker.group_epoch(m.group(1)),
                    })
                    return
                m = _CLOSE.match(path)
                if m:
                    ok = server._close_consumer(int(m.group(1)))
                    self._send_json(200 if ok else 404, {})
                    return
                m = _GROUP_OFFSETS.match(path)
                if m:
                    # offset-admin parity with the in-process broker and
                    # the Kafka adapter (kafka-consumer-groups
                    # --reset-offsets): the remote transport's missing
                    # piece for checkpoint-rewind recovery + the
                    # coordinator's retention pin
                    offs = payload.get("offsets")
                    if (not isinstance(offs, list)
                            or not all(isinstance(o, int)
                                       and not isinstance(o, bool)
                                       for o in offs)):
                        self._send_json(400, {"error": "need offsets: [int]"})
                        return
                    try:
                        server.broker.reset_offsets(
                            m.group(1), m.group(2), offs)
                    except ValueError as e:
                        self._send_json(400, {"error": str(e)})
                        return
                    self._send_json(200, {
                        "committed": server.broker.committed_offsets(
                            m.group(1), m.group(2)),
                    })
                    return
                self._send_json(404, {"error": "not found"})

        return Handler

    def start(self, host: str = "0.0.0.0", port: int = 9092) -> int:
        self._httpd = FrameworkHTTPServer((host, port), self._handler_class())
        threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="ccfd-bus"
        ).start()
        return self._httpd.server_address[1]

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        with self._lock:
            consumers = list(self._consumers.values())
            self._consumers.clear()
            self._last_poll.clear()
        for c in consumers:
            c.close()
        self.broker.close()
