"""Real-cluster adapter: the framework's Broker/Consumer surface over
``kafka-python``.

The reference's transport is a 3-broker Strimzi cluster reached by a
bootstrap string (reference deploy/frauddetection_cr.yaml:73-77,
deploy/kafka/ProducerDeployment.yaml:96-97). Every component here is
written against the Kafka-shaped API of ``bus.broker.Broker``; this module
fills the one remaining seam so ``BROKER_URL=kafka://bootstrap:9092``
swaps a real cluster in with zero component changes.

Wire format: values/keys are arbitrary JSON-able Python objects (the same
domain the networked bus server carries); they ride Kafka as UTF-8 JSON of
the bus wire form (``encode_value`` — bytes payloads ride base64, so CSV
lines stay byte-exact end to end). Keys serialize the same way, so
hash-on-key-bytes partition routing is stable on content, matching the
in-process broker's crc32-on-key-bytes intent.

Delivery semantics mirror the in-process ``Consumer`` ("offsets
auto-commit on poll", bus/broker.py — at-most-once hand-off): the
adapter's consumer polls with ``enable_auto_commit=False`` and commits
synchronously INSIDE each non-empty poll, so a successor in the group
resumes after the delivered batch; a crash mid-handling drops that batch
rather than redelivering it, identically on both transports. (Only a
crash in the narrow window between the broker fetch and the commit call
itself redelivers.)

``kafka-python`` is not in the baked image; construction degrades to a
clear RuntimeError without it. The ``kafka_module`` seam lets tests run
the full adapter logic against an in-process emulation of the
kafka-python API (tests/fake_kafka.py), which is also the recipe for any
other client library.
"""

from __future__ import annotations

import importlib
import json
import time
from typing import Any, Iterable

from ccfd_tpu.bus.broker import Record
from ccfd_tpu.bus.server import decode_value, encode_value


def _dumps(v: Any) -> bytes | None:
    if v is None:
        return None
    return json.dumps(encode_value(v), separators=(",", ":")).encode()


def _loads(b: bytes | None) -> Any:
    if b is None:
        return None
    return decode_value(json.loads(b.decode()))


def _wire_headers(headers: dict) -> list[tuple[str, bytes]]:
    """Framework headers dict -> kafka-python record headers."""
    return [(str(k), str(v).encode()) for k, v in headers.items()]


def _unwire_headers(raw) -> dict | None:
    """kafka-python record headers -> framework dict (None when absent)."""
    if not raw:
        return None
    out = {}
    for k, v in raw:
        out[str(k)] = v.decode("utf-8", "replace") if isinstance(v, bytes) else v
    return out


class KafkaAdapter:
    """``bus.broker.Broker`` surface backed by a real Kafka cluster.

    Parameters
    ----------
    bootstrap: broker bootstrap string, e.g. ``host:9092`` (reference
        ProducerDeployment.yaml:96-97).
    default_partitions: partition count for topics this adapter creates
        (the reference cluster runs 3 brokers; 3 partitions is its
        parallelism unit, frauddetection_cr.yaml:76).
    kafka_module: dependency seam — anything exposing the kafka-python
        surface (KafkaProducer/KafkaConsumer/TopicPartition, .admin,
        .errors). Defaults to ``import kafka``.
    """

    def __init__(
        self,
        bootstrap: str,
        default_partitions: int = 3,
        kafka_module: Any = None,
        timeout_s: float = 30.0,
        registry: Any = None,
    ):
        if kafka_module is None:
            try:
                kafka_module = importlib.import_module("kafka")
            except ImportError as e:
                raise RuntimeError(
                    "kafka-python is not installed; use the in-process Broker "
                    "(BROKER_URL=inproc://) or the networked bus server "
                    "(BROKER_URL=http://host:9092)"
                ) from e
        self._kafka = kafka_module
        self.bootstrap = bootstrap
        self._default_partitions = default_partitions
        self._timeout_s = timeout_s
        self._producer = kafka_module.KafkaProducer(
            bootstrap_servers=bootstrap,
            value_serializer=_dumps,
            key_serializer=_dumps,
        )
        self._meta_consumer = None  # lazy: only needed for end_offsets
        self._admin = None  # lazy: only needed for create_topic
        self._group_admins: dict[str, Any] = {}  # offset-admin consumers
        # adapter-side health series for the KafkaCluster board (broker
        # internals come from the JMX exporter; the adapter contributes its
        # own produce/send-failure view of cluster health)
        self._c_produced = self._c_send_errors = None
        if registry is not None:
            self._c_produced = registry.counter(
                "kafka_adapter_records_produced_total",
                "records acknowledged by the cluster",
            )
            self._c_send_errors = registry.counter(
                "kafka_adapter_send_errors_total",
                "sends that failed or timed out",
            )

    # -- admin ------------------------------------------------------------
    def create_topic(self, name: str, n_partitions: int | None = None) -> None:
        admin_mod = importlib.import_module(
            self._kafka.__name__ + ".admin"
        ) if not hasattr(self._kafka, "admin") else self._kafka.admin
        errors_mod = importlib.import_module(
            self._kafka.__name__ + ".errors"
        ) if not hasattr(self._kafka, "errors") else self._kafka.errors
        if self._admin is None:
            self._admin = admin_mod.KafkaAdminClient(bootstrap_servers=self.bootstrap)
        topic = admin_mod.NewTopic(
            name=name,
            num_partitions=n_partitions or self._default_partitions,
            replication_factor=1,
        )
        try:
            self._admin.create_topics([topic])
        except errors_mod.TopicAlreadyExistsError:
            pass

    def end_offsets(self, topic: str) -> list[int]:
        if self._meta_consumer is None:
            self._meta_consumer = self._kafka.KafkaConsumer(
                bootstrap_servers=self.bootstrap
            )
        parts = self._meta_consumer.partitions_for_topic(topic)
        if not parts:
            return []
        tps = [self._kafka.TopicPartition(topic, p) for p in sorted(parts)]
        eo = self._meta_consumer.end_offsets(tps)
        return [eo[tp] for tp in tps]

    def beginning_offsets(self, topic: str) -> list[int]:
        """Per-partition log-start (rises as the cluster's retention
        deletes segments) — Broker/RemoteBroker surface parity."""
        if self._meta_consumer is None:
            self._meta_consumer = self._kafka.KafkaConsumer(
                bootstrap_servers=self.bootstrap
            )
        parts = self._meta_consumer.partitions_for_topic(topic)
        if not parts:
            return []
        tps = [self._kafka.TopicPartition(topic, p) for p in sorted(parts)]
        bo = self._meta_consumer.beginning_offsets(tps)
        return [bo[tp] for tp in tps]

    # -- offset admin (crash-recovery surface, Broker-parity) -------------
    def _group_admin(self, group_id: str):
        """Cached group-scoped consumer for offset admin: the checkpoint
        coordinator describes every cut group each interval — and while
        the router's pause barrier is held — so paying consumer
        construction + coordinator discovery per call would stretch every
        checkpoint stall."""
        c = self._group_admins.get(group_id)
        if c is None:
            c = self._kafka.KafkaConsumer(
                bootstrap_servers=self.bootstrap, group_id=group_id,
                enable_auto_commit=False,
            )
            self._group_admins[group_id] = c
        return c

    def _partition_count(self, topic: str) -> int:
        if self._meta_consumer is None:
            self._meta_consumer = self._kafka.KafkaConsumer(
                bootstrap_servers=self.bootstrap
            )
        parts = self._meta_consumer.partitions_for_topic(topic)
        return len(parts or ())

    def committed_offsets(self, group_id: str, topic: str) -> list[int]:
        """Committed offset per partition for a consumer group — the
        ``kafka-consumer-groups --describe`` analog, same surface as
        ``Broker.committed_offsets`` so the checkpoint coordinator
        (runtime/recovery.py) records cuts identically against a real
        cluster. Never-committed partitions read as 0."""
        c = self._group_admin(group_id)
        return [
            int(c.committed(self._kafka.TopicPartition(topic, p)) or 0)
            for p in range(self._partition_count(topic))
        ]

    def reset_offsets(self, group_id: str, topic: str,
                      offsets: list[int]) -> None:
        """Rewind (or advance) a group's commits — Kafka's
        ``kafka-consumer-groups --reset-offsets --to-offset`` analog,
        same surface as ``Broker.reset_offsets``. Kafka's own contract
        applies: the group must have no ACTIVE members (the CLI tool
        refuses too). NOTE a merely-paused consumer loop does NOT satisfy
        this — kafka-python heartbeats keep parked consumers as live
        members — which is why the recovery coordinator recycles the
        router's consumers (Router.recycle_consumers) before rewinding.
        Out-of-range values clamp to the log end."""
        ends = self.end_offsets(topic)
        if len(offsets) != len(ends):
            raise ValueError(
                f"{topic!r} has {len(ends)} partitions, "
                f"got {len(offsets)} offsets"
            )
        om_cls = getattr(self._kafka, "OffsetAndMetadata", None)
        c = self._group_admin(group_id)
        commit_map = {}
        for p, off in enumerate(offsets):
            off = max(0, min(int(off), ends[p]))
            tp = self._kafka.TopicPartition(topic, p)
            if om_cls is None:
                commit_map[tp] = off
            else:
                try:
                    commit_map[tp] = om_cls(off, None)
                except TypeError:  # kafka-python >= 2.2 adds leader_epoch
                    commit_map[tp] = om_cls(off, None, -1)
        c.commit(commit_map)

    # -- produce ----------------------------------------------------------
    def produce(self, topic: str, value: Any, key: Any = None,
                partition: int | None = None,
                headers: dict | None = None) -> dict[str, Any]:
        """``partition`` overrides key routing (Kafka's explicit-partition
        mode) — the recovery coordinator's per-partition ``engine_restored``
        markers require it, same surface as ``Broker.produce``. ``headers``
        map to real Kafka record headers (list of (str, bytes)) — trace
        context survives the real-cluster transport too."""
        kw: dict[str, Any] = {}
        if partition is not None:
            kw["partition"] = partition
        if headers:
            kw["headers"] = _wire_headers(headers)
        fut = self._producer.send(topic, value=value, key=key, **kw)
        try:
            md = fut.get(timeout=self._timeout_s)
        except Exception:
            if self._c_send_errors is not None:
                self._c_send_errors.inc()
            raise
        if self._c_produced is not None:
            self._c_produced.inc()
        return {"topic": md.topic, "partition": md.partition, "offset": md.offset}

    def produce_batch(
        self, topic: str, values: Iterable[Any],
        keys: Iterable[Any] | None = None,
        headers: dict | None = None,
    ) -> int:
        """Pipelined sends + one flush (the producer's hot path). A send
        error fails the call after the flush resolves every in-flight
        future. Unlike the in-process broker's prefix-committed batches,
        per-record futures across partitions land in any order: an
        ARBITRARY SUBSET may be acknowledged before the call raises —
        only the counters are per-record."""
        values = list(values)
        key_list = list(keys) if keys is not None else [None] * len(values)
        if len(key_list) != len(values):
            raise ValueError("keys and values must have equal length")
        kw = {"headers": _wire_headers(headers)} if headers else {}
        futures = [
            self._producer.send(topic, value=v, key=k, **kw)
            for v, k in zip(values, key_list)
        ]
        self._producer.flush(timeout=self._timeout_s)
        # per-record accounting even on partial failure: futures that the
        # cluster acknowledged count as produced (their records ARE in the
        # log, visible to consumers — which records that is depends on
        # partition ordering, not input order), each failed future counts
        # one error, and the call still fails afterward
        n_ok = 0
        first_err: Exception | None = None
        for f in futures:
            try:
                f.get(timeout=self._timeout_s)
                n_ok += 1
            except Exception as e:  # noqa: BLE001 - re-raised below
                if self._c_send_errors is not None:
                    self._c_send_errors.inc()
                if first_err is None:
                    first_err = e
        if self._c_produced is not None and n_ok:
            self._c_produced.inc(n_ok)
        if first_err is not None:
            raise first_err
        return len(values)

    # -- consume ----------------------------------------------------------
    def consumer(self, group_id: str, topics: Iterable[str],
                 auto_commit: bool = True) -> "KafkaConsumerAdapter":
        """``auto_commit=False`` defers the offset commit to an explicit
        :meth:`KafkaConsumerAdapter.commit` call (at-least-once, the fleet
        router's commit-after-route discipline); the default keeps the
        historical commit-on-poll hand-off. Either way the underlying
        kafka-python consumer runs ``enable_auto_commit=False`` — the
        difference is only WHO calls commit, and when."""
        kc = self._kafka.KafkaConsumer(
            *topics,
            bootstrap_servers=self.bootstrap,
            group_id=group_id,
            enable_auto_commit=False,
            auto_offset_reset="earliest",
            value_deserializer=_loads,
            key_deserializer=_loads,
        )
        return KafkaConsumerAdapter(kc, group_id, tuple(topics),
                                    auto_commit=auto_commit)

    def close(self) -> None:
        self._producer.close()
        if self._meta_consumer is not None:
            self._meta_consumer.close()
        if self._admin is not None:
            self._admin.close()
        for c in self._group_admins.values():
            c.close()
        self._group_admins.clear()


class KafkaConsumerAdapter:
    """``bus.broker.Consumer`` surface over a kafka-python KafkaConsumer.

    Commit discipline mirrors the in-process Consumer (bus/broker.py:
    "auto-commit on poll", at-most-once hand-off): the batch a poll()
    delivers is committed as part of that poll, so a successor consumer in
    the group resumes AFTER it — a crash mid-handling drops that batch
    rather than redelivering it, identically on both transports.
    """

    def __init__(self, kc: Any, group_id: str, topics: tuple[str, ...],
                 auto_commit: bool = True):
        self._kc = kc
        self.group_id = group_id
        self.topics = topics
        self._closed = False
        self._auto_commit = auto_commit

    def poll(self, max_records: int = 500, timeout_s: float = 0.0) -> list[Record]:
        if self._closed:
            return []
        by_tp = self._kc.poll(
            timeout_ms=max(0, int(timeout_s * 1000)), max_records=max_records
        )
        out: list[Record] = []
        for tp, recs in sorted(by_tp.items(), key=lambda kv: (kv[0].topic, kv[0].partition)):
            for r in recs:
                out.append(
                    Record(
                        topic=r.topic,
                        partition=r.partition,
                        offset=r.offset,
                        key=r.key,
                        value=r.value,
                        # kafka timestamps are epoch-ms; bus records use
                        # epoch-s. A missing broker timestamp falls back
                        # to consume time, NOT 0: the router's decision-
                        # latency SLO observes time.time() - timestamp,
                        # and an epoch-0 stamp would poison the histogram
                        # with ~1.7e9 s "latencies"
                        # (kafka-python reports -1 for
                        # TIMESTAMP_NOT_AVAILABLE — also a fallback case)
                        timestamp=(r.timestamp / 1000.0
                                   if r.timestamp and r.timestamp > 0
                                   else time.time()),
                        headers=_unwire_headers(
                            getattr(r, "headers", None)),
                    )
                )
        if out and self._auto_commit:
            self._kc.commit()
        return out

    def assignment(self) -> list[tuple[str, int]]:
        """Currently owned (topic, partition) pairs."""
        return sorted((tp.topic, tp.partition)
                      for tp in (self._kc.assignment() or ()))

    def commit(self, offsets: Any = None, epoch: Any = None
               ) -> dict[tuple[str, int], int]:
        """Manual commit (``auto_commit=False`` mode). Kafka's own group
        generation is the epoch fence on this transport: a commit from a
        member fenced by a rebalance raises CommitFailedError, surfaced
        as the same :class:`~ccfd_tpu.bus.broker.StaleEpochError` the
        in-process and HTTP transports raise. ``offsets`` maps
        ``{(topic, partition): next_offset}``; ``None`` commits the
        consumed positions. ``epoch`` is accepted for surface parity and
        ignored — the broker's generation check is authoritative here."""
        from ccfd_tpu.bus.broker import StaleEpochError

        kw = {}
        if offsets is not None:
            tp_cls = self._kafka_tp_cls()
            meta_cls = self._kafka_meta_cls()
            kw["offsets"] = {
                tp_cls(t, int(p)): meta_cls(int(off), None)
                for (t, p), off in offsets.items()
            }
        try:
            self._kc.commit(**kw)
        except Exception as e:  # kafka.errors.CommitFailedError et al.
            if type(e).__name__ in ("CommitFailedError",
                                    "RebalanceInProgressError",
                                    "IllegalGenerationError"):
                raise StaleEpochError(self.group_id, -1, -1, str(e)) from e
            raise
        return dict(offsets or {})

    def _kafka_tp_cls(self):
        from kafka.structs import TopicPartition

        return TopicPartition

    def _kafka_meta_cls(self):
        from kafka.structs import OffsetAndMetadata

        return OffsetAndMetadata

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._kc.close()

    def __enter__(self) -> "KafkaConsumerAdapter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
