"""Model lifecycle: versioned shadow -> canary -> gated promotion -> rollback.

The reference's defining loop is feedback-driven retraining — investigator
decisions become labels that retrain the served model (reference
README.md:571-581) — and ``parallel/online.py`` reproduces the retrain but
then hot-swaps every candidate straight into production unvalidated. This
package turns that blind swap into a governed state machine:

    TRAIN -> SHADOW -> CANARY -> PROMOTE
                 \\        \\-> ROLLBACK (guardrail breach / breaker open)
                  \\-> REJECT

- :mod:`~ccfd_tpu.lifecycle.versions` — ModelVersion lineage (monotone id,
  parent, label watermark, checkpoint ref, recorded eval metrics) persisted
  so restarts resume lineage, plus the transition audit trail.
- :mod:`~ccfd_tpu.lifecycle.shadow` — the challenger scores the SAME live
  batches off the critical path; paired champion/challenger scores land on
  a bus topic.
- :mod:`~ccfd_tpu.lifecycle.evaluator` — joins shadow scores with the
  delayed human labels from the fraud process (AUC / precision@k /
  alert-rate delta) and tracks champion-vs-challenger score-distribution
  PSI (reusing :func:`ccfd_tpu.analytics.engine.psi`).
- :mod:`~ccfd_tpu.lifecycle.controller` — guardrailed transitions; the
  canary phase drives the :mod:`ccfd_tpu.serving.graph` ``hash_split``
  ROUTER weights, and any guardrail breach (or a scorer-edge breaker open)
  during canary auto-rolls back to the champion checkpoint.
"""

from ccfd_tpu.lifecycle.controller import (  # noqa: F401
    CanaryGate,
    Guardrails,
    LifecycleController,
)
from ccfd_tpu.lifecycle.evaluator import ShadowEvaluator  # noqa: F401
from ccfd_tpu.lifecycle.shadow import ShadowTap  # noqa: F401
from ccfd_tpu.lifecycle.versions import ModelVersion, VersionStore  # noqa: F401
