"""Challenger evaluation: delayed human labels + shadow score distributions.

Two evidence streams feed a candidate's verdict:

- **Labels** (the fraud process's resolution stream on ``cfg.labels_topic``
  — process/fraud.py ``record``): each labeled transaction is re-scored by
  BOTH the champion (host forward) and the challenger (double-buffered
  challenger slot), giving paired (y, p_champion, p_challenger) samples on
  exactly the same rows. From these: AUC (rank/Mann-Whitney with average
  ranks) and precision@k — the ranking-quality gates.
- **Shadow pairs** (ShadowTap's paired records on the shadow topic): the
  champion-vs-challenger score-distribution comparison over live traffic —
  per-model alert rates against ``FRAUD_THRESHOLD`` (their delta is the
  "how many more investigations would this model open" operational gate)
  and score-distribution PSI reusing :func:`ccfd_tpu.analytics.engine.psi`
  on fixed ``[0, 1]`` histograms.

The evaluator is single-candidate: ``begin(version)`` resets the
accumulators; records carrying any other version are dropped as stale.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import numpy as np

from ccfd_tpu.analytics.engine import psi
from ccfd_tpu.config import Config
from ccfd_tpu.data.ccfd import FEATURE_NAMES

DEFAULT_SCORE_BINS = 32


def auc_score(y: np.ndarray, p: np.ndarray) -> float:
    """NaN-tolerant rank AUC: :func:`ccfd_tpu.utils.metrics_math.roc_auc`
    (midrank Mann-Whitney) with "not judgeable yet" — empty input or one
    class only — reported as NaN instead of raising, which is what the
    guardrail checks key on (a NaN gate neither passes nor breaches)."""
    from ccfd_tpu.utils.metrics_math import roc_auc

    y = np.asarray(y, np.float64)
    if len(y) == 0 or y.sum() == 0 or y.sum() == len(y):
        return float("nan")
    return roc_auc(y > 0.5, np.asarray(p, np.float64))


def precision_at_k(y: np.ndarray, p: np.ndarray, k: int) -> float:
    """Fraction of true frauds in the k highest-scored rows — the
    investigator-queue quality metric (k = the queue capacity)."""
    y = np.asarray(y, np.float64)
    p = np.asarray(p, np.float64)
    if len(y) == 0:
        return float("nan")
    k = max(1, min(int(k), len(y)))
    top = np.argsort(p, kind="mergesort")[::-1][:k]
    return float(y[top].mean())


class EvalSnapshot(NamedTuple):
    version: int | None
    n_labels: int
    n_shadow_rows: int
    auc_champion: float
    auc_challenger: float
    precision_champion: float
    precision_challenger: float
    alert_rate_champion: float
    alert_rate_challenger: float
    alert_rate_delta: float
    score_psi: float

    def to_dict(self) -> dict[str, Any]:
        """JSON-safe dict: non-finite floats (not-judgeable-yet gates)
        become null — these land in the persisted audit trail and the
        ``lifecycle --json`` export, which strict parsers must accept."""
        import math

        out: dict[str, Any] = {}
        for k, v in self._asdict().items():
            if v is None or isinstance(v, int):
                out[k] = v
            else:
                f = float(v)
                out[k] = f if math.isfinite(f) else None
        return out


class ShadowEvaluator:
    def __init__(
        self,
        cfg: Config,
        broker: Any,
        scorer: Any,
        registry: Any = None,
        nbins: int = DEFAULT_SCORE_BINS,
        k_frac: float = 0.05,
        max_labels: int = 50_000,
    ):
        self.cfg = cfg
        self.scorer = scorer
        self.nbins = int(nbins)
        self.k_frac = float(k_frac)
        # label-accumulator bound: a candidate parked in SHADOW (traffic
        # too thin to ever fill its gates) must not grow the paired lists
        # forever; oldest labels age out together so the pairing holds
        self.max_labels = int(max_labels)
        self._labels_consumer = broker.consumer(
            "lifecycle-eval", (cfg.labels_topic,)
        )
        self._shadow_consumer = broker.consumer(
            "lifecycle-shadow", (cfg.shadow_topic,)
        )
        self._version: int | None = None
        self._edges = np.linspace(0.0, 1.0, self.nbins + 1)
        self._reset_accumulators()
        self._g_labels = self._g_auc = self._g_psi = self._g_delta = None
        if registry is not None:
            self._g_labels = registry.gauge(
                "ccfd_lifecycle_eval_labels",
                "labels joined against the current candidate",
            )
            self._g_rows = registry.gauge(
                "ccfd_lifecycle_eval_shadow_rows",
                "shadow-pair rows folded into the candidate's distributions",
            )
            self._g_auc = registry.gauge(
                "ccfd_lifecycle_auc",
                "label AUC by model (champion vs current challenger)",
            )
            self._g_psi = registry.gauge(
                "ccfd_lifecycle_score_psi",
                "champion-vs-challenger score-distribution PSI over live "
                "shadow traffic",
            )
            self._g_delta = registry.gauge(
                "ccfd_lifecycle_alert_rate_delta",
                "challenger minus champion alert rate at FRAUD_THRESHOLD",
            )

    def _reset_accumulators(self) -> None:
        self._y: list[float] = []
        self._p_champ: list[float] = []
        self._p_chall: list[float] = []
        self._hist_champ = np.zeros(self.nbins, np.float64)
        self._hist_chall = np.zeros(self.nbins, np.float64)
        self._alerts_champ = 0
        self._alerts_chall = 0
        self._shadow_rows = 0
        self._set_mark()

    def _set_mark(self) -> None:
        self._mark_n = len(getattr(self, "_y", ()))
        self._mark_hist_champ = np.array(
            getattr(self, "_hist_champ", np.zeros(self.nbins)), np.float64)
        self._mark_hist_chall = np.array(
            getattr(self, "_hist_chall", np.zeros(self.nbins)), np.float64)
        self._mark_alerts_champ = getattr(self, "_alerts_champ", 0)
        self._mark_alerts_chall = getattr(self, "_alerts_chall", 0)
        self._mark_rows = getattr(self, "_shadow_rows", 0)

    def mark(self) -> None:
        """Start an evidence WINDOW at the current accumulators. The
        controller marks at canary entry so canary guardrails judge what
        happened DURING the canary — a regression that only appears under
        canary serving must not be diluted away by a long green shadow
        history (``snapshot_window``)."""
        self._set_mark()

    # -- candidate lifecycle ----------------------------------------------
    def begin(self, version: int) -> None:
        self._version = int(version)
        self._reset_accumulators()

    def end(self) -> None:
        self._version = None
        self._reset_accumulators()

    @property
    def version(self) -> int | None:
        return self._version

    # cheap gate counters: the controller polls these every tick and only
    # pays for a full snapshot (rank sorts over the whole history) once
    # the verdict thresholds are actually reachable
    @property
    def n_labels(self) -> int:
        return len(self._y)

    @property
    def n_shadow_rows(self) -> int:
        return self._shadow_rows

    # -- ingestion ---------------------------------------------------------
    def poll(self, max_records: int = 4096) -> int:
        """Consume both streams once; returns records folded in. Both
        consumers drain even with no candidate active so a new candidate
        starts from the live head instead of a stale backlog."""
        folded = 0
        shadow = self._shadow_consumer.poll(max_records, 0.0)
        labels = self._labels_consumer.poll(max_records, 0.0)
        if self._version is None:
            return 0
        for rec in shadow:
            msg = rec.value or {}
            if msg.get("version") != self._version:
                continue
            champ = np.asarray(msg.get("champion", ()), np.float64)
            chall = np.asarray(msg.get("challenger", ()), np.float64)
            if champ.shape != chall.shape or champ.size == 0:
                continue
            self._hist_champ += np.histogram(
                np.clip(champ, 0.0, 1.0), bins=self._edges)[0]
            self._hist_chall += np.histogram(
                np.clip(chall, 0.0, 1.0), bins=self._edges)[0]
            thr = self.cfg.fraud_threshold
            self._alerts_champ += int((champ >= thr).sum())
            self._alerts_chall += int((chall >= thr).sum())
            self._shadow_rows += int(champ.size)
            folded += 1
        rows, ys = [], []
        for rec in labels:
            msg = rec.value or {}
            tx = msg.get("transaction") or {}
            try:
                row = [float(tx.get(n, 0.0) or 0.0) for n in FEATURE_NAMES]
                y = float(msg.get("label", 0))
            except (TypeError, ValueError):
                continue
            rows.append(row)
            ys.append(y)
        if rows:
            x = np.asarray(rows, np.float32)
            try:
                p_champ = np.asarray(self.scorer.host_score(x), np.float64)
                p_chall = np.asarray(
                    self.scorer.challenger_score(x), np.float64)
            except Exception:  # noqa: BLE001 - challenger mid-teardown:
                # drop this poll's labels rather than desync the pairing
                return folded
            self._y.extend(ys)
            self._p_champ.extend(p_champ.tolist())
            self._p_chall.extend(p_chall.tolist())
            overflow = len(self._y) - self.max_labels
            if overflow > 0:  # age out oldest, keeping the pairing intact
                del self._y[:overflow]
                del self._p_champ[:overflow]
                del self._p_chall[:overflow]
                self._mark_n = max(0, self._mark_n - overflow)
            folded += len(rows)
        if self._g_labels is not None:
            # evidence-count gauges refresh cheaply every poll; the
            # expensive AUC/PSI gauges refresh on full snapshots only
            self._g_labels.set(len(self._y))
            self._g_rows.set(self._shadow_rows)
        return folded

    # -- verdict inputs ----------------------------------------------------
    def _compute(self, y, pc, pn, hist_champ, hist_chall,
                 alerts_champ, alerts_chall, n_shadow) -> EvalSnapshot:
        y = np.asarray(y, np.float64)
        pc = np.asarray(pc, np.float64)
        pn = np.asarray(pn, np.float64)
        k = max(1, int(round(self.k_frac * len(y)))) if len(y) else 1
        alert_c = alerts_champ / n_shadow if n_shadow else float("nan")
        alert_n = alerts_chall / n_shadow if n_shadow else float("nan")
        score_psi = (
            float(psi(hist_chall, hist_champ)) if n_shadow else float("nan")
        )
        return EvalSnapshot(
            version=self._version,
            n_labels=len(y),
            n_shadow_rows=n_shadow,
            auc_champion=auc_score(y, pc),
            auc_challenger=auc_score(y, pn),
            precision_champion=precision_at_k(y, pc, k),
            precision_challenger=precision_at_k(y, pn, k),
            alert_rate_champion=alert_c,
            alert_rate_challenger=alert_n,
            alert_rate_delta=(alert_n - alert_c if n_shadow else float("nan")),
            score_psi=score_psi,
        )

    def snapshot_window(self) -> EvalSnapshot:
        """Metrics over the evidence since the last :meth:`mark` only."""
        return self._compute(
            self._y[self._mark_n:],
            self._p_champ[self._mark_n:],
            self._p_chall[self._mark_n:],
            self._hist_champ - self._mark_hist_champ,
            self._hist_chall - self._mark_hist_chall,
            self._alerts_champ - self._mark_alerts_champ,
            self._alerts_chall - self._mark_alerts_chall,
            self._shadow_rows - self._mark_rows,
        )

    def snapshot(self) -> EvalSnapshot:
        snap = self._compute(
            self._y, self._p_champ, self._p_chall,
            self._hist_champ, self._hist_chall,
            self._alerts_champ, self._alerts_chall, self._shadow_rows,
        )
        if self._g_labels is not None:
            self._g_labels.set(snap.n_labels)
            self._g_rows.set(snap.n_shadow_rows)
            if np.isfinite(snap.auc_champion):
                self._g_auc.set(snap.auc_champion,
                                labels={"model": "champion"})
            if np.isfinite(snap.auc_challenger):
                self._g_auc.set(snap.auc_challenger,
                                labels={"model": "challenger"})
            if np.isfinite(snap.score_psi):
                self._g_psi.set(snap.score_psi)
            if np.isfinite(snap.alert_rate_delta):
                self._g_delta.set(snap.alert_rate_delta)
        return snap

    def close(self) -> None:
        self._labels_consumer.close()
        self._shadow_consumer.close()
